"""Image preprocessing for the vision serving path.

The host-side half of Qwen2-VL serving: decode ``image_url`` content parts
(base64 data URLs or raw bytes), smart-resize to patch-grid multiples,
normalise, and extract patch rows in the merge-block order the vision tower
and its rotary ids expect (mirrors HF's Qwen2VLImageProcessor numerics so
checkpoints behave identically).  The reference feeds images to vLLM's own
processor inside the container; here it is the serving layer's job.
"""

from __future__ import annotations

import base64
import dataclasses
import io
import math
from typing import Optional

import numpy as np

# OpenAI-CLIP normalisation constants (Qwen2-VL's image_mean/image_std)
IMAGE_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
IMAGE_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


def smart_resize(
    height: int,
    width: int,
    factor: int = 28,
    min_pixels: int = 56 * 56,
    max_pixels: int = 14 * 14 * 4 * 1280,
) -> tuple:
    """Target (h, w): multiples of ``factor`` with area in bounds, aspect
    ratio approximately preserved (HF qwen2_vl smart_resize)."""
    if max(height, width) / min(height, width) > 200:
        raise ValueError("absurd aspect ratio")
    h_bar = max(factor, round(height / factor) * factor)
    w_bar = max(factor, round(width / factor) * factor)
    if h_bar * w_bar > max_pixels:
        beta = math.sqrt((height * width) / max_pixels)
        h_bar = math.floor(height / beta / factor) * factor
        w_bar = math.floor(width / beta / factor) * factor
    elif h_bar * w_bar < min_pixels:
        beta = math.sqrt(min_pixels / (height * width))
        h_bar = math.ceil(height * beta / factor) * factor
        w_bar = math.ceil(width * beta / factor) * factor
    return int(h_bar), int(w_bar)


def decode_image(source) -> np.ndarray:
    """data URL / base64 string / raw bytes -> RGB uint8 [H, W, 3]."""
    from PIL import Image

    if isinstance(source, str):
        if source.startswith("data:"):
            _, b64 = source.split(",", 1)
            raw = base64.b64decode(b64)
        else:
            raw = base64.b64decode(source)
    else:
        raw = bytes(source)
    img = Image.open(io.BytesIO(raw)).convert("RGB")
    return np.asarray(img)


def patchify(
    image: np.ndarray,           # [H, W, 3] uint8/float
    patch_size: int = 14,
    merge_size: int = 2,
    temporal_patch_size: int = 2,
    min_pixels: int = 56 * 56,
    max_pixels: int = 14 * 14 * 4 * 1280,
) -> tuple:
    """-> (patches [N, C*Tp*P*P], grid (1, h, w)) in the processor's
    merge-block order (temporal dim filled by frame repetition for stills,
    as HF does)."""
    from PIL import Image

    H, W = image.shape[:2]
    factor = patch_size * merge_size
    h2, w2 = smart_resize(H, W, factor, min_pixels, max_pixels)
    img = Image.fromarray(image.astype(np.uint8)).resize(
        (w2, h2), Image.BICUBIC
    )
    x = np.asarray(img, np.float32) / 255.0
    x = (x - IMAGE_MEAN) / IMAGE_STD
    x = x.transpose(2, 0, 1)                        # [C, H, W]
    x = np.tile(x[None], (temporal_patch_size, 1, 1, 1))  # [Tp, C, H, W]

    C = x.shape[1]
    gh, gw = h2 // patch_size, w2 // patch_size
    m = merge_size
    P = patch_size
    # [grid_t=1, Tp, C, gh/m, m, P, gw/m, m, P]
    x = x.reshape(1, temporal_patch_size, C, gh // m, m, P, gw // m, m, P)
    x = x.transpose(0, 3, 6, 4, 7, 2, 1, 5, 8)
    patches = x.reshape(gh * gw, C * temporal_patch_size * P * P)
    return patches.astype(np.float32), (1, gh, gw)


class VisionRunner:
    """Bundles the vision tower + special-token ids; turns chat messages
    with image parts into the engine's multimodal Request fields."""

    def __init__(
        self,
        vcfg,
        vparams,
        *,
        image_pad_id: int,
        vision_start_id: Optional[int] = None,
        vision_end_id: Optional[int] = None,
        max_pixels: int = 14 * 14 * 4 * 1280,
    ):
        self.vcfg = vcfg
        self.vparams = vparams
        self.image_pad_id = image_pad_id
        self.vision_start_id = vision_start_id
        self.vision_end_id = vision_end_id
        self.max_pixels = max_pixels

    def prepare(self, messages: list, tokenizer) -> dict:
        """-> kwargs for ``engine.Request`` (prompt_tokens + multimodal)."""
        import jax.numpy as jnp

        from helix_tpu.models.qwen2_vl import mrope_positions, vision_forward

        p = build_vl_prompt(
            messages,
            tokenizer,
            image_pad_id=self.image_pad_id,
            vision_start_id=self.vision_start_id,
            vision_end_id=self.vision_end_id,
            merge_size=self.vcfg.spatial_merge_size,
            patch_size=self.vcfg.patch_size,
            temporal_patch_size=self.vcfg.temporal_patch_size,
            max_pixels=self.max_pixels,
        )
        image_embeds = None
        if len(p.image_patches):
            patches = np.concatenate(p.image_patches, axis=0)
            image_embeds = vision_forward(
                self.vparams, self.vcfg, jnp.asarray(patches), p.grid_thw
            )
        pos3, delta = mrope_positions(
            p.input_ids,
            p.grid_thw if len(p.grid_thw) else None,
            self.image_pad_id,
            merge=self.vcfg.spatial_merge_size,
        )
        return dict(
            prompt_tokens=p.input_ids,
            image_embeds=image_embeds,
            image_positions=p.image_positions,
            positions3=pos3,
            mrope_delta=delta,
        )


@dataclasses.dataclass
class VLPrompt:
    input_ids: list
    image_patches: list      # list of np arrays per image
    grid_thw: np.ndarray     # [n_images, 3]
    image_positions: list    # indices of image-pad tokens


def build_vl_prompt(
    messages: list,
    tokenizer,
    *,
    image_pad_id: int,
    vision_start_id: Optional[int] = None,
    vision_end_id: Optional[int] = None,
    merge_size: int = 2,
    patch_size: int = 14,
    temporal_patch_size: int = 2,
    max_pixels: int = 14 * 14 * 4 * 1280,
) -> VLPrompt:
    """Chat messages (OpenAI content-parts format) -> token ids with image
    spans expanded to the right number of pad tokens, plus per-image patch
    tensors."""
    ids: list = []
    patches_all: list = []
    grids: list = []
    img_pos: list = []

    def add_image(source):
        patches, (t, gh, gw) = patchify(
            decode_image(source),
            patch_size=patch_size,
            merge_size=merge_size,
            temporal_patch_size=temporal_patch_size,
            max_pixels=max_pixels,
        )
        n_tokens = t * (gh // merge_size) * (gw // merge_size)
        if vision_start_id is not None:
            ids.append(vision_start_id)
        img_pos.extend(range(len(ids), len(ids) + n_tokens))
        ids.extend([image_pad_id] * n_tokens)
        if vision_end_id is not None:
            ids.append(vision_end_id)
        patches_all.append(patches)
        grids.append((t, gh, gw))

    for msg in messages:
        content = msg.get("content", "")
        ids.extend(tokenizer.encode(f"{msg['role']}: "))
        if isinstance(content, str):
            ids.extend(tokenizer.encode(content))
        else:
            for part in content:
                ptype = part.get("type")
                if ptype == "text":
                    ids.extend(tokenizer.encode(part.get("text", "")))
                elif ptype in ("image_url", "image"):
                    url = (
                        part.get("image_url", {}).get("url")
                        if ptype == "image_url"
                        else part.get("image")
                    )
                    add_image(url)
        ids.extend(tokenizer.encode("\n"))
    ids.extend(tokenizer.encode("assistant: "))
    return VLPrompt(
        input_ids=ids,
        image_patches=patches_all,
        grid_thw=np.asarray(grids) if grids else np.zeros((0, 3), np.int64),
        image_positions=img_pos,
    )
