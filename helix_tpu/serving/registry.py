"""Model registry: model name -> engine loop + tokenizer.

The in-process analogue of the reference's inference-proxy routing table
(``api/pkg/inferenceproxy/proxy.go:94-156`` reads the ``model`` field from
the request body and forwards to the vLLM container serving it).  Here a
profile's models map to Engines on mesh slices; the HTTP layer looks up by
name, with the same "unknown model -> 404 with available list" behaviour.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from helix_tpu.serving.engine_loop import EngineLoop


@dataclasses.dataclass
class ServedModel:
    name: str
    loop: EngineLoop
    tokenizer: object
    kind: str = "chat"           # chat | embedding | vision
    created: int = dataclasses.field(default_factory=lambda: int(time.time()))
    owned_by: str = "helix-tpu"
    context_length: Optional[int] = None
    embedder: object = None      # EmbeddingRunner for kind == "embedding"
    vision: object = None        # VisionRunner for kind == "vision"
    follower: object = None      # FollowerLoop on multi-host followers


class ModelRegistry:
    def __init__(self):
        self._models: dict[str, ServedModel] = {}

    def register(self, model: ServedModel):
        self._models[model.name] = model

    def unregister(self, name: str):
        m = self._models.pop(name, None)
        if m is not None and m.follower is not None:
            # a zombie follower would keep applying step plans against
            # the torn-down engine (duplicate collective participation)
            m.follower.stop()
        if m and m.loop:
            m.loop.stop(join=False)

    def get(self, name: str) -> Optional[ServedModel]:
        return self._models.get(name)

    def names(self) -> list:
        return sorted(self._models)

    def list(self) -> list:
        return [self._models[n] for n in self.names()]
