"""Runner log ring buffer (reference: hydra's in-memory log ring +
admin tailer — ``api/pkg/hydra/logbuf.go``, ``server/admin_runner_logs.go``).

A ``logging.Handler`` that keeps the last N records in memory; the node's
HTTP surface exposes the tail and the control plane proxies it to the
admin UI (by address or through the reverse tunnel).  Records carry the
``trace_id`` / ``request_id`` attached to the log record (via
``extra={...}``) when present, so the admin log tail correlates directly
with ``/v1/debug/traces``."""

from __future__ import annotations

import collections
import logging
import threading
import time


class RingLogBuffer(logging.Handler):
    def __init__(self, capacity: int = 2000):
        super().__init__()
        self.records: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:  # noqa: BLE001 — formatting must never raise
            line = record.getMessage()
        tid = str(getattr(record, "trace_id", "") or "")
        rid = str(getattr(record, "request_id", "") or "")
        with self._lock:
            self.records.append((time.time(), line, tid, rid))

    def push(self, line: str) -> None:
        """Non-logging writes (engine step notes, apply progress)."""
        with self._lock:
            self.records.append((time.time(), line, "", ""))

    def tail(self, n: int = 200) -> list:
        with self._lock:
            items = list(self.records)[-n:]
        out = []
        for ts, line, tid, rid in items:
            d = {"ts": ts, "line": line}
            if tid:
                d["trace_id"] = tid
            if rid:
                d["request_id"] = rid
            out.append(d)
        return out


_global: RingLogBuffer | None = None


def install(capacity: int = 2000) -> RingLogBuffer:
    """Attach one ring buffer to the root logger (idempotent).

    Deliberately does NOT change the root logger's level: the buffer
    captures whatever the deployment's logging config emits, plus
    explicit ``push()`` writes from the serving layer. Flooding other
    handlers with INFO as a construction side effect would be worse than
    a quieter ring."""
    global _global
    if _global is None:
        _global = RingLogBuffer(capacity)
        logging.getLogger().addHandler(_global)
    return _global
