"""Multi-host serving: plan-broadcast SPMD engines over a DCN feed.

SURVEY §2.2/§7 puts inter-slice DCN in the engine's court.  In JAX's
multi-controller model every process must issue the SAME jit calls in
the same order for collectives over a cross-host mesh to line up.
Serving has dynamic admission, so this module makes the call sequence
deterministic by construction — but unlike the original command-replay
journal (which made followers re-derive every host decision and
therefore pinned off every feature whose host state could drift), the
contract is now a **per-step plan broadcast**:

- the **leader** (process 0) takes HTTP traffic and runs the full host
  stack — admission, WFQ reorder, spec drafting, preemption-by-swap,
  prefix/filestore restoration, the async pipelined loop.  Its
  ``step_dispatch`` finalizes everything the device call needs; a
  ``PlanRecorder`` captures those decisions as *data* (admitted request
  docs with ``cached_tokens``, resume order, draft tokens, the prefill
  budget, the queue-pressure bit) and publishes ONE versioned
  ``StepPlan`` record per step; abort/preempt publish immediately as
  standalone ``ops`` records in arrival order;
- **followers** are pure device executors: ``FollowerLoop`` decodes a
  plan and drives the *same* engine step through a ``PlanDrive`` that
  pins every host decision to the leader's values.  No follower-side
  admission queue, scheduler, drafter, or clock participates — the
  follower's compiled step shapes are the leader's by construction.

Because plans pin decisions rather than forbidding them, the features
the old journal disabled are all live on meshes: spec decode (drafts
ride the plan), the adapter pool (followers stage residency before the
step), WFQ (budget + victim order are leader-decided data), preemption
(``ops`` records replay the swap in arrival order), the async pipeline
(plan N+1 publishes while device step N completes), and filestore
prefix hits (the plan carries ``cached_tokens``; point both hosts at
the same filestore dir and the drive verifies the restore matched).

Emission digests (rolling blake2s over per-step (request, token)
emissions, aborted requests excluded over a one-plan window to absorb
abort-arrival skew) let a follower detect silent divergence; the
``HELIX_MH_DIGEST`` knob picks strict/warn/off.

Transport is pluggable: in-process ``CommandLog`` (tests, and the ring
buffer the leader serves), or ``HTTPFeed`` (follower long-polls the
leader's ``/multihost/commands`` route over DCN with a pooled session).

ISSUE 17 grows the plane past two hosts and makes the leader
restartable:

- **N-follower fan-out** — every poll registers the follower's health
  with the leader (:meth:`PlanLeader.note_poll`): last-acked seq,
  applied step, apply latency, digest counters.  A follower sustained
  more than ``HELIX_MH_LAG_STEPS`` behind enters a typed ``lagging``
  state and the leader throttles admission (prefill budget pinned to 0,
  the PR 8 discipline) instead of letting the ring overflow into a
  fatal error; catch-up flips it back to ``healthy``.
- **Typed resync** — ``CommandLog.read_since`` no longer raises an
  unconditional fatal ``LagError``: overflow / leader-restart surface
  as a ``resync_required`` record whose ``reason`` distinguishes "I
  fell behind" (restart the follower process; it replays the ring)
  from "the leader restarted" (re-apply the profile), so the node
  agent can log the right operator action.
- **Leader failover** — the leader periodically checkpoints its
  host-side queue state (waiting-queue wire docs, parked-request
  snapshots, WFQ virtual service, prefill budget, spec EMAs, plan
  index + digest chain head) through :class:`CheckpointStore` (the
  PR 14 filestore tier: checksummed, versioned, written off the
  engine thread).  :func:`promote_follower` turns a live standby into
  the publishing leader at a digest-verified step boundary: the
  checkpoint's digest must match the standby's own chain BEFORE any
  allocator mutation, every active request parks (slot order) so the
  handoff boundary is reproducible, unknown waiting/parked state
  imports from the checkpoint, and the new leader's first record is a
  ``handoff`` carrying the chain head + a fresh checkpoint reference.
  Peers at the exact boundary cross over seamlessly (and keep
  verifying the chained digest across the handoff); fresh followers
  bootstrap from the referenced checkpoint; anything else fails typed
  and degrades to the full resync ladder — never worse than a leader
  restart today.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import itertools
import json
import logging
import os
import random
import re
import struct
import threading
import time
from typing import Optional

from helix_tpu.engine.engine import Request
from helix_tpu.engine.sampling import SamplingParams
from helix_tpu.obs import trace as obs_trace

log = logging.getLogger("helix.mh-serving")

#: Plan/request wire format version.  v1 was the command-replay journal
#: ({admits, aborts, step} records whose request docs dropped tenant /
#: sched_class / adapter / max_len); v2 is the step-plan broadcast.
#: Mixed-version clusters are rejected typed, never misparsed.
WIRE_VERSION = 2

#: Leader-state checkpoint format version (CheckpointStore envelopes).
CHECKPOINT_VERSION = 1

_DIGEST_SEED = b"\x00" * 16

# plan-plane trace ids must satisfy the adoptable-id shape contract
_PLAN_TID_RE = re.compile(r"[^A-Za-z0-9_-]")


def plan_trace_id(model: str) -> str:
    """The mesh's PLAN-PLANE trace identity (ISSUE 18): one stable,
    well-shaped trace id per model mesh, shared by the leader and every
    follower so plan publishes, follower applies, digest verifies,
    checkpoints and takeovers stitch into ONE federated timeline — a
    takeover blackout reads as a gap between the last leader publish
    and the promoted host's first, not just ``takeover_blackout_ms``
    in bench output."""
    return ("mh-plan-" + _PLAN_TID_RE.sub("-", model or "default"))[:64]

#: Follower health states in the leader's registry (ISSUE 17).  Minted
#: ONLY here — lint contract 12 fences the literals; consumers
#: (node agent, control plane, /metrics) import these names.
FOLLOWER_HEALTHY = "healthy"
FOLLOWER_LAGGING = "lagging"
FOLLOWER_LOST = "lost"
FOLLOWER_STATES = (FOLLOWER_HEALTHY, FOLLOWER_LAGGING, FOLLOWER_LOST)

#: Typed reasons on ``resync_required`` records / ResyncRequired — each
#: maps to a DIFFERENT operator action (RESYNC_ACTIONS), which is the
#: point of typing them instead of one fatal LagError.
RESYNC_RING_OVERFLOW = "ring_overflow"
RESYNC_LEADER_RESTART = "leader_restart"
RESYNC_HANDOFF_MISMATCH = "handoff_mismatch"
RESYNC_CHECKPOINT_REJECTED = "checkpoint_rejected"

RESYNC_ACTIONS = {
    RESYNC_RING_OVERFLOW: (
        "this follower fell behind the leader's plan ring: restart the "
        "follower process — it rejoins by replaying the ring from the "
        "current head (raise HELIX_MH_RING to widen the window)"
    ),
    RESYNC_LEADER_RESTART: (
        "the leader restarted and its plan sequence reset: re-apply "
        "the serving profile on every host of the mesh"
    ),
    RESYNC_HANDOFF_MISMATCH: (
        "a new leader took over at a step boundary this follower is "
        "not at: restart the follower process fresh — it bootstraps "
        "from the handoff checkpoint"
    ),
    RESYNC_CHECKPOINT_REJECTED: (
        "the takeover checkpoint failed validation on this follower: "
        "restart the follower process; if it repeats, re-apply the "
        "serving profile (the checkpoint store may be corrupt)"
    ),
}


class LagError(RuntimeError):
    """Follower fell off the ring (or ahead of it — leader restart)."""


class ResyncRequired(LagError):
    """Typed resync: carries WHY lockstep must restart (``reason`` is
    one of the RESYNC_* constants) so operators get the right action
    instead of one undifferentiated fatal error."""

    def __init__(self, msg: str, reason: str = ""):
        super().__init__(msg)
        self.reason = reason


class WireVersionError(ValueError):
    """Record from a different wire version; upgrade hosts together."""


class DivergenceError(RuntimeError):
    """Replica state no longer matches the leader's plan — lockstep lost."""


class CheckpointError(RuntimeError):
    """Leader-state checkpoint unusable (typed ``code``): corrupt blob,
    unsupported version, or no checkpoint at all."""

    def __init__(self, msg: str, code: str = "checkpoint_corrupt"):
        super().__init__(msg)
        self.code = code


class CommandLog:
    """Sequenced ring buffer with blocking reads (the leader's journal).

    The ring is a ``collections.deque``: overflow past capacity is an
    O(1) ``popleft`` per dropped record, not an O(n) list re-slice per
    publish (which made sustained publish throughput quadratic once the
    ring was full)."""

    def __init__(self, capacity: int = 4096, start_seq: int = 1):
        self.capacity = capacity
        self._records: collections.deque = collections.deque()
        # a takeover leader continues the dead leader's sequence
        # (start_seq = standby's applied seq + 1) so peers at the
        # boundary poll straight across the handoff
        self._first = start_seq
        self._next = start_seq
        self._start = start_seq
        self._cond = threading.Condition()

    def publish(self, record: dict) -> int:
        with self._cond:
            seq = self._next
            self._next += 1
            self._records.append({**record, "seq": seq})
            while len(self._records) > self.capacity:
                self._records.popleft()
                self._first += 1
            self._cond.notify_all()
            return seq

    def _resync_record(self, reason: str, since: int, msg: str) -> dict:
        """Typed ``resync_required`` record (ISSUE 17 bugfix): overflow
        and leader-restart used to surface as one unconditional fatal
        LagError raised here; as a RECORD the reason rides the feed
        transparently (HTTP included), the follower's stats can tell
        "leader restarted" from "I fell behind", and the node agent
        logs the matching operator action (RESYNC_ACTIONS)."""
        return {
            "v": WIRE_VERSION,
            "kind": "resync_required",
            "reason": reason,
            "seq": since,       # echoes the reader: applied_seq unchanged
            "first": self._first,
            "next": self._next,
            "error": msg,
        }

    def read_since(self, since: int, timeout: float = 30.0) -> list:
        """Records with seq > since; blocks up to timeout when none.
        A reader the ring can no longer serve gets a single typed
        ``resync_required`` record instead of an exception."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if since + 1 < self._first:
                    if since < self._start and self._first == self._start:
                        # the reader predates this leader's epoch (a
                        # fresh follower joining after a takeover) and
                        # the epoch head — the handoff record — is
                        # still retained: serve from the head so it
                        # can bootstrap from the handoff checkpoint
                        since = self._start - 1
                    else:
                        return [self._resync_record(
                            RESYNC_RING_OVERFLOW, since,
                            f"follower at seq {since} fell behind the "
                            f"ring (first retained: {self._first})",
                        )]
                if since >= self._next:
                    # AHEAD of the journal: the leader restarted and its
                    # sequence reset — silent empty polls here would hang
                    # the whole cluster mid-collective; surface it typed
                    # so the follower restarts and resyncs
                    return [self._resync_record(
                        RESYNC_LEADER_RESTART, since,
                        f"follower at seq {since} is ahead of the "
                        f"journal (next: {self._next}) — leader "
                        "restart?",
                    )]
                skip = max(0, since + 1 - self._first)
                out = list(itertools.islice(self._records, skip, None))
                if out:
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)


def request_to_wire(req: Request) -> dict:
    if req.image_embeds is not None:
        raise ValueError(
            "multi-host serving covers text models (VL image embeds are "
            "device-resident and not broadcast)"
        )
    return {
        "v": WIRE_VERSION,
        "id": req.id,
        "prompt_tokens": list(req.prompt_tokens),
        "sampling": dataclasses.asdict(req.sampling),
        "stop_token_ids": list(req.stop_token_ids),
        "tenant": req.tenant,
        "sched_class": req.sched_class,
        "adapter": req.adapter,
        "max_len": req.max_len,
        "trace_id": req.trace_id,
    }


def request_from_wire(doc: dict) -> Request:
    v = doc.get("v")
    if v != WIRE_VERSION:
        raise WireVersionError(
            f"request wire record version {v!r} (this host speaks "
            f"{WIRE_VERSION}); v1 records dropped tenant/sched_class/"
            "adapter/max_len and are rejected rather than misparsed — "
            "upgrade the leader and followers together"
        )
    return Request(
        id=doc["id"],
        prompt_tokens=list(doc["prompt_tokens"]),
        sampling=SamplingParams(**doc["sampling"]),
        stop_token_ids=tuple(doc["stop_token_ids"]),
        tenant=doc["tenant"],
        sched_class=doc["sched_class"],
        adapter=doc["adapter"],
        max_len=doc["max_len"],
        trace_id=doc.get("trace_id", ""),
    )


def mh_checkpoint_dir() -> str:
    """HELIX_MH_CHECKPOINT_DIR: root of the leader-state checkpoint
    store ('' = failover disabled).  Point every host of the mesh at
    the SAME directory (the PR 14 cluster-wide filestore tier)."""
    return os.environ.get("HELIX_MH_CHECKPOINT_DIR", "")


def checkpoint_store_from_env() -> Optional["CheckpointStore"]:
    d = mh_checkpoint_dir()
    return CheckpointStore(d) if d else None


class CheckpointStore:
    """Leader-state checkpoints through the PR 14 filestore tier.

    Same discipline as the KV filestore rung: a rooted
    ``control.filestore.Filestore`` under a reserved owner, every blob
    a checksummed + versioned envelope verified BEFORE use (corruption
    = typed rejection, never a misparse), writes queued to a single
    background writer so the engine thread never blocks on disk, and a
    keep-newest-K prune so the store stays bounded."""

    #: reserved owner prefix — tenants can't collide with it
    #: (Filestore._resolve keeps owners disjoint)
    OWNER = "__mh_ckpt__"

    def __init__(self, root: str, keep: Optional[int] = None):
        from helix_tpu.control.filestore import Filestore

        self.store = Filestore(root)
        if keep is None:
            try:
                keep = int(os.environ.get("HELIX_MH_CHECKPOINT_KEEP",
                                          "3") or 3)
            except ValueError:
                keep = 3
        self.keep = max(1, keep)
        self._mu = threading.Lock()
        self._writeq = None
        self._writer = None
        # counters (mh_stats / collect_mh_metrics)
        self.writes = 0
        self.write_errors = 0
        self.write_drops = 0
        self.corrupt_rejected = 0
        self.bytes_last = 0

    @staticmethod
    def _model_dir(model: str) -> str:
        safe = "".join(
            ch if ch.isalnum() or ch in "._-" else "_"
            for ch in (model or "model")
        )
        return safe or "model"

    def _blob_name(self, model: str, plan_idx: int, seq: int) -> str:
        # plan_idx starts at -1 (nothing published yet); +1 keeps the
        # zero-padded name sortable
        return (f"{self._model_dir(model)}/"
                f"ckpt-{plan_idx + 1:016d}-{max(0, seq):016d}.json")

    def save(self, model: str, state: dict) -> tuple:
        """Synchronous write (the promote path: the handoff record
        references the blob, so it must be durable first).  Returns
        ``(ref, nbytes)``."""
        payload = json.dumps(state, separators=(",", ":"),
                             sort_keys=True)
        blob_doc = {
            "v": CHECKPOINT_VERSION,
            "checksum": hashlib.blake2b(
                payload.encode(), digest_size=16
            ).hexdigest(),
            "payload": payload,
        }
        blob = json.dumps(blob_doc).encode()
        blob = self._maybe_corrupt(model, blob)
        ref = self._blob_name(
            model, int(state.get("plan_idx", -1)),
            int(state.get("seq", 0)),
        )
        self.store.write(self.OWNER, ref, blob)
        self.writes += 1
        self.bytes_last = len(blob)
        self._prune(model)
        return ref, len(blob)

    @staticmethod
    def _maybe_corrupt(model: str, blob: bytes) -> bytes:
        """Deterministic fault hook (testing/faults.py ``checkpoint``
        rules): flip one payload byte so the NEXT load rejects the blob
        the way real disk corruption would."""
        try:
            from helix_tpu.testing.faults import active
        except Exception:  # noqa: BLE001 — faults module optional
            return blob
        inj = active()
        if inj is None or not inj.checkpoint_fault(model):
            return blob
        mid = len(blob) // 2
        return blob[:mid] + bytes([blob[mid] ^ 0xFF]) + blob[mid + 1:]

    def save_async(self, model: str, state: dict) -> None:
        """Queue a periodic checkpoint for the background writer (the
        engine thread captures state; disk latency must not stall the
        step cadence — the ``_store_filestore_pages`` discipline).
        Bounded queue: a stuck disk drops checkpoints (counted), it
        never backpressures serving."""
        import queue as _queue

        with self._mu:
            if self._writer is None:
                self._writeq = _queue.Queue(maxsize=4)
                self._writer = threading.Thread(
                    target=self._write_loop,
                    name="mh-ckpt-writer", daemon=True,
                )
                self._writer.start()
        try:
            self._writeq.put_nowait((model, state))
        except _queue.Full:
            self.write_drops += 1

    def _write_loop(self) -> None:
        while True:
            model, state = self._writeq.get()
            try:
                self.save(model, state)
            except Exception:  # noqa: BLE001 — background writer
                self.write_errors += 1
                log.exception("leader checkpoint write failed")
            finally:
                self._writeq.task_done()

    def flush(self, timeout: float = 30.0) -> None:
        """Block until queued async writes land (tests, promote)."""
        q = self._writeq
        if q is None:
            return
        deadline = time.monotonic() + timeout
        while q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.01)

    def load(self, ref: str) -> dict:
        """Read + validate one checkpoint blob.  Every rung is typed:
        unreadable/corrupt envelope, checksum mismatch, or a version
        this build does not speak — callers NEVER see a half-trusted
        state dict (validate before mutate)."""
        try:
            blob = self.store.read(self.OWNER, ref)
        except OSError as e:
            raise CheckpointError(
                f"checkpoint {ref!r} unreadable: {e}",
                code="checkpoint_missing",
            )
        try:
            doc = json.loads(blob.decode())
        except (ValueError, UnicodeDecodeError):
            self.corrupt_rejected += 1
            raise CheckpointError(
                f"checkpoint {ref!r} is not a valid envelope"
            )
        if doc.get("v") != CHECKPOINT_VERSION:
            raise CheckpointError(
                f"checkpoint {ref!r} version {doc.get('v')!r} (this "
                f"build speaks {CHECKPOINT_VERSION})",
                code="checkpoint_version",
            )
        payload = doc.get("payload", "")
        claimed = str(doc.get("checksum", ""))
        have = hashlib.blake2b(
            payload.encode(), digest_size=16
        ).hexdigest()
        if not claimed or have != claimed:
            self.corrupt_rejected += 1
            raise CheckpointError(
                f"checkpoint {ref!r} checksum mismatch"
            )
        state = json.loads(payload)
        if state.get("version") != CHECKPOINT_VERSION:
            raise CheckpointError(
                f"checkpoint {ref!r} state version "
                f"{state.get('version')!r}", code="checkpoint_version",
            )
        return state

    def list_refs(self, model: str) -> list:
        """Checkpoint refs for ``model``, newest first."""
        d = self._model_dir(model)
        try:
            entries = self.store.list(self.OWNER, d)
        except PermissionError:
            return []
        names = sorted(
            (e["path"] for e in entries if not e.get("is_dir")),
            reverse=True,
        )
        return [f"{d}/{os.path.basename(n)}" for n in names]

    def load_latest(self, model: str) -> tuple:
        """Newest USABLE checkpoint as ``(ref, state)``.  A corrupt or
        version-skewed blob is skipped (counted) and the next older one
        tried — one bad write must not take failover down with it.
        Raises typed CheckpointError when nothing usable exists."""
        last_err = None
        for ref in self.list_refs(model):
            try:
                return ref, self.load(ref)
            except CheckpointError as e:
                last_err = e
                continue
        if last_err is not None:
            raise CheckpointError(
                f"no usable checkpoint for {model!r} (newest failure: "
                f"{last_err})", code=last_err.code,
            )
        raise CheckpointError(
            f"no checkpoint exists for {model!r}",
            code="checkpoint_missing",
        )

    def _prune(self, model: str) -> None:
        refs = self.list_refs(model)
        for ref in refs[self.keep:]:
            try:
                self.store.delete(self.OWNER, ref)
            except Exception:  # noqa: BLE001 — best-effort prune
                pass

    def stats(self) -> dict:
        return {
            "writes": self.writes,
            "write_errors": self.write_errors,
            "write_drops": self.write_drops,
            "corrupt_rejected": self.corrupt_rejected,
            "bytes_last": self.bytes_last,
        }


def export_sched_state(sched) -> Optional[dict]:
    """WFQ virtual-service snapshot for the leader checkpoint (None for
    FIFO / no scheduler: nothing worth carrying across a takeover)."""
    vsrv = getattr(sched, "_vsrv", None)
    vfloor = getattr(sched, "_vfloor", None)
    lock = getattr(sched, "_lock", None)
    if vsrv is None or vfloor is None or lock is None:
        return None
    with lock:
        return {
            "vsrv": {c: dict(t) for c, t in vsrv.items()},
            "vfloor": dict(vfloor),
        }


def restore_sched_state(sched, doc) -> bool:
    """Seed a fresh scheduler with a checkpointed WFQ snapshot so the
    promoted leader keeps charging tenants where the dead one left off
    (fair-share does not reset to zero on failover)."""
    if not doc:
        return False
    vsrv = getattr(sched, "_vsrv", None)
    vfloor = getattr(sched, "_vfloor", None)
    lock = getattr(sched, "_lock", None)
    if vsrv is None or vfloor is None or lock is None:
        return False
    with lock:
        for cls, tenants in (doc.get("vsrv") or {}).items():
            if cls in vsrv and isinstance(tenants, dict):
                vsrv[cls].update(
                    {str(t): float(v) for t, v in tenants.items()}
                )
        for cls, v in (doc.get("vfloor") or {}).items():
            if cls in vfloor:
                vfloor[cls] = float(v)
    return True


def export_spec_state(engine) -> Optional[dict]:
    """Per-request speculative-decoding acceptance EMAs (engine/spec.py
    ``_slots``): carried across a takeover so drafting does not re-probe
    every request from the optimistic start."""
    spec = getattr(engine, "spec", None)
    slots = getattr(spec, "_slots", None)
    if not slots:
        return None
    out = {}
    for rid, st in list(slots.items()):
        out[rid] = {
            "ema": float(getattr(st, "ema", 1.0)),
            "enabled": bool(getattr(st, "enabled", True)),
            "cooldown": int(getattr(st, "cooldown", 0)),
            "drafted": int(getattr(st, "drafted", 0)),
            "accepted": int(getattr(st, "accepted", 0)),
        }
    return out


def restore_spec_state(engine, doc) -> int:
    spec = getattr(engine, "spec", None)
    if spec is None or not doc:
        return 0
    n = 0
    for rid, st in doc.items():
        try:
            slot = spec._state(rid)
            slot.ema = float(st.get("ema", 1.0))
            slot.enabled = bool(st.get("enabled", True))
            slot.cooldown = int(st.get("cooldown", 0))
            slot.drafted = int(st.get("drafted", 0))
            slot.accepted = int(st.get("accepted", 0))
            n += 1
        except Exception:  # noqa: BLE001 — EMAs are best-effort
            continue
    return n


class PlanRecorder:
    """Captures the leader engine's per-dispatch host decisions as data.

    The engine duck-types this via ``self._plan_recorder`` (set around
    ``step_dispatch`` by :class:`PlanLeader`): admission claims call
    ``note_admit`` after ``cached_tokens`` is final, resumes append the
    resumed request id, spec drafting stores the drafted tokens per
    slot, and the dispatch prologue stores the prefill budget and the
    queue-pressure bit that pins the decode window."""

    __slots__ = ("admits", "resumes", "drafts", "budget", "queue_blocked")

    def __init__(self):
        self.admits: list = []
        self.resumes: list = []
        self.drafts: list = []
        self.budget = None
        self.queue_blocked = False

    def note_admit(self, req: Request) -> None:
        doc = request_to_wire(req)
        doc["cached_tokens"] = int(req.cached_tokens)
        self.admits.append(doc)


class PlanDrive:
    """Pins a follower engine's host decisions to the leader's plan.

    The engine duck-types this via ``self._plan_drive`` (set around
    ``step()`` by :class:`FollowerLoop`): the prefill budget and the
    queue-pressure bit are overridden, spec drafting consumes the
    plan's draft tokens verbatim instead of running the host drafter,
    resumes happen exactly in plan order, and each admission claim
    verifies its locally-restored ``cached_tokens`` against the
    leader's value (a mismatch means the prefix/filestore rungs drifted
    between hosts and the device steps would desync)."""

    __slots__ = ("budget", "queue_blocked", "drafts", "resumes",
                 "cached_tokens")

    def __init__(self, budget, queue_blocked, drafts, resumes,
                 cached_tokens):
        self.budget = budget
        self.queue_blocked = bool(queue_blocked)
        self.drafts = drafts
        self.resumes = resumes
        self.cached_tokens = cached_tokens


def _fold_digest(prev: bytes, step_idx: int, emissions, excluded) -> bytes:
    """Roll the emission digest forward over one step.

    Emissions are folded sorted (order within a step is host-side
    bookkeeping, not model output) and requests in ``excluded`` —
    aborted in this plan or the next — are skipped: an abort lands on
    the leader at arrival but on followers at the next plan boundary,
    so tail emissions of an aborted request legitimately differ over a
    one-plan window."""
    h = hashlib.blake2s(prev)
    h.update(struct.pack("<q", step_idx))
    for rid, tok in sorted(emissions):
        if rid in excluded:
            continue
        b = rid.encode("utf-8", "surrogatepass")
        h.update(struct.pack("<I", len(b)))
        h.update(b)
        h.update(struct.pack("<q", int(tok)))
    return h.digest()[:16]


class PlanLeader:
    """Engine wrapper for the leader: broadcasts one StepPlan per step.

    Duck-types the Engine surface EngineLoop uses (add_request / abort /
    step / step_dispatch / step_complete / pipeline_ready /
    discard_pending / has_work / validate_request / reap_stuck / slots /
    waiting / recent_ttfts ...).  Unlike the old command-replay journal
    it does NOT disable anything: preemption, spec decode, adapters,
    WFQ, the async pipeline, filestore prefix hits, and drain-time
    snapshot export all run on the leader and replicate as plan data.
    """

    def __init__(self, engine, journal: Optional[CommandLog] = None,
                 checkpoint_store: Optional[CheckpointStore] = None,
                 name: str = ""):
        self.engine = engine
        if journal is None:
            cap = int(os.environ.get("HELIX_MH_RING", "4096") or 4096)
            journal = CommandLog(capacity=cap)
        self.journal = journal
        self.name = name
        self._seed_counter = itertools.count(0x5EED)
        # -- N-follower health registry (ISSUE 17) ----------------------
        # follower_id -> {last_poll, last_seq, applied_step, lag_steps,
        # state, apply_ms, digest_checks, digest_mismatches, standby}
        self._followers: dict = {}
        self._followers_mu = threading.Lock()
        self.lag_steps_limit = int(
            os.environ.get("HELIX_MH_LAG_STEPS", "64") or 64
        )
        self.max_followers = int(
            os.environ.get("HELIX_MH_MAX_FOLLOWERS", "16") or 16
        )
        self.follower_ttl = float(
            os.environ.get("HELIX_MH_FOLLOWER_TTL", "15") or 15
        )
        self.followers_dropped = 0
        self.throttled_steps = 0
        self.takeovers = 0
        self.takeover_ms = 0.0
        # -- leader-state checkpointing (failover) ----------------------
        self.checkpoint_store = checkpoint_store
        self.checkpoint_seconds = float(
            os.environ.get("HELIX_MH_CHECKPOINT_SECONDS", "5") or 5
        )
        self._ckpt_last = 0.0
        self._ckpt_sched = None   # last sched snapshot seen (takeover carry)
        self.checkpoints_captured = 0
        self.checkpoint_errors = 0
        # serializes abort/preempt arrival against plan assembly: ops
        # publish IMMEDIATELY in arrival order, so the stream position
        # of an op relative to the surrounding plans is exactly the
        # order the leader's engine saw it
        self._mu = threading.Lock()
        self._carry_admits: list = []     # re-carried from a failed plan
        self._carry_resumes: list = []
        self._carry_emissions: list = []
        self._step_counter = 0
        self._last_plan_idx = -1
        self._dispatch_steps: dict = {}   # id(pend) -> plan idx
        self._plan_content: dict = {}     # plan idx -> (admits, resumes)
        self._emissions: dict = {}        # plan idx -> [(rid, tok)]
        self._done_steps: set = set()
        # plan idx -> rids aborted between that plan and the next one
        # (the digest-exclusion window: those aborts race the step's
        # completion on the leader but land post-step on followers)
        self._aborts_after_plan: dict = {}
        self._fold_next = 0
        self._digest = _DIGEST_SEED
        self._digest_step: Optional[int] = None
        self._digest_reset_pending = False
        # surfaced by bench.py and /admin stats
        self.plans_published = 0
        self.plan_bytes_total = 0
        self.plan_bytes_max = 0
        # plan-plane tracing (ISSUE 18): publish/checkpoint/takeover
        # spans land under one stable per-mesh trace id in the
        # process-wide store and federate to the cp like any runner
        # span; tests swap the store per "host"
        self._trace = obs_trace.default_store()
        self.plan_trace_id = plan_trace_id(name)

    # -- attributes EngineLoop SETS on its engine must reach the real
    # engine (a plain __getattr__ passthrough would shadow them here and
    # silently break WFQ fair-share charging and victim ordering) ------
    @property
    def prefill_budget(self):
        return self.engine.prefill_budget

    @prefill_budget.setter
    def prefill_budget(self, value):
        self.engine.prefill_budget = value

    @property
    def on_admit(self):
        return self.engine.on_admit

    @on_admit.setter
    def on_admit(self, value):
        self.engine.on_admit = value

    @property
    def victim_policy(self):
        return self.engine.victim_policy

    @victim_policy.setter
    def victim_policy(self, value):
        self.engine.victim_policy = value

    # -- mutations ----------------------------------------------------------
    def add_request(self, req: Request) -> None:
        if req.sampling.seed is None:
            # pin a seed so follower sampling is bit-identical without
            # relying on engine-internal PRNG call order
            req.sampling = dataclasses.replace(
                req.sampling, seed=next(self._seed_counter)
            )
        # validate wire-encodability up front (VL rejects here, not at
        # admission time deep inside a step)
        request_to_wire(req)
        self.engine.add_request(req)

    def _req_trace(self, rid: str) -> str:
        """The request's trace id if the engine still knows it (looked
        up BEFORE the engine op — an aborted request is gone after)."""
        get = getattr(self.engine, "get_request", None)
        req = get(rid) if callable(get) else None
        tid = getattr(req, "trace_id", "") if req is not None else ""
        return tid if obs_trace.is_trace_id(tid) else ""

    def _publish_op(self, op: str, rid: str, tid: str = "") -> None:
        # ops records publish at arrival (not at the next dispatch):
        # an abort with no step behind it must still reach followers,
        # or they keep a zombie request parked forever
        t0 = time.monotonic()
        rec: dict = {
            "v": WIRE_VERSION, "kind": "ops", "ops": [[op, rid]],
        }
        if tid:
            # ISSUE 18 bugfix: the op carries the request's trace id so
            # a cp-initiated abort is traceable THROUGH the follower —
            # HTTPFeed poll responses deliver it with the record
            rec["traces"] = {rid: tid}
        self.journal.publish(rec)
        if tid:
            self._trace.record(
                tid, "mh op publish", t0, time.monotonic(),
                plane="engine", op=op, request_id=rid,
                seq=self.journal._next - 1,
            )
        if op == "abort":
            self._aborts_after_plan.setdefault(
                self._last_plan_idx, set()
            ).add(rid)

    def abort(self, request_id: str) -> None:
        with self._mu:
            tid = self._req_trace(request_id)
            self.engine.abort(request_id)
            self._publish_op("abort", request_id, tid)

    def preempt(self, request_id: str) -> bool:
        with self._mu:
            tid = self._req_trace(request_id)
            ok = self.engine.preempt(request_id)
            if ok:
                self._publish_op("preempt", request_id, tid)
            return ok

    def preempt_for_pressure(self) -> Optional[str]:
        with self._mu:
            rid = self.engine.preempt_for_pressure()
            if rid is not None:
                self._publish_op("preempt", rid, self._req_trace(rid))
            return rid

    # snapshot IMPORT and the disaggregated prefill handoff (ISSUE
    # 11/14) would create device state that exists only on the leader —
    # a migrated-in request has no admission plan row followers could
    # replay, so its later resume would diverge.  Export stays live
    # (drain-time snapshots are leader-owned; the shipped request's
    # abort rides the next plan like any abort).
    import_request = None
    export_prefill = None

    def reap_stuck(self, max_queue_seconds: float) -> list:
        # the reaper scans the waiting queue only, and waiting requests
        # are never broadcast (only ADMITTED requests ride plans) — so a
        # reap needs no wire record at all: followers never knew the
        # request existed
        return self.engine.reap_stuck(max_queue_seconds)

    # -- follower health (ISSUE 17: N-follower fan-out) ---------------------
    def note_poll(self, follower_id: str, since: int,
                  applied_step: Optional[int] = None,
                  apply_ms: Optional[float] = None,
                  digest_checks: Optional[int] = None,
                  digest_mismatches: Optional[int] = None,
                  standby: bool = False) -> None:
        """Register one follower poll.  Called by the plan-feed route
        (HTTPFeed sends the health fields as query params) or directly
        by in-process feeds.  Bounded: at most ``max_followers``
        registrations; beyond that, new ids are dropped (counted) so a
        querystring fuzzer cannot grow the registry — or /metrics label
        cardinality — without bound."""
        if not follower_id:
            return
        now = time.monotonic()
        with self._followers_mu:
            st = self._followers.get(follower_id)
            if st is None:
                if len(self._followers) >= self.max_followers:
                    self._prune_followers(now)
                if len(self._followers) >= self.max_followers:
                    self.followers_dropped += 1
                    return
                st = self._followers[follower_id] = {
                    "state": FOLLOWER_HEALTHY,
                    "registered_ago": 0.0,
                    "applied_step": -1,
                    "lag_steps": 0,
                    "apply_ms": 0.0,
                    "digest_checks": 0,
                    "digest_mismatches": 0,
                    "standby": False,
                    "_registered": now,
                }
            st["last_poll"] = now
            st["last_seq"] = int(since)
            st["standby"] = bool(standby) or st["standby"]
            if applied_step is not None:
                st["applied_step"] = int(applied_step)
            if apply_ms is not None:
                st["apply_ms"] = float(apply_ms)
            if digest_checks is not None:
                st["digest_checks"] = int(digest_checks)
            if digest_mismatches is not None:
                st["digest_mismatches"] = int(digest_mismatches)
            lag = max(0, self._last_plan_idx - st["applied_step"])
            st["lag_steps"] = lag
            # the lag ladder: healthy <-> lagging with hysteresis (a
            # follower hovering at the limit must not flap the
            # admission throttle every poll); a lost follower that
            # polls again rejoins through the same rungs
            if lag > self.lag_steps_limit:
                st["state"] = FOLLOWER_LAGGING
            elif (st["state"] != FOLLOWER_HEALTHY
                  and lag <= max(1, self.lag_steps_limit // 2)):
                st["state"] = FOLLOWER_HEALTHY
            elif st["state"] == FOLLOWER_LOST:
                st["state"] = (FOLLOWER_LAGGING
                               if lag > self.lag_steps_limit // 2
                               else FOLLOWER_HEALTHY)

    def _refresh_follower_states(self, now: float) -> None:
        """Lock must be held: a follower that stopped polling for the
        TTL is ``lost`` — it no longer counts toward the admission
        throttle (a dead host must not freeze admission forever)."""
        for st in self._followers.values():
            if now - st.get("last_poll", 0.0) > self.follower_ttl:
                st["state"] = FOLLOWER_LOST

    def _prune_followers(self, now: float) -> None:
        """Lock must be held: evict long-lost followers to make room."""
        self._refresh_follower_states(now)
        for fid in [
            fid for fid, st in self._followers.items()
            if st["state"] == FOLLOWER_LOST
            and now - st.get("last_poll", 0.0) > 4 * self.follower_ttl
        ]:
            del self._followers[fid]

    def _lag_throttle_active(self) -> bool:
        """True while any live follower is lagging: the leader stops
        admitting new prefills (budget pinned to 0, the PR 8 budget
        discipline) so decode-only steps let the follower drain the
        ring, instead of the ring overflowing into a fatal resync."""
        with self._followers_mu:
            self._refresh_follower_states(time.monotonic())
            return any(
                st["state"] == FOLLOWER_LAGGING
                for st in self._followers.values()
            )

    def follower_health(self) -> dict:
        now = time.monotonic()
        with self._followers_mu:
            self._refresh_follower_states(now)
            out = {}
            for fid, st in self._followers.items():
                doc = {k: v for k, v in st.items()
                       if not k.startswith("_")}
                doc["registered_ago"] = round(
                    now - st.get("_registered", now), 3
                )
                doc["last_poll_ago"] = round(
                    now - st.get("last_poll", now), 3
                )
                doc.pop("last_poll", None)
                out[fid] = doc
            return out

    def mh_stats(self) -> dict:
        """Leader-side mesh health: plan-stream counters + the
        per-follower registry + checkpoint/takeover accounting.  Duck-
        typed by EngineLoop.stats() and collect_mh_metrics()."""
        followers = self.follower_health()
        states = {s: 0 for s in FOLLOWER_STATES}
        for st in followers.values():
            states[st["state"]] = states.get(st["state"], 0) + 1
        cs = self.checkpoint_store
        return {
            "role": "leader",
            "plans_published": self.plans_published,
            "plan_bytes_total": self.plan_bytes_total,
            "plan_bytes_max": self.plan_bytes_max,
            "last_plan_idx": self._last_plan_idx,
            "last_seq": self.journal._next - 1,
            "followers": followers,
            "follower_states": states,
            "followers_dropped": self.followers_dropped,
            "lag_steps_limit": self.lag_steps_limit,
            "throttled_steps": self.throttled_steps,
            "takeovers": self.takeovers,
            "takeover_ms": round(self.takeover_ms, 3),
            "checkpoints_captured": self.checkpoints_captured,
            "checkpoint_errors": self.checkpoint_errors,
            "checkpoint_seconds": self.checkpoint_seconds,
            "checkpoint_store": cs.stats() if cs is not None else None,
        }

    # -- leader-state checkpointing (ISSUE 17: failover) --------------------
    def checkpoint_due(self) -> bool:
        """Cheap gate the engine loop polls each iteration; the real
        capture is fenced behind a pipeline reconcile by the caller."""
        if self.checkpoint_store is None or self.checkpoint_seconds <= 0:
            return False
        return (time.monotonic() - self._ckpt_last
                >= self.checkpoint_seconds)

    def checkpoint_tick(self, sched=None) -> None:
        """Capture host-side queue state at a quiescent step boundary
        (engine thread, no step in flight — the caller reconciled) and
        queue it for the background filestore writer.  Capture is
        host-state only (waiting-queue wire docs + PARKED request
        snapshots from the host pool — no device gathers), so the step
        cadence pays dict-building, not disk."""
        if not self.checkpoint_due():
            return
        self._ckpt_last = time.monotonic()
        t0 = self._ckpt_last
        if sched is not None:
            self._ckpt_sched = export_sched_state(sched)
        try:
            state = self._capture_state()
        except Exception:  # noqa: BLE001 — checkpointing must not kill steps
            self.checkpoint_errors += 1
            log.exception("leader checkpoint capture failed")
            return
        if state is None:
            return
        self.checkpoints_captured += 1
        self.checkpoint_store.save_async(self.name, state)
        # capture cost on the step cadence is part of the plan-plane
        # timeline (write-out is async; this span is the capture only)
        self._trace.record(
            self.plan_trace_id, "mh checkpoint", t0, time.monotonic(),
            plane="engine", plan_idx=self._last_plan_idx,
            snapshots=len(state.get("snapshots", ())),
        )

    def _capture_state(self) -> Optional[dict]:
        """Everything a standby needs to continue the leader's host
        decisions: the waiting queue as wire docs, parked/preempted
        requests as full PR 11 snapshots, WFQ virtual service, prefill
        budget, spec EMAs, and the plan index + digest chain head that
        anchor the handoff verification."""
        from helix_tpu.serving.migration import snapshot_to_wire

        eng = self.engine
        with self._mu:
            if self._dispatch_steps:
                return None   # step in flight: not a plan boundary
            snaps = []
            for st in list(getattr(eng, "preempted", [])):
                rid = st.req.id
                try:
                    snap = eng.export_request(rid)
                except Exception:  # noqa: BLE001 — skip one, keep the rest
                    log.exception("checkpoint export failed for %s", rid)
                    snap = None
                if snap is not None:
                    snaps.append(snapshot_to_wire(snap))
            waiting = []
            for r in list(eng.waiting):
                try:
                    doc = request_to_wire(r)
                except ValueError:
                    continue   # VL cannot ride the wire
                waiting.append(doc)
            return {
                "version": CHECKPOINT_VERSION,
                "model": self.name,
                "plan_idx": self._last_plan_idx,
                "seq": self.journal._next - 1,
                "step_counter": self._step_counter,
                "digest": self._digest.hex(),
                "digest_step": self._digest_step,
                "fold_next": self._fold_next,
                "digest_reset_pending": self._digest_reset_pending,
                "pending_emissions": {
                    str(k): [[rid, int(t)] for rid, t in v]
                    for k, v in self._emissions.items()
                },
                "done_steps": sorted(self._done_steps),
                "aborts_after_plan": {
                    str(k): sorted(v)
                    for k, v in self._aborts_after_plan.items()
                },
                "active_ids": [
                    r.id for r in eng.slots if r is not None
                ],
                "snapshots": snaps,
                "waiting": waiting,
                "budget": eng.prefill_budget,
                "sched": self._ckpt_sched,
                "spec": export_spec_state(eng),
                "adapters": sorted(
                    getattr(eng, "resident_adapters", lambda: [])()
                ) if hasattr(eng, "resident_adapters") else [],
            }

    # -- the step plan ------------------------------------------------------
    def step_dispatch(self):
        eng = self.engine
        throttled = self._followers and self._lag_throttle_active()
        if throttled:
            # pin the prefill budget to 0 for THIS dispatch: no new
            # admissions, decode-only — the plan carries budget=0 so
            # followers see the same decision, and the loop's scheduler
            # re-derives its own budget next pass once the lagging
            # follower catches up
            saved_budget = eng.prefill_budget
            eng.prefill_budget = 0
            self.throttled_steps += 1
        try:
            return self._step_dispatch_inner(eng)
        finally:
            if throttled:
                eng.prefill_budget = saved_budget

    def _step_dispatch_inner(self, eng):
        t0 = time.monotonic()
        with self._mu:
            carry_admits, self._carry_admits = self._carry_admits, []
            carry_resumes, self._carry_resumes = self._carry_resumes, []
            carry_ems, self._carry_emissions = self._carry_emissions, []
            step_idx = self._step_counter
            self._step_counter += 1
            rec = PlanRecorder()
            eng._plan_recorder = rec
            try:
                emitted, pend = eng.step_dispatch()
            except Exception:
                # dispatch failed part-way: admissions recorded before
                # the failure already mutated engine state and MUST
                # still reach followers — re-carry them into the retry's
                # plan, reuse the index, and restart the digest chain
                # (emission attribution across the failure is not
                # reconstructible)
                self._carry_admits = carry_admits + rec.admits
                self._carry_resumes = carry_resumes + rec.resumes
                self._carry_emissions = carry_ems
                self._step_counter = step_idx
                self._reset_digest_chain()
                raise
            finally:
                eng._plan_recorder = None
            admits = carry_admits + rec.admits
            resumes = carry_resumes + rec.resumes
            self._advance_digest(step_idx)
            record = {
                "v": WIRE_VERSION,
                "kind": "plan",
                "step": step_idx,
                "admits": admits,
                "resumes": resumes,
                "budget": rec.budget,
                "queue_blocked": rec.queue_blocked,
                "drafts": rec.drafts,
                "digest_step": self._digest_step,
                "digest": (self._digest.hex()
                           if self._digest_step is not None else None),
            }
            if self._digest_reset_pending:
                record["digest_reset"] = True
                self._digest_reset_pending = False
            self.journal.publish(record)
            self._last_plan_idx = step_idx
            self.plans_published += 1
            nbytes = len(json.dumps(record, separators=(",", ":")))
            self.plan_bytes_total += nbytes
            self.plan_bytes_max = max(self.plan_bytes_max, nbytes)
            # plan-plane span (ISSUE 18): dispatch through publish,
            # keyed by the plan seq so the follower's apply span for
            # the same step correlates across hosts
            self._trace.record(
                self.plan_trace_id, "mh plan publish", t0,
                time.monotonic(), plane="engine", step=step_idx,
                seq=self.journal._next - 1, bytes=nbytes,
                admits=len(admits),
            )
            ems = carry_ems + [(r.id, int(t)) for r, t in emitted]
            self._emissions[step_idx] = ems
            if pend is None:
                self._done_steps.add(step_idx)
            else:
                self._dispatch_steps[id(pend)] = step_idx
                self._plan_content[step_idx] = (admits, resumes)
            return emitted, pend

    def step_complete(self, pend, emitted=None):
        base = len(emitted) if emitted is not None else 0
        out = self.engine.step_complete(pend, emitted)
        with self._mu:
            idx = self._dispatch_steps.pop(id(pend), None)
            if idx is not None:
                self._emissions.setdefault(idx, []).extend(
                    (r.id, int(t)) for r, t in out[base:]
                )
                self._done_steps.add(idx)
                self._plan_content.pop(idx, None)
        return out

    def step(self):
        emitted, pend = self.step_dispatch()
        if pend is None:
            return emitted
        try:
            return self.step_complete(pend, emitted)
        except Exception:
            self.discard_pending(pend)
            raise

    def discard_pending(self, pend) -> None:
        self.engine.discard_pending(pend)
        with self._mu:
            idx = self._dispatch_steps.pop(id(pend), None)
            if idx is None:
                return
            # the published plan never ran to completion on the leader.
            # Publish a discard marker so a replaying/rejoining follower
            # skips the dead plan; its host effects (admissions and
            # resumes survive the positional rollback) are re-carried
            # into the retry's plan.  A live follower that already
            # executed the plan treats the marker as lost lockstep and
            # restarts — on a real cross-host mesh the failed collective
            # has desynced the slice anyway, so the restart ladder is
            # the honest recovery path.
            admits, resumes = self._plan_content.pop(idx)
            self._carry_admits = admits + self._carry_admits
            self._carry_resumes = resumes + self._carry_resumes
            self._carry_emissions = (
                self._emissions.pop(idx, []) + self._carry_emissions
            )
            self._done_steps.discard(idx)
            self.journal.publish(
                {"v": WIRE_VERSION, "kind": "discard", "step": idx}
            )
            self._reset_digest_chain()

    def _reset_digest_chain(self) -> None:
        self._digest = _DIGEST_SEED
        self._digest_step = None
        self._digest_reset_pending = True
        self._emissions.clear()
        self._done_steps.clear()
        self._aborts_after_plan.clear()
        self._fold_next = self._step_counter

    def _advance_digest(self, plan_idx: int) -> None:
        # digest(M) folds step M's emissions minus requests aborted in
        # the stream window between plan M and plan M+1: those aborts
        # race step M's completion on the leader (the engine skips a
        # freed slot's emission at reconcile) but land post-step on
        # followers, so both sides exclude them.  Folding M therefore
        # waits until plan M+1 is being published, when the window has
        # closed.
        while self._fold_next < plan_idx:
            m = self._fold_next
            if m not in self._done_steps:
                break
            excl = self._aborts_after_plan.pop(m, set())
            ems = self._emissions.pop(m, [])
            self._digest = _fold_digest(self._digest, m, ems, excl)
            self._digest_step = m
            self._done_steps.discard(m)
            self._fold_next += 1

    # -- passthrough --------------------------------------------------------
    def __getattr__(self, name):
        return getattr(self.engine, name)


class FollowerLoop:
    """Drives this host's engine replica from the leader's plan feed.

    Recovery posture (round-3 verdict weak #7 — the failure paths need
    drills, not just detection):

    - **Follower killed mid-stream**: start a NEW FollowerLoop with a
      fresh engine replica and replay from seq 0 — as long as the ring
      still retains the journal head, replay reconstructs bit-identical
      engine state (``test_multihost_serving.TestFailureDrills``).  The
      engine is deterministic given the plan sequence, so rejoining is
      a pure function of the ring.
    - **Fell off the ring / leader restarted / divergence detected**:
      fatal for lockstep.  The loop stops, ``error`` carries an
      operator-actionable message, and ``on_lost_lockstep(error)`` fires
      so the node agent can surface it (restart the serving process; it
      will resync by replaying the ring, or from the profile re-apply
      if the ring head is gone).
    - **Transient feed errors** retry with capped exponential backoff +
      jitter (``HELIX_MH_BACKOFF_BASE``/``HELIX_MH_BACKOFF_CAP``);
      counters are surfaced in :meth:`stats`.
    """

    def __init__(self, engine, feed, poll_timeout: float = 5.0,
                 on_lost_lockstep=None, name: str = "",
                 follower_id: str = "", standby: Optional[bool] = None,
                 checkpoint_store: Optional[CheckpointStore] = None,
                 on_leader_lost=None):
        self.engine = engine
        self.feed = feed                  # .read_since(seq, timeout)
        self.poll_timeout = poll_timeout
        self.name = name                  # model (fault keying, ckpt refs)
        self.follower_id = follower_id or (
            os.environ.get("HELIX_MH_FOLLOWER_ID", "")
            or f"follower-{os.getpid():x}"
        )
        if standby is None:
            standby = (os.environ.get("HELIX_MH_STANDBY", "")
                       .strip().lower() in ("1", "true", "yes", "on"))
        self.standby = bool(standby)
        self.checkpoint_store = checkpoint_store
        self.applied_seq = 0
        self.steps = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[str] = None
        self.on_lost_lockstep = on_lost_lockstep
        # standby auto-promotion trigger: after this many CONSECUTIVE
        # transient feed failures (the leader host is gone, not just a
        # blip) a standby stops retrying and fires on_leader_lost so
        # the node agent can promote it (0 = never self-trigger)
        self.on_leader_lost = on_leader_lost
        self.promote_after = int(
            os.environ.get("HELIX_MH_PROMOTE_AFTER", "0") or 0
        )
        self.digest_mode = (
            os.environ.get("HELIX_MH_DIGEST", "strict").strip().lower()
            or "strict"
        )
        self.backoff_base = float(
            os.environ.get("HELIX_MH_BACKOFF_BASE", "0.05") or 0.05
        )
        self.backoff_cap = float(
            os.environ.get("HELIX_MH_BACKOFF_CAP", "5.0") or 5.0
        )
        self._skip: set = set()            # plan idxs discarded by the leader
        self._applied_step = -1
        self._prev = None                  # (step idx, emissions)
        # plan idx -> rids aborted by ops records seen after that plan;
        # mirrors the leader's digest-exclusion window by stream position
        self._aborts_after_plan: dict = {}
        self._digest = _DIGEST_SEED
        self._digest_by_step: collections.OrderedDict = (
            collections.OrderedDict()
        )
        self._last_folded_step: Optional[int] = None
        # fresh-bootstrap digest adoption (see _fold_and_check): after
        # joining via a handoff checkpoint we track the leader's chain
        # verbatim until it catches up to our own first executed step
        self._adopt_digest = False
        # rids aborted via ops records (bounded): a takeover must not
        # resurrect them from a pre-abort checkpoint
        self._ops_aborted: collections.OrderedDict = (
            collections.OrderedDict()
        )
        # counters (stats())
        self.plans_applied = 0
        self.plans_skipped = 0
        self.feed_errors = 0
        self.backoff_seconds_total = 0.0
        self.digest_checks = 0
        self.digest_mismatches = 0
        self.records_duplicate = 0
        self.records_gap = 0
        self.handoffs = 0
        self.resync_reason = ""
        self.apply_ms = 0.0                # EMA of per-plan apply wall
        # plan-plane tracing (ISSUE 18): apply/digest spans land under
        # the mesh's shared plan trace id, keyed by plan step/seq so
        # they correlate with the leader's publish spans after
        # federation stitches both hosts on the cp
        self._trace = obs_trace.default_store()
        self.plan_trace_id = plan_trace_id(name)
        # in-process feeds register our health with the leader the way
        # HTTPFeed does via query params
        if hasattr(feed, "bind_follower"):
            feed.bind_follower(self)

    # -- plan application ---------------------------------------------------
    def apply(self, record: dict) -> None:
        v = record.get("v")
        if v != WIRE_VERSION:
            raise WireVersionError(
                f"plan record version {v!r} (this host speaks "
                f"{WIRE_VERSION}) — upgrade the leader and followers "
                "together"
            )
        if record.get("kind") == "resync_required":
            reason = record.get("reason", "")
            self.resync_reason = reason
            raise ResyncRequired(
                record.get("error")
                or f"leader requires resync ({reason})",
                reason=reason,
            )
        if record.get("kind") == "handoff":
            self._apply_handoff(record)
            return
        if record.get("kind") == "discard":
            self._handle_discard(record)
            self.applied_seq = record["seq"]
            return
        if record.get("kind") == "ops":
            self._apply_ops(record)
            self.applied_seq = record["seq"]
            return
        step_idx = record["step"]
        if step_idx in self._skip:
            # the leader discarded this plan before completing it
            self._skip.discard(step_idx)
            self.plans_skipped += 1
            self.applied_seq = record["seq"]
            return
        if step_idx <= self._applied_step:
            # a plan we already executed arriving again is not a
            # harmless duplicate (seq dedup upstream catches those):
            # the stream itself went backwards — lockstep is gone
            raise DivergenceError(
                f"plan {step_idx} arrived again (this replica already "
                f"applied through step {self._applied_step})"
            )
        t0 = time.monotonic()
        self._fold_and_check(record)
        eng = self.engine
        cached = {}
        for doc in record.get("admits", []):
            req = request_from_wire(doc)
            if req.adapter and hasattr(eng, "ensure_adapter_resident"):
                if not eng.ensure_adapter_resident(req.adapter):
                    raise DivergenceError(
                        f"plan {step_idx}: adapter {req.adapter!r} for "
                        f"{req.id} is not stageable on this replica"
                    )
            cached[req.id] = int(doc.get("cached_tokens", 0))
            eng.add_request(req)
        if record.get("resumes") and hasattr(eng, "ensure_adapter_resident"):
            want = set(record["resumes"])
            for st in list(getattr(eng, "preempted", [])):
                if st.req.id in want and st.req.adapter:
                    eng.ensure_adapter_resident(st.req.adapter)
        drive = PlanDrive(
            budget=record.get("budget"),
            queue_blocked=record.get("queue_blocked", False),
            drafts=[(int(s), [int(t) for t in toks])
                    for s, toks in record.get("drafts", [])],
            resumes=list(record.get("resumes", [])),
            cached_tokens=cached,
        )
        eng._plan_drive = drive
        try:
            emitted = eng.step()
        finally:
            eng._plan_drive = None
        if eng.waiting:
            raise DivergenceError(
                f"plan {step_idx}: {len(eng.waiting)} admitted requests "
                "left unclaimed after the step — replica resources do "
                "not match the leader's"
            )
        if drive.resumes:
            raise DivergenceError(
                f"plan {step_idx}: resumes not applied: {drive.resumes}"
            )
        self._prev = (step_idx, [(r.id, int(t)) for r, t in emitted])
        self._applied_step = step_idx
        self.steps += 1
        self.plans_applied += 1
        self.applied_seq = record["seq"]
        dt_ms = (time.monotonic() - t0) * 1000.0
        self.apply_ms = (dt_ms if self.apply_ms == 0.0
                         else 0.8 * self.apply_ms + 0.2 * dt_ms)
        # the follower half of the plan-plane timeline (ISSUE 18):
        # same trace id and step/seq as the leader's publish span
        self._trace.record(
            self.plan_trace_id, "mh plan apply", t0, time.monotonic(),
            plane="engine", step=step_idx, seq=record["seq"],
            follower=self.follower_id,
        )

    def _apply_ops(self, record: dict) -> None:
        # ops records sit in the stream exactly where the leader's
        # engine saw the abort/preempt relative to the surrounding
        # plans, so applying them in stream order keeps the replica's
        # slot/page state in step
        eng = self.engine
        raw_traces = record.get("traces")
        op_traces = raw_traces if isinstance(raw_traces, dict) else {}
        for op in record.get("ops", []):
            kind, rid = op[0], op[1]
            t0 = time.monotonic()
            if kind == "abort":
                eng.abort(rid)
                self._aborts_after_plan.setdefault(
                    self._applied_step, set()
                ).add(rid)
                # remember the abort (bounded): a later takeover must
                # not resurrect this request from an older checkpoint
                self._ops_aborted[rid] = True
                while len(self._ops_aborted) > 65536:
                    self._ops_aborted.popitem(last=False)
            elif kind == "preempt":
                if not eng.preempt(rid):
                    raise DivergenceError(
                        f"ops after step {self._applied_step}: preempt "
                        f"of {rid} failed on this replica (request "
                        "unknown or not swappable)"
                    )
            else:
                raise DivergenceError(
                    f"ops after step {self._applied_step}: unknown op "
                    f"{kind!r}"
                )
            # under the REQUEST's trace id (carried by the op record,
            # ISSUE 18 bugfix): a cp-initiated abort now shows its
            # follower-side application on the same stitched timeline
            tid = op_traces.get(rid, "")
            if obs_trace.is_trace_id(tid):
                self._trace.record(
                    tid, "mh op apply", t0, time.monotonic(),
                    plane="engine", op=kind, request_id=rid,
                    follower=self.follower_id,
                )

    def _handle_discard(self, record: dict) -> None:
        target = record["step"]
        self._skip.discard(target)
        if target <= self._applied_step:
            raise DivergenceError(
                f"this replica already executed step {target} that the "
                "leader discarded after a step failure"
            )
        # the plan was skipped (or predates our join): restart the
        # digest chain in step with the leader's reset
        self._prev = None
        self._digest = _DIGEST_SEED
        self._aborts_after_plan.clear()
        self._last_folded_step = None

    # -- takeover handoff (ISSUE 17) ----------------------------------------
    def _apply_handoff(self, record: dict) -> None:
        """A new leader took over at plan ``plan_idx``.  Three rungs:

        - **seamless cross-over** — this replica is at EXACTLY the
          boundary and its digest chain matches the record's head: park
          every active request (slot order — the same boundary parking
          the promoted leader did), keep going.  Zero lost state.
        - **fresh bootstrap** — this replica has executed nothing:
          import the referenced checkpoint (validated before any
          mutation) and join at the boundary.
        - anything else is typed ``resync_required``: restart fresh
          and take the bootstrap rung — the degrade ladder, never a
          silent divergence."""
        plan_idx = int(record["plan_idx"])
        fresh = self._applied_step < 0 and self.plans_applied == 0
        if fresh:
            self._bootstrap_from_handoff(record)
        elif self._applied_step == plan_idx:
            # verify the chained digest ACROSS the handoff before any
            # mutation: the new leader adopted the standby's chain; if
            # ours disagrees we were already diverged from the old
            # stream and must not cross over
            ds = record.get("digest_step")
            want = record.get("digest")
            if ds is not None and want:
                have = self._digest_by_step.get(ds)
                self.digest_checks += 1
                if have is not None and have != want:
                    self.digest_mismatches += 1
                    msg = (f"handoff digest mismatch at step {ds}: new "
                           f"leader {want}, replica {have}")
                    if self.digest_mode == "strict":
                        raise DivergenceError(msg)
                    log.warning("%s", msg)
            self._preempt_all_active()
        else:
            self.resync_reason = RESYNC_HANDOFF_MISMATCH
            raise ResyncRequired(
                f"leader handoff at step {plan_idx} but this replica "
                f"is at step {self._applied_step} — "
                + RESYNC_ACTIONS[RESYNC_HANDOFF_MISMATCH],
                reason=RESYNC_HANDOFF_MISMATCH,
            )
        self.handoffs += 1
        self.applied_seq = record["seq"]

    def _preempt_all_active(self) -> None:
        """Park every slot-active request in slot order: the promoted
        leader did exactly this at the boundary, so replica slot/page
        state matches and the resumes the new leader schedules replay
        deterministically on both sides."""
        eng = self.engine
        for req in list(eng.slots):
            if req is None:
                continue
            if not eng.preempt(req.id):
                raise DivergenceError(
                    f"handoff: cannot park active request {req.id} on "
                    "this replica (leader failover needs the host KV "
                    "tier — host_pool_bytes > 0 — on every host)"
                )

    def _bootstrap_from_handoff(self, record: dict) -> None:
        """Fresh replica joining a post-takeover stream: rebuild engine
        state from the handoff's checkpoint.  All snapshots decode and
        checksum-validate BEFORE the first import touches the
        allocator; a failure leaves this (empty) replica restartable
        with a typed reason."""
        ref = record.get("ckpt")
        if not ref or self.checkpoint_store is None:
            self.resync_reason = RESYNC_CHECKPOINT_REJECTED
            raise ResyncRequired(
                "handoff carries no loadable checkpoint (set "
                "HELIX_MH_CHECKPOINT_DIR to the shared filestore on "
                "every host) — "
                + RESYNC_ACTIONS[RESYNC_CHECKPOINT_REJECTED],
                reason=RESYNC_CHECKPOINT_REJECTED,
            )
        from helix_tpu.serving.migration import wire_to_snapshot

        try:
            ckpt = self.checkpoint_store.load(ref)
            # decode + meta-checksum EVERY snapshot before importing
            # any (import_request re-verifies page checksums before
            # its own allocator mutation)
            snaps = [wire_to_snapshot(doc)
                     for doc in ckpt.get("snapshots", [])]
        except Exception as e:  # noqa: BLE001 — typed reject, not a crash
            self.resync_reason = RESYNC_CHECKPOINT_REJECTED
            raise ResyncRequired(
                f"handoff checkpoint {ref!r} rejected: {e} — "
                + RESYNC_ACTIONS[RESYNC_CHECKPOINT_REJECTED],
                reason=RESYNC_CHECKPOINT_REJECTED,
            )
        eng = self.engine
        for snap in snaps:
            eng.import_request(snap)   # parks KV-bearing snapshots
        # waiting-queue docs are NOT imported: the new leader holds the
        # queue and will admit them through future plan records
        restore_spec_state(eng, ckpt.get("spec"))
        self._applied_step = int(record["plan_idx"])
        self._prev = None
        self._adopt_digest = True

    def _fold_and_check(self, record: dict) -> None:
        if record.get("digest_reset"):
            self._prev = None
            self._digest = _DIGEST_SEED
            self._aborts_after_plan.clear()
            self._last_folded_step = None
            self._adopt_digest = False
        if self._adopt_digest:
            # fresh bootstrap from a handoff checkpoint: the steps the
            # leader is still folding digests for ran before we joined,
            # so we ADOPT its published chain verbatim until it reaches
            # our own first executed step — from there normal folding
            # takes over and mismatches are detectable again
            ds = record.get("digest_step")
            want = record.get("digest")
            if ds is not None and want:
                self._digest = bytes.fromhex(want)
                self._digest_by_step[ds] = want
                self._last_folded_step = ds
                if self._prev is not None and self._prev[0] <= ds:
                    self._prev = None
                for k in [k for k in self._aborts_after_plan
                          if k <= ds]:
                    self._aborts_after_plan.pop(k, None)
                if (self._prev is not None
                        and self._prev[0] == ds + 1):
                    self._adopt_digest = False
            return
        if self._prev is not None:
            m, ems = self._prev
            excl = self._aborts_after_plan.pop(m, set())
            self._digest = _fold_digest(self._digest, m, ems, excl)
            self._digest_by_step[m] = self._digest.hex()
            self._last_folded_step = m
            self._prev = None
            while len(self._digest_by_step) > 128:
                self._digest_by_step.popitem(last=False)
        want = record.get("digest")
        ds = record.get("digest_step")
        if want is None or ds is None or self.digest_mode == "off":
            return
        have = self._digest_by_step.get(ds)
        if have is None:
            # we joined (or reset) after step ds; nothing to compare
            return
        self.digest_checks += 1
        t0 = time.monotonic()
        ok = have == want
        # digest verification is a first-class plan-plane event
        # (ISSUE 18): a mismatch must be findable on the stitched
        # timeline at the exact step where lockstep died
        self._trace.record(
            self.plan_trace_id, "mh digest verify", t0,
            time.monotonic(), plane="engine", step=ds,
            seq=record.get("seq", -1),
            outcome="ok" if ok else "mismatch",
            follower=self.follower_id,
        )
        if not ok:
            self.digest_mismatches += 1
            msg = (f"emission digest mismatch at step {ds}: leader "
                   f"{want}, replica {have}")
            if self.digest_mode == "strict":
                raise DivergenceError(msg)
            log.warning("%s", msg)

    # -- pump ----------------------------------------------------------------
    def _pump(self, records: list) -> int:
        """Apply one poll's batch under strict sequence discipline:
        records sort by seq (a reordering transport is repaired, not
        fatal), already-applied seqs skip idempotently (duplicates),
        and a GAP stops the batch — the missing record re-reads from
        the ring on the next poll.  This is what makes the plan-feed
        fault family (drop/duplicate/reorder) recoverable instead of a
        divergence."""
        records = _maybe_fault_records(self.name, records)
        records = sorted(records, key=lambda r: r.get("seq", 0))
        # prescan for discard markers so a replayed/batched feed skips
        # dead plans instead of executing steps the leader rolled back
        for r in records:
            if r.get("kind") == "discard":
                self._skip.add(r.get("step"))
        applied = 0
        for r in records:
            kind = r.get("kind")
            if kind == "handoff":
                # epoch-boundary record: carries its own seq semantics,
                # but a re-delivered handoff we already crossed must
                # still dedup (a second preempt-all would diverge)
                if 0 < r.get("seq", 0) <= self.applied_seq:
                    self.records_duplicate += 1
                    continue
                self.apply(r)
                applied += 1
                continue
            if kind == "resync_required":
                # typed ladder record: seq mirrors OUR position, so it
                # bypasses the gap/dup discipline by design
                self.apply(r)
                applied += 1
                continue
            seq = r.get("seq", 0)
            if seq <= self.applied_seq:
                self.records_duplicate += 1
                continue
            if seq > self.applied_seq + 1:
                self.records_gap += 1
                break
            self.apply(r)
            applied += 1
        return applied

    def run_once(self, timeout: Optional[float] = None) -> int:
        records = self.feed.read_since(
            self.applied_seq,
            timeout=self.poll_timeout if timeout is None else timeout,
        )
        return self._pump(records)

    def _fail(self, msg: str) -> None:
        action = RESYNC_ACTIONS.get(
            self.resync_reason,
            "restart this follower with a fresh engine replica (it "
            "replays the leader's ring from the retained head on "
            "start); if the ring no longer retains it, re-apply the "
            "serving profile on every host",
        )
        self.error = f"{msg} — lockstep lost; {action}"
        log.error("follower lost lockstep: %s", self.error)
        if self.on_lost_lockstep is not None:
            try:
                self.on_lost_lockstep(self.error)
            except Exception:  # noqa: BLE001 — operator hook
                log.exception("on_lost_lockstep hook failed")

    def start(self) -> "FollowerLoop":
        def run():
            attempt = 0
            while not self._stop.is_set():
                try:
                    records = self.feed.read_since(
                        self.applied_seq, timeout=self.poll_timeout
                    )
                except LagError as e:
                    # falling off the ring (or a leader restart) is
                    # fatal for lockstep: the process must restart and
                    # resync from the ring head (or a profile re-apply
                    # when the head is gone)
                    self._fail(str(e))
                    return
                except Exception as e:  # noqa: BLE001 — transient feed
                    attempt += 1
                    self.feed_errors += 1
                    if (self.standby and self.promote_after > 0
                            and attempt >= self.promote_after):
                        # the leader host is GONE, not blinking: a
                        # standby stops retrying and hands control to
                        # the promotion hook (node agent / operator)
                        self.error = (
                            f"leader unreachable after {attempt} "
                            f"consecutive feed failures ({e}) — "
                            "standby ready for promotion"
                        )
                        log.error("%s", self.error)
                        if self.on_leader_lost is not None:
                            try:
                                self.on_leader_lost(self)
                            except Exception:  # noqa: BLE001 — hook
                                log.exception(
                                    "on_leader_lost hook failed"
                                )
                        return
                    delay = min(
                        self.backoff_cap,
                        self.backoff_base * (2 ** min(attempt, 16)),
                    ) * (0.5 + random.random() / 2.0)
                    self.backoff_seconds_total += delay
                    log.warning(
                        "follower feed error (attempt %d, retry in "
                        "%.2fs): %s", attempt, delay, e,
                    )
                    self._stop.wait(delay)
                    continue
                attempt = 0
                try:
                    self._pump(records)
                except (LagError, WireVersionError, DivergenceError) as e:
                    self._fail(str(e))
                    return
                except Exception as e:  # noqa: BLE001 — half-applied plan
                    # an engine error mid-plan cannot be retried (the
                    # plan may be half-applied) — treat as divergence
                    self._fail(f"plan apply failed: {e!r}")
                    return

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def drain_feed(self, timeout: float = 0.25) -> int:
        """Consume whatever tail the feed still serves without blocking
        on new publishes (the promote path: a leader that died AFTER
        publishing records the standby has not applied yet must not
        lose them — this is the CommandLog-tail replay that carries the
        standby to the digest-verified boundary)."""
        total = 0
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                n = self.run_once(timeout=0.02)
            except Exception:  # noqa: BLE001 — feed is dying; tail over
                break
            if n == 0:
                break
            total += n
        return total

    def stats(self) -> dict:
        return {
            "follower_id": self.follower_id,
            "standby": self.standby,
            "applied_seq": self.applied_seq,
            "applied_step": self._applied_step,
            "steps": self.steps,
            "plans_applied": self.plans_applied,
            "plans_skipped": self.plans_skipped,
            "feed_errors": self.feed_errors,
            "backoff_seconds_total": round(self.backoff_seconds_total, 3),
            "digest_mode": self.digest_mode,
            "digest_checks": self.digest_checks,
            "digest_mismatches": self.digest_mismatches,
            "records_duplicate": self.records_duplicate,
            "records_gap": self.records_gap,
            "handoffs": self.handoffs,
            "resync_reason": self.resync_reason,
            "apply_ms": round(self.apply_ms, 3),
            "reconnects": getattr(self.feed, "reconnects", 0),
        }


class HTTPFeed:
    """Follower-side transport: long-poll the leader over DCN.

    Keeps a pooled ``requests.Session`` alive across polls (one TCP/TLS
    handshake per leader, not per long-poll); on a transport error the
    pool is dropped so the next poll reconnects cleanly, counted in
    ``reconnects``."""

    def __init__(self, leader_url: str, model: str):
        self.leader_url = leader_url.rstrip("/")
        self.model = model
        self._session = None
        self.reconnects = 0
        self._follower = None

    def bind_follower(self, follower) -> None:
        """FollowerLoop self-registration: every poll carries the
        follower's identity + health as query params so the leader's
        registry (PlanLeader.note_poll) sees N followers without a
        second control channel."""
        self._follower = follower

    def _sess(self):
        if self._session is None:
            import requests

            self._session = requests.Session()
        return self._session

    def read_since(self, since: int, timeout: float = 30.0) -> list:
        params = {
            "since": since, "timeout": timeout, "model": self.model,
        }
        f = self._follower
        if f is not None:
            params.update({
                "follower_id": f.follower_id,
                "applied_step": f._applied_step,
                "apply_ms": round(f.apply_ms, 3),
                "digest_checks": f.digest_checks,
                "digest_mismatches": f.digest_mismatches,
                "standby": int(f.standby),
            })
        try:
            resp = self._sess().get(
                f"{self.leader_url}/multihost/commands",
                params=params,
                timeout=timeout + 10,
            )
            doc = resp.json()
        except Exception:
            # drop the pooled connections; the next poll reconnects
            self.reconnects += 1
            sess, self._session = self._session, None
            if sess is not None:
                try:
                    sess.close()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
            raise
        if doc.get("lagged"):
            raise LagError(doc.get("error", "fell off the leader's ring"))
        return doc.get("records", [])


class LocalFeed:
    """In-process feed (tests, bench, chaos): reads the leader's ring
    directly AND registers the bound follower's health on every poll —
    the same contract HTTPFeed provides via query params over DCN, so
    the N-follower registry and lag ladder exercise without HTTP."""

    def __init__(self, leader: PlanLeader, follower_id: str = ""):
        self.leader = leader
        self.follower_id = follower_id
        self._follower = None
        self.reconnects = 0

    def bind_follower(self, follower) -> None:
        self._follower = follower
        if not self.follower_id:
            self.follower_id = follower.follower_id

    def retarget(self, leader: PlanLeader) -> None:
        """Point the feed at a NEW leader (post-takeover re-point)."""
        self.leader = leader
        self.reconnects += 1

    def read_since(self, since: int, timeout: float = 30.0) -> list:
        f = self._follower
        self.leader.note_poll(
            self.follower_id or "local", since,
            applied_step=f._applied_step if f is not None else None,
            apply_ms=f.apply_ms if f is not None else None,
            digest_checks=f.digest_checks if f is not None else None,
            digest_mismatches=(f.digest_mismatches
                               if f is not None else None),
            standby=f.standby if f is not None else False,
        )
        return self.leader.journal.read_since(since, timeout)


def _maybe_fault_records(model: str, records: list) -> list:
    """Plan-feed fault hook (testing/faults.py): deterministically
    drop / duplicate / delay / reorder records of one poll batch, keyed
    by model+step.  The seq discipline in FollowerLoop._pump is what
    makes these recoverable — which is exactly what the fault family
    exists to prove."""
    if not records:
        return records
    try:
        from helix_tpu.testing.faults import active
    except Exception:  # noqa: BLE001 — faults module optional
        return records
    inj = active()
    if inj is None:
        return records
    out = []
    reorder = False
    for r in records:
        act = inj.plan_feed_fault(
            model, r.get("step", r.get("seq", 0))
        )
        if act is None:
            out.append(r)
            continue
        action = act.get("action", "")
        if action == "drop":
            continue
        if action == "duplicate":
            out.extend([r, r])
        elif action == "delay":
            time.sleep(float(act.get("seconds", 0.05)))
            out.append(r)
        elif action == "reorder":
            reorder = True
            out.append(r)
        else:
            out.append(r)
    if reorder and len(out) > 1:
        out = list(reversed(out))
    return out


# ---------------------------------------------------------------------------
# leader failover (ISSUE 17)
# ---------------------------------------------------------------------------

def promote_follower(follower: FollowerLoop,
                     store: Optional[CheckpointStore] = None,
                     name: str = "",
                     journal_capacity: Optional[int] = None,
                     sched=None) -> PlanLeader:
    """Promote a live standby follower into the publishing leader.

    The digest-verified handoff, in order — every rung validates BEFORE
    it mutates, and every failure raises typed (DivergenceError /
    ResyncRequired / CheckpointError) leaving the operator on today's
    full-resync ladder, never worse:

    1. stop the pump thread and **drain the feed tail** — records the
       dead leader published that this standby has not applied yet
       replay now (the CommandLog-tail replay to the boundary);
    2. load the newest usable checkpoint and **verify its digest chain
       head against the standby's own chain** — a standby that would
       diverge refuses here, before any allocator mutation;
    3. **park every slot-active request in slot order** — the boundary
       every surviving peer can reproduce from the handoff record (and
       the reason failover requires the host KV tier);
    4. import checkpoint state the standby never saw (the waiting
       queue and parked requests admitted before the standby joined),
       skipping everything the replica already knows or saw aborted;
    5. build the PlanLeader with the **digest chain continued
       exactly** (same chain value, same pending fold window, same
       abort-exclusion windows) and the journal sequence continued
       (peers at the boundary poll straight across);
    6. write a **fresh checkpoint at the boundary** and publish a
       ``handoff`` record referencing it as the first record of the
       new epoch — fresh followers bootstrap from it, peers verify the
       chained digest across the handoff.
    """
    t0 = time.monotonic()
    name = name or follower.name
    store = store if store is not None else follower.checkpoint_store
    follower.stop()
    follower.drain_feed()
    eng = follower.engine
    ckpt = None
    if store is not None:
        try:
            _ref, ckpt = store.load_latest(name)
        except CheckpointError as e:
            if e.code != "checkpoint_missing":
                raise
            # no checkpoint yet (young leader): promote from live
            # replica state alone — the dead leader's waiting queue
            # and WFQ history are lost, which is exactly the pre-17
            # behavior for those requests
            log.warning(
                "promoting %s without a checkpoint: %s", name, e
            )
    boundary = follower._applied_step
    if ckpt is not None:
        ds = ckpt.get("digest_step")
        want = ckpt.get("digest")
        if ds is not None and want and want != _DIGEST_SEED.hex():
            have = follower._digest_by_step.get(ds)
            if have is not None and have != want:
                raise DivergenceError(
                    f"takeover refused: checkpoint digest at step {ds} "
                    f"is {want} but this standby's chain says {have} — "
                    "the standby diverged from the dead leader's "
                    "stream; re-apply the serving profile (full resync)"
                )
            if have is None and boundary < int(ckpt.get("plan_idx", -1)):
                raise ResyncRequired(
                    f"takeover refused: this standby is at step "
                    f"{boundary}, behind the checkpoint's plan "
                    f"{ckpt.get('plan_idx')} and the ring tail is "
                    "gone — "
                    + RESYNC_ACTIONS[RESYNC_RING_OVERFLOW],
                    reason=RESYNC_RING_OVERFLOW,
                )
    # ---- validation is done; mutation starts here ----
    for req in list(eng.slots):
        if req is not None and not eng.preempt(req.id):
            raise DivergenceError(
                f"takeover: cannot park active request {req.id} at the "
                "handoff boundary (leader failover needs the host KV "
                "tier — host_pool_bytes > 0)"
            )
    if ckpt is not None:
        from helix_tpu.serving.migration import wire_to_snapshot

        known = getattr(eng, "_requests", {})
        for doc in ckpt.get("snapshots", []):
            rid = doc.get("request_id", "")
            if rid in known or rid in follower._ops_aborted:
                continue   # replica state is newer — authoritative
            eng.import_request(wire_to_snapshot(doc))
        for doc in ckpt.get("waiting", []):
            rid = doc.get("id", "")
            if rid in known or rid in follower._ops_aborted:
                continue
            eng.add_request(request_from_wire(doc))
        if ckpt.get("budget") is not None:
            eng.prefill_budget = ckpt["budget"]
        restore_spec_state(eng, ckpt.get("spec"))
        if sched is not None:
            restore_sched_state(sched, ckpt.get("sched"))
    cap = journal_capacity or int(
        os.environ.get("HELIX_MH_RING", "4096") or 4096
    )
    journal = CommandLog(capacity=cap,
                         start_seq=follower.applied_seq + 1)
    leader = PlanLeader(eng, journal=journal, checkpoint_store=store,
                        name=name)
    # continue the digest chain EXACTLY where the replica's stands:
    # the first new plan folds the boundary step and surviving peers
    # verify the chain across the handoff
    leader._step_counter = boundary + 1
    leader._last_plan_idx = boundary
    leader._digest = follower._digest
    leader._digest_step = follower._last_folded_step
    if follower._prev is not None:
        pstep, ems = follower._prev
        leader._emissions[pstep] = list(ems)
        leader._done_steps.add(pstep)
        leader._fold_next = pstep
    else:
        leader._fold_next = boundary + 1
    leader._aborts_after_plan = {
        k: set(v) for k, v in follower._aborts_after_plan.items()
    }
    if ckpt is not None and sched is None:
        leader._ckpt_sched = ckpt.get("sched")
    leader.takeovers = 1
    ref = None
    if store is not None:
        state = leader._capture_state()
        if sched is not None:
            state["sched"] = export_sched_state(sched)
        ref, _n = store.save(name, state)   # durable BEFORE the handoff
    journal.publish({
        "v": WIRE_VERSION,
        "kind": "handoff",
        "plan_idx": boundary,
        "digest": (leader._digest.hex()
                   if leader._digest_step is not None else None),
        "digest_step": leader._digest_step,
        "ckpt": ref,
    })
    leader.takeover_ms = (time.monotonic() - t0) * 1000.0
    # the takeover itself is a plan-plane span (ISSUE 18): on the
    # stitched timeline the blackout reads as the gap between the dead
    # leader's last publish and this span, and this span's width is
    # the promotion cost
    leader._trace.record(
        leader.plan_trace_id, "mh promote follower", t0,
        time.monotonic(), plane="engine", boundary=boundary,
        follower=follower.follower_id,
        ckpt=ref or "(none)",
    )
    log.warning(
        "standby %s promoted to leader for %s at step %d in %.1f ms "
        "(checkpoint %s)", follower.follower_id, name or "<model>",
        boundary, leader.takeover_ms, ref,
    )
    return leader


def cold_start_leader(engine, store: CheckpointStore, name: str = "",
                      journal_capacity: Optional[int] = None) -> PlanLeader:
    """Last-resort failover rung: a FRESH process (no live replica
    state) becomes leader from the newest checkpoint alone.  Honest
    about its limits: steps the dead leader ran after the checkpoint
    are lost and will be re-decided, so delivery for requests active
    at the checkpoint degrades from exactly-once to at-least-once, and
    surviving followers past the checkpoint boundary get a typed
    resync instead of a seamless cross-over.  Use a live standby
    (promote_follower) when one exists."""
    t0 = time.monotonic()
    ref, ckpt = store.load_latest(name)   # typed CheckpointError if unusable
    from helix_tpu.serving.migration import wire_to_snapshot

    snaps = [wire_to_snapshot(d) for d in ckpt.get("snapshots", [])]
    for snap in snaps:                    # all validated above, pre-mutation
        engine.import_request(snap)
    for doc in ckpt.get("waiting", []):
        engine.add_request(request_from_wire(doc))
    if ckpt.get("budget") is not None:
        engine.prefill_budget = ckpt["budget"]
    restore_spec_state(engine, ckpt.get("spec"))
    boundary = int(ckpt.get("plan_idx", -1))
    cap = journal_capacity or int(
        os.environ.get("HELIX_MH_RING", "4096") or 4096
    )
    journal = CommandLog(capacity=cap,
                         start_seq=int(ckpt.get("seq", 0)) + 1)
    leader = PlanLeader(engine, journal=journal, checkpoint_store=store,
                        name=name)
    leader._step_counter = max(boundary + 1,
                               int(ckpt.get("step_counter", 0)))
    leader._last_plan_idx = boundary
    leader._digest = bytes.fromhex(
        ckpt.get("digest") or _DIGEST_SEED.hex()
    )
    leader._digest_step = ckpt.get("digest_step")
    leader._fold_next = int(ckpt.get("fold_next", boundary + 1))
    leader._digest_reset_pending = bool(
        ckpt.get("digest_reset_pending", False)
    )
    leader._emissions = {
        int(k): [(rid, int(t)) for rid, t in v]
        for k, v in (ckpt.get("pending_emissions") or {}).items()
    }
    leader._done_steps = set(ckpt.get("done_steps") or [])
    leader._aborts_after_plan = {
        int(k): set(v)
        for k, v in (ckpt.get("aborts_after_plan") or {}).items()
    }
    leader._ckpt_sched = ckpt.get("sched")
    leader.takeovers = 1
    journal.publish({
        "v": WIRE_VERSION,
        "kind": "handoff",
        "plan_idx": boundary,
        "digest": (leader._digest.hex()
                   if leader._digest_step is not None else None),
        "digest_step": leader._digest_step,
        "ckpt": ref,
    })
    leader.takeover_ms = (time.monotonic() - t0) * 1000.0
    log.warning(
        "cold-start leader for %s from checkpoint %s at step %d "
        "(at-least-once window: steps after the checkpoint were "
        "re-decided)", name or "<model>", ref, boundary,
    )
    return leader


# ---------------------------------------------------------------------------
# observability: the ONLY minting site for helix_mh_* series and the
# heartbeat mesh-health block (lint contract 12 fences both here)
# ---------------------------------------------------------------------------

def collect_mh_metrics(c, loop, labels: dict) -> None:
    """Scrape-time helix_mh_* family for a leader engine (bounded: one
    follower label per registry entry, and the registry itself is
    bounded by HELIX_MH_MAX_FOLLOWERS)."""
    eng = getattr(loop, "engine", None)
    ms = getattr(eng, "mh_stats", None)
    if not callable(ms):
        return
    st = ms()
    c.counter(
        "helix_mh_plans_published_total", st["plans_published"], labels,
        help="Step-plan records published by this leader",
    )
    c.counter(
        "helix_mh_plan_bytes_total", st["plan_bytes_total"], labels,
        help="Serialized bytes of all published step plans",
    )
    c.gauge(
        "helix_mh_last_plan_idx", st["last_plan_idx"], labels,
        help="Newest published plan index",
    )
    c.counter(
        "helix_mh_throttled_steps_total", st["throttled_steps"], labels,
        help="Dispatches with admission throttled for a lagging follower",
    )
    c.counter(
        "helix_mh_followers_dropped_total", st["followers_dropped"],
        labels,
        help="Follower registrations dropped at the registry bound",
    )
    c.counter(
        "helix_mh_takeovers_total", st["takeovers"], labels,
        help="Leader takeovers this process performed",
    )
    c.counter(
        "helix_mh_checkpoints_total", st["checkpoints_captured"], labels,
        help="Leader-state checkpoints captured",
    )
    c.counter(
        "helix_mh_checkpoint_errors_total", st["checkpoint_errors"],
        labels,
        help="Checkpoint captures that failed",
    )
    cs = st.get("checkpoint_store") or {}
    c.gauge(
        "helix_mh_checkpoint_bytes_last", cs.get("bytes_last", 0),
        labels, help="Size of the newest written checkpoint blob",
    )
    c.counter(
        "helix_mh_checkpoint_corrupt_total",
        cs.get("corrupt_rejected", 0), labels,
        help="Checkpoint blobs rejected by checksum/version validation",
    )
    for state, n in st["follower_states"].items():
        c.gauge(
            "helix_mh_followers", n, {**labels, "state": state},
            help="Registered followers by health state",
        )
    for fid, f in st["followers"].items():
        fl = {**labels, "follower": fid}
        c.gauge(
            "helix_mh_follower_lag_steps", f["lag_steps"], fl,
            help="Steps this follower trails the newest plan",
        )
        c.gauge(
            "helix_mh_follower_apply_seconds",
            f.get("apply_ms", 0.0) / 1000.0, fl,
            help="Follower-reported per-plan apply wall (EMA)",
        )
        c.counter(
            "helix_mh_follower_digest_mismatches_total",
            f.get("digest_mismatches", 0), fl,
            help="Digest mismatches this follower reported",
        )


def mh_heartbeat_block(models) -> dict:
    """Per-model mesh-health block for the node agent's heartbeat (the
    /v1/cluster/status source).  Leaders report the follower registry
    summary; followers/standbys report their applied position and any
    typed resync reason."""
    out = {}
    for m in models:
        f = getattr(m, "follower", None)
        if f is not None:
            st = f.stats()
            out[m.name] = {
                "role": "standby" if f.standby else "follower",
                "follower_id": st["follower_id"],
                "applied_seq": st["applied_seq"],
                "applied_step": st["applied_step"],
                "digest_mismatches": st["digest_mismatches"],
                "resync_reason": st["resync_reason"],
                "error": getattr(f, "error", None) or "",
            }
            continue
        loop = getattr(m, "loop", None)
        eng = getattr(loop, "engine", None)
        ms = getattr(eng, "mh_stats", None)
        if not callable(ms):
            continue
        st = ms()
        worst_lag = max(
            (fs["lag_steps"] for fs in st["followers"].values()),
            default=0,
        )
        out[m.name] = {
            "role": "leader",
            "last_plan_idx": st["last_plan_idx"],
            "followers": st["follower_states"],
            "worst_lag_steps": worst_lag,
            "throttled_steps": st["throttled_steps"],
            "takeovers": st["takeovers"],
            "checkpoints_captured": st["checkpoints_captured"],
        }
    return out


def validate_mh_block(raw) -> dict:
    """Control-plane-side sanitation of a heartbeat's mesh block: a
    runner-supplied dict, so entries clamp to the known schema with
    finite numbers and bounded counts — malformed blocks degrade to {}
    and never reject the heartbeat (the PR 4/7 hardening pattern)."""
    import math

    if not isinstance(raw, dict):
        return {}
    out = {}
    for model, doc in list(raw.items())[:32]:
        if not isinstance(model, str) or not isinstance(doc, dict):
            continue
        role = doc.get("role")
        if role not in ("leader", "follower", "standby"):
            continue
        ent = {"role": role}
        for key in ("last_plan_idx", "worst_lag_steps",
                    "throttled_steps", "takeovers",
                    "checkpoints_captured", "applied_seq",
                    "applied_step", "digest_mismatches"):
            v = doc.get(key)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            try:
                fv = float(v)
            except (OverflowError, ValueError):
                continue
            if math.isfinite(fv):
                ent[key] = int(fv)
        followers = doc.get("followers")
        if isinstance(followers, dict):
            ent["followers"] = {
                s: int(followers[s])
                for s in FOLLOWER_STATES
                if isinstance(followers.get(s), int)
                and not isinstance(followers.get(s), bool)
            }
        for key in ("follower_id", "resync_reason", "error"):
            v = doc.get(key)
            if isinstance(v, str):
                ent[key] = v[:256]
        out[model[:128]] = ent
    return out


# the old name survived one release; keep the alias so operator tooling
# importing LockstepLeader keeps working against the plan broadcast
LockstepLeader = PlanLeader
