"""Multi-host serving: plan-broadcast SPMD engines over a DCN feed.

SURVEY §2.2/§7 puts inter-slice DCN in the engine's court.  In JAX's
multi-controller model every process must issue the SAME jit calls in
the same order for collectives over a cross-host mesh to line up.
Serving has dynamic admission, so this module makes the call sequence
deterministic by construction — but unlike the original command-replay
journal (which made followers re-derive every host decision and
therefore pinned off every feature whose host state could drift), the
contract is now a **per-step plan broadcast**:

- the **leader** (process 0) takes HTTP traffic and runs the full host
  stack — admission, WFQ reorder, spec drafting, preemption-by-swap,
  prefix/filestore restoration, the async pipelined loop.  Its
  ``step_dispatch`` finalizes everything the device call needs; a
  ``PlanRecorder`` captures those decisions as *data* (admitted request
  docs with ``cached_tokens``, resume order, draft tokens, the prefill
  budget, the queue-pressure bit) and publishes ONE versioned
  ``StepPlan`` record per step; abort/preempt publish immediately as
  standalone ``ops`` records in arrival order;
- **followers** are pure device executors: ``FollowerLoop`` decodes a
  plan and drives the *same* engine step through a ``PlanDrive`` that
  pins every host decision to the leader's values.  No follower-side
  admission queue, scheduler, drafter, or clock participates — the
  follower's compiled step shapes are the leader's by construction.

Because plans pin decisions rather than forbidding them, the features
the old journal disabled are all live on meshes: spec decode (drafts
ride the plan), the adapter pool (followers stage residency before the
step), WFQ (budget + victim order are leader-decided data), preemption
(``ops`` records replay the swap in arrival order), the async pipeline
(plan N+1 publishes while device step N completes), and filestore
prefix hits (the plan carries ``cached_tokens``; point both hosts at
the same filestore dir and the drive verifies the restore matched).

Emission digests (rolling blake2s over per-step (request, token)
emissions, aborted requests excluded over a one-plan window to absorb
abort-arrival skew) let a follower detect silent divergence; the
``HELIX_MH_DIGEST`` knob picks strict/warn/off.

Transport is pluggable: in-process ``CommandLog`` (tests, and the ring
buffer the leader serves), or ``HTTPFeed`` (follower long-polls the
leader's ``/multihost/commands`` route over DCN with a pooled session).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import itertools
import json
import logging
import os
import random
import struct
import threading
import time
from typing import Optional

from helix_tpu.engine.engine import Request
from helix_tpu.engine.sampling import SamplingParams

log = logging.getLogger("helix.mh-serving")

#: Plan/request wire format version.  v1 was the command-replay journal
#: ({admits, aborts, step} records whose request docs dropped tenant /
#: sched_class / adapter / max_len); v2 is the step-plan broadcast.
#: Mixed-version clusters are rejected typed, never misparsed.
WIRE_VERSION = 2

_DIGEST_SEED = b"\x00" * 16


class LagError(RuntimeError):
    """Follower fell off the ring (or ahead of it — leader restart)."""


class WireVersionError(ValueError):
    """Record from a different wire version; upgrade hosts together."""


class DivergenceError(RuntimeError):
    """Replica state no longer matches the leader's plan — lockstep lost."""


class CommandLog:
    """Sequenced ring buffer with blocking reads (the leader's journal).

    The ring is a ``collections.deque``: overflow past capacity is an
    O(1) ``popleft`` per dropped record, not an O(n) list re-slice per
    publish (which made sustained publish throughput quadratic once the
    ring was full)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._records: collections.deque = collections.deque()
        self._first = 1
        self._next = 1
        self._cond = threading.Condition()

    def publish(self, record: dict) -> int:
        with self._cond:
            seq = self._next
            self._next += 1
            self._records.append({**record, "seq": seq})
            while len(self._records) > self.capacity:
                self._records.popleft()
                self._first += 1
            self._cond.notify_all()
            return seq

    def read_since(self, since: int, timeout: float = 30.0) -> list:
        """Records with seq > since; blocks up to timeout when none.
        Raises LagError when the follower fell off the ring."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if since + 1 < self._first:
                    raise LagError(
                        f"follower at seq {since} fell behind the ring "
                        f"(first retained: {self._first})"
                    )
                if since >= self._next:
                    # AHEAD of the journal: the leader restarted and its
                    # sequence reset — silent empty polls here would hang
                    # the whole cluster mid-collective; fail loudly so
                    # the follower restarts and resyncs
                    raise LagError(
                        f"follower at seq {since} is ahead of the "
                        f"journal (next: {self._next}) — leader restart?"
                    )
                skip = max(0, since + 1 - self._first)
                out = list(itertools.islice(self._records, skip, None))
                if out:
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)


def request_to_wire(req: Request) -> dict:
    if req.image_embeds is not None:
        raise ValueError(
            "multi-host serving covers text models (VL image embeds are "
            "device-resident and not broadcast)"
        )
    return {
        "v": WIRE_VERSION,
        "id": req.id,
        "prompt_tokens": list(req.prompt_tokens),
        "sampling": dataclasses.asdict(req.sampling),
        "stop_token_ids": list(req.stop_token_ids),
        "tenant": req.tenant,
        "sched_class": req.sched_class,
        "adapter": req.adapter,
        "max_len": req.max_len,
        "trace_id": req.trace_id,
    }


def request_from_wire(doc: dict) -> Request:
    v = doc.get("v")
    if v != WIRE_VERSION:
        raise WireVersionError(
            f"request wire record version {v!r} (this host speaks "
            f"{WIRE_VERSION}); v1 records dropped tenant/sched_class/"
            "adapter/max_len and are rejected rather than misparsed — "
            "upgrade the leader and followers together"
        )
    return Request(
        id=doc["id"],
        prompt_tokens=list(doc["prompt_tokens"]),
        sampling=SamplingParams(**doc["sampling"]),
        stop_token_ids=tuple(doc["stop_token_ids"]),
        tenant=doc["tenant"],
        sched_class=doc["sched_class"],
        adapter=doc["adapter"],
        max_len=doc["max_len"],
        trace_id=doc.get("trace_id", ""),
    )


class PlanRecorder:
    """Captures the leader engine's per-dispatch host decisions as data.

    The engine duck-types this via ``self._plan_recorder`` (set around
    ``step_dispatch`` by :class:`PlanLeader`): admission claims call
    ``note_admit`` after ``cached_tokens`` is final, resumes append the
    resumed request id, spec drafting stores the drafted tokens per
    slot, and the dispatch prologue stores the prefill budget and the
    queue-pressure bit that pins the decode window."""

    __slots__ = ("admits", "resumes", "drafts", "budget", "queue_blocked")

    def __init__(self):
        self.admits: list = []
        self.resumes: list = []
        self.drafts: list = []
        self.budget = None
        self.queue_blocked = False

    def note_admit(self, req: Request) -> None:
        doc = request_to_wire(req)
        doc["cached_tokens"] = int(req.cached_tokens)
        self.admits.append(doc)


class PlanDrive:
    """Pins a follower engine's host decisions to the leader's plan.

    The engine duck-types this via ``self._plan_drive`` (set around
    ``step()`` by :class:`FollowerLoop`): the prefill budget and the
    queue-pressure bit are overridden, spec drafting consumes the
    plan's draft tokens verbatim instead of running the host drafter,
    resumes happen exactly in plan order, and each admission claim
    verifies its locally-restored ``cached_tokens`` against the
    leader's value (a mismatch means the prefix/filestore rungs drifted
    between hosts and the device steps would desync)."""

    __slots__ = ("budget", "queue_blocked", "drafts", "resumes",
                 "cached_tokens")

    def __init__(self, budget, queue_blocked, drafts, resumes,
                 cached_tokens):
        self.budget = budget
        self.queue_blocked = bool(queue_blocked)
        self.drafts = drafts
        self.resumes = resumes
        self.cached_tokens = cached_tokens


def _fold_digest(prev: bytes, step_idx: int, emissions, excluded) -> bytes:
    """Roll the emission digest forward over one step.

    Emissions are folded sorted (order within a step is host-side
    bookkeeping, not model output) and requests in ``excluded`` —
    aborted in this plan or the next — are skipped: an abort lands on
    the leader at arrival but on followers at the next plan boundary,
    so tail emissions of an aborted request legitimately differ over a
    one-plan window."""
    h = hashlib.blake2s(prev)
    h.update(struct.pack("<q", step_idx))
    for rid, tok in sorted(emissions):
        if rid in excluded:
            continue
        b = rid.encode("utf-8", "surrogatepass")
        h.update(struct.pack("<I", len(b)))
        h.update(b)
        h.update(struct.pack("<q", int(tok)))
    return h.digest()[:16]


class PlanLeader:
    """Engine wrapper for the leader: broadcasts one StepPlan per step.

    Duck-types the Engine surface EngineLoop uses (add_request / abort /
    step / step_dispatch / step_complete / pipeline_ready /
    discard_pending / has_work / validate_request / reap_stuck / slots /
    waiting / recent_ttfts ...).  Unlike the old command-replay journal
    it does NOT disable anything: preemption, spec decode, adapters,
    WFQ, the async pipeline, filestore prefix hits, and drain-time
    snapshot export all run on the leader and replicate as plan data.
    """

    def __init__(self, engine, journal: Optional[CommandLog] = None):
        self.engine = engine
        if journal is None:
            cap = int(os.environ.get("HELIX_MH_RING", "4096") or 4096)
            journal = CommandLog(capacity=cap)
        self.journal = journal
        self._seed_counter = itertools.count(0x5EED)
        # serializes abort/preempt arrival against plan assembly: ops
        # publish IMMEDIATELY in arrival order, so the stream position
        # of an op relative to the surrounding plans is exactly the
        # order the leader's engine saw it
        self._mu = threading.Lock()
        self._carry_admits: list = []     # re-carried from a failed plan
        self._carry_resumes: list = []
        self._carry_emissions: list = []
        self._step_counter = 0
        self._last_plan_idx = -1
        self._dispatch_steps: dict = {}   # id(pend) -> plan idx
        self._plan_content: dict = {}     # plan idx -> (admits, resumes)
        self._emissions: dict = {}        # plan idx -> [(rid, tok)]
        self._done_steps: set = set()
        # plan idx -> rids aborted between that plan and the next one
        # (the digest-exclusion window: those aborts race the step's
        # completion on the leader but land post-step on followers)
        self._aborts_after_plan: dict = {}
        self._fold_next = 0
        self._digest = _DIGEST_SEED
        self._digest_step: Optional[int] = None
        self._digest_reset_pending = False
        # surfaced by bench.py and /admin stats
        self.plans_published = 0
        self.plan_bytes_total = 0
        self.plan_bytes_max = 0

    # -- attributes EngineLoop SETS on its engine must reach the real
    # engine (a plain __getattr__ passthrough would shadow them here and
    # silently break WFQ fair-share charging and victim ordering) ------
    @property
    def prefill_budget(self):
        return self.engine.prefill_budget

    @prefill_budget.setter
    def prefill_budget(self, value):
        self.engine.prefill_budget = value

    @property
    def on_admit(self):
        return self.engine.on_admit

    @on_admit.setter
    def on_admit(self, value):
        self.engine.on_admit = value

    @property
    def victim_policy(self):
        return self.engine.victim_policy

    @victim_policy.setter
    def victim_policy(self, value):
        self.engine.victim_policy = value

    # -- mutations ----------------------------------------------------------
    def add_request(self, req: Request) -> None:
        if req.sampling.seed is None:
            # pin a seed so follower sampling is bit-identical without
            # relying on engine-internal PRNG call order
            req.sampling = dataclasses.replace(
                req.sampling, seed=next(self._seed_counter)
            )
        # validate wire-encodability up front (VL rejects here, not at
        # admission time deep inside a step)
        request_to_wire(req)
        self.engine.add_request(req)

    def _publish_op(self, op: str, rid: str) -> None:
        # ops records publish at arrival (not at the next dispatch):
        # an abort with no step behind it must still reach followers,
        # or they keep a zombie request parked forever
        self.journal.publish(
            {"v": WIRE_VERSION, "kind": "ops", "ops": [[op, rid]]}
        )
        if op == "abort":
            self._aborts_after_plan.setdefault(
                self._last_plan_idx, set()
            ).add(rid)

    def abort(self, request_id: str) -> None:
        with self._mu:
            self.engine.abort(request_id)
            self._publish_op("abort", request_id)

    def preempt(self, request_id: str) -> bool:
        with self._mu:
            ok = self.engine.preempt(request_id)
            if ok:
                self._publish_op("preempt", request_id)
            return ok

    def preempt_for_pressure(self) -> Optional[str]:
        with self._mu:
            rid = self.engine.preempt_for_pressure()
            if rid is not None:
                self._publish_op("preempt", rid)
            return rid

    # snapshot IMPORT and the disaggregated prefill handoff (ISSUE
    # 11/14) would create device state that exists only on the leader —
    # a migrated-in request has no admission plan row followers could
    # replay, so its later resume would diverge.  Export stays live
    # (drain-time snapshots are leader-owned; the shipped request's
    # abort rides the next plan like any abort).
    import_request = None
    export_prefill = None

    def reap_stuck(self, max_queue_seconds: float) -> list:
        # the reaper scans the waiting queue only, and waiting requests
        # are never broadcast (only ADMITTED requests ride plans) — so a
        # reap needs no wire record at all: followers never knew the
        # request existed
        return self.engine.reap_stuck(max_queue_seconds)

    # -- the step plan ------------------------------------------------------
    def step_dispatch(self):
        eng = self.engine
        with self._mu:
            carry_admits, self._carry_admits = self._carry_admits, []
            carry_resumes, self._carry_resumes = self._carry_resumes, []
            carry_ems, self._carry_emissions = self._carry_emissions, []
            step_idx = self._step_counter
            self._step_counter += 1
            rec = PlanRecorder()
            eng._plan_recorder = rec
            try:
                emitted, pend = eng.step_dispatch()
            except Exception:
                # dispatch failed part-way: admissions recorded before
                # the failure already mutated engine state and MUST
                # still reach followers — re-carry them into the retry's
                # plan, reuse the index, and restart the digest chain
                # (emission attribution across the failure is not
                # reconstructible)
                self._carry_admits = carry_admits + rec.admits
                self._carry_resumes = carry_resumes + rec.resumes
                self._carry_emissions = carry_ems
                self._step_counter = step_idx
                self._reset_digest_chain()
                raise
            finally:
                eng._plan_recorder = None
            admits = carry_admits + rec.admits
            resumes = carry_resumes + rec.resumes
            self._advance_digest(step_idx)
            record = {
                "v": WIRE_VERSION,
                "kind": "plan",
                "step": step_idx,
                "admits": admits,
                "resumes": resumes,
                "budget": rec.budget,
                "queue_blocked": rec.queue_blocked,
                "drafts": rec.drafts,
                "digest_step": self._digest_step,
                "digest": (self._digest.hex()
                           if self._digest_step is not None else None),
            }
            if self._digest_reset_pending:
                record["digest_reset"] = True
                self._digest_reset_pending = False
            self.journal.publish(record)
            self._last_plan_idx = step_idx
            self.plans_published += 1
            nbytes = len(json.dumps(record, separators=(",", ":")))
            self.plan_bytes_total += nbytes
            self.plan_bytes_max = max(self.plan_bytes_max, nbytes)
            ems = carry_ems + [(r.id, int(t)) for r, t in emitted]
            self._emissions[step_idx] = ems
            if pend is None:
                self._done_steps.add(step_idx)
            else:
                self._dispatch_steps[id(pend)] = step_idx
                self._plan_content[step_idx] = (admits, resumes)
            return emitted, pend

    def step_complete(self, pend, emitted=None):
        base = len(emitted) if emitted is not None else 0
        out = self.engine.step_complete(pend, emitted)
        with self._mu:
            idx = self._dispatch_steps.pop(id(pend), None)
            if idx is not None:
                self._emissions.setdefault(idx, []).extend(
                    (r.id, int(t)) for r, t in out[base:]
                )
                self._done_steps.add(idx)
                self._plan_content.pop(idx, None)
        return out

    def step(self):
        emitted, pend = self.step_dispatch()
        if pend is None:
            return emitted
        try:
            return self.step_complete(pend, emitted)
        except Exception:
            self.discard_pending(pend)
            raise

    def discard_pending(self, pend) -> None:
        self.engine.discard_pending(pend)
        with self._mu:
            idx = self._dispatch_steps.pop(id(pend), None)
            if idx is None:
                return
            # the published plan never ran to completion on the leader.
            # Publish a discard marker so a replaying/rejoining follower
            # skips the dead plan; its host effects (admissions and
            # resumes survive the positional rollback) are re-carried
            # into the retry's plan.  A live follower that already
            # executed the plan treats the marker as lost lockstep and
            # restarts — on a real cross-host mesh the failed collective
            # has desynced the slice anyway, so the restart ladder is
            # the honest recovery path.
            admits, resumes = self._plan_content.pop(idx)
            self._carry_admits = admits + self._carry_admits
            self._carry_resumes = resumes + self._carry_resumes
            self._carry_emissions = (
                self._emissions.pop(idx, []) + self._carry_emissions
            )
            self._done_steps.discard(idx)
            self.journal.publish(
                {"v": WIRE_VERSION, "kind": "discard", "step": idx}
            )
            self._reset_digest_chain()

    def _reset_digest_chain(self) -> None:
        self._digest = _DIGEST_SEED
        self._digest_step = None
        self._digest_reset_pending = True
        self._emissions.clear()
        self._done_steps.clear()
        self._aborts_after_plan.clear()
        self._fold_next = self._step_counter

    def _advance_digest(self, plan_idx: int) -> None:
        # digest(M) folds step M's emissions minus requests aborted in
        # the stream window between plan M and plan M+1: those aborts
        # race step M's completion on the leader (the engine skips a
        # freed slot's emission at reconcile) but land post-step on
        # followers, so both sides exclude them.  Folding M therefore
        # waits until plan M+1 is being published, when the window has
        # closed.
        while self._fold_next < plan_idx:
            m = self._fold_next
            if m not in self._done_steps:
                break
            excl = self._aborts_after_plan.pop(m, set())
            ems = self._emissions.pop(m, [])
            self._digest = _fold_digest(self._digest, m, ems, excl)
            self._digest_step = m
            self._done_steps.discard(m)
            self._fold_next += 1

    # -- passthrough --------------------------------------------------------
    def __getattr__(self, name):
        return getattr(self.engine, name)


class FollowerLoop:
    """Drives this host's engine replica from the leader's plan feed.

    Recovery posture (round-3 verdict weak #7 — the failure paths need
    drills, not just detection):

    - **Follower killed mid-stream**: start a NEW FollowerLoop with a
      fresh engine replica and replay from seq 0 — as long as the ring
      still retains the journal head, replay reconstructs bit-identical
      engine state (``test_multihost_serving.TestFailureDrills``).  The
      engine is deterministic given the plan sequence, so rejoining is
      a pure function of the ring.
    - **Fell off the ring / leader restarted / divergence detected**:
      fatal for lockstep.  The loop stops, ``error`` carries an
      operator-actionable message, and ``on_lost_lockstep(error)`` fires
      so the node agent can surface it (restart the serving process; it
      will resync by replaying the ring, or from the profile re-apply
      if the ring head is gone).
    - **Transient feed errors** retry with capped exponential backoff +
      jitter (``HELIX_MH_BACKOFF_BASE``/``HELIX_MH_BACKOFF_CAP``);
      counters are surfaced in :meth:`stats`.
    """

    def __init__(self, engine, feed, poll_timeout: float = 5.0,
                 on_lost_lockstep=None):
        self.engine = engine
        self.feed = feed                  # .read_since(seq, timeout)
        self.poll_timeout = poll_timeout
        self.applied_seq = 0
        self.steps = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[str] = None
        self.on_lost_lockstep = on_lost_lockstep
        self.digest_mode = (
            os.environ.get("HELIX_MH_DIGEST", "strict").strip().lower()
            or "strict"
        )
        self.backoff_base = float(
            os.environ.get("HELIX_MH_BACKOFF_BASE", "0.05") or 0.05
        )
        self.backoff_cap = float(
            os.environ.get("HELIX_MH_BACKOFF_CAP", "5.0") or 5.0
        )
        self._skip: set = set()            # plan idxs discarded by the leader
        self._applied_step = -1
        self._prev = None                  # (step idx, emissions)
        # plan idx -> rids aborted by ops records seen after that plan;
        # mirrors the leader's digest-exclusion window by stream position
        self._aborts_after_plan: dict = {}
        self._digest = _DIGEST_SEED
        self._digest_by_step: collections.OrderedDict = (
            collections.OrderedDict()
        )
        # counters (stats())
        self.plans_applied = 0
        self.plans_skipped = 0
        self.feed_errors = 0
        self.backoff_seconds_total = 0.0
        self.digest_checks = 0
        self.digest_mismatches = 0

    # -- plan application ---------------------------------------------------
    def apply(self, record: dict) -> None:
        v = record.get("v")
        if v != WIRE_VERSION:
            raise WireVersionError(
                f"plan record version {v!r} (this host speaks "
                f"{WIRE_VERSION}) — upgrade the leader and followers "
                "together"
            )
        if record.get("kind") == "discard":
            self._handle_discard(record)
            self.applied_seq = record["seq"]
            return
        if record.get("kind") == "ops":
            self._apply_ops(record)
            self.applied_seq = record["seq"]
            return
        step_idx = record["step"]
        if step_idx in self._skip:
            # the leader discarded this plan before completing it
            self._skip.discard(step_idx)
            self.plans_skipped += 1
            self.applied_seq = record["seq"]
            return
        self._fold_and_check(record)
        eng = self.engine
        cached = {}
        for doc in record.get("admits", []):
            req = request_from_wire(doc)
            if req.adapter and hasattr(eng, "ensure_adapter_resident"):
                if not eng.ensure_adapter_resident(req.adapter):
                    raise DivergenceError(
                        f"plan {step_idx}: adapter {req.adapter!r} for "
                        f"{req.id} is not stageable on this replica"
                    )
            cached[req.id] = int(doc.get("cached_tokens", 0))
            eng.add_request(req)
        if record.get("resumes") and hasattr(eng, "ensure_adapter_resident"):
            want = set(record["resumes"])
            for st in list(getattr(eng, "preempted", [])):
                if st.req.id in want and st.req.adapter:
                    eng.ensure_adapter_resident(st.req.adapter)
        drive = PlanDrive(
            budget=record.get("budget"),
            queue_blocked=record.get("queue_blocked", False),
            drafts=[(int(s), [int(t) for t in toks])
                    for s, toks in record.get("drafts", [])],
            resumes=list(record.get("resumes", [])),
            cached_tokens=cached,
        )
        eng._plan_drive = drive
        try:
            emitted = eng.step()
        finally:
            eng._plan_drive = None
        if eng.waiting:
            raise DivergenceError(
                f"plan {step_idx}: {len(eng.waiting)} admitted requests "
                "left unclaimed after the step — replica resources do "
                "not match the leader's"
            )
        if drive.resumes:
            raise DivergenceError(
                f"plan {step_idx}: resumes not applied: {drive.resumes}"
            )
        self._prev = (step_idx, [(r.id, int(t)) for r, t in emitted])
        self._applied_step = step_idx
        self.steps += 1
        self.plans_applied += 1
        self.applied_seq = record["seq"]

    def _apply_ops(self, record: dict) -> None:
        # ops records sit in the stream exactly where the leader's
        # engine saw the abort/preempt relative to the surrounding
        # plans, so applying them in stream order keeps the replica's
        # slot/page state in step
        eng = self.engine
        for op in record.get("ops", []):
            kind, rid = op[0], op[1]
            if kind == "abort":
                eng.abort(rid)
                self._aborts_after_plan.setdefault(
                    self._applied_step, set()
                ).add(rid)
            elif kind == "preempt":
                if not eng.preempt(rid):
                    raise DivergenceError(
                        f"ops after step {self._applied_step}: preempt "
                        f"of {rid} failed on this replica (request "
                        "unknown or not swappable)"
                    )
            else:
                raise DivergenceError(
                    f"ops after step {self._applied_step}: unknown op "
                    f"{kind!r}"
                )

    def _handle_discard(self, record: dict) -> None:
        target = record["step"]
        self._skip.discard(target)
        if target <= self._applied_step:
            raise DivergenceError(
                f"this replica already executed step {target} that the "
                "leader discarded after a step failure"
            )
        # the plan was skipped (or predates our join): restart the
        # digest chain in step with the leader's reset
        self._prev = None
        self._digest = _DIGEST_SEED
        self._aborts_after_plan.clear()

    def _fold_and_check(self, record: dict) -> None:
        if record.get("digest_reset"):
            self._prev = None
            self._digest = _DIGEST_SEED
            self._aborts_after_plan.clear()
        if self._prev is not None:
            m, ems = self._prev
            excl = self._aborts_after_plan.pop(m, set())
            self._digest = _fold_digest(self._digest, m, ems, excl)
            self._digest_by_step[m] = self._digest.hex()
            self._prev = None
            while len(self._digest_by_step) > 128:
                self._digest_by_step.popitem(last=False)
        want = record.get("digest")
        ds = record.get("digest_step")
        if want is None or ds is None or self.digest_mode == "off":
            return
        have = self._digest_by_step.get(ds)
        if have is None:
            # we joined (or reset) after step ds; nothing to compare
            return
        self.digest_checks += 1
        if have != want:
            self.digest_mismatches += 1
            msg = (f"emission digest mismatch at step {ds}: leader "
                   f"{want}, replica {have}")
            if self.digest_mode == "strict":
                raise DivergenceError(msg)
            log.warning("%s", msg)

    # -- pump ----------------------------------------------------------------
    def run_once(self) -> int:
        records = self.feed.read_since(
            self.applied_seq, timeout=self.poll_timeout
        )
        # prescan for discard markers so a replayed/batched feed skips
        # dead plans instead of executing steps the leader rolled back
        for r in records:
            if r.get("kind") == "discard":
                self._skip.add(r.get("step"))
        for r in records:
            self.apply(r)
        return len(records)

    def _fail(self, msg: str) -> None:
        self.error = (
            f"{msg} — lockstep lost; restart this follower with a fresh "
            "engine replica (it replays the leader's ring from seq 0 on "
            "start); if the ring no longer retains seq 1, re-apply the "
            "serving profile on both hosts"
        )
        log.error("follower lost lockstep: %s", self.error)
        if self.on_lost_lockstep is not None:
            try:
                self.on_lost_lockstep(self.error)
            except Exception:  # noqa: BLE001 — operator hook
                log.exception("on_lost_lockstep hook failed")

    def start(self) -> "FollowerLoop":
        def run():
            attempt = 0
            while not self._stop.is_set():
                try:
                    records = self.feed.read_since(
                        self.applied_seq, timeout=self.poll_timeout
                    )
                except LagError as e:
                    # falling off the ring (or a leader restart) is
                    # fatal for lockstep: the process must restart and
                    # resync from the ring head (or a profile re-apply
                    # when the head is gone)
                    self._fail(str(e))
                    return
                except Exception as e:  # noqa: BLE001 — transient feed
                    attempt += 1
                    self.feed_errors += 1
                    delay = min(
                        self.backoff_cap,
                        self.backoff_base * (2 ** min(attempt, 16)),
                    ) * (0.5 + random.random() / 2.0)
                    self.backoff_seconds_total += delay
                    log.warning(
                        "follower feed error (attempt %d, retry in "
                        "%.2fs): %s", attempt, delay, e,
                    )
                    self._stop.wait(delay)
                    continue
                attempt = 0
                try:
                    for r in records:
                        if r.get("kind") == "discard":
                            self._skip.add(r.get("step"))
                    for r in records:
                        self.apply(r)
                except (LagError, WireVersionError, DivergenceError) as e:
                    self._fail(str(e))
                    return
                except Exception as e:  # noqa: BLE001 — half-applied plan
                    # an engine error mid-plan cannot be retried (the
                    # plan may be half-applied) — treat as divergence
                    self._fail(f"plan apply failed: {e!r}")
                    return

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def stats(self) -> dict:
        return {
            "applied_seq": self.applied_seq,
            "steps": self.steps,
            "plans_applied": self.plans_applied,
            "plans_skipped": self.plans_skipped,
            "feed_errors": self.feed_errors,
            "backoff_seconds_total": round(self.backoff_seconds_total, 3),
            "digest_mode": self.digest_mode,
            "digest_checks": self.digest_checks,
            "digest_mismatches": self.digest_mismatches,
            "reconnects": getattr(self.feed, "reconnects", 0),
        }


class HTTPFeed:
    """Follower-side transport: long-poll the leader over DCN.

    Keeps a pooled ``requests.Session`` alive across polls (one TCP/TLS
    handshake per leader, not per long-poll); on a transport error the
    pool is dropped so the next poll reconnects cleanly, counted in
    ``reconnects``."""

    def __init__(self, leader_url: str, model: str):
        self.leader_url = leader_url.rstrip("/")
        self.model = model
        self._session = None
        self.reconnects = 0

    def _sess(self):
        if self._session is None:
            import requests

            self._session = requests.Session()
        return self._session

    def read_since(self, since: int, timeout: float = 30.0) -> list:
        try:
            resp = self._sess().get(
                f"{self.leader_url}/multihost/commands",
                params={
                    "since": since, "timeout": timeout, "model": self.model,
                },
                timeout=timeout + 10,
            )
            doc = resp.json()
        except Exception:
            # drop the pooled connections; the next poll reconnects
            self.reconnects += 1
            sess, self._session = self._session, None
            if sess is not None:
                try:
                    sess.close()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
            raise
        if doc.get("lagged"):
            raise LagError(doc.get("error", "fell off the leader's ring"))
        return doc.get("records", [])


# the old name survived one release; keep the alias so operator tooling
# importing LockstepLeader keeps working against the plan broadcast
LockstepLeader = PlanLeader
