"""Multi-host serving: lockstep SPMD engines over a DCN command log.

SURVEY §2.2/§7 puts inter-slice DCN in the engine's court; round 2
covered multi-host *training* only (VERDICT missing #5: "no multi-host
serving").  In JAX's multi-controller model every process must issue the
SAME jit calls in the same order for collectives over a cross-host mesh
to line up.  Serving has dynamic admission, so this module makes the
call sequence deterministic by construction:

- the **leader** (process 0) takes HTTP traffic; every mutation
  (admit/abort, incl. reaper aborts) is journaled; each engine step
  publishes one sequenced record {admits, aborts, step} BEFORE the step
  runs;
- **followers** replay the journal: apply the same admissions (explicit
  seeds pinned by the leader, so sampling is bit-identical), then call
  ``engine.step()`` — the identical jit sequence on their shards of the
  global mesh.  Their emitted tokens are discarded; only the leader
  streams to clients.

Transport is pluggable: in-process ``CommandLog`` (tests, and the ring
buffer the leader serves), or ``HTTPFeed`` (follower long-polls the
leader's ``/multihost/commands`` route over DCN).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import threading
import time
from typing import Optional

from helix_tpu.engine.engine import Request
from helix_tpu.engine.sampling import SamplingParams

log = logging.getLogger("helix.mh-serving")


class CommandLog:
    """Sequenced ring buffer with blocking reads (the leader's journal)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._records: list = []          # [(seq, record)]
        self._first = 1
        self._next = 1
        self._cond = threading.Condition()

    def publish(self, record: dict) -> int:
        with self._cond:
            seq = self._next
            self._next += 1
            self._records.append({**record, "seq": seq})
            if len(self._records) > self.capacity:
                dropped = len(self._records) - self.capacity
                self._records = self._records[dropped:]
                self._first += dropped
            self._cond.notify_all()
            return seq

    def read_since(self, since: int, timeout: float = 30.0) -> list:
        """Records with seq > since; blocks up to timeout when none.
        Raises LagError when the follower fell off the ring."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if since + 1 < self._first:
                    raise LagError(
                        f"follower at seq {since} fell behind the ring "
                        f"(first retained: {self._first})"
                    )
                if since >= self._next:
                    # AHEAD of the journal: the leader restarted and its
                    # sequence reset — silent empty polls here would hang
                    # the whole cluster mid-collective; fail loudly so
                    # the follower restarts and resyncs
                    raise LagError(
                        f"follower at seq {since} is ahead of the "
                        f"journal (next: {self._next}) — leader restart?"
                    )
                out = [r for r in self._records if r["seq"] > since]
                if out:
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)


class LagError(RuntimeError):
    pass


def request_to_wire(req: Request) -> dict:
    if req.image_embeds is not None:
        raise ValueError(
            "multi-host serving covers text models (VL image embeds are "
            "device-resident and not journalled)"
        )
    return {
        "id": req.id,
        "prompt_tokens": list(req.prompt_tokens),
        "sampling": dataclasses.asdict(req.sampling),
        "stop_token_ids": list(req.stop_token_ids),
    }


def request_from_wire(doc: dict) -> Request:
    return Request(
        id=doc["id"],
        prompt_tokens=list(doc["prompt_tokens"]),
        sampling=SamplingParams(**doc["sampling"]),
        stop_token_ids=tuple(doc["stop_token_ids"]),
    )


class LockstepLeader:
    """Engine wrapper for the leader: journals every mutation and emits
    one record per step.  Duck-types the Engine surface EngineLoop uses
    (add_request / abort / step / has_work / validate_request /
    reap_stuck / slots / waiting / recent_ttfts ...)."""

    def __init__(self, engine, journal: Optional[CommandLog] = None):
        self.engine = engine
        self.journal = journal or CommandLog()
        self._pending_admits: list = []
        self._pending_aborts: list = []
        self._seed_counter = itertools.count(0x5EED)

    # -- mutations (journalled) --------------------------------------------
    def add_request(self, req: Request) -> None:
        if req.sampling.seed is None:
            # pin a seed so follower sampling is bit-identical without
            # relying on engine-internal PRNG call order
            req.sampling = dataclasses.replace(
                req.sampling, seed=next(self._seed_counter)
            )
        self._pending_admits.append(request_to_wire(req))
        self.engine.add_request(req)

    def abort(self, request_id: str) -> None:
        self._pending_aborts.append(request_id)
        self.engine.abort(request_id)

    def reap_stuck(self, max_queue_seconds: float) -> list:
        reaped = self.engine.reap_stuck(max_queue_seconds)
        # time-based decisions MUST replicate as explicit aborts — the
        # followers' clocks play no part in the call sequence
        for req in reaped:
            self._pending_aborts.append(req.id)
        return reaped

    def step(self):
        self.journal.publish(
            {
                "admits": self._pending_admits,
                "aborts": self._pending_aborts,
                "step": True,
            }
        )
        self._pending_admits = []
        self._pending_aborts = []
        return self.engine.step()

    def preempt_for_pressure(self):
        """Preemption-by-swap is a leader-LOCAL scheduling move the
        journal does not replicate: followers would keep decoding the
        parked victim and their per-step emissions would diverge from
        the leader's.  Disabled under lockstep — the degradation ladder
        falls through to the typed kv_exhausted shed (which replicates
        as an explicit abort)."""
        return None

    # snapshot export/import (ISSUE 11) are leader-local state moves the
    # journal cannot express — a migrated-away request would keep
    # decoding on followers, a migrated-in one would exist only on the
    # leader.  Absent attributes make the engine loop's drain exporter
    # degrade to the ordinary shed (and imports fail typed).
    export_request = None
    import_request = None
    # the disaggregated prefill handoff (ISSUE 14) is the same
    # leader-local state move — pinned off for the same reason
    export_prefill = None
    # the filestore KV tier reads local disk at admission, which would
    # desync follower replay (cached_tokens diverge) — never armed here
    kv_filestore = None

    # -- passthrough --------------------------------------------------------
    def __getattr__(self, name):
        return getattr(self.engine, name)


class FollowerLoop:
    """Replays the leader's journal against this host's engine replica.

    Recovery posture (round-3 verdict weak #7 — the failure paths need
    drills, not just detection):

    - **Follower killed mid-stream**: start a NEW FollowerLoop with a
      fresh engine replica and replay from seq 0 — as long as the ring
      still retains the journal head, replay reconstructs bit-identical
      engine state (``test_multihost_serving.TestFailureDrills``).  The
      engine is deterministic given the command sequence, so rejoining is
      a pure function of the ring.
    - **Fell off the ring / leader restarted**: fatal for lockstep.  The
      loop stops, ``error`` carries an operator-actionable message, and
      ``on_lost_lockstep(error)`` fires so the node agent can surface it
      (restart the serving process; it will resync by replaying the ring,
      or from the profile re-apply if the ring head is gone).
    """

    def __init__(self, engine, feed, poll_timeout: float = 5.0,
                 on_lost_lockstep=None):
        self.engine = engine
        self.feed = feed                  # .read_since(seq, timeout)
        self.poll_timeout = poll_timeout
        self.applied_seq = 0
        self.steps = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[str] = None
        self.on_lost_lockstep = on_lost_lockstep

    def apply(self, record: dict) -> None:
        for doc in record.get("admits", []):
            self.engine.add_request(request_from_wire(doc))
        for rid in record.get("aborts", []):
            self.engine.abort(rid)
        if record.get("step"):
            self.engine.step()
            self.steps += 1
        self.applied_seq = record["seq"]

    def run_once(self) -> int:
        records = self.feed.read_since(
            self.applied_seq, timeout=self.poll_timeout
        )
        for r in records:
            self.apply(r)
        return len(records)

    def start(self) -> "FollowerLoop":
        def run():
            while not self._stop.is_set():
                try:
                    self.run_once()
                except LagError as e:
                    # falling off the ring is fatal for lockstep: the
                    # process must restart and resync from the ring head
                    # (or a profile re-apply when the head is gone)
                    self.error = (
                        f"{e} — lockstep lost; restart this follower "
                        "with a fresh engine replica (it replays the "
                        "leader's ring from seq 0 on start); if the ring "
                        "no longer retains seq 1, re-apply the serving "
                        "profile on both hosts"
                    )
                    log.error("follower lost lockstep: %s", self.error)
                    if self.on_lost_lockstep is not None:
                        try:
                            self.on_lost_lockstep(self.error)
                        except Exception:  # noqa: BLE001 — operator hook
                            log.exception("on_lost_lockstep hook failed")
                    return
                except Exception as e:  # noqa: BLE001 — transient feed
                    log.warning("follower feed error: %s", e)
                    time.sleep(1.0)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)


class HTTPFeed:
    """Follower-side transport: long-poll the leader over DCN."""

    def __init__(self, leader_url: str, model: str):
        self.leader_url = leader_url.rstrip("/")
        self.model = model

    def read_since(self, since: int, timeout: float = 30.0) -> list:
        import json
        import urllib.parse
        import urllib.request

        q = urllib.parse.urlencode(
            {"since": since, "timeout": timeout, "model": self.model}
        )
        req = urllib.request.Request(
            f"{self.leader_url}/multihost/commands?{q}"
        )
        with urllib.request.urlopen(req, timeout=timeout + 10) as r:
            doc = json.loads(r.read())
        if doc.get("lagged"):
            raise LagError(doc.get("error", "fell off the leader's ring"))
        return doc.get("records", [])
