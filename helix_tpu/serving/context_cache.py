"""Context-caching API (ISSUE 20): persist a prompt prefix once, reference it forever.

The serving half of the million-token-context work: tiered KV residency
(engine/engine.py) makes a huge context *hold*; this module makes it
*cheap to reuse*.  ``POST /v1/context`` tokenises a prompt prefix, runs
it through the engine ONCE as a pinned prefill-only request (``ctx_pin``
forces full device residency so the prefix-cache adoption + filestore
write-through fire exactly as for any resident prompt), and registers a
**content-addressed handle** — ``ctx-`` + blake2b of the token bytes —
in a small registry persisted through the PR 14 filestore root.  A later
chat/completions request carrying ``context_id`` prepends the cached
token span; the engine's prefix cache (HBM -> host -> filestore ladder)
then serves the span's pages without recomputing prefill, so TTFT drops
to roughly the cost of the *new* tokens only.

Contract, following the residency-ladder discipline:

- handles are **content-addressed**: creating the same prefix twice
  yields the same handle and charges nothing new — idempotent by
  construction;
- creation is **quota'd per tenant** (the PR 7 identity):
  ``HELIX_CTX_TENANT_TOKENS`` caps the total cached tokens a tenant may
  hold; past it new creations are rejected with a typed counter, reads
  are never gated;
- a registry entry that fails to load degrades to a **miss** (the
  request is told the handle is unknown; nothing ever attends wrong
  tokens) with a typed counter;
- the ``helix_ctx_*`` metric family is minted ONLY here
  (``tools/lint_metrics.py`` contract 15); the runner's /metrics calls
  :func:`collect_ctx_metrics`, the node agent heartbeats
  :meth:`ContextCache.stats_block` via :func:`context_cache_for`, and
  the control plane clamps the block with :func:`validate_ctx_block`.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import threading
import time
from typing import Optional

log = logging.getLogger("helix.context_cache")

# ---------------------------------------------------------------------------
# metric vocabulary (lint_metrics contract 15: minted only in this module)
# ---------------------------------------------------------------------------

CTX_CREATES = "helix_ctx_creates_total"
CTX_HITS = "helix_ctx_hits_total"
CTX_MISSES = "helix_ctx_misses_total"
CTX_QUOTA_REJECTS = "helix_ctx_quota_rejects_total"
CTX_LOAD_ERRORS = "helix_ctx_load_errors_total"
CTX_ENTRIES = "helix_ctx_entries"
CTX_TOKENS = "helix_ctx_tokens"

# handles are content-addressed: the blake2b digest of the token-id
# bytes, so identical prefixes collapse to one entry across tenants,
# requests, and restarts
_HANDLE_DIGEST_CHARS = 24


def ctx_tenant_token_cap() -> int:
    """HELIX_CTX_TENANT_TOKENS: total cached prompt tokens one tenant
    may hold across its context handles (0/unset = unlimited)."""
    try:
        return int(os.environ.get("HELIX_CTX_TENANT_TOKENS", "0") or 0)
    except (TypeError, ValueError):
        return 0


def context_handle(token_ids) -> str:
    """The content-addressed handle for a token prefix."""
    h = hashlib.blake2b(digest_size=16)
    for t in token_ids:
        h.update(int(t).to_bytes(4, "little", signed=False))
    return "ctx-" + h.hexdigest()[:_HANDLE_DIGEST_CHARS]


class ContextCache:
    """Handle -> cached-prompt-prefix registry, persisted through the
    filestore root (``root=''`` = in-memory only, dies with the
    process — dev/tests).

    Thread contract: HTTP handler threads create/resolve concurrently
    and the heartbeat thread reads ``stats_block``; one lock guards the
    registry, metric counters are plain GIL-atomic int reads."""

    # registry blobs live under one reserved owner prefix in the
    # backing store — KV page blobs (kv-pages) and user files share the
    # same root without colliding (Filestore._resolve keeps owners
    # disjoint)
    OWNER = "ctx-cache"

    def __init__(self, root: str = "",
                 tenant_token_cap: Optional[int] = None):
        self.root = root
        self.store = None
        if root:
            from helix_tpu.control.filestore import Filestore

            self.store = Filestore(root)
        self.tenant_token_cap = (
            tenant_token_cap if tenant_token_cap is not None
            else ctx_tenant_token_cap()
        )
        self._lock = threading.Lock()
        # handle -> {"tenant", "tokens" (count), "created"}; the token
        # ids themselves load lazily from per-handle blobs so startup
        # and heartbeats never touch million-token payloads
        self._index: dict = {}
        # handle -> list[int], populated on create / first resolve
        self._tokens: dict = {}
        # typed counters (scrape-time GIL-atomic reads)
        self.creates = 0
        self.hits = 0
        self.misses = 0
        self.quota_rejects = 0
        self.load_errors = 0
        if self.store is not None:
            self._index = self._load_index()

    # -- persistence -------------------------------------------------------
    def _index_path(self) -> str:
        return "index.json"

    def _blob_path(self, handle: str) -> str:
        return f"{handle[4:6] or '00'}/{handle}.json"

    def _load_index(self) -> dict:
        try:
            doc = json.loads(
                self.store.read(self.OWNER, self._index_path())
            )
            return {
                str(h): {
                    "tenant": str(e.get("tenant", "")),
                    "tokens": int(e.get("tokens", 0)),
                    "created": float(e.get("created", 0.0)),
                }
                for h, e in doc.items()
                if isinstance(e, dict)
            }
        except FileNotFoundError:
            return {}
        except Exception:  # noqa: BLE001 — a mangled index resets, never errors
            log.warning("context-cache index unreadable; starting empty")
            return {}

    def _save_index_locked(self) -> None:
        if self.store is None:
            return
        try:
            self.store.write(
                self.OWNER, self._index_path(),
                json.dumps(self._index).encode(),
            )
        except OSError:
            log.warning("could not persist context-cache index")

    # -- quota -------------------------------------------------------------
    def usage(self, tenant: str) -> int:
        """Total cached tokens charged to ``tenant``."""
        with self._lock:
            return sum(
                e["tokens"] for e in self._index.values()
                if e["tenant"] == tenant
            )

    def admit(self, tenant: str, n_tokens: int) -> bool:
        """Would caching ``n_tokens`` more keep ``tenant`` inside its
        quota?  False increments the typed reject counter — call once
        per creation attempt, BEFORE paying the prefill."""
        if self.tenant_token_cap <= 0:
            return True
        if self.usage(tenant) + int(n_tokens) > self.tenant_token_cap:
            self.quota_rejects += 1
            return False
        return True

    # -- registry operations -----------------------------------------------
    def contains(self, handle: str) -> bool:
        with self._lock:
            return handle in self._index

    def put(self, token_ids, tenant: str = "") -> str:
        """Register a prefix; returns its handle.  Content-addressed:
        an already-registered prefix returns the existing handle and
        charges nothing new."""
        ids = [int(t) for t in token_ids]
        handle = context_handle(ids)
        with self._lock:
            if handle in self._index:
                return handle
            self._index[handle] = {
                "tenant": tenant,
                "tokens": len(ids),
                "created": time.time(),
            }
            self._tokens[handle] = ids
            if self.store is not None:
                try:
                    self.store.write(
                        self.OWNER, self._blob_path(handle),
                        json.dumps(
                            {"tokens": ids, "tenant": tenant}
                        ).encode(),
                    )
                except OSError:
                    log.warning(
                        "could not persist context blob %s", handle
                    )
            self._save_index_locked()
        self.creates += 1
        return handle

    def get(self, handle: str) -> Optional[list]:
        """The cached token ids for ``handle``, or None (unknown handle
        or unreadable blob — both are misses; a request must never
        attend a prefix we cannot reproduce exactly)."""
        with self._lock:
            known = handle in self._index
            ids = self._tokens.get(handle)
        if not known:
            self.misses += 1
            return None
        if ids is not None:
            self.hits += 1
            return list(ids)
        # index knows it but the tokens are not memory-resident: a
        # restart with a persisted registry — load the blob lazily
        try:
            raw = self.store.read(self.OWNER, self._blob_path(handle))
            doc = json.loads(raw)
            ids = [int(t) for t in doc["tokens"]]
            if context_handle(ids) != handle:
                raise ValueError("content address mismatch")
        except Exception as e:  # noqa: BLE001 — unreadable blob = typed miss
            self.load_errors += 1
            self.misses += 1
            log.warning("dropping unreadable context %s: %s", handle, e)
            with self._lock:
                self._index.pop(handle, None)
                self._save_index_locked()
            return None
        with self._lock:
            self._tokens[handle] = ids
        self.hits += 1
        return list(ids)

    def delete(self, handle: str) -> bool:
        with self._lock:
            if handle not in self._index:
                return False
            self._index.pop(handle, None)
            self._tokens.pop(handle, None)
            if self.store is not None:
                try:
                    self.store.delete(self.OWNER, self._blob_path(handle))
                except (FileNotFoundError, PermissionError, OSError):
                    pass
            self._save_index_locked()
        return True

    def entries(self) -> list:
        """Bounded listing for the HTTP surface (metadata only)."""
        with self._lock:
            return [
                {"id": h, "tokens": e["tokens"], "created": e["created"]}
                for h, e in sorted(
                    self._index.items(), key=lambda kv: kv[1]["created"]
                )
            ]

    # -- observability -----------------------------------------------------
    def stats_block(self) -> dict:
        """The heartbeat ctx block (clamped server-side by
        :func:`validate_ctx_block` like every runner-supplied block);
        ``{}`` while empty and idle so heartbeats stay small."""
        with self._lock:
            entries = len(self._index)
            tokens = sum(e["tokens"] for e in self._index.values())
        if not entries and not (self.creates or self.hits or self.misses):
            return {}
        return {
            "entries": entries,
            "tokens": tokens,
            "creates": self.creates,
            "hits": self.hits,
            "misses": self.misses,
            "quota_rejects": self.quota_rejects,
        }


# one cache per filestore root per process: the OpenAI surface creates
# and resolves handles, the node agent heartbeats the same instance's
# stats — they must agree
_CACHES: dict = {}
_CACHES_LOCK = threading.Lock()


def context_cache_for(root: str = "") -> ContextCache:
    """The process-wide :class:`ContextCache` bound to ``root`` (the
    PR 14 filestore root; '' = in-memory)."""
    with _CACHES_LOCK:
        cache = _CACHES.get(root)
        if cache is None:
            cache = _CACHES[root] = ContextCache(root)
        return cache


# -- federation wire validation (the PR 7 pattern) ---------------------


def _count(v) -> int:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return 0
    try:
        f = float(v)
    except (OverflowError, ValueError):
        return 0
    if not math.isfinite(f) or f < 0:
        return 0
    return int(min(f, 2**53))


def validate_ctx_block(raw) -> dict:
    """Clamp one runner-supplied context-cache block to the wire
    schema.  Like the PR 7 tenant blocks this NEVER raises and never
    rejects: a malformed block (NaN counters, wrong types) degrades to
    ``{}`` or clamped fields — rejecting would TTL-evict a healthy
    runner over a telemetry bug."""
    if not isinstance(raw, dict):
        return {}
    out = {
        k: _count(raw.get(k))
        for k in ("entries", "tokens", "creates", "hits", "misses",
                  "quota_rejects")
    }
    if not any(out.values()):
        return {}
    return out


# -- metric minting (lint_metrics contract 15) -------------------------
#
# Every helix_ctx_* series is minted HERE and only here; the runner
# surface imports this collector.


def collect_ctx_metrics(c, cache: Optional["ContextCache"]) -> None:
    """Runner-side context-cache series (scrape-time collector; plain
    GIL-atomic reads).  No-op before a cache exists."""
    if cache is None:
        return
    with cache._lock:
        entries = len(cache._index)
        tokens = sum(e["tokens"] for e in cache._index.values())
    c.gauge(
        CTX_ENTRIES, entries,
        help="Context handles registered on this runner",
    )
    c.gauge(
        CTX_TOKENS, tokens,
        help="Total prompt tokens held across context handles",
    )
    c.counter(
        CTX_CREATES, cache.creates,
        help="Context handles created (prefix prefilled + registered)",
    )
    c.counter(
        CTX_HITS, cache.hits,
        help="Requests that resolved a context handle (cached-span "
             "prefill skipped via the prefix-cache ladder)",
    )
    c.counter(
        CTX_MISSES, cache.misses,
        help="context_id references that resolved to no usable entry",
    )
    c.counter(
        CTX_QUOTA_REJECTS, cache.quota_rejects,
        help="Context creations rejected by the per-tenant token quota",
    )
    c.counter(
        CTX_LOAD_ERRORS, cache.load_errors,
        help="Persisted context blobs dropped as unreadable/mismatched "
             "(degrade to miss, never wrong tokens)",
    )
