from helix_tpu.serving.tokenizer import ByteTokenizer, load_tokenizer
from helix_tpu.serving.engine_loop import EngineLoop
from helix_tpu.serving.registry import ModelRegistry, ServedModel

__all__ = [
    "ByteTokenizer",
    "load_tokenizer",
    "EngineLoop",
    "ModelRegistry",
    "ServedModel",
]
