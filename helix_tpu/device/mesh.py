"""Device-mesh construction for SPMD serving and training.

The reference expresses parallel layout as Docker Compose GPU ``device_ids``
plus vLLM's ``--tensor-parallel-size`` (``SURVEY.md`` §2.2 "Parallelism
strategies").  Here the layout is a first-class object: a ``MeshSpec`` names
logical axes (data / fsdp / tensor / sequence / expert) and a chip count per
axis; ``build_mesh`` realises it as a ``jax.sharding.Mesh`` over a contiguous
slice of devices.  Profiles (``helix_tpu.control.profile``) map model names to
MeshSpecs the way compose profiles map vLLM services to ``device_ids``
(``design/sample-profiles/8xH100-vllm.yaml`` in the reference).

Axis conventions (used by ``helix_tpu.parallel.sharding`` rules):
  - ``dp``   data parallel (across requests / batch)
  - ``fsdp`` fully-sharded data parallel (weights sharded over dp axis)
  - ``tp``   tensor parallel (heads / ffn sharded, collectives over ICI)
  - ``sp``   sequence/context parallel (ring attention for long context)
  - ``ep``   expert parallel (MoE)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("dp", "fsdp", "pp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named-axis mesh layout over a number of chips.

    ``device_offset``/``num_devices`` let several models share one host's
    chips by claiming disjoint slices — the TPU equivalent of compose
    services pinned to disjoint GPU ``device_ids``
    (``api/pkg/runner/composeparse/parse.go:49-102``).
    """

    dp: int = 1
    fsdp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    device_offset: int = 0

    @property
    def num_devices(self) -> int:
        return (
            self.dp * self.fsdp * self.pp * self.ep * self.sp * self.tp
        )

    def axis_sizes(self) -> dict[str, int]:
        return {
            "dp": self.dp,
            "fsdp": self.fsdp,
            "pp": self.pp,
            "ep": self.ep,
            "sp": self.sp,
            "tp": self.tp,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MeshSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: int(v) for k, v in d.items() if k in known})

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def tp_only(cls, n: int, device_offset: int = 0) -> "MeshSpec":
        return cls(tp=n, device_offset=device_offset)


def slice_devices(
    spec: MeshSpec, devices: Optional[Sequence] = None
) -> list:
    """Pick the contiguous device slice this spec claims."""
    if devices is None:
        devices = jax.devices()
    lo, hi = spec.device_offset, spec.device_offset + spec.num_devices
    if hi > len(devices):
        raise ValueError(
            f"MeshSpec wants devices [{lo}, {hi}) but only "
            f"{len(devices)} devices are visible"
        )
    return list(devices)[lo:hi]


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    """Realise a MeshSpec as a ``jax.sharding.Mesh``.

    Axis order puts ``tp`` innermost so tensor-parallel collectives ride the
    fastest ICI links (adjacent chips), and ``dp`` outermost so data-parallel
    gradient reduction can span DCN across hosts — the standard TPU layout
    recipe (scaling-book; contrast with the reference where NCCL topology is
    vLLM-internal, ``SURVEY.md`` §2.2).
    """
    devs = slice_devices(spec, devices)
    sizes = [spec.axis_sizes()[a] for a in AXIS_ORDER]
    arr = np.asarray(devs, dtype=object).reshape(sizes)
    return Mesh(arr, AXIS_ORDER)


def default_mesh_spec(
    num_devices: Optional[int] = None,
    max_tp: int = 8,
) -> MeshSpec:
    """Heuristic single-model layout: as much TP as divides the chip count
    (capped), remainder into dp — a sensible default for decoder LLM serving
    where TP over ICI minimises per-token latency."""
    if num_devices is None:
        num_devices = len(jax.devices())
    tp = math.gcd(num_devices, max_tp)
    return MeshSpec(tp=tp, dp=num_devices // tp)
