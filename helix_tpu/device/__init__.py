from helix_tpu.device.detect import (
    AcceleratorStatus,
    detect_accelerators,
    tpu_generation,
    total_hbm_bytes,
)
from helix_tpu.device.mesh import MeshSpec, build_mesh, slice_devices

__all__ = [
    "AcceleratorStatus",
    "detect_accelerators",
    "tpu_generation",
    "total_hbm_bytes",
    "MeshSpec",
    "build_mesh",
    "slice_devices",
]
