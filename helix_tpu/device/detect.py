"""TPU topology enumeration and HBM accounting.

This is the TPU-native replacement for the reference's GPU detection layer
(``api/pkg/gpudetect/gpudetect.go:77-177`` shells out to ``nvidia-smi`` /
``rocm-smi``; ``api/pkg/runner/gpuarch/canonical.go`` canonicalises
architectures).  Instead of parsing CSV from a vendor tool we ask the runtime
directly: ``jax.devices()`` enumerates chips and ``device.memory_stats()``
gives per-chip HBM totals/usage — the numbers the control plane's
compatibility checks and the engine's residency manager budget against.

Record shape deliberately mirrors the reference's ``types.GPUStatus``
(``api/pkg/types/runner.go:48-63``: vendor/arch/VRAM total-used-free/driver)
with ``vendor="tpu"`` and ``arch`` = chip generation, so heartbeat JSON stays
interchangeable.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional


# Canonical generation table: maps substrings of jax device_kind to the
# canonical architecture string used in profiles/compatibility, plus
# datasheet HBM capacity (bytes) used as a fallback when memory_stats() is
# unavailable (e.g. CPU simulation of a TPU mesh).
_TPU_GENERATIONS = (
    # (needle in device_kind.lower(), canonical arch, HBM bytes per chip)
    ("v6e", "v6e", 32 * 1024**3),
    ("v6", "v6e", 32 * 1024**3),
    ("v5p", "v5p", 95 * 1024**3),
    ("v5 lite", "v5e", 16 * 1024**3),
    ("v5lite", "v5e", 16 * 1024**3),
    ("v5e", "v5e", 16 * 1024**3),
    ("v5", "v5p", 95 * 1024**3),
    ("v4", "v4", 32 * 1024**3),
    ("v3", "v3", 32 * 1024**3),
    ("v2", "v2", 16 * 1024**3),
)


def tpu_generation(device_kind: str) -> str:
    """Canonicalise a jax ``device_kind`` string to a TPU generation.

    The analogue of the reference's compute-capability -> "hopper"/"ampere"
    mapping (``api/pkg/runner/gpuarch/canonical.go``).
    """
    kind = device_kind.lower()
    for needle, arch, _ in _TPU_GENERATIONS:
        if needle in kind:
            return arch
    return "unknown"


def _datasheet_hbm(device_kind: str) -> int:
    kind = device_kind.lower()
    for needle, _, hbm in _TPU_GENERATIONS:
        if needle in kind:
            return hbm
    return 0


@dataclasses.dataclass(frozen=True)
class AcceleratorStatus:
    """Per-chip status record, wire-compatible with the reference heartbeat.

    Mirrors ``types.GPUStatus`` (``api/pkg/types/runner.go:48-63``) so the
    control plane's compatibility filter needs only a new vendor branch.
    """

    index: int
    vendor: str                  # "tpu" | "cpu"
    arch: str                    # "v5e" | "v5p" | ... (gpuarch equivalent)
    device_kind: str             # raw jax device_kind
    total_memory_bytes: int      # HBM capacity
    used_memory_bytes: int       # HBM in use (live buffers)
    free_memory_bytes: int
    core_on_chip: int = 1
    process_index: int = 0
    coords: Optional[tuple] = None
    driver: str = ""             # libtpu/jax version string

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["coords"] = list(self.coords) if self.coords is not None else None
        return d


def _memory_stats(device) -> tuple[int, int]:
    """(total_bytes, used_bytes) for a device; falls back to datasheet."""
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats:
        total = int(
            stats.get("bytes_limit")
            or stats.get("bytes_reservable_limit")
            or 0
        )
        used = int(stats.get("bytes_in_use", 0))
        if total:
            return total, used
    return _datasheet_hbm(getattr(device, "device_kind", "")), 0


def detect_accelerators(devices: Optional[list] = None) -> list[AcceleratorStatus]:
    """Enumerate accelerators with HBM accounting.

    Replaces the reference's ``gpudetect.DetectGPUs`` (nvidia-smi CSV parse at
    ``gpudetect.go:77-123``) with a direct runtime query — no subprocess, no
    parsing, works identically under the CPU simulator used in tests.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    driver = f"jax-{jax.__version__}"
    out = []
    for d in devices:
        kind = getattr(d, "device_kind", "cpu")
        platform = getattr(d, "platform", "cpu")
        is_tpu = platform in ("tpu", "axon") or "tpu" in kind.lower() or tpu_generation(kind) != "unknown"
        total, used = _memory_stats(d)
        coords = getattr(d, "coords", None)
        out.append(
            AcceleratorStatus(
                index=d.id,
                vendor="tpu" if is_tpu else platform,
                arch=tpu_generation(kind) if is_tpu else platform,
                device_kind=kind,
                total_memory_bytes=total,
                used_memory_bytes=used,
                free_memory_bytes=max(total - used, 0),
                core_on_chip=getattr(d, "num_cores", 1) if not isinstance(getattr(d, "num_cores", 1), property) else 1,
                process_index=d.process_index,
                coords=tuple(coords) if coords is not None else None,
                driver=driver,
            )
        )
    return out


def total_hbm_bytes(devices: Optional[list] = None) -> int:
    """Aggregate HBM across visible chips (residency-manager budget)."""
    return sum(a.total_memory_bytes for a in detect_accelerators(devices))


@functools.lru_cache(maxsize=1)
def platform_name() -> str:
    import jax

    return jax.devices()[0].platform


def live_hbm_bytes(device=None) -> int:
    """Bytes currently held live on ``device`` (default: first device)."""
    import jax

    d = device if device is not None else jax.devices()[0]
    _, used = _memory_stats(d)
    return used
