"""helix-tpu CLI.

The counterpart of the reference's cobra CLI (``api/cmd/helix/root.go:45-72``
— serve/apply/chat/...), argparse-based:

- ``serve``      — control plane (router, profiles, heartbeats, sessions,
                   OpenAI passthrough).  Reference: ``helix serve``.
- ``serve-node`` — TPU node agent: applies a serving profile as in-process
  Engines and exposes the OpenAI surface.  Replaces the reference's sandbox
  node stack (compose-manager + inference-proxy + heartbeat).
- ``profile``    — validate / describe profile YAML (composeparse analogue).
- ``chat``       — one-shot chat against a server (reference: ``helix chat``).
- ``bench``      — run the standard benchmark.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_serve_node(args) -> int:
    from aiohttp import web

    from helix_tpu.control.node_agent import NodeAgent
    from helix_tpu.control.profile import ServingProfile
    from helix_tpu.serving.openai_api import OpenAIServer

    agent = NodeAgent(
        runner_id=args.runner_id,
        heartbeat_url=args.control_plane,
        heartbeat_interval=args.heartbeat_interval,
        address=args.advertise or f"http://127.0.0.1:{args.port}",
    )
    if args.profile:
        with open(args.profile) as f:
            profile = ServingProfile.from_yaml(f.read())
        state = agent.apply_profile(profile)
        if state.status == "failed":
            print(f"profile apply failed: {state.error}", file=sys.stderr)
            return 1
        print(f"profile '{profile.name}' running: {state.models}")
    if args.control_plane:
        agent.start_heartbeat(poll_assignment=not args.profile)
    server = OpenAIServer(agent.registry)
    app = server.build_app()

    # expose agent state for the control plane / debugging
    async def state_handler(request):
        return web.json_response(agent.heartbeat_payload())

    app.router.add_get("/api/v1/state", state_handler)
    print(f"helix-tpu node listening on {args.host}:{args.port}")
    web.run_app(app, host=args.host, port=args.port, print=None)
    return 0


def _cmd_serve(args) -> int:
    from aiohttp import web

    from helix_tpu.control.server import ControlPlane

    cp = ControlPlane(db_path=args.db)
    print(f"helix-tpu control plane listening on {args.host}:{args.port}")
    web.run_app(cp.build_app(), host=args.host, port=args.port, print=None)
    return 0


def _cmd_profile(args) -> int:
    from helix_tpu.control.profile import ServingProfile

    with open(args.file) as f:
        profile = ServingProfile.from_yaml(f.read())
    errors = profile.validate()
    out = {
        "name": profile.name,
        "models": profile.model_names,
        "requirement": profile.requirement.to_dict(),
        "valid": not errors,
        "errors": errors,
    }
    print(json.dumps(out, indent=2))
    return 0 if not errors else 1


def _cmd_chat(args) -> int:
    import requests

    r = requests.post(
        f"{args.url}/v1/chat/completions",
        json={
            "model": args.model,
            "messages": [{"role": "user", "content": args.message}],
            "max_tokens": args.max_tokens,
            "temperature": args.temperature,
        },
        timeout=600,
    )
    if r.status_code != 200:
        print(r.text, file=sys.stderr)
        return 1
    print(r.json()["choices"][0]["message"]["content"])
    return 0


def _cmd_bench(args) -> int:
    import runpy

    runpy.run_module("bench", run_name="__main__")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="helix-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    n = sub.add_parser("serve-node", help="run a TPU serving node")
    n.add_argument("--profile", help="profile YAML to apply at boot")
    n.add_argument("--runner-id", default="node-0")
    n.add_argument("--host", default="0.0.0.0")
    n.add_argument("--port", type=int, default=8000)
    n.add_argument("--control-plane", help="control plane base URL")
    n.add_argument("--heartbeat-interval", type=float, default=30.0)
    n.add_argument("--advertise", help="address the control plane dials back")
    n.set_defaults(fn=_cmd_serve_node)

    s = sub.add_parser("serve", help="run the control plane")
    s.add_argument("--host", default="0.0.0.0")
    s.add_argument("--port", type=int, default=8080)
    s.add_argument("--db", default="helix.db")
    s.set_defaults(fn=_cmd_serve)

    pr = sub.add_parser("profile", help="validate a profile YAML")
    pr.add_argument("file")
    pr.set_defaults(fn=_cmd_profile)

    c = sub.add_parser("chat", help="one-shot chat against a server")
    c.add_argument("message")
    c.add_argument("--url", default="http://127.0.0.1:8000")
    c.add_argument("--model", required=True)
    c.add_argument("--max-tokens", type=int, default=256)
    c.add_argument("--temperature", type=float, default=0.0)
    c.set_defaults(fn=_cmd_chat)

    b = sub.add_parser("bench", help="run the standard benchmark")
    b.set_defaults(fn=_cmd_bench)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
