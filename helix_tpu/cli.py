"""helix-tpu CLI.

The counterpart of the reference's cobra CLI (``api/cmd/helix/root.go:45-72``
— serve/apply/chat/...), argparse-based:

- ``serve``      — control plane (router, profiles, heartbeats, sessions,
                   OpenAI passthrough).  Reference: ``helix serve``.
- ``serve-node`` — TPU node agent: applies a serving profile as in-process
  Engines and exposes the OpenAI surface.  Replaces the reference's sandbox
  node stack (compose-manager + inference-proxy + heartbeat).
- ``profile``    — validate / describe profile YAML (composeparse analogue).
- ``chat``       — one-shot chat against a server (reference: ``helix chat``).
- ``bench``      — run the standard benchmark.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_serve_node(args) -> int:
    from aiohttp import web

    from helix_tpu.control.node_agent import NodeAgent
    from helix_tpu.control.profile import ServingProfile
    from helix_tpu.serving.openai_api import OpenAIServer

    tunnel_mode = getattr(args, "tunnel", False)
    if tunnel_mode and not args.control_plane:
        print(
            "serve-node: --tunnel requires --control-plane (the tunnel "
            "dials out to it)", file=sys.stderr,
        )
        return 2
    agent = NodeAgent(
        runner_id=args.runner_id,
        heartbeat_url=args.control_plane,
        heartbeat_interval=args.heartbeat_interval,
        # tunnel mode advertises NO address: the control plane dispatches
        # through the reverse tunnel (NAT'd node, no listening TCP port)
        address=(
            "" if tunnel_mode
            else args.advertise or f"http://127.0.0.1:{args.port}"
        ),
    )

    # control-plane-requested drain (ISSUE 12 autoscale scale-down):
    # once the agent's graceful ladder finishes, deliver SIGTERM to
    # ourselves — both serving modes already translate it into a clean
    # exit 0 (graceful_shutdown is idempotent, the second call returns
    # the recorded stats), so the drained host actually frees itself
    # for the autoscaler to terminate
    def _exit_after_drain():
        import os
        import signal as _signal

        os.kill(os.getpid(), _signal.SIGTERM)

    agent.on_drain = _exit_after_drain
    if args.profile:
        with open(args.profile) as f:
            profile = ServingProfile.from_yaml(f.read())
        state = agent.apply_profile(profile)
        if state.status == "failed":
            print(f"profile apply failed: {state.error}", file=sys.stderr)
            return 1
        print(f"profile '{profile.name}' running: {state.models}")
    if args.control_plane:
        agent.start_heartbeat(poll_assignment=not args.profile)
    server = OpenAIServer(agent.registry)
    app = server.build_app()

    # expose agent state for the control plane / debugging
    async def state_handler(request):
        return web.json_response(agent.heartbeat_payload())

    app.router.add_get("/api/v1/state", state_handler)

    # graceful shutdown (ISSUE 11): SIGTERM/SIGINT (the rolling-restart
    # signals) set `draining` in the heartbeat, drain in-flight streams
    # for HELIX_DRAIN_SECONDS, export survivors to a peer runner, then
    # exit 0 — a restart no longer hard-kills client streams
    async def _graceful(_app):
        import asyncio

        await asyncio.get_running_loop().run_in_executor(
            None, agent.graceful_shutdown
        )

    app.on_shutdown.append(_graceful)

    if tunnel_mode:
        import asyncio
        import os
        import signal
        import tempfile

        from helix_tpu.control.tunnel import TunnelAgent

        sock = getattr(args, "unix_socket", None) or os.path.join(
            tempfile.mkdtemp(prefix="helix-node-"), "openai.sock"
        )

        async def main():
            runner = web.AppRunner(app)
            await runner.setup()
            await web.UnixSite(runner, sock).start()
            print(
                f"helix-tpu node on unix socket {sock}; tunnelling to "
                f"{args.control_plane}"
            )
            ta = TunnelAgent(
                args.runner_id, args.control_plane, unix_socket=sock,
                runner_token=agent.runner_token,
            )
            loop = asyncio.get_running_loop()
            stop = asyncio.Event()
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    loop.add_signal_handler(sig, stop.set)
                except (NotImplementedError, RuntimeError):
                    pass   # non-main thread / platform without signals
            ta_task = asyncio.create_task(ta.run())
            stop_task = asyncio.create_task(stop.wait())
            await asyncio.wait(
                {ta_task, stop_task},
                return_when=asyncio.FIRST_COMPLETED,
            )
            if stop.is_set():
                print("draining before exit (SIGTERM/SIGINT)...")
                await loop.run_in_executor(None, agent.graceful_shutdown)
                ta_task.cancel()
            for t in (ta_task, stop_task):
                t.cancel()

        asyncio.run(main())
        return 0
    import signal

    from aiohttp.web_runner import GracefulExit

    def _sigterm(signum, frame):
        # run_app catches GracefulExit, runs app cleanup (our on_shutdown
        # drain hook included) and returns normally -> exit 0
        raise GracefulExit()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass   # not the main thread (embedded/test use)
    print(f"helix-tpu node listening on {args.host}:{args.port}")
    web.run_app(app, host=args.host, port=args.port, print=None)
    return 0


def _cmd_serve(args) -> int:
    from aiohttp import web

    from helix_tpu.control.server import ControlPlane

    api_host = (
        "127.0.0.1"
        if args.host in ("0.0.0.0", "127.0.0.1", "localhost", "::")
        else args.host
    )
    compute_cfg = None
    if getattr(args, "compute_floor", 0) or getattr(args, "compute_max", 0):
        from helix_tpu.control.compute import ManagerConfig

        compute_cfg = ManagerConfig(
            floor=args.compute_floor,
            max=args.compute_max,
            idle_timeout=args.compute_idle_timeout,
        )
    cp = ControlPlane(
        db_path=args.db,
        sandbox_agents_url=(
            f"http://{api_host}:{args.port}"
            if getattr(args, "sandbox_agents", False)
            else None
        ),
        external_agent_argv=(
            __import__("shlex").split(args.external_agent)
            if getattr(args, "external_agent", "")
            else None
        ),
        compute_cfg=compute_cfg,
    )
    print(f"helix-tpu control plane listening on {args.host}:{args.port}")
    web.run_app(cp.build_app(), host=args.host, port=args.port, print=None)
    return 0


def _cmd_profile(args) -> int:
    from helix_tpu.control.profile import ServingProfile

    with open(args.file) as f:
        profile = ServingProfile.from_yaml(f.read())
    errors = profile.validate()
    out = {
        "name": profile.name,
        "models": profile.model_names,
        "requirement": profile.requirement.to_dict(),
        "valid": not errors,
        "errors": errors,
    }
    print(json.dumps(out, indent=2))
    return 0 if not errors else 1


def _cmd_apply(args) -> int:
    """Apply a helix.yaml app to the control plane (reference:
    ``helix apply -f helix.yaml``, ``api/pkg/cli/apps/local.go``)."""
    import requests

    with open(args.file) as f:
        raw = f.read()
    r = requests.post(
        f"{args.url}/api/v1/apps",
        data=raw,
        headers={"Content-Type": "application/x-yaml"},
        timeout=30,
    )
    if r.status_code != 200:
        print(r.text, file=sys.stderr)
        return 1
    doc = r.json()
    print(f"applied app '{doc['name']}' ({doc['id']})")
    return 0


def _api(args, method: str, path: str, **kw):
    """Authenticated control-plane call shared by the admin verbs
    (reference: the cobra CLI's API client, ``api/pkg/cli/``)."""
    import os

    import requests

    key = getattr(args, "api_key", None) or os.environ.get(
        "HELIX_API_KEY", ""
    )
    headers = kw.pop("headers", {})
    if key:
        headers["Authorization"] = f"Bearer {key}"
    r = requests.request(
        method, f"{args.url}{path}", headers=headers, timeout=60, **kw
    )
    if r.status_code >= 400:
        print(r.text, file=sys.stderr)
        raise SystemExit(1)
    return r.json()


def _cmd_org(args) -> int:
    if args.action == "create":
        doc = _api(args, "POST", "/api/v1/orgs", json={"name": args.name})
        print(f"created org {doc['id']}")
    elif args.action == "list":
        for o in _api(args, "GET", "/api/v1/orgs")["orgs"]:
            print(f"{o['id']}\t{o['name']}")
    elif args.action == "add-member":
        _api(
            args, "POST", f"/api/v1/orgs/{args.org}/members",
            json={"user_id": args.user, "role": args.role},
        )
        print(f"added {args.user} to {args.org} as {args.role}")
    elif args.action == "members":
        for m in _api(
            args, "GET", f"/api/v1/orgs/{args.org}/members"
        )["members"]:
            print(f"{m['user_id']}\t{m['role']}")
    return 0


def _cmd_knowledge(args) -> int:
    if args.action == "list":
        for k in _api(args, "GET", "/api/v1/knowledge")["knowledge"]:
            print(f"{k['id']}\t{k['state']}\tv{k['version']}\t{k['name']}")
    elif args.action == "create":
        body = {"name": args.name}
        if args.path:
            body["path"] = args.path
        if args.urls:
            body["urls"] = args.urls
            if args.crawl_depth:
                body["crawl_depth"] = args.crawl_depth
        doc = _api(args, "POST", "/api/v1/knowledge", json=body)
        print(f"created knowledge {doc['id']} ({doc['state']})")
    elif args.action == "search":
        doc = _api(
            args, "POST", f"/api/v1/knowledge/{args.id}/search",
            json={"query": args.query, "top_k": args.top_k},
        )
        for r in doc["results"]:
            print(f"[{r['score']:.3f}] {r['text'][:120]}")
    elif args.action == "refresh":
        _api(args, "POST", f"/api/v1/knowledge/{args.id}/refresh")
        print("refresh queued")
    elif args.action == "delete":
        _api(args, "DELETE", f"/api/v1/knowledge/{args.id}")
        print("deleted")
    return 0


def _cmd_operator(args) -> int:
    """K8s operator: reconcile AIApp CRs into control-plane apps
    (reference: operator/ kubebuilder controller)."""
    import os
    import time as _time

    from helix_tpu.services.k8s_operator import AIAppReconciler, K8sClient

    if args.kubeconfig_url:
        k8s = K8sClient(args.kubeconfig_url, token=args.k8s_token)
    else:
        k8s = K8sClient.in_cluster()
    rec = AIAppReconciler(
        k8s,
        helix_url=args.api or os.environ.get(
            "HELIX_API_URL", "http://localhost:8080"
        ),
        helix_token=os.environ.get("HELIX_API_TOKEN", ""),
        resync_interval=args.resync,
    ).start()
    print("operator running (ctrl-c to stop)")
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        rec.stop()
    return 0


def _cmd_evals(args) -> int:
    """Evaluation suites/runs (reference: the `evals` verb,
    api/cmd/helix/evals.go, + suite/run routes server.go:1058-1067)."""
    import json as _json

    base = f"/api/v1/apps/{args.app}"
    if args.action == "list":
        for s in _api(args, "GET", f"{base}/evaluation-suites")["suites"]:
            nq = len(s.get("questions", []))
            print(f"{s['id']}\t{nq} questions\t{s.get('name', '')}")
    elif args.action == "create":
        with open(args.file) as f:
            raw = f.read()
        try:
            doc = _json.loads(raw)
        except ValueError:
            import yaml as _yaml

            doc = _yaml.safe_load(raw)
        s = _api(args, "POST", f"{base}/evaluation-suites", json=doc)
        print(f"created suite {s['id']} ({len(s['questions'])} questions)")
    elif args.action == "run":
        run = _api(
            args, "POST", f"{base}/evaluation-suites/{args.id}/runs"
        )
        rid = run["id"]
        print(f"run {rid} started")
        import time as _time

        while True:
            run = _api(args, "GET", f"{base}/evaluation-runs/{rid}")
            if run["status"] in ("completed", "failed", "cancelled"):
                break
            _time.sleep(1.0)
        summ = run.get("summary", {})
        print(
            f"{run['status']}: {summ.get('passed', 0)}/"
            f"{summ.get('total_questions', 0)} passed"
        )
        for r in run.get("results", []):
            mark = "PASS" if r["passed"] else "FAIL"
            print(f"  [{mark}] {r['question'][:70]}")
        return 0 if run["status"] == "completed" and not summ.get(
            "failed", 0
        ) else 1
    elif args.action == "runs":
        for r in _api(
            args, "GET", f"{base}/evaluation-suites/{args.id}/runs"
        )["runs"]:
            summ = r.get("summary", {})
            print(
                f"{r['id']}\t{r['status']}\t"
                f"{summ.get('passed', 0)}/{summ.get('total_questions', 0)}"
            )
    elif args.action == "show":
        print(
            _json.dumps(
                _api(args, "GET", f"{base}/evaluation-runs/{args.id}"),
                indent=2,
            )
        )
    elif args.action == "delete":
        _api(args, "DELETE", f"{base}/evaluation-suites/{args.id}")
        print("deleted")
    return 0


def _cmd_secret(args) -> int:
    if args.action == "set":
        value = args.value
        if value is None:
            import getpass

            value = getpass.getpass(f"value for {args.name}: ")
        _api(
            args, "POST", "/api/v1/secrets",
            json={"name": args.name, "value": value},
        )
        print(f"secret {args.name} stored")
    elif args.action == "list":
        for s in _api(args, "GET", "/api/v1/secrets")["secrets"]:
            print(s["name"])
    elif args.action == "delete":
        _api(args, "DELETE", f"/api/v1/secrets/{args.name}")
        print("deleted")
    return 0


def _cmd_runner(args) -> int:
    if args.action == "list":
        for r in _api(args, "GET", "/api/v1/runners")["runners"]:
            models = ",".join(r["models"]) or "-"
            print(
                f"{r['id']}\t{r['profile_name'] or '-'}\t"
                f"{r['profile_status']}\t{models}"
            )
    elif args.action == "logs":
        doc = _api(
            args, "GET",
            f"/api/v1/runners/{args.id}/logs?tail={args.tail}",
        )
        for entry in doc["logs"]:
            print(entry["line"])
    return 0


def _cmd_config_reference(args) -> int:
    from helix_tpu.config_reference import render

    print(render())
    return 0


def _cmd_chat(args) -> int:
    import requests

    r = requests.post(
        f"{args.url}/v1/chat/completions",
        json={
            "model": args.model,
            "messages": [{"role": "user", "content": args.message}],
            "max_tokens": args.max_tokens,
            "temperature": args.temperature,
        },
        timeout=600,
    )
    if r.status_code != 200:
        print(r.text, file=sys.stderr)
        return 1
    print(r.json()["choices"][0]["message"]["content"])
    return 0


def _cmd_bench(args) -> int:
    import runpy

    runpy.run_module("bench", run_name="__main__")
    return 0


def _cmd_sft(args) -> int:
    """LoRA SFT: the `fine-tune a model from a JSONL dataset` surface the
    reference exposed through fine-tune sessions (axolotl, deleted)."""
    import dataclasses as _dc
    import json as _json

    from helix_tpu.parallel.multihost import (
        MultiHostConfig,
        host_local_slice,
        initialize,
        is_coordinator,
    )

    # join the DCN world BEFORE the first backend query (jax.devices()
    # must span every host for the global mesh)
    # per-field merge: a flag the user passed overrides env; an omitted
    # flag (None default) falls back to env.  None-sentinels matter:
    # --host-rank 0 and --num-hosts 1 are legitimate explicit values.
    env_cfg = MultiHostConfig.from_env()

    def _flag(name, env_val):
        v = getattr(args, name, None)
        return env_val if v is None else v

    mh = MultiHostConfig(
        coordinator=_flag("coordinator", env_cfg.coordinator),
        num_processes=_flag("num_hosts", env_cfg.num_processes),
        process_id=_flag("host_rank", env_cfg.process_id),
    )
    distributed = initialize(mh)

    import jax

    from helix_tpu.device.mesh import default_mesh_spec, build_mesh
    from helix_tpu.models.common import CATALOG, ModelConfig
    from helix_tpu.models.llama import init_params, param_logical_axes
    from helix_tpu.parallel.sharding import shard_params
    from helix_tpu.serving.tokenizer import load_tokenizer
    from helix_tpu.training.checkpoint import resume_trainer, save_checkpoint
    from helix_tpu.training.data import load_jsonl, pack_examples
    from helix_tpu.training.lora import LoraConfig
    from helix_tpu.training.sft import SFTConfig, SFTTrainer

    tokenizer = load_tokenizer(args.checkpoint, args.model)
    if args.checkpoint:
        from helix_tpu.models.loader import load_params

        model_cfg, params = load_params(args.checkpoint)
    else:
        model_cfg = CATALOG.get(args.model) or ModelConfig.tiny(name=args.model)
        params = init_params(model_cfg, jax.random.PRNGKey(0))

    n_dev = len(jax.devices())
    mesh = None
    if distributed:
        from helix_tpu.parallel.multihost import global_mesh_spec

        mesh = build_mesh(global_mesh_spec())
        params = shard_params(params, mesh, param_logical_axes(model_cfg))
    elif n_dev > 1:
        mesh = build_mesh(default_mesh_spec(n_dev))
        params = shard_params(params, mesh, param_logical_axes(model_cfg))

    cfg = SFTConfig(
        lora=LoraConfig(rank=args.rank, alpha=args.alpha),
        learning_rate=args.lr,
        total_steps=args.steps,
        batch_size=args.batch_size,
        seq_len=args.seq_len,
    )
    rank0 = not distributed or is_coordinator()
    trainer = SFTTrainer(model_cfg, params, cfg, mesh=mesh)
    if args.resume and args.output:
        if resume_trainer(trainer, args.output) and rank0:
            print(f"resumed from step {trainer.step_num}")

    examples = load_jsonl(args.data, tokenizer)
    if rank0:
        print(f"loaded {len(examples)} examples")

    def batches():
        epoch = 0
        while True:
            for b in pack_examples(
                examples, cfg.batch_size, cfg.seq_len, shuffle_seed=epoch
            ):
                if distributed:
                    # every host packs the same deterministic global batch
                    # and feeds only its own rows (dp-outermost layout)
                    b = _dc.replace(b, **{
                        f.name: host_local_slice(
                            getattr(b, f.name), mh.process_id,
                            mh.num_processes,
                        )
                        for f in _dc.fields(b)
                    })
                yield b
            epoch += 1

    def on_log(m):
        if rank0:
            print(_json.dumps(m), flush=True)   # one log stream (rank 0)

    def on_step(step):
        if args.output and step % args.save_every == 0:
            # checkpoint save is a cross-process collective (every rank
            # writes its addressable shards + a sync barrier) — it MUST
            # run on all hosts, to a shared filesystem.  Fired from the
            # per-step hook so --save-every is honoured exactly, not
            # only when it happens to align with --log-every.
            save_checkpoint(
                args.output, trainer.step_num, trainer.lora_params,
                trainer.opt_state,
                lora_scaling=trainer.cfg.lora.scaling,
            )

    trainer.train(
        batches(), log_every=args.log_every, on_log=on_log, on_step=on_step
    )
    if args.output:
        # all ranks participate in the (collective) save; rank 0 narrates
        save_checkpoint(
            args.output, trainer.step_num, trainer.lora_params,
            trainer.opt_state,
            lora_scaling=trainer.cfg.lora.scaling,
        )
        if rank0:
            print(f"saved adapters to {args.output}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="helix-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    n = sub.add_parser("serve-node", help="run a TPU serving node")
    n.add_argument("--profile", help="profile YAML to apply at boot")
    n.add_argument("--runner-id", default="node-0")
    n.add_argument("--host", default="0.0.0.0")
    n.add_argument("--port", type=int, default=8000)
    n.add_argument("--control-plane", help="control plane base URL")
    n.add_argument("--heartbeat-interval", type=float, default=30.0)
    n.add_argument("--advertise", help="address the control plane dials back")
    n.add_argument(
        "--tunnel", action="store_true",
        help="no listening TCP port: serve on a unix socket and dial an "
             "outbound reverse tunnel to the control plane (NAT'd nodes)",
    )
    n.add_argument("--unix-socket", help="socket path for --tunnel mode")
    n.set_defaults(fn=_cmd_serve_node)

    s = sub.add_parser("serve", help="run the control plane")
    s.add_argument("--host", default="0.0.0.0")
    s.add_argument("--port", type=int, default=8080)
    s.add_argument("--db", default="helix.db")
    s.add_argument(
        "--sandbox-agents", action="store_true",
        help="run spec-task agents in isolated resource-limited "
             "subprocesses instead of in-process",
    )
    s.add_argument(
        "--external-agent", default="",
        help="drive a third-party ACP coding-agent CLI for spec tasks "
             "(e.g. 'claude-code-acp'); overrides --sandbox-agents",
    )
    s.add_argument(
        "--compute-floor", type=int, default=0,
        help="autoscaler: minimum provisioned hosts (stub provider "
             "unless one is wired programmatically)",
    )
    s.add_argument("--compute-max", type=int, default=0,
                   help="autoscaler: hard host ceiling (0 = floor only)")
    s.add_argument("--compute-idle-timeout", type=float, default=600.0,
                   help="autoscaler: idle seconds before shedding a host")
    s.set_defaults(fn=_cmd_serve)

    db = sub.add_parser(
        "desktop-bridge",
        help="guest agent: serve this process's GUI desktop to a "
             "control plane (runs inside a sandbox)",
    )
    db.add_argument("--control-plane", required=True)
    db.add_argument("--name", default="bridged-desktop")
    db.add_argument("--fps", type=float, default=10.0)
    db.add_argument("--api-key", default="")

    def _cmd_desktop_bridge(args):
        from helix_tpu.desktop.bridge import main as bridge_main

        argv = ["--control-plane", args.control_plane,
                "--name", args.name, "--fps", str(args.fps)]
        if args.api_key:
            argv += ["--api-key", args.api_key]
        return bridge_main(argv)

    db.set_defaults(fn=_cmd_desktop_bridge)

    ts = sub.add_parser(
        "tts-server",
        help="run the TTS sidecar (/v1/audio/speech, Klatt backend)",
    )
    ts.add_argument("--port", type=int, default=8444)

    def _cmd_tts(args):
        import asyncio as _asyncio

        from aiohttp import web as _web

        from helix_tpu.services.tts import TTSService

        async def main():
            runner = _web.AppRunner(TTSService().build_app())
            await runner.setup()
            await _web.TCPSite(runner, "0.0.0.0", args.port).start()
            print(f"tts-server on :{args.port}")
            while True:
                await _asyncio.sleep(3600)

        _asyncio.run(main())
        return 0

    ts.set_defaults(fn=_cmd_tts)

    pr = sub.add_parser("profile", help="validate a profile YAML")
    pr.add_argument("file")
    pr.set_defaults(fn=_cmd_profile)

    ap = sub.add_parser("apply", help="apply a helix.yaml app")
    ap.add_argument("-f", "--file", required=True)
    ap.add_argument("--url", default="http://127.0.0.1:8080")
    ap.set_defaults(fn=_cmd_apply)

    c = sub.add_parser("chat", help="one-shot chat against a server")
    c.add_argument("message")
    c.add_argument("--url", default="http://127.0.0.1:8000")
    c.add_argument("--model", required=True)
    c.add_argument("--max-tokens", type=int, default=256)
    c.add_argument("--temperature", type=float, default=0.0)
    c.set_defaults(fn=_cmd_chat)

    # shared --url/--api-key live on every ACTION subparser (parents=)
    # so the natural `helix org list --url ...` order works
    api_flags = argparse.ArgumentParser(add_help=False)
    api_flags.add_argument("--url", default="http://127.0.0.1:8080")
    api_flags.add_argument(
        "--api-key", help="bearer key (or HELIX_API_KEY)"
    )

    o = sub.add_parser("org", help="org administration")
    osub = o.add_subparsers(dest="action", required=True)
    oc = osub.add_parser("create", parents=[api_flags])
    oc.add_argument("name")
    osub.add_parser("list", parents=[api_flags])
    om = osub.add_parser("add-member", parents=[api_flags])
    om.add_argument("org")
    om.add_argument("user")
    om.add_argument("--role", default="member")
    ol = osub.add_parser("members", parents=[api_flags])
    ol.add_argument("org")
    o.set_defaults(fn=_cmd_org)

    k = sub.add_parser("knowledge", help="knowledge sources")
    ksub = k.add_subparsers(dest="action", required=True)
    ksub.add_parser("list", parents=[api_flags])
    kc = ksub.add_parser("create", parents=[api_flags])
    kc.add_argument("name")
    kc.add_argument("--path")
    kc.add_argument("--urls", nargs="*")
    kc.add_argument("--crawl-depth", type=int, default=0)
    ks = ksub.add_parser("search", parents=[api_flags])
    ks.add_argument("id")
    ks.add_argument("query")
    ks.add_argument("--top-k", type=int, default=5)
    kr = ksub.add_parser("refresh", parents=[api_flags])
    kr.add_argument("id")
    kd = ksub.add_parser("delete", parents=[api_flags])
    kd.add_argument("id")
    k.set_defaults(fn=_cmd_knowledge)

    op = sub.add_parser(
        "operator", help="K8s operator: reconcile AIApp CRs into apps"
    )
    op.add_argument("--api", default="", help="control plane URL")
    op.add_argument("--kubeconfig-url", default="",
                    help="K8s API URL (empty = in-cluster config)")
    op.add_argument("--k8s-token", default="")
    op.add_argument("--resync", type=float, default=30.0)
    op.set_defaults(fn=_cmd_operator)

    ev = sub.add_parser("evals", help="evaluate an app with a test suite")
    evsub = ev.add_subparsers(dest="action", required=True)
    for act, extra in (
        ("list", ()), ("create", ("file",)), ("run", ("id",)),
        ("runs", ("id",)), ("show", ("id",)), ("delete", ("id",)),
    ):
        ep = evsub.add_parser(act, parents=[api_flags])
        ep.add_argument("--app", required=True, help="app id")
        for a in extra:
            ep.add_argument(a)
    ev.set_defaults(fn=_cmd_evals)

    se = sub.add_parser("secret", help="user secrets")
    sesub = se.add_subparsers(dest="action", required=True)
    ss = sesub.add_parser("set", parents=[api_flags])
    ss.add_argument("name")
    ss.add_argument("value", nargs="?")
    sesub.add_parser("list", parents=[api_flags])
    sd = sesub.add_parser("delete", parents=[api_flags])
    sd.add_argument("name")
    se.set_defaults(fn=_cmd_secret)

    ru = sub.add_parser("runner", help="runner administration")
    rusub = ru.add_subparsers(dest="action", required=True)
    rusub.add_parser("list", parents=[api_flags])
    rl = rusub.add_parser("logs", parents=[api_flags])
    rl.add_argument("id")
    rl.add_argument("--tail", type=int, default=200)
    ru.set_defaults(fn=_cmd_runner)

    cr = sub.add_parser(
        "config-reference",
        help="print every HELIX_* environment variable the runtime reads",
    )
    cr.set_defaults(fn=_cmd_config_reference)

    b = sub.add_parser("bench", help="run the standard benchmark")
    b.set_defaults(fn=_cmd_bench)

    t = sub.add_parser("sft", help="LoRA supervised fine-tune from JSONL")
    t.add_argument("--data", required=True, help="JSONL dataset path")
    t.add_argument("--model", default="tiny", help="catalogue model name")
    t.add_argument("--checkpoint", help="HF checkpoint dir (weights+tokenizer)")
    t.add_argument("--output", help="adapter checkpoint dir")
    t.add_argument("--resume", action="store_true")
    t.add_argument("--rank", type=int, default=16)
    t.add_argument("--alpha", type=float, default=32.0)
    t.add_argument("--lr", type=float, default=1e-4)
    t.add_argument("--steps", type=int, default=100)
    t.add_argument("--batch-size", type=int, default=8)
    t.add_argument("--seq-len", type=int, default=1024)
    t.add_argument("--save-every", type=int, default=50)
    t.add_argument("--log-every", type=int, default=10)
    t.add_argument("--coordinator", default=None,
                   help="multi-host: process 0's host:port (DCN world)")
    t.add_argument("--num-hosts", type=int, default=None)
    t.add_argument("--host-rank", type=int, default=None)
    t.set_defaults(fn=_cmd_sft)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
