"""Paged decode attention: one query token per sequence over the page pool.

This is the decode-loop hot op (SURVEY.md §7 hard part #1) — the reference
gets it from vLLM's PagedAttention CUDA kernels inside its containers; here
it is TPU-owned:

- ``paged_decode_attention_reference`` — XLA gather-based oracle: gathers
  each sequence's pages, masks beyond its length, plain softmax.  Correct
  everywhere; bandwidth-wasteful (gathers ``max_pages`` per seq).
- ``paged_decode_attention`` — Pallas kernel (``helix_tpu/ops/paged_kernel``)
  that walks only the pages each sequence actually uses, page table
  scalar-prefetched into SMEM, double-buffered HBM->VMEM DMA.

Length convention: ``lengths[b]`` = number of PAST tokens in the cache for
sequence b (the current token's position).  The current token's K/V arrive
as ``k_new``/``v_new`` and are appended logically at slot ``lengths[b]`` —
the engine scatters them into pages *after* the forward pass, so the kernel
must include them itself (write-after-attend keeps the model functional).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from helix_tpu.ops.attention import DEFAULT_MASK_VALUE


def paged_decode_attention_reference(
    q,            # [B, H, D]
    k_pages,      # [KVH, N, P, D]
    v_pages,
    page_tables,  # [B, maxP] int32
    lengths,      # [B] int32 — past tokens in cache
    k_new=None,   # [B, KVH, D] current token's K (logically at slot lengths[b])
    v_new=None,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    B, H, D = q.shape
    KVH, N, P, _ = k_pages.shape
    maxP = page_tables.shape[1]
    group = H // KVH
    scale = scale if scale is not None else 1.0 / math.sqrt(D)

    # Gather each sequence's pages: [KVH, B, maxP, P, D] -> [B, KVH, T, D]
    T = maxP * P
    kg = (
        k_pages[:, page_tables]
        .reshape(KVH, B, T, D)
        .transpose(1, 0, 2, 3)
        .astype(jnp.float32)
    )
    vg = (
        v_pages[:, page_tables]
        .reshape(KVH, B, T, D)
        .transpose(1, 0, 2, 3)
        .astype(jnp.float32)
    )
    valid = jnp.arange(T)[None, :] < lengths[:, None]  # [B, T]
    if k_new is not None:
        kg = jnp.concatenate(
            [kg, k_new[:, :, None, :].astype(jnp.float32)], axis=2
        )
        vg = jnp.concatenate(
            [vg, v_new[:, :, None, :].astype(jnp.float32)], axis=2
        )
        valid = jnp.concatenate([valid, jnp.ones((B, 1), bool)], axis=1)

    qg = q.reshape(B, KVH, group, D).astype(jnp.float32)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, kg) * scale
    s = jnp.where(valid[:, None, None, :], s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgt,bktd->bkgd", p, vg)
    return out.reshape(B, H, D).astype(q.dtype)


def paged_decode_attention(
    q,
    k_pages,
    v_pages,
    page_tables,
    lengths,
    k_new=None,
    v_new=None,
    *,
    scale: Optional[float] = None,
    backend: Optional[str] = None,
):
    """Dispatcher: Pallas kernel on TPU, reference elsewhere."""
    if backend is None:
        platform = jax.devices()[0].platform
        backend = "pallas" if platform in ("tpu", "axon") else "reference"
    if backend == "pallas":
        from helix_tpu.ops.paged_kernel import paged_decode_attention_tpu

        return paged_decode_attention_tpu(
            q, k_pages, v_pages, page_tables, lengths, k_new, v_new,
            scale=scale,
        )
    return paged_decode_attention_reference(
        q, k_pages, v_pages, page_tables, lengths, k_new, v_new, scale=scale
    )
