"""Paged decode attention: one query token per sequence over the page pool.

This is the decode-loop hot op (SURVEY.md §7 hard part #1) — the reference
gets it from vLLM's PagedAttention CUDA kernels inside its containers; here
it is TPU-owned:

- ``paged_decode_attention_reference`` — XLA gather-based oracle over one
  layer's pages: gathers each sequence's pages, masks beyond its length,
  plain softmax.  Correct everywhere; bandwidth-wasteful (gathers
  ``max_pages`` per seq).
- ``paged_decode_attention`` — attend-and-write over the FULL pool
  (``[L, N, P, KVH, D]``): Pallas kernel (``helix_tpu/ops/paged_kernel``)
  that walks only the pages each sequence actually uses, one whole-page
  ``[P, KVH, D]`` DMA per page, and writes the current token's K/V into its
  page in-place (pool aliased through the call) — the decode loop contains
  NO scatter, so XLA never relays the pool out (the r3 trace showed the
  external-scatter design spending ~40% of each decode window transposing
  the pool).  Returns ``(out, k_pages, v_pages, k_scale, v_scale)``.

Int8 pools: pass the per-(slot, head) f32 scale pools (``k_scale`` /
``v_scale``, shape ``[L, N, P, KVH]``) and both paths dequantize
in-register right after the page fetch — HBM traffic stays at 1 byte/elem.
The current token's K/V is quantized through the SAME codec before both
the attention fold-in and the page write, so decode at step t+1 reads
exactly the values step t attended over.

Length convention: ``lengths[b]`` = number of PAST tokens in the cache for
sequence b (the current token's position).  The current token's K/V arrive
as ``k_new``/``v_new``; the kernel folds them into attention as a virtual
final block AND persists them at slot ``lengths[b]`` of the page table.
Inactive slots (``active[b] == 0``) read nothing (their tables may point at
reallocated pages) and write to the garbage page 0.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from helix_tpu.ops.attention import DEFAULT_MASK_VALUE


def paged_decode_attention_reference(
    q,            # [B, H, D]
    k_pages,      # [N, P, KVH, D] — ONE layer's pages
    v_pages,
    page_tables,  # [B, maxP] int32
    lengths,      # [B] int32 — past tokens in cache
    k_new=None,   # [B, KVH, D] current token's K (logically at slot lengths[b])
    v_new=None,
    *,
    scale: Optional[float] = None,
    k_scale=None,  # [N, P, KVH] f32 — ONE layer's scale pool (int8 pages)
    v_scale=None,
) -> jax.Array:
    B, H, D = q.shape
    N, P, KVH, _ = k_pages.shape
    maxP = page_tables.shape[1]
    group = H // KVH
    scale = scale if scale is not None else 1.0 / math.sqrt(D)

    # Gather each sequence's pages: [B, maxP, P, KVH, D] -> [B, KVH, T, D]
    T = maxP * P
    kg = k_pages[page_tables].astype(jnp.float32)
    vg = v_pages[page_tables].astype(jnp.float32)
    if k_scale is not None:
        kg = kg * k_scale[page_tables].astype(jnp.float32)[..., None]
        vg = vg * v_scale[page_tables].astype(jnp.float32)[..., None]
    kg = kg.reshape(B, T, KVH, D).transpose(0, 2, 1, 3)
    vg = vg.reshape(B, T, KVH, D).transpose(0, 2, 1, 3)
    valid = jnp.arange(T)[None, :] < lengths[:, None]  # [B, T]
    if k_new is not None:
        kg = jnp.concatenate(
            [kg, k_new[:, :, None, :].astype(jnp.float32)], axis=2
        )
        vg = jnp.concatenate(
            [vg, v_new[:, :, None, :].astype(jnp.float32)], axis=2
        )
        valid = jnp.concatenate([valid, jnp.ones((B, 1), bool)], axis=1)

    qg = q.reshape(B, KVH, group, D).astype(jnp.float32)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, kg) * scale
    s = jnp.where(valid[:, None, None, :], s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgt,bktd->bkgd", p, vg)
    return out.reshape(B, H, D).astype(q.dtype)


def _reference_attend_and_write(
    q, k_pages, v_pages, page_tables, lengths, layer, active, k_new, v_new,
    *, scale, k_scale=None, v_scale=None,
):
    """XLA oracle for the attend-and-write op (CPU tests / non-TPU)."""
    B = q.shape[0]
    L_, N, P, KVH, D = k_pages.shape
    kp_l = k_pages[layer]
    vp_l = v_pages[layer]
    ks_l = None if k_scale is None else k_scale[layer]
    vs_l = None if v_scale is None else v_scale[layer]
    kn_s = vn_s = None
    if k_scale is not None:
        # quantize the current token through the SAME codec the write
        # persists, and fold the dequantized values into attention — the
        # virtual final block then matches what later steps read back
        from helix_tpu.ops.quant import dequantize_kv, quantize_kv

        k_new, kn_s = quantize_kv(k_new)
        v_new, vn_s = quantize_kv(v_new)
        k_att = dequantize_kv(k_new, kn_s)
        v_att = dequantize_kv(v_new, vn_s)
    else:
        k_att, v_att = k_new, v_new
    # inactive slots must not attend over their (possibly reallocated)
    # pages: zero their length
    lengths_eff = lengths * active
    out = paged_decode_attention_reference(
        q, kp_l, vp_l, page_tables, lengths_eff, k_att, v_att,
        scale=scale, k_scale=ks_l, v_scale=vs_l,
    )
    # persist the current token: flat token index into [N*P]; inactive
    # slots land on garbage page 0
    pidx = jnp.take_along_axis(
        page_tables, (lengths // P)[:, None], axis=1
    )[:, 0]
    flat = jnp.where(active > 0, pidx * P + lengths % P, 0)
    kp_l = kp_l.reshape(N * P, KVH, D).at[flat].set(
        k_new.astype(k_pages.dtype), mode="drop"
    ).reshape(N, P, KVH, D)
    vp_l = vp_l.reshape(N * P, KVH, D).at[flat].set(
        v_new.astype(v_pages.dtype), mode="drop"
    ).reshape(N, P, KVH, D)
    k_pages = k_pages.at[layer].set(kp_l)
    v_pages = v_pages.at[layer].set(vp_l)
    if k_scale is not None:
        ks_l = ks_l.reshape(N * P, KVH).at[flat].set(
            kn_s, mode="drop"
        ).reshape(N, P, KVH)
        vs_l = vs_l.reshape(N * P, KVH).at[flat].set(
            vn_s, mode="drop"
        ).reshape(N, P, KVH)
        k_scale = k_scale.at[layer].set(ks_l)
        v_scale = v_scale.at[layer].set(vs_l)
    return out, k_pages, v_pages, k_scale, v_scale


def paged_decode_attention(
    q,            # [B, H, D]
    k_pages,      # [L, N, P, KVH, D] — FULL pool
    v_pages,
    page_tables,  # [B, maxP]
    lengths,      # [B]
    layer,        # scalar int32 — which layer's pages to use
    active,       # [B] int32 — 0 = parked slot (no read, garbage write)
    k_new,        # [B, KVH, D]
    v_new,
    *,
    scale: Optional[float] = None,
    backend: Optional[str] = None,
    k_scale=None,  # [L, N, P, KVH] f32 — int8 pools' scale pools
    v_scale=None,
):
    """Attend one query token per sequence over its pages and persist the
    token's K/V — pool in, pool out (aliased in-place on TPU).

    Returns ``(out, k_pages, v_pages, k_scale, v_scale)``; the scale pools
    are ``None`` when the pool is full-precision.

    Dispatcher: Pallas kernel on TPU, XLA reference elsewhere.
    """
    if backend is None:
        platform = jax.devices()[0].platform
        backend = "pallas" if platform in ("tpu", "axon") else "reference"
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if backend == "pallas":
        from helix_tpu.ops.paged_kernel import paged_decode_attention_tpu

        return paged_decode_attention_tpu(
            q, k_pages, v_pages, page_tables, lengths, layer, active,
            k_new, v_new, scale=scale, k_scale=k_scale, v_scale=v_scale,
        )
    return _reference_attend_and_write(
        q, k_pages, v_pages, page_tables, lengths, layer, active,
        k_new, v_new, scale=scale, k_scale=k_scale, v_scale=v_scale,
    )
