"""Ragged paged attention: ONE op for every device-step caller.

This is the serving engine's only attention over the page pool (SURVEY.md
§7 hard part #1) — the reference gets the decode case from vLLM's
PagedAttention CUDA kernels inside its containers; here the op is
TPU-owned AND generalized the way the Ragged Paged Attention paper
(PAPERS.md) argues for: per-row sequence metadata instead of one compiled
shape per caller.

- ``ragged_paged_attention`` — the dispatcher.  Queries arrive as a flat
  token axis ``[T, H, D]`` carved into up to R **rows** (one row = one
  sequence's fresh tokens this call): ``t0[r]``/``q_len[r]`` delimit row
  r's tokens, ``hist[r]`` is its pages-resident history length, and
  ``tables[r]`` its page-table row.  Every engine caller is a metadata
  assignment over this one contract:

  * plain decode — R slots, ``q_len`` 1 each, ``hist`` = position;
  * speculative verify — ``q_len`` = 1 + drafted tokens (ragged);
  * packed / cache-hit prefill — one row per admitted prompt,
    ``hist`` = its prefix-cache-resident tokens (0 for a cold prompt);
  * chunked prefill — one row, ``q_len`` = chunk, ``hist`` = chunk start;
  * the mixed step — prefill rows and decode rows in the same call.

- ``ragged_paged_attention_reference`` — XLA gather-based oracle: gathers
  each row's pages, masks beyond its history, and runs the plain-softmax
  ``mha_reference`` with segment ids (row identity) + absolute positions
  (causality).  Correct everywhere; bandwidth-wasteful (gathers
  ``max_pages`` per row).
- ``ragged_paged_attention_tpu`` (``helix_tpu/ops/paged_kernel``) — the
  Pallas kernel: walks ONLY the pages each row actually uses (ragged over
  rows), one whole-page ``[P, KVH, D]`` DMA per page, 8-token query
  blocks, int8 dequantization in-register after the page fetch.

- ``paged_decode_attention_reference`` is kept as the decode-shaped
  numerics oracle for tests (one query token per sequence, no fresh-token
  self-attention plumbing).

Semantics shared by both backends:

- token t of row r sits at absolute position ``hist[r] + (t - t0[r])``;
  it attends the row's pages-resident history ``[0, hist[r])`` plus the
  row's fresh tokens up to and including itself (causal).  Fresh K/V are
  attended RAW (as given) — exactly what the pre-unification prefill and
  verify paths did; persistence into pages is the caller's separate
  ``write_kv`` scatter.
- rows never see each other: cross-row attention is masked (the packed-
  prefill segment contract).
- a row with ``q_len[r] == 0`` is unused; tokens outside every row
  produce unspecified output the caller must ignore.
- int8 pools: pass the per-(slot, head) f32 scale pools (``k_scale`` /
  ``v_scale``, ``[L, N, P, KVH]``); history dequantizes in-register right
  after the page fetch — HBM traffic stays at 1 byte/elem.

Layout contract (both backends): ``t0`` is ascending and rows are
disjoint; rows may start at any offset (the Pallas kernel pads the flat
axis internally so its 8-token query blocks never DMA out of bounds).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from helix_tpu.ops.attention import DEFAULT_MASK_VALUE, mha_reference
from helix_tpu.parallel.ring_attention import _merge_stats


def paged_decode_attention_reference(
    q,            # [B, H, D]
    k_pages,      # [N, P, KVH, D] — ONE layer's pages
    v_pages,
    page_tables,  # [B, maxP] int32
    lengths,      # [B] int32 — past tokens in cache
    k_new=None,   # [B, KVH, D] current token's K (logically at slot lengths[b])
    v_new=None,
    *,
    scale: Optional[float] = None,
    k_scale=None,  # [N, P, KVH] f32 — ONE layer's scale pool (int8 pages)
    v_scale=None,
) -> jax.Array:
    B, H, D = q.shape
    N, P, KVH, _ = k_pages.shape
    maxP = page_tables.shape[1]
    group = H // KVH
    scale = scale if scale is not None else 1.0 / math.sqrt(D)

    # Gather each sequence's pages: [B, maxP, P, KVH, D] -> [B, KVH, T, D]
    T = maxP * P
    kg = k_pages[page_tables].astype(jnp.float32)
    vg = v_pages[page_tables].astype(jnp.float32)
    if k_scale is not None:
        kg = kg * k_scale[page_tables].astype(jnp.float32)[..., None]
        vg = vg * v_scale[page_tables].astype(jnp.float32)[..., None]
    kg = kg.reshape(B, T, KVH, D).transpose(0, 2, 1, 3)
    vg = vg.reshape(B, T, KVH, D).transpose(0, 2, 1, 3)
    valid = jnp.arange(T)[None, :] < lengths[:, None]  # [B, T]
    if k_new is not None:
        kg = jnp.concatenate(
            [kg, k_new[:, :, None, :].astype(jnp.float32)], axis=2
        )
        vg = jnp.concatenate(
            [vg, v_new[:, :, None, :].astype(jnp.float32)], axis=2
        )
        valid = jnp.concatenate([valid, jnp.ones((B, 1), bool)], axis=1)

    qg = q.reshape(B, KVH, group, D).astype(jnp.float32)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, kg) * scale
    s = jnp.where(valid[:, None, None, :], s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgt,bktd->bkgd", p, vg)
    return out.reshape(B, H, D).astype(q.dtype)


def _row_of_tokens(t0, q_len, T: int):
    """Per-token row assignment from ascending disjoint row extents.

    Returns ``(row, q_off)``: ``row[t]`` is the owning row id or -1 for
    tokens outside every row; ``q_off[t]`` the token's offset within its
    row (garbage where ``row < 0``)."""
    t = jnp.arange(T)
    # last row whose start is <= t (t0 ascending)
    cand = jnp.sum((t[:, None] >= t0[None, :]).astype(jnp.int32), axis=1) - 1
    cand = jnp.clip(cand, 0, t0.shape[0] - 1)
    start = t0[cand]
    in_row = (t >= start) & (t < start + q_len[cand])
    return jnp.where(in_row, cand, -1), t - start


def _cold_chunk_stats(q, row, cold_k, cold_v, cold_row, cold_len, *,
                      scale, k_scale=None, v_scale=None):
    """Online-softmax stats of the flat queries vs. staged cold chunks.

    ``cold_k``/``cold_v`` are ONE layer's staged cold-middle chunks
    ``[nC, Ct, KVH, D]`` (pool dtype; ``k_scale``/``v_scale`` are the
    matching ``[nC, Ct, KVH]`` scale slabs for int8 pools), ``cold_row``
    the owning flat-axis row per chunk (-1 = padding chunk) and
    ``cold_len`` the valid token count per chunk.  A ``lax.scan`` in
    ascending chunk order folds each chunk's blockwise stats into a
    running ``(m, l, acc)`` with the exact ``ring_attention`` combine —
    the deterministic merge order is what keeps tiered runs reproducible.
    Cold tokens all precede every live query (they are the demoted middle
    of the history), so no causal mask is needed: ownership + chunk
    length decide visibility.  Returns fp32 ``(m [1,H,T,1], l, acc
    [1,H,T,D])``.
    """
    T, H, D = q.shape
    KVH = cold_k.shape[2]
    qf = q[None].astype(jnp.float32)                     # [1, T, H, D]
    acc0 = jnp.zeros((1, H, T, D), jnp.float32)
    l0 = jnp.zeros((1, H, T, 1), jnp.float32)
    m0 = l0 - jnp.inf

    def fold(carry, xs):
        m, l, acc = carry
        ck, cv, crow, clen, cks, cvs = xs                # [Ct, KVH, D], ...
        if cks is not None:
            ck = ck.astype(jnp.float32) * cks[..., None]
            cv = cv.astype(jnp.float32) * cvs[..., None]
        ck = ck.astype(q.dtype)
        cv = cv.astype(q.dtype)
        if KVH != H:
            ck = jnp.repeat(ck, H // KVH, axis=1)
            cv = jnp.repeat(cv, H // KVH, axis=1)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk",
            qf,
            ck[None].astype(jnp.float32),
        ) * scale
        ok = (row[:, None] == crow) & (row[:, None] >= 0)  # [T, 1]
        ok = ok & (jnp.arange(ck.shape[0])[None, :] < clen)
        s = jnp.where(ok[None, None], s, DEFAULT_MASK_VALUE)
        bm = jnp.max(s, axis=-1, keepdims=True)
        bp = jnp.exp(s - bm)
        bl = jnp.sum(bp, axis=-1, keepdims=True)
        bacc = jnp.einsum(
            "bhqk,bkhd->bhqd", bp, cv[None].astype(jnp.float32)
        )
        return _merge_stats(m, l, acc, bm, bl, bacc), None

    xs = (cold_k, cold_v, cold_row, cold_len, k_scale, v_scale)
    (m, l, acc), _ = jax.lax.scan(fold, (m0, l0, acc0), xs)
    return m, l, acc


def ragged_paged_attention_reference(
    q,            # [T, H, D] flat fresh queries
    k_new,        # [T, KVH, D] fresh K/V, attended raw
    v_new,
    k_pages,      # [L, N, P, KVH, D] — FULL pool
    v_pages,
    layer,        # scalar int32 — which layer's pages to read
    t0,           # [R] int32 — row r's first flat token (ascending)
    q_len,        # [R] int32 — row r's fresh-token count (0 = unused)
    hist,         # [R] int32 — row r's pages-resident history tokens
    tables,       # [R, maxP] int32 — row r's page table
    *,
    scale: Optional[float] = None,
    k_scale=None,  # [L, N, P, KVH] f32 — int8 pools' scale pools
    v_scale=None,
    span_lo=None,  # [R] int32 — first cold (non-resident) history token
    span_hi=None,  # [R] int32 — one past the last cold history token
    cold_k=None,   # [L, nC, Ct, KVH, D] staged cold-middle chunks
    cold_v=None,
    cold_row=None,     # [nC] int32 — owning row per chunk (-1 = padding)
    cold_len=None,     # [nC] int32 — valid tokens per chunk
    cold_k_scale=None,  # [L, nC, Ct, KVH] f32 — int8 chunk scales
    cold_v_scale=None,
) -> jax.Array:
    """XLA oracle for the ragged contract: gather every row's pages, build
    one segment-masked kv axis (R histories + the fresh tokens) and run
    the plain-softmax oracle.  Numerics match the pre-unification callers:
    history dequantized then cast to the compute dtype, fresh K/V raw,
    masked positions at ``DEFAULT_MASK_VALUE`` (``exp`` → exactly 0.0, so
    the gather's fixed ``maxP`` width cannot perturb live sums).

    Tiered KV residency (``span_lo``/``span_hi`` + ``cold_*``): row r's
    history tokens in ``[span_lo[r], span_hi[r])`` are NOT pages-resident
    (their table entries were demoted to the host tier and point at
    garbage) — they are excluded from the hot gather's mask and instead
    attended from the staged cold chunks via the online-softmax
    ``(m, l, acc)`` combine, chunks first in ascending order, then the
    hot+fresh block, so one deterministic merge reproduces the monolithic
    masked softmax over the identical values.  ``span_lo == span_hi == 0``
    rows are fully resident and unaffected; with no tiered arguments the
    legacy single-softmax path runs byte-identically.
    """
    T, H, D = q.shape
    R, maxP = tables.shape
    _, N, P, KVH, _ = k_pages.shape
    Hs = maxP * P
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    row, q_off = _row_of_tokens(t0, q_len, T)
    q_pos = jnp.where(row >= 0, hist[jnp.clip(row, 0)] + q_off, 0)

    kp_l = k_pages[layer]
    vp_l = v_pages[layer]
    kh = kp_l[tables]                       # [R, maxP, P, KVH, D]
    vh = vp_l[tables]
    if k_scale is not None:
        kh = kh.astype(jnp.float32) * k_scale[layer][tables][..., None]
        vh = vh.astype(jnp.float32) * v_scale[layer][tables][..., None]
    kh = kh.astype(q.dtype).reshape(1, R * Hs, KVH, D)
    vh = vh.astype(q.dtype).reshape(1, R * Hs, KVH, D)
    hist_tok = jnp.arange(Hs)
    resident = hist_tok[None, :] < hist[:, None]          # [R, Hs]
    if span_lo is not None:
        cold = (hist_tok[None, :] >= span_lo[:, None]) & (
            hist_tok[None, :] < span_hi[:, None]
        )
        resident = resident & ~cold
    kv_seg_h = jnp.where(
        resident,
        jnp.arange(R)[:, None] + 1,
        0,
    ).reshape(1, R * Hs)
    kv_pos_h = jnp.broadcast_to(hist_tok[None, :], (R, Hs)).reshape(
        1, R * Hs
    )
    k_all = jnp.concatenate([kh, k_new.astype(q.dtype)[None]], axis=1)
    v_all = jnp.concatenate([vh, v_new.astype(q.dtype)[None]], axis=1)
    seg_fresh = jnp.where(row >= 0, row + 1, 0)
    kv_seg = jnp.concatenate([kv_seg_h, seg_fresh[None]], axis=1)
    kv_pos = jnp.concatenate([kv_pos_h, q_pos[None]], axis=1)
    if cold_k is None:
        out = mha_reference(
            q[None], k_all, v_all,
            causal=True,
            q_positions=q_pos[None],
            kv_positions=kv_pos,
            q_segment_ids=seg_fresh[None],
            kv_segment_ids=kv_seg,
            scale=scale,
        )
        return out[0]

    # Streamed path: cold chunk stats first (ascending chunk order), then
    # the hot + fresh block's stats, one final combine.  Same masked
    # logits as ``mha_reference`` would build for the hot block.
    cm, cl, cacc = _cold_chunk_stats(
        q, row, cold_k[layer], cold_v[layer], cold_row, cold_len,
        scale=scale,
        k_scale=None if cold_k_scale is None else cold_k_scale[layer],
        v_scale=None if cold_v_scale is None else cold_v_scale[layer],
    )
    kf = k_all if k_all.shape[2] == H else jnp.repeat(
        k_all, H // KVH, axis=2
    )
    vf = v_all if v_all.shape[2] == H else jnp.repeat(
        v_all, H // KVH, axis=2
    )
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q[None].astype(jnp.float32),
        kf.astype(jnp.float32),
    ) * scale
    mask = q_pos[None][:, None, :, None] >= kv_pos[:, None, None, :]
    mask = mask & (
        seg_fresh[None][:, None, :, None] == kv_seg[:, None, None, :]
    )
    s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    hm = jnp.max(s, axis=-1, keepdims=True)
    hp = jnp.exp(s - hm)
    hl = jnp.sum(hp, axis=-1, keepdims=True)
    hacc = jnp.einsum("bhqk,bkhd->bhqd", hp, vf.astype(jnp.float32))
    m, l, acc = _merge_stats(cm, cl, cacc, hm, hl, hacc)
    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l).transpose(0, 2, 1, 3)                 # [1, T, H, D]
    return out[0].astype(q.dtype)


def ragged_paged_attention(
    q,            # [T, H, D] flat fresh queries across all rows
    k_new,        # [T, KVH, D] fresh K/V (attended raw; caller persists)
    v_new,
    k_pages,      # [L, N, P, KVH, D] — FULL pool
    v_pages,
    layer,        # scalar int32
    t0,           # [R] int32 — row starts (ascending; 8-aligned on pallas)
    q_len,        # [R] int32 — fresh tokens per row (0 = unused row)
    hist,         # [R] int32 — pages-resident history tokens per row
    tables,       # [R, maxP] int32
    *,
    scale: Optional[float] = None,
    backend: Optional[str] = None,
    k_scale=None,  # [L, N, P, KVH] f32 — int8 pools' scale pools
    v_scale=None,
    span_lo=None,  # [R] tiered rows: cold history span start (tokens)
    span_hi=None,
    cold_k=None,   # [L, nC, Ct, KVH, D] staged cold-middle chunks
    cold_v=None,
    cold_row=None,
    cold_len=None,
    cold_k_scale=None,
    cold_v_scale=None,
):
    """THE paged-attention entry point: every device-step caller (packed/
    chunk prefill, decode, mixed, spec-verify) is a metadata assignment
    over this one contract.  Returns ``out [T, H, D]``.

    Dispatcher: Pallas kernel on TPU, XLA gather oracle elsewhere.
    Tiered-residency metadata (``span_lo``/``cold_*``) routes to the
    reference path on every backend: the Pallas kernel walks resident
    pages only and has no carried-stats entry point yet, and silently
    dropping the cold middle would be wrong KV — the fallback is the
    honest degrade until the kernel grows the combine.
    """
    tiered = cold_k is not None or span_lo is not None
    if backend is None:
        platform = jax.devices()[0].platform
        backend = "pallas" if platform in ("tpu", "axon") else "reference"
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if backend == "pallas" and not tiered:
        from helix_tpu.ops.paged_kernel import ragged_paged_attention_tpu

        return ragged_paged_attention_tpu(
            q, k_new, v_new, k_pages, v_pages, layer, t0, q_len, hist,
            tables, scale=scale, k_scale=k_scale, v_scale=v_scale,
        )
    return ragged_paged_attention_reference(
        q, k_new, v_new, k_pages, v_pages, layer, t0, q_len, hist,
        tables, scale=scale, k_scale=k_scale, v_scale=v_scale,
        span_lo=span_lo, span_hi=span_hi,
        cold_k=cold_k, cold_v=cold_v,
        cold_row=cold_row, cold_len=cold_len,
        cold_k_scale=cold_k_scale, cold_v_scale=cold_v_scale,
    )
