"""Ragged paged attention: ONE op for every device-step caller.

This is the serving engine's only attention over the page pool (SURVEY.md
§7 hard part #1) — the reference gets the decode case from vLLM's
PagedAttention CUDA kernels inside its containers; here the op is
TPU-owned AND generalized the way the Ragged Paged Attention paper
(PAPERS.md) argues for: per-row sequence metadata instead of one compiled
shape per caller.

- ``ragged_paged_attention`` — the dispatcher.  Queries arrive as a flat
  token axis ``[T, H, D]`` carved into up to R **rows** (one row = one
  sequence's fresh tokens this call): ``t0[r]``/``q_len[r]`` delimit row
  r's tokens, ``hist[r]`` is its pages-resident history length, and
  ``tables[r]`` its page-table row.  Every engine caller is a metadata
  assignment over this one contract:

  * plain decode — R slots, ``q_len`` 1 each, ``hist`` = position;
  * speculative verify — ``q_len`` = 1 + drafted tokens (ragged);
  * packed / cache-hit prefill — one row per admitted prompt,
    ``hist`` = its prefix-cache-resident tokens (0 for a cold prompt);
  * chunked prefill — one row, ``q_len`` = chunk, ``hist`` = chunk start;
  * the mixed step — prefill rows and decode rows in the same call.

- ``ragged_paged_attention_reference`` — XLA gather-based oracle: gathers
  each row's pages, masks beyond its history, and runs the plain-softmax
  ``mha_reference`` with segment ids (row identity) + absolute positions
  (causality).  Correct everywhere; bandwidth-wasteful (gathers
  ``max_pages`` per row).
- ``ragged_paged_attention_tpu`` (``helix_tpu/ops/paged_kernel``) — the
  Pallas kernel: walks ONLY the pages each row actually uses (ragged over
  rows), one whole-page ``[P, KVH, D]`` DMA per page, 8-token query
  blocks, int8 dequantization in-register after the page fetch.

- ``paged_decode_attention_reference`` is kept as the decode-shaped
  numerics oracle for tests (one query token per sequence, no fresh-token
  self-attention plumbing).

Semantics shared by both backends:

- token t of row r sits at absolute position ``hist[r] + (t - t0[r])``;
  it attends the row's pages-resident history ``[0, hist[r])`` plus the
  row's fresh tokens up to and including itself (causal).  Fresh K/V are
  attended RAW (as given) — exactly what the pre-unification prefill and
  verify paths did; persistence into pages is the caller's separate
  ``write_kv`` scatter.
- rows never see each other: cross-row attention is masked (the packed-
  prefill segment contract).
- a row with ``q_len[r] == 0`` is unused; tokens outside every row
  produce unspecified output the caller must ignore.
- int8 pools: pass the per-(slot, head) f32 scale pools (``k_scale`` /
  ``v_scale``, ``[L, N, P, KVH]``); history dequantizes in-register right
  after the page fetch — HBM traffic stays at 1 byte/elem.

Layout contract (both backends): ``t0`` is ascending and rows are
disjoint; rows may start at any offset (the Pallas kernel pads the flat
axis internally so its 8-token query blocks never DMA out of bounds).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from helix_tpu.ops.attention import DEFAULT_MASK_VALUE, mha_reference


def paged_decode_attention_reference(
    q,            # [B, H, D]
    k_pages,      # [N, P, KVH, D] — ONE layer's pages
    v_pages,
    page_tables,  # [B, maxP] int32
    lengths,      # [B] int32 — past tokens in cache
    k_new=None,   # [B, KVH, D] current token's K (logically at slot lengths[b])
    v_new=None,
    *,
    scale: Optional[float] = None,
    k_scale=None,  # [N, P, KVH] f32 — ONE layer's scale pool (int8 pages)
    v_scale=None,
) -> jax.Array:
    B, H, D = q.shape
    N, P, KVH, _ = k_pages.shape
    maxP = page_tables.shape[1]
    group = H // KVH
    scale = scale if scale is not None else 1.0 / math.sqrt(D)

    # Gather each sequence's pages: [B, maxP, P, KVH, D] -> [B, KVH, T, D]
    T = maxP * P
    kg = k_pages[page_tables].astype(jnp.float32)
    vg = v_pages[page_tables].astype(jnp.float32)
    if k_scale is not None:
        kg = kg * k_scale[page_tables].astype(jnp.float32)[..., None]
        vg = vg * v_scale[page_tables].astype(jnp.float32)[..., None]
    kg = kg.reshape(B, T, KVH, D).transpose(0, 2, 1, 3)
    vg = vg.reshape(B, T, KVH, D).transpose(0, 2, 1, 3)
    valid = jnp.arange(T)[None, :] < lengths[:, None]  # [B, T]
    if k_new is not None:
        kg = jnp.concatenate(
            [kg, k_new[:, :, None, :].astype(jnp.float32)], axis=2
        )
        vg = jnp.concatenate(
            [vg, v_new[:, :, None, :].astype(jnp.float32)], axis=2
        )
        valid = jnp.concatenate([valid, jnp.ones((B, 1), bool)], axis=1)

    qg = q.reshape(B, KVH, group, D).astype(jnp.float32)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, kg) * scale
    s = jnp.where(valid[:, None, None, :], s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bkgt,bktd->bkgd", p, vg)
    return out.reshape(B, H, D).astype(q.dtype)


def _row_of_tokens(t0, q_len, T: int):
    """Per-token row assignment from ascending disjoint row extents.

    Returns ``(row, q_off)``: ``row[t]`` is the owning row id or -1 for
    tokens outside every row; ``q_off[t]`` the token's offset within its
    row (garbage where ``row < 0``)."""
    t = jnp.arange(T)
    # last row whose start is <= t (t0 ascending)
    cand = jnp.sum((t[:, None] >= t0[None, :]).astype(jnp.int32), axis=1) - 1
    cand = jnp.clip(cand, 0, t0.shape[0] - 1)
    start = t0[cand]
    in_row = (t >= start) & (t < start + q_len[cand])
    return jnp.where(in_row, cand, -1), t - start


def ragged_paged_attention_reference(
    q,            # [T, H, D] flat fresh queries
    k_new,        # [T, KVH, D] fresh K/V, attended raw
    v_new,
    k_pages,      # [L, N, P, KVH, D] — FULL pool
    v_pages,
    layer,        # scalar int32 — which layer's pages to read
    t0,           # [R] int32 — row r's first flat token (ascending)
    q_len,        # [R] int32 — row r's fresh-token count (0 = unused)
    hist,         # [R] int32 — row r's pages-resident history tokens
    tables,       # [R, maxP] int32 — row r's page table
    *,
    scale: Optional[float] = None,
    k_scale=None,  # [L, N, P, KVH] f32 — int8 pools' scale pools
    v_scale=None,
) -> jax.Array:
    """XLA oracle for the ragged contract: gather every row's pages, build
    one segment-masked kv axis (R histories + the fresh tokens) and run
    the plain-softmax oracle.  Numerics match the pre-unification callers:
    history dequantized then cast to the compute dtype, fresh K/V raw,
    masked positions at ``DEFAULT_MASK_VALUE`` (``exp`` → exactly 0.0, so
    the gather's fixed ``maxP`` width cannot perturb live sums)."""
    T, H, D = q.shape
    R, maxP = tables.shape
    _, N, P, KVH, _ = k_pages.shape
    Hs = maxP * P
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    row, q_off = _row_of_tokens(t0, q_len, T)
    q_pos = jnp.where(row >= 0, hist[jnp.clip(row, 0)] + q_off, 0)

    kp_l = k_pages[layer]
    vp_l = v_pages[layer]
    kh = kp_l[tables]                       # [R, maxP, P, KVH, D]
    vh = vp_l[tables]
    if k_scale is not None:
        kh = kh.astype(jnp.float32) * k_scale[layer][tables][..., None]
        vh = vh.astype(jnp.float32) * v_scale[layer][tables][..., None]
    kh = kh.astype(q.dtype).reshape(1, R * Hs, KVH, D)
    vh = vh.astype(q.dtype).reshape(1, R * Hs, KVH, D)
    hist_tok = jnp.arange(Hs)
    kv_seg_h = jnp.where(
        hist_tok[None, :] < hist[:, None],
        jnp.arange(R)[:, None] + 1,
        0,
    ).reshape(1, R * Hs)
    kv_pos_h = jnp.broadcast_to(hist_tok[None, :], (R, Hs)).reshape(
        1, R * Hs
    )
    k_all = jnp.concatenate([kh, k_new.astype(q.dtype)[None]], axis=1)
    v_all = jnp.concatenate([vh, v_new.astype(q.dtype)[None]], axis=1)
    seg_fresh = jnp.where(row >= 0, row + 1, 0)
    kv_seg = jnp.concatenate([kv_seg_h, seg_fresh[None]], axis=1)
    kv_pos = jnp.concatenate([kv_pos_h, q_pos[None]], axis=1)
    out = mha_reference(
        q[None], k_all, v_all,
        causal=True,
        q_positions=q_pos[None],
        kv_positions=kv_pos,
        q_segment_ids=seg_fresh[None],
        kv_segment_ids=kv_seg,
        scale=scale,
    )
    return out[0]


def ragged_paged_attention(
    q,            # [T, H, D] flat fresh queries across all rows
    k_new,        # [T, KVH, D] fresh K/V (attended raw; caller persists)
    v_new,
    k_pages,      # [L, N, P, KVH, D] — FULL pool
    v_pages,
    layer,        # scalar int32
    t0,           # [R] int32 — row starts (ascending; 8-aligned on pallas)
    q_len,        # [R] int32 — fresh tokens per row (0 = unused row)
    hist,         # [R] int32 — pages-resident history tokens per row
    tables,       # [R, maxP] int32
    *,
    scale: Optional[float] = None,
    backend: Optional[str] = None,
    k_scale=None,  # [L, N, P, KVH] f32 — int8 pools' scale pools
    v_scale=None,
):
    """THE paged-attention entry point: every device-step caller (packed/
    chunk prefill, decode, mixed, spec-verify) is a metadata assignment
    over this one contract.  Returns ``out [T, H, D]``.

    Dispatcher: Pallas kernel on TPU, XLA gather oracle elsewhere.
    """
    if backend is None:
        platform = jax.devices()[0].platform
        backend = "pallas" if platform in ("tpu", "axon") else "reference"
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if backend == "pallas":
        from helix_tpu.ops.paged_kernel import ragged_paged_attention_tpu

        return ragged_paged_attention_tpu(
            q, k_new, v_new, k_pages, v_pages, layer, t0, q_len, hist,
            tables, scale=scale, k_scale=k_scale, v_scale=v_scale,
        )
    return ragged_paged_attention_reference(
        q, k_new, v_new, k_pages, v_pages, layer, t0, q_len, hist,
        tables, scale=scale, k_scale=k_scale, v_scale=v_scale,
    )
