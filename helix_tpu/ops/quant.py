"""Weight-only int8 quantization.

Fits Llama-3-8B (16.06 GB bf16 — over a v5e chip's 16 GiB HBM) on a single
chip and halves weight HBM traffic, which is the decode bottleneck.  The
reference reaches the same goal by passing ``--quantization`` flags to vLLM
containers; here it is a pytree transform:

- per-output-channel absmax scales (fp32), symmetric, no zero point;
- matmul runs ``x_bf16 @ cast(w_int8 -> bf16)`` then scales the output —
  the cast happens in VMEM after the (halved) HBM fetch, so bandwidth wins
  are kept while the MXU stays in its well-tuned bf16 path;
- norms/biases stay bf16 (negligible bytes, precision-critical).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


# Floor on KV quantization scales: keeps all-zero (never-written) page
# slots exactly representable and the dequant multiply finite.
KV_SCALE_EPS = 1e-8


def quantize_kv(x: jax.Array):
    """Symmetric int8 KV quantization with per-(token-slot, kv-head)
    fp32 scales — absmax over the trailing head_dim axis only.

    Per-slot (not whole-page) granularity is what makes incremental
    decode writes safe: appending a token never has to requantize the
    page's existing slots against a new scale, it just writes its own
    ``[KVH, D]`` codes plus a ``[KVH]`` scale row.

    x: ``[..., KVH, D]`` -> (int8 ``[..., KVH, D]``, f32 ``[..., KVH]``).
    """
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(absmax / 127.0, KV_SCALE_EPS)
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jax.Array, scale: jax.Array, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv` (broadcasts the per-head scale over
    head_dim)."""
    out = q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
    return out.astype(dtype)


def quantize_tensor(w: jax.Array):
    """Symmetric int8, per-output-channel (last axis) scales.

    Stacked-layer weights ``[L, in, out]`` keep independent scales per layer
    (reduce over the contraction axes only, never the leading layer axis).
    Returns {"weight": int8 array, "scale": f32}.
    """
    wf = w.astype(jnp.float32)
    # reduce ONLY the contraction (input) axis: leading axes are batch
    # dims (stacked layers, stacked experts) that must keep independent
    # scales — reducing over experts would let one loud expert crush the
    # quantization levels of the others
    reduce_axes = (w.ndim - 2,)
    absmax = jnp.max(jnp.abs(wf), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"weight": q, "scale": scale.astype(jnp.float32)}


def quantize_params(params: Any) -> Any:
    """Quantize every matmul weight in a model tree; embedding rows get
    per-row scales (lookup then rescale)."""

    def walk(tree, path=()):
        if isinstance(tree, dict):
            out = {}
            for k, v in tree.items():
                if (
                    k == "weight"
                    and hasattr(v, "ndim")
                    and v.ndim >= 2
                    and not any("norm" in p for p in path)
                ):
                    if path and path[-1] == "embed":
                        # embedding: quantize per row (axis -1 reduce)
                        wf = v.astype(jnp.float32)
                        absmax = jnp.max(jnp.abs(wf), axis=-1, keepdims=True)
                        scale = jnp.maximum(absmax / 127.0, 1e-8)
                        q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(
                            jnp.int8
                        )
                        out["weight"] = q
                        out["embed_scale"] = scale.astype(jnp.float32)
                    else:
                        qd = quantize_tensor(v)
                        out["weight"] = qd["weight"]
                        out["scale"] = qd["scale"]
                else:
                    out[k] = walk(v, path + (k,))
            return out
        return tree

    return walk(params)


def quantized_logical_axes(axes_tree: Any) -> Any:
    """Transform a logical-axes tree matching the *unquantized* param layout
    (``models.llama.param_logical_axes``) into one matching
    ``quantize_params``' output layout, so int8 trees can be sharded with
    ``parallel.sharding.shard_params`` / used as jit out_shardings.

    Mirrors the walk in ``quantize_params``: every quantized ``weight``
    gains a ``scale`` whose reduced (contraction) axes are replicated and
    whose output-channel axis keeps the weight's sharding — the dequant
    multiply then needs no extra collectives.  Embeddings gain a per-row
    ``embed_scale`` sharded like the vocab axis.
    """

    def walk(tree, path=()):
        if isinstance(tree, dict):
            out = {}
            for k, v in tree.items():
                if (
                    k == "weight"
                    and isinstance(v, tuple)
                    and len(v) >= 2
                    and not any("norm" in p for p in path)
                ):
                    out["weight"] = v
                    if path and path[-1] == "embed":
                        out["embed_scale"] = (v[0], None)
                    else:
                        out["scale"] = tuple(
                            a if i == len(v) - 1 else None
                            for i, a in enumerate(v)
                        )
                else:
                    out[k] = walk(v, path + (k,))
            return out
        return tree

    return walk(axes_tree)


def maybe_dequant_dense(x, p: dict, adapter_ids=None, compute_dtype=None):
    """Dense through a weight dict {weight[, scale, bias, lora_a/lora_b,
    lora_pool_a/lora_pool_b/lora_pool_scale]}.

    Handles int8 weight-only dequant, a single grafted LoRA adapter
    (``helix_tpu.training.lora`` — the merge-at-apply fallback), and the
    batched multi-LoRA pool (``helix_tpu.engine.adapters``) in one place
    so every projection in every model family composes with all three.

    The pool path is BGMV-style: ``lora_pool_a [N, in, r]`` /
    ``lora_pool_b [N, r, out]`` stack N adapter slots (slot 0 = the
    zero identity adapter) and ``adapter_ids [..., S]`` names each
    token's slot; the per-slot low-rank products are masked by the
    token's one-hot slot selection BEFORE the B matmul, so summing over
    N recovers exactly ``scale[g] * (x_t @ A[g]) @ B[g]`` per token —
    two dense rank-sized einsums on the MXU, no per-token weight
    gathers.  Rows at slot 0 contribute an exact ``+0.0``, keeping
    greedy outputs for adapter-free traffic bit-identical."""
    compute_dtype = compute_dtype or x.dtype
    w = p["weight"]
    scale = p.get("scale")
    cdims = (((x.ndim - 1,), (0,)), ((), ()))
    # int8 weights feed the dot directly (mixed-precision dot_general):
    # XLA:TPU converts the int8 operand in VMEM after the (halved) HBM
    # fetch, ~20% faster than an explicit astype which can materialise a
    # converted copy outside the dot fusion.
    out = jax.lax.dot_general(
        x, w, cdims, preferred_element_type=jnp.float32,
    )
    if scale is not None:
        out = out * scale.reshape((1,) * (out.ndim - 1) + (-1,))
    if "lora_a" in p:
        low = jax.lax.dot_general(
            x, p["lora_a"].astype(compute_dtype), cdims,
            preferred_element_type=jnp.float32,
        )
        out = out + p["lora_scale"] * jax.lax.dot_general(
            low.astype(compute_dtype), p["lora_b"].astype(compute_dtype),
            cdims, preferred_element_type=jnp.float32,
        )
    if adapter_ids is not None and "lora_pool_a" in p:
        pa = p["lora_pool_a"].astype(compute_dtype)   # [N, in, r]
        pb = p["lora_pool_b"].astype(compute_dtype)   # [N, r, out]
        psc = p["lora_pool_scale"]                    # [N] f32
        n_slots = pa.shape[0]
        onehot = jax.nn.one_hot(
            adapter_ids, n_slots, dtype=jnp.float32
        )                                             # [..., S, N]
        low = jnp.einsum(
            "...si,nir->...snr", x, pa,
            preferred_element_type=jnp.float32,
        )
        # mask by slot selection: only the token's own adapter row
        # survives, so the n-sum in the second einsum IS the gather
        low = (low * onehot[..., None]).astype(compute_dtype)
        delta = jnp.einsum(
            "...snr,nro->...so", low, pb,
            preferred_element_type=jnp.float32,
        )
        tok_scale = jnp.einsum(
            "...sn,n->...s", onehot, psc.astype(jnp.float32)
        )
        out = out + tok_scale[..., None] * delta
    b = p.get("bias")
    if b is not None:
        out = out + b.astype(jnp.float32)
    return out.astype(compute_dtype)


def embed_lookup(p: dict, tokens, compute_dtype):
    """Embedding lookup through a possibly row-quantized table."""
    w = p["weight"]
    emb = w[tokens]
    if w.dtype == jnp.int8:
        emb = emb.astype(jnp.float32) * p["embed_scale"][tokens]
    return emb.astype(compute_dtype)
