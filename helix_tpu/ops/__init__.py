from helix_tpu.ops.norms import rms_norm, layer_norm
from helix_tpu.ops.rope import apply_rope, rope_frequencies
from helix_tpu.ops.attention import flash_attention, mha_reference

__all__ = [
    "rms_norm",
    "layer_norm",
    "apply_rope",
    "rope_frequencies",
    "flash_attention",
    "mha_reference",
]
