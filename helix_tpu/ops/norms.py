"""Normalisation ops.

Kept as plain XLA: norm -> matmul chains fuse well under the TPU compiler
(elementwise ops fold into the adjacent MXU op's epilogue), so a Pallas
kernel here would only pessimise scheduling.  Accumulation is fp32 even for
bf16 activations — matches what the MXU wants and avoids bf16 variance
underflow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6, offset: float = 0.0):
    """RMSNorm (Llama/Qwen style). ``offset=1.0`` gives Gemma's (1+w) form."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * (offset + weight.astype(jnp.float32))).astype(dtype)


def layer_norm(x, weight, bias=None, eps: float = 1e-5):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
    normed = (xf - mean) * (var + eps) ** -0.5
    out = normed * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)
