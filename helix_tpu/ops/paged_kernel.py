"""Pallas TPU ragged paged-decode attention kernel (attend-and-write).

Per-sequence decode attention that walks ONLY the pages each sequence
actually uses (ragged over the batch), instead of gathering
``max_pages_per_seq`` like the XLA reference path — the design of Ragged
Paged Attention (PAPERS.md) specialised to decode:

- Page tables, lengths, active flags and the layer index are
  **scalar-prefetched into SMEM**, so DMA source addresses are computed
  before the kernel body runs.
- The pool is ``[L, N, P, KVH, D]``: one ``(layer, page)`` slice is a
  contiguous ``[P, KVH, D]`` block, fetched HBM -> VMEM in ONE
  double-buffered async DMA carrying every kv head (the previous
  head-major pool needed ``KVH`` separate 4 KB DMAs per page — 8x the
  descriptor traffic).
- Grid is ``(B,)``: each program owns one sequence and computes all
  ``KVH`` head groups from the same VMEM-resident chunk.
- Online softmax in fp32; the current token's K/V is folded in as a final
  virtual block, then **persisted into its page by an in-kernel DMA**
  (pool aliased input->output) — the decode loop needs no external
  scatter, which is what kept XLA from relaying the pool (r3 trace: ~40%
  of each decode window went to those layout copies).
- **Int8 pools**: when scale pools ride along, pages stream to VMEM as
  int8 (half the bf16 HBM bytes) together with their ``[P, KVH]`` f32
  scale rows, and dequantization happens **in-register** right before the
  score dot — the MXU still sees fp32 operands.  The current token is
  quantized through the same codec on the host side of the pallas_call
  and its codes + scale row are DMA'd into the page, so step t+1 reads
  exactly the values step t attended over.  (The scale buffers' minor dim
  is ``KVH`` — narrower than a 128 lane tile, so Mosaic pads them; they
  are ~``D/4``x smaller than the data buffers, so the padding is noise.)
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from helix_tpu.ops.attention import DEFAULT_MASK_VALUE

# jax renamed these between versions; support both spellings
_MemorySpace = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace
_CompilerParams = (
    getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
)


def _decode_kernel(
    # scalar prefetch
    pt_ref,      # SMEM [B, maxP] int32 page tables
    len_ref,     # SMEM [B] int32 past lengths
    act_ref,     # SMEM [B] int32 active flags
    layer_ref,   # SMEM [1] int32 layer index
    # inputs / outputs / scratch — order depends on ``quantized``:
    #   plain: q, knew, vnew, k_hbm, v_hbm | o, ko_hbm, vo_hbm
    #          | kbuf, vbuf, sems, wsems
    #   quant: q, knew(i8), vnew(i8), kns, vns, k_hbm, v_hbm, ks_hbm,
    #          vs_hbm | o, ko_hbm, vo_hbm, kso_hbm, vso_hbm
    #          | kbuf, vbuf, ksbuf, vsbuf, sems, ssems, wsems
    *refs,
    scale: float,
    page_size: int,
    pages_per_chunk: int,
    max_pages: int,
    kv_heads: int,
    group: int,
    quantized: bool,
):
    if quantized:
        (q_ref, knew_ref, vnew_ref, kns_ref, vns_ref,
         k_hbm, v_hbm, ks_hbm, vs_hbm,
         o_ref, ko_hbm, vo_hbm, kso_hbm, vso_hbm,
         kbuf, vbuf, ksbuf, vsbuf, sems, ssems, wsems) = refs
    else:
        (q_ref, knew_ref, vnew_ref, k_hbm, v_hbm,
         o_ref, ko_hbm, vo_hbm, kbuf, vbuf, sems, wsems) = refs
    b = pl.program_id(0)
    lyr = layer_ref[0]
    P, C, KVH = page_size, pages_per_chunk, kv_heads
    act = act_ref[b]
    # parked slots read nothing: their tables may point at reallocated pages
    L = len_ref[b] * act
    npages = jax.lax.div(L + P - 1, P)
    nchunks = jax.lax.div(npages + C - 1, C)
    max_chunks = (max_pages + C - 1) // C

    def start_chunk(ci, slot):
        for c in range(C):  # static unroll over pages in a chunk
            @pl.when(ci * C + c < npages)
            def _():
                page = pt_ref[b, ci * C + c]
                pltpu.make_async_copy(
                    k_hbm.at[lyr, page],
                    kbuf.at[slot, c],
                    sems.at[slot, c, 0],
                ).start()
                pltpu.make_async_copy(
                    v_hbm.at[lyr, page],
                    vbuf.at[slot, c],
                    sems.at[slot, c, 1],
                ).start()
                if quantized:
                    pltpu.make_async_copy(
                        ks_hbm.at[lyr, page],
                        ksbuf.at[slot, c],
                        ssems.at[slot, c, 0],
                    ).start()
                    pltpu.make_async_copy(
                        vs_hbm.at[lyr, page],
                        vsbuf.at[slot, c],
                        ssems.at[slot, c, 1],
                    ).start()

    def wait_chunk(ci, slot):
        for c in range(C):
            @pl.when(ci * C + c < npages)
            def _():
                page = pt_ref[b, ci * C + c]
                pltpu.make_async_copy(
                    k_hbm.at[lyr, page],
                    kbuf.at[slot, c],
                    sems.at[slot, c, 0],
                ).wait()
                pltpu.make_async_copy(
                    v_hbm.at[lyr, page],
                    vbuf.at[slot, c],
                    sems.at[slot, c, 1],
                ).wait()
                if quantized:
                    pltpu.make_async_copy(
                        ks_hbm.at[lyr, page],
                        ksbuf.at[slot, c],
                        ssems.at[slot, c, 0],
                    ).wait()
                    pltpu.make_async_copy(
                        vs_hbm.at[lyr, page],
                        vsbuf.at[slot, c],
                        ssems.at[slot, c, 1],
                    ).wait()

    q = q_ref[0].astype(jnp.float32)  # [KVH, group, D]
    D = q.shape[-1]
    H = KVH * group

    # Block-diagonal q [H, KVH*D]: query head h occupies the column block
    # of its kv head.  Scores for ALL heads then come from ONE 128-aligned
    # MXU dot against the chunk buffer viewed flat [T, KVH*D] — no
    # per-head strided slices, no 8-way unrolled small dots (the unrolled
    # form cost ~5 ms/step across the 32 layer calls, 30% of the decode
    # step).  The PV dot accumulates [H, KVH*D]; off-block columns hold
    # garbage that the final per-head extraction never reads.
    q_bd_rows = []
    for k in range(KVH):
        row = [jnp.zeros((group, k * D), jnp.float32)] if k else []
        row.append(q[k])
        if k < KVH - 1:
            row.append(jnp.zeros((group, (KVH - 1 - k) * D), jnp.float32))
        q_bd_rows.append(jnp.concatenate(row, axis=1) if len(row) > 1
                         else row[0])
    q_bd = jnp.concatenate(q_bd_rows, axis=0)       # [H, KVH*D]

    # persist the current token's K/V into its page (write-after-nothing:
    # slot lengths[b] is strictly beyond the masked read range, so the
    # attention below never observes this write).  Parked slots write to
    # the garbage page 0 — but their stale position can sit AT page
    # capacity, so clamp the table index before the SMEM read (jnp.where
    # evaluates both branches; an unclamped len//P == maxP reads past the
    # prefetch buffer).
    pt_idx = jnp.minimum(jax.lax.div(len_ref[b], P), max_pages - 1)
    w_page = jnp.where(act > 0, pt_ref[b, pt_idx], 0)
    w_off = jax.lax.rem(len_ref[b], P) * act
    kw = pltpu.make_async_copy(
        knew_ref.at[0], ko_hbm.at[lyr, w_page, w_off], wsems.at[0]
    )
    vw = pltpu.make_async_copy(
        vnew_ref.at[0], vo_hbm.at[lyr, w_page, w_off], wsems.at[1]
    )
    kw.start()
    vw.start()
    if quantized:
        ksw = pltpu.make_async_copy(
            kns_ref.at[0], kso_hbm.at[lyr, w_page, w_off], wsems.at[2]
        )
        vsw = pltpu.make_async_copy(
            vns_ref.at[0], vso_hbm.at[lyr, w_page, w_off], wsems.at[3]
        )
        ksw.start()
        vsw.start()

    @pl.when(nchunks > 0)
    def _():
        start_chunk(0, 0)

    def body(ci, carry):
        m_prev, l_prev, acc_prev = carry    # [H,1], [H,1], [H, KVH*D]
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < nchunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        if quantized:
            # in-register dequant: int8 codes x per-(slot, head) scale —
            # the HBM fetch above moved 1 byte/elem; the MXU sees fp32
            k_flat = (
                kbuf[slot].astype(jnp.float32)
                * ksbuf[slot][..., None]
            ).reshape(C * P, KVH * D)
            v_flat = (
                vbuf[slot].astype(jnp.float32)
                * vsbuf[slot][..., None]
            ).reshape(C * P, KVH * D)
        else:
            k_flat = kbuf[slot].reshape(C * P, KVH * D).astype(jnp.float32)
            v_flat = vbuf[slot].reshape(C * P, KVH * D).astype(jnp.float32)
        token0 = ci * C * P
        tok = token0 + jax.lax.broadcasted_iota(jnp.int32, (1, C * P), 1)
        in_range = tok < L                  # [1, T]
        # un-DMA'd buffer regions (pages past this sequence's length) hold
        # garbage; the softmax weight there is exactly 0, but 0 * NaN
        # still poisons the PV accumulation — zero V explicitly.  (K needs
        # no guard: its scores are overwritten by the mask.  With int8
        # pools the garbage risk lives in the f32 SCALE buffer, which the
        # dequant multiply above has already folded into v_flat — this
        # same guard covers it.)
        v_flat = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, (C * P, 1), 0)
            < L - token0,
            v_flat, 0,
        )

        s = jax.lax.dot_general(
            q_bd, k_flat, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                           # [H, T]
        s = jnp.where(in_range, s, DEFAULT_MASK_VALUE)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc_prev * alpha + jax.lax.dot_general(
            p, v_flat, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                   # [H, KVH*D]
        return m_new, l_new, acc_new

    m0 = jnp.full((H, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((H, 1), jnp.float32)
    acc0 = jnp.zeros((H, KVH * D), jnp.float32)

    def guarded_body(ci, carry):
        return jax.lax.cond(
            ci < nchunks, lambda c: body(ci, c), lambda c: c, carry
        )

    m, l, acc = jax.lax.fori_loop(
        0, max_chunks, guarded_body, (m0, l0, acc0)
    )

    # fold in the current token's K/V (virtual final block, always valid);
    # int8 mode dequantizes the token's own codes so the fold-in matches
    # what the page write persists bit-for-bit
    if quantized:
        knew_flat = (
            knew_ref[0].astype(jnp.float32) * kns_ref[0][..., None]
        ).reshape(KVH * D)
        vnew_flat = (
            vnew_ref[0].astype(jnp.float32) * vns_ref[0][..., None]
        ).reshape(KVH * D)
    else:
        knew_flat = knew_ref[0].reshape(KVH * D).astype(jnp.float32)
        vnew_flat = vnew_ref[0].reshape(KVH * D).astype(jnp.float32)
    s_new = jax.lax.dot_general(
        q_bd, knew_flat[:, None], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                               # [H, 1]
    m_f = jnp.maximum(m, s_new)
    p_new = jnp.exp(s_new - m_f)
    alpha = jnp.exp(m - m_f)
    l_f = alpha * l + p_new
    acc_f = acc * alpha + p_new * vnew_flat[None, :]
    out = acc_f / l_f                       # [H, KVH*D]
    for k in range(KVH):                    # extract each head's block
        o_ref[0, k] = out[
            k * group:(k + 1) * group, k * D:(k + 1) * D
        ].astype(o_ref.dtype)

    kw.wait()
    vw.wait()
    if quantized:
        ksw.wait()
        vsw.wait()


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret")
)
def paged_decode_attention_tpu(
    q,            # [B, H, D]
    k_pages,      # [L, N, P, KVH, D] — FULL pool, aliased through
    v_pages,
    page_tables,  # [B, maxP]
    lengths,      # [B]
    layer,        # scalar int32
    active,       # [B] int32
    k_new,        # [B, KVH, D]
    v_new,
    *,
    scale: Optional[float] = None,
    interpret: bool = False,
    k_scale=None,  # [L, N, P, KVH] f32 — present iff the pool is int8
    v_scale=None,
):
    """Returns ``(out, k_pages, v_pages, k_scale, v_scale)``; the scale
    pools are ``None`` for full-precision pools (pytree structure keys the
    jit trace, so both modes share this entry point)."""
    B, H, D = q.shape
    L, N, P, KVH, _ = k_pages.shape
    maxP = page_tables.shape[1]
    group = H // KVH
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    C = max(1, 128 // P)
    C = min(C, maxP)
    quantized = k_scale is not None

    qg = q.reshape(B, KVH, group, D)
    kernel = functools.partial(
        _decode_kernel,
        scale=scale,
        page_size=P,
        pages_per_chunk=C,
        max_pages=maxP,
        kv_heads=KVH,
        group=group,
        quantized=quantized,
    )
    token_specs = [
        pl.BlockSpec((1, KVH, group, D), lambda b, *_: (b, 0, 0, 0)),
        pl.BlockSpec((1, KVH, D), lambda b, *_: (b, 0, 0)),
        pl.BlockSpec((1, KVH, D), lambda b, *_: (b, 0, 0)),
    ]
    pool_specs = [
        pl.BlockSpec(memory_space=_MemorySpace.ANY),
        pl.BlockSpec(memory_space=_MemorySpace.ANY),
    ]
    if quantized:
        from helix_tpu.ops.quant import quantize_kv

        knew_q, kns = quantize_kv(k_new.reshape(B, KVH, D))
        vnew_q, vns = quantize_kv(v_new.reshape(B, KVH, D))
        in_specs = (
            token_specs
            + [
                pl.BlockSpec((1, KVH), lambda b, *_: (b, 0)),
                pl.BlockSpec((1, KVH), lambda b, *_: (b, 0)),
            ]
            + pool_specs
            + pool_specs   # scale pools stay in ANY/HBM too
        )
        out_shape = [
            jax.ShapeDtypeStruct((B, KVH, group, D), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ]
        out_specs = [
            pl.BlockSpec((1, KVH, group, D), lambda b, *_: (b, 0, 0, 0)),
        ] + pool_specs + pool_specs
        scratch = [
            pltpu.VMEM((2, C, P, KVH, D), k_pages.dtype),
            pltpu.VMEM((2, C, P, KVH, D), v_pages.dtype),
            pltpu.VMEM((2, C, P, KVH), jnp.float32),
            pltpu.VMEM((2, C, P, KVH), jnp.float32),
            pltpu.SemaphoreType.DMA((2, C, 2)),
            pltpu.SemaphoreType.DMA((2, C, 2)),
            pltpu.SemaphoreType.DMA((4,)),
        ]
        # flat input order: pt, len, act, layer, q, knew, vnew, kns, vns,
        # k_pages(9), v_pages(10), k_scale(11), v_scale(12) -> outputs
        # (out, k_pages, v_pages, k_scale, v_scale)
        aliases = {9: 1, 10: 2, 11: 3, 12: 4}
        inputs = (
            qg, knew_q, vnew_q, kns, vns, k_pages, v_pages,
            k_scale, v_scale,
        )
    else:
        in_specs = token_specs + pool_specs
        out_shape = [
            jax.ShapeDtypeStruct((B, KVH, group, D), q.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ]
        out_specs = [
            pl.BlockSpec((1, KVH, group, D), lambda b, *_: (b, 0, 0, 0)),
        ] + pool_specs
        scratch = [
            pltpu.VMEM((2, C, P, KVH, D), k_pages.dtype),
            pltpu.VMEM((2, C, P, KVH, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, C, 2)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
        # flat input order: pt, len, act, layer, q, knew, vnew, k_pages(7),
        # v_pages(8) -> outputs (out, k_pages, v_pages)
        aliases = {7: 1, 8: 2}
        inputs = (
            qg,
            k_new.reshape(B, KVH, D),
            v_new.reshape(B, KVH, D),
            k_pages,
            v_pages,
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    res = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
    )(
        page_tables.astype(jnp.int32),
        lengths.astype(jnp.int32),
        active.astype(jnp.int32),
        jnp.asarray(layer, jnp.int32).reshape(1),
        *inputs,
    )
    if quantized:
        out, kp, vp, ks, vs = res
    else:
        out, kp, vp = res
        ks = vs = None
    return out.reshape(B, H, D), kp, vp, ks, vs
