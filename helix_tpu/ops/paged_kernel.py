"""Pallas TPU ragged paged-attention kernel — ONE kernel for every caller.

The Ragged Paged Attention design (PAPERS.md) specialised to this
engine's page pool: a flat query axis carved into per-sequence **rows**
(row → query start/length, KV history length, page-table row), so packed/
chunk prefill, plain decode, the mixed prefill+decode step and
speculative verify are all metadata assignments over one compiled kernel
instead of one trace family per caller.

- Row metadata (``t0``/``q_len``/``hist``/``tables``) and the layer index
  are **scalar-prefetched into SMEM**, so every DMA source address is
  computed before the kernel body runs.
- The pool is ``[L, N, P, KVH, D]``: one ``(layer, page)`` slice is a
  contiguous ``[P, KVH, D]`` block, fetched HBM -> VMEM in ONE
  double-buffered async DMA carrying every kv head.
- Grid is ``(R, NQ)``: program ``(r, i)`` owns 8-token query block ``i``
  of row ``r`` (programs past the row's ragged length skip everything) and
  computes all ``KVH`` head groups from the same VMEM-resident chunks.
  Rows are ragged: a decode row is 1 token, a verify row ``1+k`` tokens, a
  prefill row a whole chunk — the grid walks ONLY the pages and fresh
  blocks each row actually uses, which is where the padding-waste win
  comes from.
- Online softmax in fp32 over (a) the row's pages-resident history and
  (b) the row's fresh tokens up to the causal limit.  Fresh K/V arrive
  raw (``k_new``/``v_new`` on the flat token axis) and are attended as
  given; persistence into pages is the caller's separate ``write_kv``
  scatter (the flat one-index scatter that keeps the pool's row-major
  layout — see ``engine/kv_cache.py``).
- **Int8 pools**: history pages stream to VMEM as int8 (half the bf16 HBM
  bytes) together with their ``[P, KVH]`` f32 scale rows, and
  dequantization happens **in-register** right before the score dot — the
  MXU still sees fp32 operands.  Fresh tokens are attended at full
  precision (matching the pre-unification prefill/verify numerics); the
  write path quantizes through the shared codec.
- Scores for ALL heads of a q block come from ONE 128-aligned MXU dot:
  the block-diagonal q layout ``[8*H, KVH*D]`` (query head h occupies the
  column block of its kv head) against the chunk buffer viewed flat
  ``[T, KVH*D]`` — no per-head strided slices (the same trick the
  decode-only predecessor kernel used, extended to 8-token q blocks).

Layout contract: rows are disjoint and ascending on the flat axis; rows
may start at ANY offset.  A row's final partial query block writes
garbage into the following flat positions, but the grid iterates rows in
ascending order ("arbitrary" = sequential on TPU), so every later row's
program overwrites its own positions afterwards — and the wrapper pads
the flat axis with 8 tail tokens so the LAST row's spill lands in
scratch, never out of bounds.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from helix_tpu.ops.attention import DEFAULT_MASK_VALUE

# jax renamed these between versions; support both spellings
_MemorySpace = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace
_CompilerParams = (
    getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
)

BQ = 8  # query-block tokens: one f32 sublane tile; bounds ragged waste


def _ragged_kernel(
    # scalar prefetch
    t0_ref,      # SMEM [R] int32 row starts on the flat token axis
    qlen_ref,    # SMEM [R] int32 fresh tokens per row (0 = unused)
    hist_ref,    # SMEM [R] int32 pages-resident history tokens per row
    pt_ref,      # SMEM [R, maxP] int32 page tables
    layer_ref,   # SMEM [1] int32 layer index
    # inputs / outputs / scratch — order depends on ``quantized``:
    #   plain: qf, knf, vnf, k_hbm, v_hbm | o_hbm
    #          | qbuf, kbuf, vbuf, knbuf, vnbuf, obuf, sems, fsems, qsem,
    #            osem
    #   quant: ... + ks_hbm, vs_hbm pools and ksbuf/vsbuf/ssems scratch
    *refs,
    scale: float,
    page_size: int,
    pages_per_chunk: int,
    max_pages: int,
    kv_heads: int,
    group: int,
    quantized: bool,
):
    if quantized:
        (qf, knf, vnf, k_hbm, v_hbm, ks_hbm, vs_hbm,
         o_hbm,
         qbuf, kbuf, vbuf, ksbuf, vsbuf, knbuf, vnbuf, obuf,
         sems, ssems, fsems, qsem, osem) = refs
    else:
        (qf, knf, vnf, k_hbm, v_hbm,
         o_hbm,
         qbuf, kbuf, vbuf, knbuf, vnbuf, obuf,
         sems, fsems, qsem, osem) = refs
    r = pl.program_id(0)
    i = pl.program_id(1)
    lyr = layer_ref[0]
    P, C, KVH = page_size, pages_per_chunk, kv_heads
    qlen_r = qlen_ref[r]
    hist_r = hist_ref[r]
    base = t0_ref[r] + i * BQ

    @pl.when(i * BQ < qlen_r)
    def _program():
        # ---- fetch this q block --------------------------------------
        qcp = pltpu.make_async_copy(
            qf.at[pl.ds(base, BQ)], qbuf, qsem
        )
        qcp.start()

        npages = jax.lax.div(hist_r + P - 1, P)
        nchunks = jax.lax.div(npages + C - 1, C)
        max_chunks = (max_pages + C - 1) // C

        def start_chunk(ci, slot):
            for c in range(C):  # static unroll over pages in a chunk
                @pl.when(ci * C + c < npages)
                def _():
                    page = pt_ref[r, ci * C + c]
                    pltpu.make_async_copy(
                        k_hbm.at[lyr, page],
                        kbuf.at[slot, c],
                        sems.at[slot, c, 0],
                    ).start()
                    pltpu.make_async_copy(
                        v_hbm.at[lyr, page],
                        vbuf.at[slot, c],
                        sems.at[slot, c, 1],
                    ).start()
                    if quantized:
                        pltpu.make_async_copy(
                            ks_hbm.at[lyr, page],
                            ksbuf.at[slot, c],
                            ssems.at[slot, c, 0],
                        ).start()
                        pltpu.make_async_copy(
                            vs_hbm.at[lyr, page],
                            vsbuf.at[slot, c],
                            ssems.at[slot, c, 1],
                        ).start()

        def wait_chunk(ci, slot):
            for c in range(C):
                @pl.when(ci * C + c < npages)
                def _():
                    page = pt_ref[r, ci * C + c]
                    pltpu.make_async_copy(
                        k_hbm.at[lyr, page],
                        kbuf.at[slot, c],
                        sems.at[slot, c, 0],
                    ).wait()
                    pltpu.make_async_copy(
                        v_hbm.at[lyr, page],
                        vbuf.at[slot, c],
                        sems.at[slot, c, 1],
                    ).wait()
                    if quantized:
                        pltpu.make_async_copy(
                            ks_hbm.at[lyr, page],
                            ksbuf.at[slot, c],
                            ssems.at[slot, c, 0],
                        ).wait()
                        pltpu.make_async_copy(
                            vs_hbm.at[lyr, page],
                            vsbuf.at[slot, c],
                            ssems.at[slot, c, 1],
                        ).wait()

        @pl.when(nchunks > 0)
        def _():
            start_chunk(0, 0)

        qcp.wait()
        q = qbuf[...].astype(jnp.float32)    # [BQ, KVH, group, D]
        D = q.shape[-1]
        H = KVH * group
        RQ = BQ * H                          # q_bd rows

        # Block-diagonal q [BQ*H, KVH*D]: kv head k's query rows occupy
        # the column block of its kv head — ONE MXU dot scores every
        # head of every block token against a flat [T, KVH*D] kv view.
        q_bd_rows = []
        for k in range(KVH):
            blk = q[:, k].reshape(BQ * group, D)   # token-major rows
            row = [jnp.zeros((BQ * group, k * D), jnp.float32)] if k else []
            row.append(blk)
            if k < KVH - 1:
                row.append(
                    jnp.zeros((BQ * group, (KVH - 1 - k) * D), jnp.float32)
                )
            q_bd_rows.append(
                jnp.concatenate(row, axis=1) if len(row) > 1 else row[0]
            )
        q_bd = jnp.concatenate(q_bd_rows, axis=0)   # [BQ*H, KVH*D]
        # token offset of each q_bd row within the block (rows are
        # [kv_head, token, group]-major)
        r_iota = jax.lax.broadcasted_iota(jnp.int32, (RQ, 1), 0)
        tok_of_row = jax.lax.rem(r_iota, BQ * group) // group  # [RQ, 1]
        q_off_row = i * BQ + tok_of_row                         # [RQ, 1]

        # ---- history pages: online softmax over the ragged page walk --
        def body(ci, carry):
            m_prev, l_prev, acc_prev = carry   # [RQ,1],[RQ,1],[RQ,KVH*D]
            slot = jax.lax.rem(ci, 2)

            @pl.when(ci + 1 < nchunks)
            def _():
                start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

            wait_chunk(ci, slot)
            if quantized:
                k_flat = (
                    kbuf[slot].astype(jnp.float32)
                    * ksbuf[slot][..., None]
                ).reshape(C * P, KVH * D)
                v_flat = (
                    vbuf[slot].astype(jnp.float32)
                    * vsbuf[slot][..., None]
                ).reshape(C * P, KVH * D)
            else:
                k_flat = (
                    kbuf[slot].reshape(C * P, KVH * D).astype(jnp.float32)
                )
                v_flat = (
                    vbuf[slot].reshape(C * P, KVH * D).astype(jnp.float32)
                )
            token0 = ci * C * P
            tok = token0 + jax.lax.broadcasted_iota(
                jnp.int32, (1, C * P), 1
            )
            in_range = tok < hist_r             # [1, T]
            # un-DMA'd buffer regions (pages past this row's history)
            # hold garbage; the softmax weight there is exactly 0, but
            # 0 * NaN still poisons the PV accumulation — zero V
            # explicitly (the int8 scale garbage folds into v_flat, so
            # this one guard covers it too).
            v_flat = jnp.where(
                jax.lax.broadcasted_iota(jnp.int32, (C * P, 1), 0)
                < hist_r - token0,
                v_flat, 0,
            )
            s = jax.lax.dot_general(
                q_bd, k_flat, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                           # [RQ, T]
            s = jnp.where(in_range, s, DEFAULT_MASK_VALUE)
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc_prev * alpha + jax.lax.dot_general(
                p, v_flat, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        m0 = jnp.full((RQ, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((RQ, 1), jnp.float32)
        acc0 = jnp.zeros((RQ, KVH * D), jnp.float32)

        def guarded_body(ci, carry):
            return jax.lax.cond(
                ci < nchunks, lambda c: body(ci, c), lambda c: c, carry
            )

        m, l, acc = jax.lax.fori_loop(
            0, max_chunks, guarded_body, (m0, l0, acc0)
        )

        # ---- fresh tokens of this row, block by block (causal) --------
        def fresh_body(j, carry):
            m_prev, l_prev, acc_prev = carry
            src = t0_ref[r] + j * BQ
            kcp = pltpu.make_async_copy(
                knf.at[pl.ds(src, BQ)], knbuf, fsems.at[0]
            )
            vcp = pltpu.make_async_copy(
                vnf.at[pl.ds(src, BQ)], vnbuf, fsems.at[1]
            )
            kcp.start()
            vcp.start()
            kcp.wait()
            vcp.wait()
            kf = knbuf[...].reshape(BQ, KVH * D).astype(jnp.float32)
            vf = vnbuf[...].reshape(BQ, KVH * D).astype(jnp.float32)
            kv_off = j * BQ + jax.lax.broadcasted_iota(
                jnp.int32, (1, BQ), 1
            )                                   # [1, BQ]
            # a partial tail block reads the NEXT row's fresh tokens (or
            # flat padding); their softmax weight is exactly 0, but a
            # skipped neighbour row's uninitialized output feeds later
            # layers' projections, so its V here can be NaN — and
            # 0 * NaN still poisons the PV accumulation.  Zero V
            # out-of-row, same guard as the history path.
            vf = jnp.where(
                j * BQ + jax.lax.broadcasted_iota(
                    jnp.int32, (BQ, 1), 0
                ) < qlen_r,
                vf, 0,
            )
            ok = (kv_off < qlen_r) & (kv_off <= q_off_row)  # [RQ, BQ]
            s = jax.lax.dot_general(
                q_bd, kf, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                           # [RQ, BQ]
            s = jnp.where(ok, s, DEFAULT_MASK_VALUE)
            m_cur = jnp.max(s, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc_prev * alpha + jax.lax.dot_general(
                p, vf, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        m, l, acc = jax.lax.fori_loop(0, i + 1, fresh_body, (m, l, acc))

        # fully-masked q rows (block-tail padding past the row's ragged
        # length) have l == 0; guard the divide so garbage stays finite
        out = acc / jnp.where(l > 0, l, 1.0)    # [RQ, KVH*D]
        for k in range(KVH):                    # extract each head block
            obuf[:, k] = out[
                k * BQ * group:(k + 1) * BQ * group,
                k * D:(k + 1) * D,
            ].reshape(BQ, group, D).astype(obuf.dtype)
        ocp = pltpu.make_async_copy(
            obuf, o_hbm.at[pl.ds(base, BQ)], osem
        )
        ocp.start()
        ocp.wait()


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def ragged_paged_attention_tpu(
    q,            # [T, H, D] flat fresh queries
    k_new,        # [T, KVH, D] fresh K/V, attended raw
    v_new,
    k_pages,      # [L, N, P, KVH, D] — FULL pool (read-only here)
    v_pages,
    layer,        # scalar int32
    t0,           # [R] int32 row starts (ascending, disjoint)
    q_len,        # [R] int32 fresh tokens per row (0 = unused)
    hist,         # [R] int32 history tokens per row
    tables,       # [R, maxP] int32
    *,
    scale: Optional[float] = None,
    interpret: bool = False,
    k_scale=None,  # [L, N, P, KVH] f32 — present iff the pool is int8
    v_scale=None,
    **tiered,      # span_lo/span_hi/cold_* — NOT supported in-kernel yet
):
    """Returns ``out [T, H, D]``.  Rows may start at any offset; the
    flat axis is padded internally so partial query blocks never DMA out
    of bounds.

    Tiered-residency metadata (``span_lo``/``span_hi``/``cold_*`` from
    the streamed cold-middle path) is rejected here: this kernel walks
    only pages-resident history and carries no external ``(m, l, acc)``
    stats, so accepting the arguments and ignoring them would silently
    drop the demoted middle — wrong KV.  The dispatcher in
    ``helix_tpu.ops.paged`` routes tiered calls to the reference path;
    the guard keeps any direct caller honest."""
    if any(v is not None for v in tiered.values()):
        raise NotImplementedError(
            "ragged_paged_attention_tpu: tiered cold-middle attention "
            f"({sorted(k for k, v in tiered.items() if v is not None)}) "
            "is reference-only; dispatch via ragged_paged_attention"
        )
    T, H, D = q.shape
    L, N, P, KVH, _ = k_pages.shape
    R, maxP = tables.shape
    group = H // KVH
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    C = max(1, 128 // P)
    C = min(C, maxP)
    quantized = k_scale is not None
    # pad so the last row's final (possibly unaligned, possibly partial)
    # 8-token query block stays in bounds: need Tpad >= T + (BQ - 1) and
    # Tpad % BQ == 0
    Tpad = (T + 2 * BQ - 2) // BQ * BQ
    if Tpad != T:
        zpad = Tpad - T
        q = jnp.concatenate([q, jnp.zeros((zpad, H, D), q.dtype)], axis=0)
        k_new = jnp.concatenate(
            [k_new, jnp.zeros((zpad, KVH, D), k_new.dtype)], axis=0
        )
        v_new = jnp.concatenate(
            [v_new, jnp.zeros((zpad, KVH, D), v_new.dtype)], axis=0
        )
    NQ = Tpad // BQ

    qg = q.reshape(Tpad, KVH, group, D)
    kernel = functools.partial(
        _ragged_kernel,
        scale=scale,
        page_size=P,
        pages_per_chunk=C,
        max_pages=maxP,
        kv_heads=KVH,
        group=group,
        quantized=quantized,
    )
    any_spec = pl.BlockSpec(memory_space=_MemorySpace.ANY)
    in_specs = [any_spec] * (7 if quantized else 5)
    out_spec = any_spec
    scratch = [
        pltpu.VMEM((BQ, KVH, group, D), q.dtype),           # qbuf
        pltpu.VMEM((2, C, P, KVH, D), k_pages.dtype),       # kbuf
        pltpu.VMEM((2, C, P, KVH, D), v_pages.dtype),       # vbuf
    ]
    if quantized:
        scratch += [
            pltpu.VMEM((2, C, P, KVH), jnp.float32),        # ksbuf
            pltpu.VMEM((2, C, P, KVH), jnp.float32),        # vsbuf
        ]
    scratch += [
        pltpu.VMEM((BQ, KVH, D), k_new.dtype),              # knbuf
        pltpu.VMEM((BQ, KVH, D), v_new.dtype),              # vnbuf
        pltpu.VMEM((BQ, KVH, group, D), q.dtype),           # obuf
        pltpu.SemaphoreType.DMA((2, C, 2)),                 # sems
    ]
    if quantized:
        scratch += [pltpu.SemaphoreType.DMA((2, C, 2))]     # ssems
    scratch += [
        pltpu.SemaphoreType.DMA((2,)),                      # fsems
        pltpu.SemaphoreType.DMA(()),                        # qsem
        pltpu.SemaphoreType.DMA(()),                        # osem
    ]
    inputs = (
        (qg, k_new, v_new, k_pages, v_pages, k_scale, v_scale)
        if quantized
        else (qg, k_new, v_new, k_pages, v_pages)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(R, NQ),
        in_specs=in_specs,
        out_specs=out_spec,
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Tpad, KVH, group, D), q.dtype),
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
    )(
        t0.astype(jnp.int32),
        q_len.astype(jnp.int32),
        hist.astype(jnp.int32),
        tables.astype(jnp.int32),
        jnp.asarray(layer, jnp.int32).reshape(1),
        *inputs,
    )
    return out.reshape(Tpad, H, D)[:T]
