"""Pallas TPU ragged paged-decode attention kernel.

Per-sequence decode attention that walks ONLY the pages each sequence
actually uses (ragged over the batch), instead of gathering
``max_pages_per_seq`` like the XLA reference path — the design of Ragged
Paged Attention (PAPERS.md) specialised to decode:

- Page tables + lengths are **scalar-prefetched into SMEM**, so DMA source
  addresses are computed before the kernel body runs.
- KV pages stream HBM -> VMEM with **double-buffered async DMA**; chunks of
  ``C = ceil(128 / page_size)`` pages are fetched per step so the score
  matmul runs at full 128-lane width.
- Online softmax in fp32 scratch; the current token's K/V (not yet written
  to the pool — the engine scatters after the forward pass) is folded in as
  a final virtual block.

Grid is ``(B, KVH)``; each program owns one sequence x one kv-head group
(``group = H / KVH`` query heads).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from helix_tpu.ops.attention import DEFAULT_MASK_VALUE


def _decode_kernel(
    # scalar prefetch
    pt_ref,      # SMEM [B, maxP] int32 page tables
    len_ref,     # SMEM [B] int32 past lengths
    # inputs
    q_ref,       # VMEM [1, 1, group, D]
    knew_ref,    # VMEM [1, 1, 1, D]
    vnew_ref,    # VMEM [1, 1, 1, D]
    k_hbm,       # ANY  [KVH, N, P, D]
    v_hbm,
    # outputs
    o_ref,       # VMEM [1, 1, group, D]
    # scratch
    kbuf,        # VMEM [2, C*P, D]
    vbuf,        # VMEM [2, C*P, D]
    sems,        # DMA sems [2, C, 2]
    *,
    scale: float,
    page_size: int,
    pages_per_chunk: int,
    max_pages: int,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    P, C = page_size, pages_per_chunk
    L = len_ref[b]
    npages = jax.lax.div(L + P - 1, P)
    nchunks = jax.lax.div(npages + C - 1, C)
    max_chunks = (max_pages + C - 1) // C

    def start_chunk(ci, slot):
        for c in range(C):  # static unroll over pages in a chunk
            @pl.when(ci * C + c < npages)
            def _():
                page = pt_ref[b, ci * C + c]
                pltpu.make_async_copy(
                    k_hbm.at[h, page],
                    kbuf.at[slot, pl.ds(c * P, P), :],
                    sems.at[slot, c, 0],
                ).start()
                pltpu.make_async_copy(
                    v_hbm.at[h, page],
                    vbuf.at[slot, pl.ds(c * P, P), :],
                    sems.at[slot, c, 1],
                ).start()

    def wait_chunk(ci, slot):
        for c in range(C):
            @pl.when(ci * C + c < npages)
            def _():
                page = pt_ref[b, ci * C + c]
                pltpu.make_async_copy(
                    k_hbm.at[h, page],
                    kbuf.at[slot, pl.ds(c * P, P), :],
                    sems.at[slot, c, 0],
                ).wait()
                pltpu.make_async_copy(
                    v_hbm.at[h, page],
                    vbuf.at[slot, pl.ds(c * P, P), :],
                    sems.at[slot, c, 1],
                ).wait()

    q = q_ref[0, 0].astype(jnp.float32)  # [group, D]
    group, D = q.shape

    @pl.when(nchunks > 0)
    def _():
        start_chunk(0, 0)

    def body(ci, carry):
        m_prev, l_prev, acc_prev = carry
        slot = jax.lax.rem(ci, 2)

        @pl.when(ci + 1 < nchunks)
        def _():
            start_chunk(ci + 1, jax.lax.rem(ci + 1, 2))

        wait_chunk(ci, slot)
        k = kbuf[slot].astype(jnp.float32)       # [C*P, D]
        v = vbuf[slot]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                # [group, C*P]
        token0 = ci * C * P
        tok = token0 + jax.lax.broadcasted_iota(jnp.int32, (1, C * P), 1)
        s = jnp.where(tok < L, s, DEFAULT_MASK_VALUE)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc_prev * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((group, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((group, 1), jnp.float32)
    acc0 = jnp.zeros((group, D), jnp.float32)

    def guarded_body(ci, carry):
        return jax.lax.cond(
            ci < nchunks, lambda c: body(ci, c), lambda c: c, carry
        )

    m, l, acc = jax.lax.fori_loop(0, max_chunks, guarded_body, (m0, l0, acc0))

    # fold in the current token's K/V (virtual final block, always valid)
    knew = knew_ref[0, 0, 0].astype(jnp.float32)    # [D]
    vnew = vnew_ref[0, 0, 0].astype(jnp.float32)
    s_new = jax.lax.dot_general(
        q, knew[:, None], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                    # [group, 1]
    m_f = jnp.maximum(m, s_new)
    p_new = jnp.exp(s_new - m_f)
    alpha = jnp.exp(m - m_f)
    l_f = alpha * l + p_new
    acc_f = acc * alpha + p_new * vnew[None, :]
    o_ref[0, 0] = (acc_f / l_f).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret")
)
def paged_decode_attention_tpu(
    q,            # [B, H, D]
    k_pages,      # [KVH, N, P, D]
    v_pages,
    page_tables,  # [B, maxP]
    lengths,      # [B]
    k_new,        # [B, KVH, D]
    v_new,
    *,
    scale: Optional[float] = None,
    interpret: bool = False,
):
    B, H, D = q.shape
    KVH, N, P, _ = k_pages.shape
    maxP = page_tables.shape[1]
    group = H // KVH
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    C = max(1, 128 // P)
    C = min(C, maxP)

    qg = q.reshape(B, KVH, group, D)
    knew4 = k_new.reshape(B, KVH, 1, D)
    vnew4 = v_new.reshape(B, KVH, 1, D)
    kernel = functools.partial(
        _decode_kernel,
        scale=scale,
        page_size=P,
        pages_per_chunk=C,
        max_pages=maxP,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KVH),
        in_specs=[
            pl.BlockSpec((1, 1, group, D), lambda b, h, *_: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, 1, D), lambda b, h, *_: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, 1, D), lambda b, h, *_: (b, h, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, 1, group, D), lambda b, h, *_: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, C * P, D), k_pages.dtype),
            pltpu.VMEM((2, C * P, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, C, 2)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, group, D), q.dtype),
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
    )(
        page_tables.astype(jnp.int32),
        lengths.astype(jnp.int32),
        qg,
        knew4,
        vnew4,
        k_pages,
        v_pages,
    )
    return out.reshape(B, H, D)
