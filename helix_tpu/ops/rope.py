"""Rotary position embeddings (RoPE), including Llama-3 frequency scaling.

Computed on the fly from position ids rather than precomputed tables so the
same function serves ragged prefill (arbitrary positions per token) and
decode (one position per sequence) without gather ops that would break XLA
fusion.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    theta: float = 500000.0,
    scaling: dict | None = None,
) -> np.ndarray:
    """Inverse frequencies, with optional Llama-3-style rope scaling.

    ``scaling`` follows HF config ``rope_scaling`` with
    ``rope_type=llama3``: {factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings}.
    """
    inv_freq = 1.0 / (
        theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim)
    )
    if scaling is not None and not isinstance(scaling, dict):
        scaling = dict(scaling)   # configs store it as a sorted item tuple
    if scaling and scaling.get("rope_type", scaling.get("type")) == "llama3":
        factor = scaling["factor"]
        low = scaling["low_freq_factor"]
        high = scaling["high_freq_factor"]
        orig = scaling["original_max_position_embeddings"]
        wavelen = 2 * np.pi / inv_freq
        # three bands: high-freq untouched, low-freq divided by factor,
        # middle smoothly interpolated
        smooth = (orig / wavelen - low) / (high - low)
        smooth = np.clip(smooth, 0.0, 1.0)
        scaled = inv_freq / factor
        inv_freq = (1 - smooth) * scaled + smooth * inv_freq
    return inv_freq.astype(np.float32)


def apply_rope(x, positions, inv_freq):
    """Rotate q or k.

    x:         [..., seq, heads, head_dim]
    positions: broadcastable to [..., seq] (int32)
    inv_freq:  [head_dim // 2]
    """
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., seq, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., seq, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
