"""Attention: XLA reference implementation + Pallas TPU flash kernel.

The reference framework never implements attention itself — it is inside the
vLLM CUDA containers its compose profiles launch (``SURVEY.md`` §2.2).  Here
it is owned code:

- ``mha_reference`` — pure-XLA multi-head attention with GQA, causal and
  packed-segment masking.  Used on CPU (tests) and as the numerics oracle.
- ``flash_attention`` — Pallas TPU kernel, online-softmax tiling so the
  [S, S] score matrix never materialises in HBM; fp32 accumulation on the
  MXU; grid iterates kv-blocks innermost with VMEM scratch carrying the
  running (max, sum, acc) between iterations.

Decode-time paged attention over the KV cache lives in
``helix_tpu.ops.paged`` (ragged paged attention per PAPERS.md).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _repeat_kv(k, num_q_heads):
    """[B, S, KVH, D] -> [B, S, H, D] for GQA in the reference path."""
    kvh = k.shape[-2]
    if kvh == num_q_heads:
        return k
    return jnp.repeat(k, num_q_heads // kvh, axis=-2)


def mha_reference(
    q,
    k,
    v,
    *,
    causal: bool = True,
    q_positions=None,
    kv_positions=None,
    q_segment_ids=None,
    kv_segment_ids=None,
    logits_soft_cap: Optional[float] = None,
    scale: Optional[float] = None,
):
    """Numerics oracle. q: [B, Sq, H, D]; k/v: [B, Skv, KVH, D].

    ``q_positions``/``kv_positions`` make causal masking correct for ragged
    prefill where query block i sits at an arbitrary absolute position.
    ``segment_ids`` mask cross-sequence attention in packed batches.
    """
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    k = _repeat_kv(k, H)
    v = _repeat_kv(v, H)
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if logits_soft_cap is not None:
        logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
    mask = jnp.ones((B, 1, Sq, Skv), dtype=bool)
    if causal:
        qp = (
            q_positions
            if q_positions is not None
            else jnp.broadcast_to(jnp.arange(Sq)[None], (B, Sq))
        )
        kp = (
            kv_positions
            if kv_positions is not None
            else jnp.broadcast_to(jnp.arange(Skv)[None], (B, Skv))
        )
        mask = mask & (qp[:, None, :, None] >= kp[:, None, None, :])
    if q_segment_ids is not None and kv_segment_ids is not None:
        mask = mask & (
            q_segment_ids[:, None, :, None] == kv_segment_ids[:, None, None, :]
        )
    logits = jnp.where(mask, logits, DEFAULT_MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU flash attention
# ---------------------------------------------------------------------------


def _flash_kernel(
    qpos_ref,   # VMEM [1, 1, BQ] int32 — this q block's absolute positions
    kpos_ref,   # VMEM [1, 1, BK]
    qseg_ref,   # VMEM [1, 1, BQ]
    kseg_ref,   # VMEM [1, 1, BK]
    q_ref,      # [1, 1, BQ, D]  (layout [B, H, S, D])
    k_ref,      # [1, 1, BK, D]
    v_ref,
    o_ref,      # [1, 1, BQ, D]
    m_scr,      # VMEM [BQ, 1] fp32
    l_scr,      # VMEM [BQ, 1] fp32
    acc_scr,    # VMEM [BQ, D] fp32
    *,
    scale: float,
    causal: bool,
    use_segments: bool,
    block_q: int,
    block_kv: int,
    soft_cap: Optional[float],
):
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qp = qpos_ref[0, 0, :]
    kp = kpos_ref[0, 0, :]

    if causal:
        # Causal block skipping: a kv block wholly above the diagonal
        # (every key position beyond every query position) contributes
        # nothing — skip its matmuls entirely. Computed from the position
        # blocks, so it is exact for ragged/chunked prefill too; for the
        # default arange positions it degenerates to the classic
        # lower-triangle grid walk (~2x fewer MXU FLOPs at long S).
        run = jnp.max(qp) >= jnp.min(kp)
    else:
        run = True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)

        mask = jnp.ones((block_q, block_kv), dtype=bool)
        if causal:
            mask = mask & (qp[:, None] >= kp[None, :])
        if use_segments:
            mask = mask & (
                qseg_ref[0, 0, :][:, None] == kseg_ref[0, 0, :][None, :]
            )
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)

        m_prev = m_scr[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype),
            v_ref[0, 0, :, :],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = m_new
        l_scr[:] = l_new
        acc_scr[:] = acc

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros, not NaN
        o_ref[0, 0, :, :] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal",
        "scale",
        "logits_soft_cap",
        "block_q",
        "block_kv",
        "interpret",
    ),
)
def flash_attention(
    q,
    k,
    v,
    *,
    q_positions=None,
    kv_positions=None,
    q_segment_ids=None,
    kv_segment_ids=None,
    causal: bool = True,
    scale: Optional[float] = None,
    logits_soft_cap: Optional[float] = None,
    block_q: int = 256,
    block_kv: int = 256,
    interpret: bool = False,
):
    """Flash attention for prefill. q: [B, Sq, H, D]; k/v: [B, Skv, KVH, D].

    GQA is handled in the grid index map (each q head reads its kv group's
    block — no materialised ``repeat``).  Sequences shorter than the block
    size fall through with single-block grids; callers pad S to a multiple
    of the block (the engine pads to page size anyway).
    """
    B, Sq, H, D = q.shape
    Skv, KVH = k.shape[1], k.shape[2]
    group = H // KVH
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    block_q = min(block_q, Sq)
    block_kv = min(block_kv, Skv)
    if Sq % block_q or Skv % block_kv:
        raise ValueError(
            f"seq lens ({Sq}, {Skv}) must be multiples of blocks "
            f"({block_q}, {block_kv})"
        )
    nq, nk = Sq // block_q, Skv // block_kv

    def bcast_i32(x, default, shape):
        if x is None:
            x = default
        return jnp.broadcast_to(x, shape).astype(jnp.int32)

    # [B, 1, S] so position/segment blocks satisfy TPU tiling (last two block
    # dims = (1, block) with the 1 equal to the full middle dim).
    qpos = bcast_i32(q_positions, jnp.arange(Sq)[None], (B, Sq))[:, None, :]
    kpos = bcast_i32(kv_positions, jnp.arange(Skv)[None], (B, Skv))[:, None, :]
    use_segments = q_segment_ids is not None
    qseg = bcast_i32(q_segment_ids, 0, (B, Sq))[:, None, :]
    kseg = bcast_i32(kv_segment_ids, 0, (B, Skv))[:, None, :]

    # Kernel operates in [B, H, S, D]: the blocked (S, D) pair lands in the
    # last two dims as TPU tiling requires.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (B, H, nq, nk)
    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        causal=causal,
        use_segments=use_segments,
        block_q=block_q,
        block_kv=block_kv,
        soft_cap=logits_soft_cap,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q), lambda b, h, i, j: (b, 0, i)),   # qpos
            pl.BlockSpec((1, 1, block_kv), lambda b, h, i, j: (b, 0, j)),  # kpos
            pl.BlockSpec((1, 1, block_q), lambda b, h, i, j: (b, 0, i)),   # qseg
            pl.BlockSpec((1, 1, block_kv), lambda b, h, i, j: (b, 0, j)),  # kseg
            pl.BlockSpec(
                (1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_kv, D), lambda b, h, i, j: (b, h // group, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_kv, D), lambda b, h, i, j: (b, h // group, j, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(qpos, kpos, qseg, kseg, qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


def attention(
    q,
    k,
    v,
    *,
    backend: Optional[str] = None,
    **kwargs,
):
    """Dispatch: Pallas on TPU, reference elsewhere (CPU tests, debugging).

    Auto mode keys off the process default backend (works under tracing,
    where per-array .devices() is unavailable)."""
    if backend is None:
        platform = jax.devices()[0].platform
        backend = "pallas" if platform in ("tpu", "axon") else "reference"
    if backend == "pallas":
        return flash_attention(q, k, v, **kwargs)
    kwargs.pop("block_q", None)
    kwargs.pop("block_kv", None)
    kwargs.pop("interpret", None)
    return mha_reference(q, k, v, **kwargs)
