"""Logical-axis sharding rules: pick a mesh, annotate, let XLA insert collectives.

The reference delegates all intra-model parallelism to vLLM's NCCL world
(``SURVEY.md`` §2.2); here sharding is owned by the framework.  Every weight
and activation carries *logical* axis names ("embed", "heads", "mlp", …);
``LOGICAL_RULES`` maps those to mesh axes from ``helix_tpu.device.mesh``.
``jax.jit`` + ``NamedSharding`` then compile in the right all-gathers /
reduce-scatters over ICI — no hand-written collectives on the hot path.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis name -> mesh axis (or tuple of mesh axes, or None = replicated)
# Megatron-style layout: attention heads and the MLP hidden dim shard over
# tp; embedding/vocab shards over tp for the big matmuls; batch shards over
# dp; sequence shards over sp (ring attention); weights optionally shard
# over fsdp on their non-tp axis.
LOGICAL_RULES: dict[str, Any] = {
    # activations
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed_act": None,
    # weights
    "vocab": "tp",
    "embed": "fsdp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "mlp": "tp",
    "expert": "ep",
    # stacked-layer leading axis: sharding it over a pp mesh axis IS
    # pipeline-parallel placement — each pp group holds a contiguous
    # block of layers and the lax.scan's per-layer slice makes XLA move
    # the activations between groups (inference pipelining for models
    # that exceed one chip group's HBM)
    "layers": "pp",
    # kv cache
    "cache_batch": ("dp", "fsdp"),
    "cache_heads": "tp",
    "pages": None,
    # lora
    "lora_rank": None,
}


def spec_for(logical_axes: Sequence[Optional[str]], rules=None) -> P:
    """Translate a tuple of logical axis names into a PartitionSpec."""
    rules = rules or LOGICAL_RULES
    return P(*[rules.get(a) if a is not None else None for a in logical_axes])


def logical_sharding(
    mesh: Mesh, logical_axes: Sequence[Optional[str]], rules=None
) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules))


def _prune_spec_for_mesh(mesh: Mesh, spec: P) -> P:
    """Drop mesh axes of size 1 (keeps XLA layouts clean) and axes the mesh
    does not define."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def keep(ax):
        if ax is None:
            return None
        if isinstance(ax, (tuple, list)):
            kept = tuple(a for a in ax if sizes.get(a, 1) > 1)
            return kept if kept else None
        return ax if sizes.get(ax, 1) > 1 else None

    return P(*[keep(a) for a in spec])


def with_constraint(x, mesh: Mesh, logical_axes: Sequence[Optional[str]]):
    """``lax.with_sharding_constraint`` via logical names (activation pins)."""
    spec = _prune_spec_for_mesh(mesh, spec_for(logical_axes))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def shard_params(params: Any, mesh: Mesh, axes_tree: Any) -> Any:
    """Device-put a parameter pytree according to a matching tree of logical
    axis tuples (the pytree analogue of flax's ``partitioning`` metadata but
    without a framework dependency — params stay plain dicts of jax.Arrays).
    """

    def place(leaf, axes):
        spec = _prune_spec_for_mesh(mesh, spec_for(axes))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, params, axes_tree)


def sharding_tree(mesh: Mesh, axes_tree: Any) -> Any:
    """Tree of NamedShardings from a tree of logical-axes tuples (for use as
    ``jit(..., in_shardings=...)``)."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, _prune_spec_for_mesh(mesh, spec_for(axes))),
        axes_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )
