"""Multi-host (DCN) distributed training: data parallelism across hosts.

SURVEY §2.2/§7: the reference's NCCL/MPI world is replaced by XLA
collectives — ICI inside a slice, DCN between hosts — with gradient
all-reduce placed by sharding, not hand-written comms. This module owns
the process-level plumbing jax needs for that:

- ``MultiHostConfig`` (coordinator address, process count, rank) from
  flags or ``HELIX_COORDINATOR``/``HELIX_NUM_HOSTS``/``HELIX_HOST_RANK``;
- ``initialize()`` wraps ``jax.distributed.initialize`` (a no-op for a
  single host, so the same entrypoint serves both);
- ``global_mesh_spec()`` lays out dp **outermost over hosts** (gradient
  all-reduce rides DCN once per step — the bandwidth-tolerant axis) and
  tp/sp innermost (latency-sensitive collectives stay on ICI within a
  host), the standard TPU recipe;
- ``host_local_slice()`` + ``device_batch_from_local()`` feed each
  process ITS shard of the global batch via
  ``jax.make_array_from_process_local_data`` — no host ever materialises
  the global batch, which is what makes the dp axis scale past one
  host's memory.

Serving-plane DP across hosts is intentionally NOT here: N hosts serving
one model name are load-balanced by the router's per-model round-robin
(``control/router.py``), mirroring the reference
(``inferencerouter/router.go:168-198``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from helix_tpu.device.mesh import MeshSpec


@dataclasses.dataclass(frozen=True)
class MultiHostConfig:
    coordinator: str = ""        # "host:port" of process 0
    num_processes: int = 1
    process_id: int = 0

    @classmethod
    def from_env(cls, env=None) -> "MultiHostConfig":
        env = env if env is not None else os.environ
        return cls(
            coordinator=env.get("HELIX_COORDINATOR", ""),
            num_processes=int(env.get("HELIX_NUM_HOSTS", "1") or 1),
            process_id=int(env.get("HELIX_HOST_RANK", "0") or 0),
        )

    def validate(self) -> None:
        if self.num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        if not (0 <= self.process_id < self.num_processes):
            raise ValueError(
                f"process_id {self.process_id} outside "
                f"[0, {self.num_processes})"
            )
        if self.num_processes > 1 and not self.coordinator:
            raise ValueError(
                "multi-host training needs a coordinator address "
                "(process 0's host:port)"
            )
        if self.coordinator and self.num_processes <= 1:
            raise ValueError(
                "a coordinator address was given but num_processes is 1 — "
                "did you forget --num-hosts / HELIX_NUM_HOSTS? Refusing to "
                "train a silent single-host copy."
            )


def initialize(cfg: Optional[MultiHostConfig] = None) -> bool:
    """Join the jax distributed system; no-op (False) for a single host.

    Must run before the first backend query — after this,
    ``jax.devices()`` spans every host's chips and jit'd computations over
    a global mesh insert DCN collectives automatically.
    """
    cfg = cfg or MultiHostConfig.from_env()
    cfg.validate()
    if cfg.num_processes <= 1:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    return True


def is_coordinator() -> bool:
    import jax

    return jax.process_index() == 0


def global_mesh_spec(
    num_devices: Optional[int] = None,
    num_hosts: Optional[int] = None,
    max_tp: int = 8,
) -> MeshSpec:
    """dp-over-hosts x tp-within-host layout for the global device set.

    tp never crosses a host boundary (its all-reduces are on every matmul
    — they must stay on ICI); dp is a multiple of the host count so each
    host's chips sit in whole dp rows and the gradient all-reduce between
    hosts is the only DCN traffic.
    """
    import jax

    if num_devices is None:
        num_devices = jax.device_count()       # global, all processes
    if num_hosts is None:
        num_hosts = jax.process_count()
    if num_devices % num_hosts:
        raise ValueError(
            f"{num_devices} devices do not divide over {num_hosts} hosts"
        )
    per_host = num_devices // num_hosts
    import math

    tp = math.gcd(per_host, max_tp)
    return MeshSpec(dp=num_devices // tp, tp=tp)


def host_local_slice(array, process_id: int, num_processes: int):
    """This host's rows of a [global_batch, ...] array (contiguous block
    layout, matching dp-outermost device order)."""
    n = array.shape[0]
    if n % num_processes:
        raise ValueError(
            f"global batch {n} does not divide over {num_processes} hosts"
        )
    per = n // num_processes
    return array[process_id * per : (process_id + 1) * per]


def device_batch_from_local(local_tree: dict, mesh, axes=("batch", None)):
    """Assemble global device arrays from per-process local shards.

    Each process passes only ITS slice; ``make_array_from_process_local_
    data`` stitches the global logical array with the batch axis sharded
    over dp — cross-host assembly without any host gather.
    """
    import jax
    import jax.numpy as jnp

    from helix_tpu.parallel.sharding import logical_sharding

    sh = logical_sharding(mesh, axes)
    return {
        k: jax.make_array_from_process_local_data(sh, jnp.asarray(v))
        for k, v in local_tree.items()
    }
