"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context is absent in the reference (``SURVEY.md`` §5 "Long-context /
sequence parallelism: Absent... No ring attention / blockwise / Ulysses / CP
anywhere"); here it is a first-class engine capability.  Blockwise ring
attention (Liu et al.) the XLA way:

- the sequence shards over ``sp``; each device holds local Q, K, V blocks;
- ``sp_size`` steps: each device computes blockwise attention of its local
  Q against the KV block currently resident, folds it into running online-
  softmax stats (m, l, acc), then rotates KV one hop with ``lax.ppermute``
  — a neighbour exchange that XLA maps onto ICI ring links;
- communication overlaps compute (XLA schedules the collective-permute
  concurrently with the local block matmul), bytes per step are the KV
  shard, never the full sequence; peak memory is O(S/sp).

Inside each step the local block runs the same Pallas flash kernel the
engine uses on TPU (reference path on CPU), so causal masking with absolute
positions falls out of the existing kernels' ``q_positions/kv_positions``
support rather than per-device index bookkeeping.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from helix_tpu.ops.attention import DEFAULT_MASK_VALUE


def _block_stats(q, k, v, qpos, kpos, scale, causal):
    """Blockwise attention stats for one (Q shard, KV block) pair.

    q: [B, Sq, H, D]; k/v: [B, Skv, KVH, D] -> (m [B,H,Sq,1], l, acc
    [B,H,Sq,D]) in fp32.  GQA handled by head repeat at the stats level.
    """
    B, Sq, H, D = q.shape
    KVH = k.shape[2]
    if KVH != H:
        k = jnp.repeat(k, H // KVH, axis=2)
        v = jnp.repeat(v, H // KVH, axis=2)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        mask = qpos[:, None, :, None] >= kpos[:, None, None, :]
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    m = jnp.max(s, axis=-1, keepdims=True)                       # [B,H,Sq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return m, l, acc


def _merge_stats(m1, l1, a1, m2, l2, a2):
    m = jnp.maximum(m1, m2)
    e1 = jnp.exp(m1 - m)
    e2 = jnp.exp(m2 - m)
    return m, l1 * e1 + l2 * e2, a1 * e1 + a2 * e2


def _ring_body(q, k, v, qpos, kpos, axis_name, scale, causal):
    """Runs inside shard_map: local shards + ppermute ring."""
    # axis_size is missing on older jax; psum of a literal 1 constant-folds
    # to the concrete axis size on every version
    if hasattr(jax.lax, "axis_size"):
        sp = jax.lax.axis_size(axis_name)
    else:
        sp = jax.lax.psum(1, axis_name)
    B, Sq, H, D = q.shape

    # derive the init carry from q so it carries the same varying-manual-axes
    # type as the loop outputs (jax>=0.9 shard_map typing)
    acc = jnp.zeros_like(q, jnp.float32).transpose(0, 2, 1, 3)  # [B,H,Sq,D]
    l = acc[..., :1]
    m = l - jnp.inf

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(i, carry):
        m, l, acc, k, v, kpos = carry
        bm, bl, bacc = _block_stats(q, k, v, qpos, kpos, scale, causal)
        m, l, acc = _merge_stats(m, l, acc, bm, bl, bacc)
        # rotate KV (and its positions) one hop — skipped after last use
        k, v, kpos = jax.lax.cond(
            i < sp - 1,
            lambda ops: tuple(
                jax.lax.ppermute(o, axis_name, perm) for o in ops
            ),
            lambda ops: ops,
            (k, v, kpos),
        )
        return m, l, acc, k, v, kpos

    m, l, acc, _, _, _ = jax.lax.fori_loop(
        0, sp, step, (m, l, acc, k, v, kpos)
    )
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (padding) -> zeros
    out = (acc / l).transpose(0, 2, 1, 3)   # [B, Sq, H, D]
    return out.astype(q.dtype)


def ring_attention(
    q,            # [B, Sq, H, D] sharded on Sq over axis_name
    k,            # [B, Skv, KVH, D] sharded on Skv (Skv may differ from Sq)
    v,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    q_positions=None,    # [B, Sq] absolute positions (sharded like Sq)
    kv_positions=None,   # [B, Skv] — defaults to q_positions semantics
    causal: bool = True,
    scale: Optional[float] = None,
):
    """Sequence-parallel attention over a mesh axis.

    Call with globally-shaped arrays; shard_map splits them on the
    sequence axis.  Positions default to ``arange(S)``.  ``Skv`` may
    exceed ``Sq`` (cross-attention of a prefill chunk against cached
    history + itself): each device holds an Skv/sp KV shard and the ring
    rotates shards so every Q shard sees all of KV with O(Skv/sp) peak
    memory — the long-context serving path."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    if q_positions is None:
        q_positions = jnp.broadcast_to(jnp.arange(Sq)[None], (B, Sq))
    if kv_positions is None:
        kv_positions = (
            q_positions
            if Skv == Sq
            else jnp.broadcast_to(jnp.arange(Skv)[None], (B, Skv))
        )

    # Non-divisible geometry pads up to the next sp multiple instead of
    # making the caller fall back to replicated attention (round-2 verdict:
    # the headline long-context feature silently disengaged). Padded KV
    # slots take a sentinel position past any real one so the causal mask
    # excludes them from every real query; padded Q rows sit just below the
    # sentinel so they attend only real KV (keeps their softmax sane) and
    # are sliced off before returning.
    sp_size = mesh.shape[axis_name]
    pad_q = (-Sq) % sp_size
    pad_kv = (-Skv) % sp_size
    if pad_q or pad_kv:
        if not causal:
            raise ValueError(
                "ring_attention padding requires causal masking to exclude "
                f"padded KV (Sq={Sq}, Skv={Skv} not divisible by "
                f"sp={sp_size})"
            )
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        q_positions = jnp.pad(
            q_positions, ((0, 0), (0, pad_q)), constant_values=(1 << 30) - 1
        ).astype(q_positions.dtype)
        k = jnp.pad(k, ((0, 0), (0, pad_kv), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_kv), (0, 0), (0, 0)))
        kv_positions = jnp.pad(
            kv_positions, ((0, 0), (0, pad_kv)), constant_values=1 << 30
        ).astype(kv_positions.dtype)

    seq = P(None, axis_name, None, None)
    pos = P(None, axis_name)

    body = functools.partial(
        _ring_body, axis_name=axis_name, scale=scale, causal=causal
    )
    # check_rep=False: older jax's replication checker mistypes the ring's
    # fori_loop carry under grad (the ppermute rotates a carry whose
    # replication it tracks as axis-varying on input but not output) and
    # rejects a correct program; newer jax removed the parameter, so only
    # pass it where it exists.
    import inspect

    kw = (
        {"check_rep": False}
        if "check_rep" in inspect.signature(shard_map).parameters
        else {}
    )
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(seq, seq, seq, pos, pos),
        out_specs=seq,
        **kw,
    )
    out = fn(q, k, v, q_positions, kv_positions)
    return out[:, :Sq] if pad_q else out
