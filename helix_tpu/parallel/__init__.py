from helix_tpu.parallel.sharding import (
    LOGICAL_RULES,
    logical_sharding,
    shard_params,
    with_constraint,
)

__all__ = [
    "LOGICAL_RULES",
    "logical_sharding",
    "shard_params",
    "with_constraint",
]
