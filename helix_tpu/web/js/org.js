/* Helix Org: bot org-chart (layered SVG), channels, platform bindings,
 * scheduled activations — the UI over /api/v1/org/*. */
import {$, $row, api, esc, toast} from "./core.js";

function chartSvg(bots, reporting) {
  // layer bots by depth in the reporting DAG (roots = no managers)
  const managers = {};
  for (const e of reporting)
    (managers[e.report] = managers[e.report] || []).push(e.manager);
  const depth = {};
  const d = (id, seen = new Set()) => {
    if (depth[id] !== undefined) return depth[id];
    if (seen.has(id)) return 0;
    seen.add(id);
    const ms = managers[id] || [];
    depth[id] = ms.length ? 1 + Math.max(...ms.map(x => d(x, seen))) : 0;
    return depth[id];
  };
  bots.forEach(b => d(b.id));
  const layers = [];
  for (const b of bots) (layers[depth[b.id]] = layers[depth[b.id]] || []).push(b);
  const W = 1080, RH = 74, BW = 150, BH = 44;
  const pos = {};
  layers.forEach((layer, li) => layer.forEach((b, i) => {
    pos[b.id] = [ (i + 0.5) * (W / layer.length) - BW/2, li * RH + 8 ];
  }));
  const H = Math.max(layers.length * RH + 10, 60);
  let s = `<svg class="chart" viewBox="0 0 ${W} ${H}" width="100%" height="${H}">`;
  for (const e of reporting) {
    const a = pos[e.manager], b = pos[e.report];
    if (!a || !b) continue;
    s += `<line x1="${a[0]+BW/2}" y1="${a[1]+BH}" x2="${b[0]+BW/2}" y2="${b[1]}"/>`;
  }
  for (const b of bots) {
    const [x, y] = pos[b.id];
    s += `<rect x="${x}" y="${y}" width="${BW}" height="${BH}"/>` +
      `<text x="${x+BW/2}" y="${y+19}" text-anchor="middle">${esc(b.name)}${b.agent ? " ⚙" : ""}</text>` +
      `<text x="${x+BW/2}" y="${y+35}" text-anchor="middle" style="fill:var(--dim);font-size:10px">${esc((b.role||"").slice(0,24))}</text>`;
  }
  return s + "</svg>";
}

export async function render(m) {
  const top = $(`<div class="panel row">
    <input id="bname" placeholder="bot name">
    <input id="brole" class="grow" placeholder="role prompt">
    <label class="id"><input type="checkbox" id="bagent"> agent session</label>
    <button class="primary" id="mkbot">Create bot</button></div>`);
  m.appendChild(top);
  const chartPanel = $(`<div class="panel"><h3>Org chart</h3>
    <div id="chart"></div>
    <div class="row" style="margin-top:8px">
      <select id="rrep"></select><span class="id">reports to</span>
      <select id="rmgr"></select>
      <button class="ghost" id="raddr">Add line</button></div></div>`);
  m.appendChild(chartPanel);
  const chanPanel = $(`<div class="panel"><h3>Channels</h3>
    <div class="row"><select id="csel" class="grow"></select>
      <input id="cname" placeholder="new channel">
      <select id="cowner"></select>
      <button class="ghost" id="mkchan">Create</button></div>
    <div id="clog" class="chat-log" style="height:240px;margin-top:8px"></div>
    <div class="row" style="margin-top:8px">
      <input id="cbox" class="grow" placeholder="Message the channel (@bot to address one)...">
      <button class="primary" id="cpost">Post</button></div></div>`);
  m.appendChild(chanPanel);
  const bindPanel = $(`<div class="panel"><h3>Platform routing (Slack / Teams / Discord)</h3>
    <table id="bt"></table>
    <div class="row" style="margin-top:8px">
      <select id="bplat"><option>slack</option><option>teams</option><option>discord</option></select>
      <input id="bext" placeholder="platform channel id (e.g. C0ABC123)">
      <select id="bchan"></select>
      <button class="ghost" id="bgo">Bind</button>
      <span class="id">webhook: POST /api/v1/org/platform/&lt;kind&gt;</span></div></div>`);
  m.appendChild(bindPanel);
  const actPanel = $(`<div class="panel"><h3>Scheduled activations (stream cron)</h3>
    <table id="at"></table>
    <div class="row" style="margin-top:8px">
      <select id="abot"></select>
      <select id="achan"></select>
      <input id="acron" placeholder="cron: m h dom mon dow" value="0 9 * * *">
      <input id="anote" class="grow" placeholder="activation note">
      <button class="ghost" id="ago">Schedule</button></div></div>`);
  m.appendChild(actPanel);

  async function refresh() {
    const chart = await api("/api/v1/org/chart").catch(() => ({bots:[],reporting:[]}));
    chartPanel.querySelector("#chart").innerHTML =
      chart.bots.length ? chartSvg(chart.bots, chart.reporting) : "no bots yet";
    for (const sel of ["#rrep", "#rmgr"])
      chartPanel.querySelector(sel).innerHTML = "";
    for (const sel of ["#cowner"]) chanPanel.querySelector(sel).innerHTML = "";
    actPanel.querySelector("#abot").innerHTML = "";
    for (const b of chart.bots) {
      chartPanel.querySelector("#rrep").appendChild(new Option(b.name, b.id));
      chartPanel.querySelector("#rmgr").appendChild(new Option(b.name, b.id));
      chanPanel.querySelector("#cowner").appendChild(new Option(b.name, b.id));
      actPanel.querySelector("#abot").appendChild(new Option(b.name, b.id));
    }
    const {channels} = await api("/api/v1/org/channels").catch(() => ({channels:[]}));
    const sel = chanPanel.querySelector("#csel");
    const prev = sel.value;
    sel.innerHTML = "";
    bindPanel.querySelector("#bchan").innerHTML = "";
    actPanel.querySelector("#achan").innerHTML = "";
    for (const c of channels) {
      sel.appendChild(new Option(c.name, c.id));
      bindPanel.querySelector("#bchan").appendChild(new Option(c.name, c.id));
      actPanel.querySelector("#achan").appendChild(new Option(c.name, c.id));
    }
    if (prev) sel.value = prev;
    const byId = Object.fromEntries(channels.map(c => [c.id, c.name]));
    const {bindings} = await api("/api/v1/org/bindings").catch(() => ({bindings:[]}));
    const bt = bindPanel.querySelector("#bt");
    bt.innerHTML = `<tr><th>platform</th><th>external channel</th><th>org channel</th></tr>`;
    for (const b of bindings || [])
      bt.appendChild($row(`<tr><td>${esc(b.platform)}</td>
        <td>${esc(b.external_id)}</td><td>${esc(byId[b.channel_id] || b.channel_id)}</td></tr>`));
    const {activations} = await api("/api/v1/org/activations").catch(() => ({activations:[]}));
    const at = actPanel.querySelector("#at");
    at.innerHTML = `<tr><th>bot</th><th>channel</th><th>schedule</th><th>note</th><th></th></tr>`;
    const bots = Object.fromEntries(chart.bots.map(b => [b.id, b.name]));
    for (const a of activations || []) {
      const tr = $row(`<tr><td>${esc(bots[a.bot_id] || a.bot_id)}</td>
        <td>${esc(byId[a.channel_id] || a.channel_id)}</td>
        <td><code>${esc(a.schedule)}</code></td><td>${esc(a.note)}</td><td></td></tr>`);
      const del = $(`<button class="ghost danger">remove</button>`);
      del.onclick = async () => {
        await api(`/api/v1/org/activations/${a.id}`, {method:"DELETE"});
        refresh();
      };
      tr.lastElementChild.appendChild(del);
      at.appendChild(tr);
    }
    loadLog();
  }
  async function loadLog() {
    const cid = chanPanel.querySelector("#csel").value;
    const log = chanPanel.querySelector("#clog");
    log.innerHTML = "";
    if (!cid) return;
    const {messages} = await api(`/api/v1/org/channels/${cid}/messages`);
    for (const msg of messages) {
      const d = $(`<div class="msg ${msg.author.startsWith("bot:") ? "assistant" : "user"}"></div>`);
      d.textContent = `${msg.author}: ${msg.body}`;
      log.appendChild(d);
    }
    log.scrollTop = log.scrollHeight;
  }
  top.querySelector("#mkbot").onclick = async () => {
    await api("/api/v1/org/bots", {method:"POST", body: JSON.stringify({
      name: top.querySelector("#bname").value,
      role: top.querySelector("#brole").value,
      agent: top.querySelector("#bagent").checked})});
    refresh();
  };
  chartPanel.querySelector("#raddr").onclick = async () => {
    await api("/api/v1/org/reporting", {method:"POST", body: JSON.stringify({
      report: chartPanel.querySelector("#rrep").value,
      manager: chartPanel.querySelector("#rmgr").value})});
    refresh();
  };
  chanPanel.querySelector("#mkchan").onclick = async () => {
    await api("/api/v1/org/channels", {method:"POST", body: JSON.stringify({
      name: chanPanel.querySelector("#cname").value,
      owner_bot: chanPanel.querySelector("#cowner").value})});
    refresh();
  };
  chanPanel.querySelector("#csel").onchange = loadLog;
  chanPanel.querySelector("#cpost").onclick = async () => {
    const cid = chanPanel.querySelector("#csel").value;
    const box = chanPanel.querySelector("#cbox");
    if (!cid || !box.value.trim()) return;
    await api(`/api/v1/org/channels/${cid}/messages`, {method:"POST",
      body: JSON.stringify({body: box.value})});
    box.value = "";
    loadLog();
  };
  bindPanel.querySelector("#bgo").onclick = async () => {
    await api("/api/v1/org/bindings", {method:"POST", body: JSON.stringify({
      platform: bindPanel.querySelector("#bplat").value,
      external_id: bindPanel.querySelector("#bext").value,
      channel_id: bindPanel.querySelector("#bchan").value})});
    toast("channel bound");
    refresh();
  };
  actPanel.querySelector("#ago").onclick = async () => {
    await api("/api/v1/org/activations", {method:"POST", body: JSON.stringify({
      bot_id: actPanel.querySelector("#abot").value,
      channel_id: actPanel.querySelector("#achan").value,
      schedule: actPanel.querySelector("#acron").value,
      note: actPanel.querySelector("#anote").value})});
    toast("activation scheduled");
    refresh();
  };
  refresh();
}
