/* Secrets: envelope-encrypted values referenced as ${secrets.NAME}. */
import {$, $row, api, esc} from "./core.js";

export async function render(m) {
  const form = $(`<div class="panel row">
    <input id="sn" placeholder="SECRET_NAME">
    <input id="sv" class="grow" placeholder="value" type="password">
    <button class="primary" id="sgo">Set secret</button>
    <span class="id">referenced as \${secrets.NAME} in app prompts/tools</span></div>`);
  m.appendChild(form);
  const p = $(`<div class="panel"><table id="st"></table></div>`);
  m.appendChild(p);
  async function refresh() {
    const {secrets} = await api("/api/v1/secrets").catch(() => ({secrets:[]}));
    const st = p.querySelector("#st");
    st.innerHTML = `<tr><th>name</th><th></th></tr>`;
    for (const s of secrets || []) {
      const name = s.name || s;
      const tr = $row(`<tr><td>${esc(name)}</td><td></td></tr>`);
      const del = $(`<button class="ghost danger">delete</button>`);
      del.onclick = async () => {
        await api(`/api/v1/secrets/${encodeURIComponent(name)}`, {method:"DELETE"});
        refresh();
      };
      tr.lastElementChild.appendChild(del);
      st.appendChild(tr);
    }
    if (!(secrets || []).length)
      st.appendChild($row(`<tr><td colspan="2" class="id">no secrets</td></tr>`));
  }
  form.querySelector("#sgo").onclick = async () => {
    await api("/api/v1/secrets", {method:"POST", body: JSON.stringify({
      name: form.querySelector("#sn").value,
      value: form.querySelector("#sv").value})});
    form.querySelector("#sv").value = "";
    refresh();
  };
  refresh();
}
