/* Runners + serving-profile editor: heartbeats, profile assignment with
 * compatibility filtering, runner logs. */
import {$, $row, api, authHeaders, esc, setRefresh, tab, toast} from "./core.js";

export async function render(m) {
  const p = $(`<div class="panel"><h3>TPU runners</h3><table id="rt"></table></div>`);
  const logPanel = $(`<div class="panel" style="display:none">
    <h3 id="lt"></h3>
    <pre id="lp" class="code"></pre>
  </div>`);
  m.appendChild(p);
  m.appendChild(logPanel);

  const profPanel = $(`<div class="panel"><h3>Serving profiles</h3>
    <table id="pt"></table>
    <textarea id="py" class="code" rows="8" style="margin-top:8px"
      placeholder="name: my-profile&#10;requirement: {chips: 8, vendor: tpu}&#10;models:&#10;  - name: meta-llama/Meta-Llama-3-8B-Instruct&#10;    mesh: {tp: 4, device_offset: 0}"></textarea>
    <div class="row" style="margin-top:8px">
      <button class="primary" id="pc">Create profile</button>
      <button class="ghost" id="pe">Load into editor…</button></div></div>`);
  m.appendChild(profPanel);

  async function refresh() {
    // don't clobber an in-progress interaction: skip the cycle while the
    // operator has a control inside the runners table focused
    if (p.contains(document.activeElement) &&
        document.activeElement.tagName !== "BODY") return;
    const picked = {};   // preserve pending (unassigned) dropdown choices
    for (const sel of p.querySelectorAll("select[data-runner]"))
      picked[sel.dataset.runner] = sel.value;
    const {runners} = await api("/api/v1/runners");
    const {profiles} = await api("/api/v1/profiles").catch(() => ({profiles:[]}));
    const rt = p.querySelector("#rt");
    rt.innerHTML = `<tr><th>id</th><th>profile</th><th>status</th>
      <th>models</th><th>chips</th><th>assign</th><th></th></tr>`;
    for (const r of runners) {
      const tr = $row(`<tr><td>${esc(r.id)}</td>
        <td>${esc(r.profile_name)}</td>
        <td><span class="tag ${esc(r.profile_status)}">${esc(r.profile_status)}</span></td>
        <td>${esc((r.models || []).join(", "))}</td>
        <td>${(r.accelerators || []).length}</td><td></td><td></td></tr>`);
      const cell = tr.children[5];
      const sel = document.createElement("select");
      sel.dataset.runner = r.id;
      cell.appendChild(sel);
      api(`/api/v1/runners/${r.id}/compatible-profiles`)
        .then(doc => {
          for (const n of doc.profiles) sel.appendChild(new Option(n, n));
          sel.value = picked[r.id] || r.profile_name || sel.value;
        }).catch(() => {});
      const go = $(`<button class="ghost">assign</button>`);
      go.onclick = async () => {
        await api(`/api/v1/runners/${r.id}/assign-profile`, {method:"POST",
          body: JSON.stringify({profile_name: sel.value})});
        toast(`assigned ${sel.value} to ${r.id}`);
        refresh();
      };
      cell.appendChild(go);
      const clr = $(`<button class="ghost danger">clear</button>`);
      clr.onclick = async () => {
        await api(`/api/v1/runners/${r.id}/assignment`, {method:"DELETE"});
        refresh();
      };
      cell.appendChild(clr);
      const lb = $(`<button class="ghost">logs</button>`);
      lb.onclick = async () => {
        logPanel.style.display = "";
        logPanel.querySelector("#lt").textContent = `logs: ${r.id}`;
        const pre = logPanel.querySelector("#lp");
        pre.textContent = "loading…";
        const doc = await api(`/api/v1/runners/${r.id}/logs?tail=300`)
          .catch(e => ({error: String(e)}));
        pre.textContent = doc.logs
          ? doc.logs.map(l => l.line).join("\n") || "(empty)"
          : JSON.stringify(doc);
        pre.scrollTop = pre.scrollHeight;
      };
      tr.children[6].appendChild(lb);
      rt.appendChild(tr);
    }
    if (!runners.length)
      rt.appendChild($row(`<tr><td colspan="7" class="id">no runners heartbeating</td></tr>`));

    const pt = profPanel.querySelector("#pt");
    pt.innerHTML = `<tr><th>name</th><th>requirement</th><th>models</th><th></th></tr>`;
    for (const doc of profiles) {
      const req = doc.requirement || {};
      const tr = $row(`<tr><td>${esc(doc.name)}</td>
        <td>${esc(`${req.chips || 1} × ${req.vendor || "tpu"} ${req.generation || ""}`)}</td>
        <td>${esc((doc.models || []).map(x => x.name).join(", "))}</td><td></td></tr>`);
      const del = $(`<button class="ghost danger">delete</button>`);
      del.onclick = async () => {
        await api(`/api/v1/profiles/${encodeURIComponent(doc.name)}`, {method:"DELETE"});
        refresh();
      };
      tr.lastElementChild.appendChild(del);
      pt.appendChild(tr);
    }
  }
  profPanel.querySelector("#pc").onclick = async () => {
    const r = await fetch("/api/v1/profiles", {method:"POST",
      headers: Object.assign({"Content-Type":"application/yaml"}, authHeaders()),
      body: profPanel.querySelector("#py").value});
    const doc = await r.json();
    if (!r.ok) { toast(doc.error?.message || `HTTP ${r.status}`); return; }
    toast(`profile ${doc.name} saved`);
    refresh();
  };
  profPanel.querySelector("#pe").onclick = async () => {
    const name = prompt("profile name to load") || "";
    if (!name) return;
    const doc = await api(`/api/v1/profiles/${encodeURIComponent(name)}`);
    profPanel.querySelector("#py").value = JSON.stringify(doc, null, 2);
  };
  refresh();
  setRefresh(() => { if (tab === "runners") refresh(); }, 3000);
}
