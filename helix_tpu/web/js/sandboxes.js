/* Org dev sandboxes: run commands, browse files, watch the desktop
 * (reference: the organization sandbox console). */
import {$, $row, api, esc, render as rerender} from "./core.js";

export async function render(m) {
  const {orgs} = await api("/api/v1/orgs").catch(() => ({orgs: []}));
  const top = $(`<div class="panel row">
    <select id="so"></select>
    <input id="sn" placeholder="sandbox name">
    <label class="id"><input type="checkbox" id="sd"> desktop</label>
    <button class="primary" id="mk">Create sandbox</button></div>`);
  m.appendChild(top);
  for (const o of orgs)
    top.querySelector("#so").appendChild(new Option(o.name, o.id));
  top.querySelector("#mk").onclick = async () => {
    const oid = top.querySelector("#so").value;
    await api(`/api/v1/orgs/${oid}/sandboxes`, {method: "POST",
      body: JSON.stringify({name: top.querySelector("#sn").value,
                            with_desktop: top.querySelector("#sd").checked})});
    rerender();
  };

  const list = $(`<div class="panel"><h3>Sandboxes</h3>
    <table><thead><tr><th>name</th><th>org</th><th>status</th>
    <th>commands</th><th></th></tr></thead><tbody id="sb"></tbody></table>
    </div>`);
  m.appendChild(list);
  const sb = list.querySelector("#sb");
  const console_ = $(`<div class="panel" style="display:none">
    <h3 id="ct">console</h3>
    <div class="row"><input id="cc" class="grow" placeholder="shell command">
      <button class="ghost" id="cgo">Run</button></div>
    <pre id="cl" style="max-height:260px;overflow:auto"></pre>
    <div id="cf" class="id"></div></div>`);
  m.appendChild(console_);

  const listings = await Promise.all(orgs.map(
    o => api(`/api/v1/orgs/${o.id}/sandboxes`)
      .catch(() => ({sandboxes: []}))));
  orgs.forEach((o, oi) => {
    for (const s of listings[oi].sandboxes) {
      const tr = $row(`<tr><td>${esc(s.name)}</td><td>${esc(o.name)}</td>
        <td>${esc(s.status)}</td><td>${s.commands}</td>
        <td><button class="ghost open">open</button>
            <button class="ghost del">destroy</button></td></tr>`);
      tr.querySelector(".open").onclick = () => openConsole(o.id, s);
      tr.querySelector(".del").onclick = async () => {
        await api(`/api/v1/orgs/${o.id}/sandboxes/${s.id}`,
                  {method: "DELETE"});
        rerender();
      };
      sb.appendChild(tr);
    }
  });

  function openConsole(oid, s) {
    console_.style.display = "";
    console_.querySelector("#ct").textContent = `console: ${s.name}`;
    const log = console_.querySelector("#cl");
    log.textContent = "";   // a previous sandbox's transcript is not ours
    console_.querySelector("#cgo").onclick = async () => {
      const cmd = console_.querySelector("#cc").value;
      const c = await api(`/api/v1/orgs/${oid}/sandboxes/${s.id}/commands`,
        {method: "POST", body: JSON.stringify({command: cmd})});
      log.textContent += `$ ${cmd}\n`;
      // poll to just past the server's 300s command timeout, backing off
      const deadline = Date.now() + 310_000;
      while (Date.now() < deadline) {
        const st = await api(
          `/api/v1/orgs/${oid}/sandboxes/${s.id}/commands/${c.id}`);
        if (st.status !== "running") {
          const {lines} = await api(
            `/api/v1/orgs/${oid}/sandboxes/${s.id}/commands/${c.id}/logs`);
          log.textContent += lines.join("\n") +
            `\n[exit ${st.exit_code}]\n`;
          log.scrollTop = log.scrollHeight;
          break;
        }
        await new Promise(r => setTimeout(r, 500));
      }
      listFiles();
    };
    async function listFiles() {
      const {files} = await api(
        `/api/v1/orgs/${oid}/sandboxes/${s.id}/files/list`)
        .catch(() => ({files: []}));
      console_.querySelector("#cf").textContent =
        "files: " + (files.map(f => f.name).join(", ") || "(empty)");
    }
    listFiles();
  }
}
