/* Provider editor + served models. */
import {$, $row, api, esc} from "./core.js";

export async function render(m) {
  const p = $(`<div class="panel"><h3>Inference providers</h3>
    <table id="pv"></table></div>`);
  m.appendChild(p);
  const form = $(`<div class="panel row">
    <input id="pn" placeholder="name">
    <select id="pk"><option>openai_compat</option><option>anthropic</option></select>
    <input id="pu" class="grow" placeholder="base url">
    <input id="pkey" placeholder="api key" type="password">
    <button class="primary" id="pgo">Register</button></div>`);
  m.appendChild(form);
  const mp = $(`<div class="panel"><h3>Served models</h3><table id="mt"></table></div>`);
  m.appendChild(mp);
  async function refresh() {
    const {providers} = await api("/api/v1/providers").catch(() => ({providers:[]}));
    const pv = p.querySelector("#pv");
    pv.innerHTML = `<tr><th>name</th><th>kind</th><th>base url</th><th>key</th></tr>`;
    for (const x of providers)
      pv.appendChild($row(`<tr><td>${esc(x.name)}</td><td>${esc(x.kind)}</td>
        <td>${esc(x.base_url)}</td><td>${x.has_key ? "•••" : "-"}</td></tr>`));
    const models = await api("/v1/models").catch(() => ({data:[]}));
    const mt = mp.querySelector("#mt");
    mt.innerHTML = `<tr><th>id</th><th>owner</th><th>context</th></tr>`;
    for (const md of models.data || [])
      mt.appendChild($row(`<tr><td>${esc(md.id)}</td><td>${esc(md.owned_by || "")}</td>
        <td>${esc(md.context_length || "")}</td></tr>`));
  }
  form.querySelector("#pgo").onclick = async () => {
    await api("/api/v1/providers", {method:"POST", body: JSON.stringify({
      name: form.querySelector("#pn").value,
      kind: form.querySelector("#pk").value,
      base_url: form.querySelector("#pu").value,
      api_key: form.querySelector("#pkey").value})});
    refresh();
  };
  refresh();
}
