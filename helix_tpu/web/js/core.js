/* Core: auth, helpers, tab router.  Each tab is an ES module under
 * /ui/js/<tab>.js exporting `render(main, ctx)`; the router dynamic-imports
 * it so one broken page never takes down the app shell. */

export const TABS = ["chat","sessions","projects","tasks","apps","org",
  "desktops","sandboxes","knowledge","runners","compute","providers",
  "wallet","evals","oauth","secrets","triggers","admin"];

export let tab = location.hash.slice(1) || "chat";
export let ME = null;
let refreshTimer = null;

export const $ = (h) => {
  const d = document.createElement("div"); d.innerHTML = h;
  return d.firstElementChild;
};
export const $row = (h) => {
  const t = document.createElement("table"); t.innerHTML = h;
  return t.querySelector("tr");
};
export const esc = (s) => String(s ?? "").replace(/[&<>"']/g,
  (c) => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));

export function authHeaders() {
  const k = localStorage.getItem("helix_api_key");
  return k ? {"Authorization": `Bearer ${k}`} : {};
}

export async function api(p, opts = {}) {
  opts.headers = Object.assign({}, authHeaders(), opts.headers || {});
  const r = await fetch(p, opts);
  if (r.status === 401) { showLogin(); throw new Error("unauthenticated"); }
  const doc = await r.json().catch(() => ({}));
  if (!r.ok) {
    const msg = doc.error?.message || `HTTP ${r.status}`;
    toast(msg);
    throw new Error(msg);
  }
  return doc;
}

export function toast(msg) {
  const t = $(`<div class="toast"></div>`);
  t.textContent = msg;
  document.body.appendChild(t);
  setTimeout(() => t.remove(), 5000);
}

/* pages register their polling loop here; the router clears it on tab
 * switch so background tabs never keep fetching */
export function setRefresh(fn, ms) {
  if (refreshTimer) clearInterval(refreshTimer);
  refreshTimer = setInterval(fn, ms);
}

/* ------------------------------------------------------------------ auth */
function showLogin() {
  document.getElementById("login-overlay").style.display = "";
}
function hideLogin() {
  document.getElementById("login-overlay").style.display = "none";
}
export async function whoami() {
  try {
    const doc = await api("/api/v1/auth/me");
    ME = doc.user;
    document.getElementById("who").textContent =
      doc.auth_required
        ? `${ME.email || ME.name}${ME.admin ? " (admin)" : ""}`
        : "auth disabled";
    document.getElementById("logout").style.display =
      doc.auth_required ? "" : "none";
    hideLogin();
    return true;
  } catch { return false; }
}

document.getElementById("logout").onclick = () => {
  localStorage.removeItem("helix_api_key"); location.reload();
};
document.getElementById("login-go").onclick = async () => {
  // validate BEFORE persisting: a bad key must not poison later loads,
  // and a network failure is not a rejection
  const key = document.getElementById("login-key").value.trim();
  const err = document.getElementById("login-err");
  let r;
  try {
    r = await fetch("/api/v1/auth/me",
      {headers: {"Authorization": `Bearer ${key}`}});
  } catch (e) {
    err.textContent = `server unreachable: ${e.message || e}`;
    return;
  }
  if (r.status === 401) { err.textContent = "key rejected"; return; }
  if (!r.ok) { err.textContent = `server error (HTTP ${r.status})`; return; }
  localStorage.setItem("helix_api_key", key);
  await whoami();
  render();
};
document.getElementById("boot-go").onclick = async () => {
  try {
    const r = await fetch("/api/v1/users", {method:"POST",
      body: JSON.stringify({email:
        document.getElementById("boot-email").value, admin:true})});
    const doc = await r.json();
    if (!r.ok) throw new Error(doc.error?.message || `HTTP ${r.status}`);
    localStorage.setItem("helix_api_key", doc.api_key);
    toast(`admin created — key saved to this browser`);
    if (await whoami()) render();
  } catch (e) {
    document.getElementById("login-err").textContent = String(e.message || e);
  }
};

/* ---------------------------------------------------------------- router */
function nav() {
  const n = document.getElementById("nav");
  n.innerHTML = "";
  for (const t of TABS) {
    const b = document.createElement("button");
    b.textContent = t;
    b.className = t === tab ? "active" : "";
    b.onclick = () => { tab = t; location.hash = t; render(); };
    n.appendChild(b);
  }
}

export async function render() {
  if (!TABS.includes(tab)) tab = "chat";   // stale bookmarks from old tabs
  nav();
  if (refreshTimer) { clearInterval(refreshTimer); refreshTimer = null; }
  const m = document.getElementById("main");
  m.innerHTML = "";
  try {
    const mod = await import(`/ui/js/${tab}.js`);
    await mod.render(m);
  } catch (e) {
    const d = $(`<div class="panel" style="color:var(--err)"></div>`);
    d.textContent = `failed to load ${tab}: ${e.message || e}`;
    m.appendChild(d);
  }
}

window.addEventListener("hashchange", () => {
  tab = location.hash.slice(1) || "chat"; render();
});
// render regardless of auth state: a transient auth/me failure must not
// leave a blank page (tabs surface their own errors; 401s raise the
// login overlay from the api() wrapper)
whoami().finally(() => render());
