/* App editor: helix.yaml upsert + app list/inspect/delete. */
import {$, $row, api, authHeaders, esc, toast} from "./core.js";

export async function render(m) {
  const editor = $(`<div class="panel"><h3>App editor (helix.yaml)</h3>
    <textarea id="yaml" class="code" rows="12"
      placeholder="apiVersion: app.aispec.org/v1alpha1&#10;kind: AIApp&#10;metadata:&#10;  name: my-app&#10;spec: ..."></textarea>
    <div class="row" style="margin-top:8px">
      <button class="primary" id="save">Apply</button>
      <span class="id">POSTs the YAML to /api/v1/apps (upsert by name)</span>
    </div></div>`);
  m.appendChild(editor);
  editor.querySelector("#save").onclick = async () => {
    const r = await fetch("/api/v1/apps", {method:"POST",
      headers: Object.assign({"Content-Type":"application/yaml"}, authHeaders()),
      body: editor.querySelector("#yaml").value});
    const doc = await r.json();
    if (!r.ok) { toast(doc.error?.message || `HTTP ${r.status}`); return; }
    toast(`applied app ${doc.name}`);
    refresh();
  };
  const listPanel = $(`<div class="panel"><h3>Apps</h3>
    <table><tr><th>id</th><th>name</th><th>owner</th><th></th><th></th></tr>
    </table><pre class="code" id="doc" style="display:none"></pre></div>`);
  m.appendChild(listPanel);
  async function refresh() {
    const {apps} = await api("/api/v1/apps").catch(() => ({apps:[]}));
    const tbl = listPanel.querySelector("table");
    tbl.innerHTML = "<tr><th>id</th><th>name</th><th>owner</th><th></th><th></th></tr>";
    for (const a of apps) {
      const tr = $row(`<tr><td>${esc(a.id)}</td><td>${esc(a.name)}</td>
        <td>${esc(a.owner)}</td><td></td><td></td></tr>`);
      const v = $(`<button class="ghost">view</button>`);
      v.onclick = async () => {
        const doc = await api(`/api/v1/apps/${a.id}`);
        const pre = listPanel.querySelector("#doc");
        pre.style.display = "";
        pre.textContent = JSON.stringify(doc, null, 2);
      };
      tr.children[3].appendChild(v);
      const del = $(`<button class="ghost danger">delete</button>`);
      del.onclick = async () => {
        await api(`/api/v1/apps/${a.id}`, {method:"DELETE"}); refresh();
      };
      tr.children[4].appendChild(del);
      tbl.appendChild(tr);
    }
    if (!apps.length)
      listPanel.querySelector("table").appendChild(
        $row(`<tr><td colspan="5" class="id">no apps yet</td></tr>`));
  }
  refresh();
}
