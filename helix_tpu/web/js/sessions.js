/* Session browser: history, transcript viewer, delete. */
import {$, $row, api, esc, render as rerender} from "./core.js";

export async function render(m) {
  const wrap = $(`<div class="panel"><h3>Session history</h3>
    <table><tr><th>id</th><th>name</th><th>owner</th><th></th><th></th></tr></table>
    <div id="detail"></div></div>`);
  m.appendChild(wrap);
  const {sessions} = await api("/api/v1/sessions").catch(() => ({sessions:[]}));
  const tbl = wrap.querySelector("table");
  const detail = wrap.querySelector("#detail");
  for (const s of sessions) {
    const tr = $row(`<tr><td>${esc(s.id)}</td><td>${esc(s.name)}</td>
      <td>${esc(s.owner)}</td><td></td><td></td></tr>`);
    const b = $(`<button class="ghost">open</button>`);
    b.onclick = async () => {
      const doc = await api(`/api/v1/sessions/${s.id}`);
      detail.innerHTML = `<h3 style="margin-top:14px">${esc(s.name)}</h3>`;
      for (const it of doc.interactions || []) {
        const d = $(`<div class="msg ${esc(it.role || "assistant")}"></div>`);
        d.textContent = `${it.role}: ${
          typeof it.content === "string" ? it.content
          : JSON.stringify(it.content)}`.slice(0, 2000);
        detail.appendChild(d);
      }
      if (!(doc.interactions || []).length)
        detail.appendChild($(`<div class="id">no interactions</div>`));
    };
    tr.children[3].appendChild(b);
    const del = $(`<button class="ghost danger">delete</button>`);
    del.onclick = async () => {
      await api(`/api/v1/sessions/${s.id}`, {method:"DELETE"});
      rerender();
    };
    tr.children[4].appendChild(del);
    tbl.appendChild(tr);
  }
  if (!sessions.length)
    wrap.appendChild($(`<div class="id">no sessions yet</div>`));
}
