/* Admin: health, users/keys, organizations, notifications, error ring,
 * DB migrations ledger, version. */
import {$, $row, api, esc} from "./core.js";

export async function render(m) {
  const health = await api("/healthz").catch(() => ({}));
  const lic = await api("/api/v1/config/license").catch(() => ({}));
  m.appendChild($(`<div class="panel row">
    <div><div class="statlabel">status</div><div class="stat">${esc(health.status || "?")}</div></div>
    <div style="margin-left:24px"><div class="statlabel">runners</div>
      <div class="stat">${health.runners ?? "?"}</div></div>
    <div style="margin-left:24px"><div class="statlabel">license</div>
      <div class="stat">${esc(lic.tier || "?")}</div>
      <div class="id">${esc(lic.license ? `${lic.license.org} · ${lic.license.seats} seats` : (lic.error || "community tier"))}</div></div></div>`));

  const users = $(`<div class="panel"><h3>Users & API keys</h3>
    <div class="row"><input id="ue" placeholder="email">
      <input id="un" placeholder="name">
      <label class="id"><input type="checkbox" id="ua"> admin</label>
      <button class="primary" id="ugo">Create user</button></div>
    <div id="ukey" class="id" style="margin-top:6px"></div>
    <div class="row" style="margin-top:10px">
      <input id="kid" placeholder="user id">
      <button class="ghost" id="kgo">Mint API key</button></div>
    <div id="kout" class="id" style="margin-top:6px"></div></div>`);
  m.appendChild(users);
  users.querySelector("#ugo").onclick = async () => {
    const doc = await api("/api/v1/users", {method:"POST", body: JSON.stringify({
      email: users.querySelector("#ue").value,
      name: users.querySelector("#un").value,
      admin: users.querySelector("#ua").checked})});
    users.querySelector("#ukey").textContent =
      `created ${doc.id} — API key (copy now, shown once): ${doc.api_key}`;
  };
  users.querySelector("#kgo").onclick = async () => {
    const uid = users.querySelector("#kid").value.trim();
    const doc = await api(`/api/v1/users/${uid}/keys`, {method:"POST",
      body: JSON.stringify({name:"web"})});
    users.querySelector("#kout").textContent = `new key: ${doc.api_key}`;
  };

  const orgs = $(`<div class="panel"><h3>Organizations</h3>
    <div class="row"><input id="on" placeholder="org name">
      <button class="ghost" id="ogo">Create org</button></div>
    <table id="ot" style="margin-top:8px"></table></div>`);
  m.appendChild(orgs);
  async function loadOrgs() {
    const {orgs: list} = await api("/api/v1/orgs").catch(() => ({orgs:[]}));
    const ot = orgs.querySelector("#ot");
    ot.innerHTML = `<tr><th>id</th><th>name</th><th>members</th></tr>`;
    for (const o of list || []) {
      const tr = $row(`<tr><td>${esc(o.id)}</td><td>${esc(o.name)}</td><td>…</td></tr>`);
      api(`/api/v1/orgs/${o.id}/members`).then(doc => {
        tr.lastElementChild.textContent =
          (doc.members || []).map(x => x.user_id || x).join(", ") || "-";
      }).catch(() => {});
      ot.appendChild(tr);
    }
  }
  orgs.querySelector("#ogo").onclick = async () => {
    await api("/api/v1/orgs", {method:"POST", body: JSON.stringify({
      name: orgs.querySelector("#on").value})});
    loadOrgs();
  };
  loadOrgs();

  const mig = $(`<div class="panel"><h3>Database migrations</h3>
    <table id="mt"></table></div>`);
  m.appendChild(mig);
  const {migrations} = await api("/api/v1/admin/migrations")
    .catch(() => ({migrations:[]}));
  const mt = mig.querySelector("#mt");
  mt.innerHTML = `<tr><th>component</th><th>version</th><th>name</th><th>applied</th></tr>`;
  for (const x of migrations || [])
    mt.appendChild($row(`<tr><td>${esc(x.component)}</td><td>${x.version}</td>
      <td>${esc(x.name)}</td>
      <td>${esc(new Date((x.applied_at || 0) * 1000).toLocaleString())}</td></tr>`));

  const notif = $(`<div class="panel"><h3>Notifications</h3><table id="nt"></table></div>`);
  m.appendChild(notif);
  const {notifications} = await api("/api/v1/notifications")
    .catch(() => ({notifications:[]}));
  const nt = notif.querySelector("#nt");
  nt.innerHTML = `<tr><th>when</th><th>kind</th><th>title</th><th>body</th></tr>`;
  for (const n of (notifications || []).slice(0, 50)) {
    const tr = $row(`<tr><td>${esc(new Date(n.created_at * 1000).toLocaleTimeString())}</td>
      <td><span class="tag">${esc(n.kind)}</span></td><td></td><td></td></tr>`);
    tr.children[2].textContent = n.title;
    tr.children[3].textContent = (n.body || "").slice(0, 160);
    nt.appendChild(tr);
  }

  const errs = $(`<div class="panel"><h3>Error ring (janitor)</h3><table id="et"></table></div>`);
  m.appendChild(errs);
  const {errors} = await api("/api/v1/errors").catch(() => ({errors:[]}));
  const et = errs.querySelector("#et");
  et.innerHTML = `<tr><th>when</th><th>where</th><th>error</th></tr>`;
  for (const e of (errors || []).slice(-50).reverse()) {
    const tr = $row(`<tr><td>${esc(new Date((e.ts || 0) * 1000).toLocaleTimeString())}</td>
      <td>${esc(e.where || e.source || "")}</td><td></td></tr>`);
    tr.lastElementChild.textContent = (e.error || e.message || "").slice(0, 200);
    et.appendChild(tr);
  }
  if (!(errors || []).length)
    et.appendChild($row(`<tr><td colspan="3" class="id">no captured errors</td></tr>`));
}
