/* Kanban: spec tasks across the board with live PR/CI state on the
 * cards (polled every 4 s), spec review actions, PR diff viewer. */
import {$, $row, api, authHeaders, esc, setRefresh, tab} from "./core.js";

const COLS = {backlog:["backlog","planning","spec_revision"],
  "spec review":["spec_review"],
  implementing:["implementation_queued","implementing"],
  "pr review":["pr_review"], done:["done","failed","cancelled"]};

export async function render(m) {
  const top = $(`<div class="panel row">
    <input id="proj" placeholder="project" value="default">
    <input id="title" class="grow" placeholder="task title">
    <button class="primary" id="mk">Create task</button></div>`);
  m.appendChild(top);
  const board = $(`<div class="board"></div>`);
  m.appendChild(board);
  top.querySelector("#mk").onclick = async () => {
    await api("/api/v1/spec-tasks", {method:"POST", body: JSON.stringify({
      project: top.querySelector("#proj").value,
      title: top.querySelector("#title").value})});
    refresh();
  };
  async function refresh() {
    const {tasks} = await api("/api/v1/spec-tasks");
    // one PR-index fetch per cycle: cards show live PR + CI state
    const prs = Object.fromEntries(
      ((await api("/api/v1/pull-requests").catch(() => ({pull_requests:[]})))
        .pull_requests || []).map(p => [p.id, p]));
    board.innerHTML = "";
    for (const [name, statuses] of Object.entries(COLS)) {
      const col = $(`<div class="col"><h3>${esc(name)}</h3></div>`);
      for (const t of tasks.filter(t => statuses.includes(t.status))) {
        const c = $(`<div class="card"><b>${esc(t.title)}</b>
          <div class="id">${esc(t.id)} · <span class="tag ${esc(t.status)}">${esc(t.status)}</span></div>
        </div>`);
        const pr = t.pr_id ? prs[t.pr_id] : null;
        if (pr) {
          c.appendChild($(`<div class="id">PR <span class="tag ${esc(pr.status)}">${esc(pr.status)}</span>
            · CI <span class="tag ${esc(pr.ci_status)}">${esc(pr.ci_status)}</span></div>`));
        }
        c.querySelector("b").style.cursor = "pointer";
        c.querySelector("b").onclick = () => taskDetail(t);
        if (t.status === "spec_review") {
          const a = $(`<button class="ghost">approve</button>`);
          a.onclick = async () => { await api(`/api/v1/spec-tasks/${t.id}/review`,
            {method:"POST", body:JSON.stringify({decision:"approve"})}); refresh(); };
          c.appendChild(a);
          const rc = $(`<button class="ghost">request changes</button>`);
          rc.onclick = async () => {
            const comment = prompt("What should change?") || "";
            if (!comment) return;
            await api(`/api/v1/spec-tasks/${t.id}/review`, {method:"POST",
              body: JSON.stringify({decision:"request_changes", comment})});
            refresh();
          };
          c.appendChild(rc);
        }
        if (t.status === "pr_review" && t.pr_id) {
          const mg = $(`<button class="ghost">merge PR</button>`);
          mg.onclick = async () => { await api(`/api/v1/pull-requests/${t.pr_id}/merge`,
            {method:"POST"}); refresh(); };
          c.appendChild(mg);
        }
        if (t.error) {
          const e = $(`<div style="color:var(--err);font-size:11px"></div>`);
          e.textContent = t.error.slice(0, 120);
          c.appendChild(e);
        }
        col.appendChild(c);
      }
      board.appendChild(col);
    }
  }
  refresh();
  setRefresh(() => { if (tab === "tasks") refresh(); }, 4000);

  async function taskDetail(t) {
    const doc = await api(`/api/v1/spec-tasks/${t.id}`);
    let detail = m.querySelector("#task-detail");
    if (detail) detail.remove();
    detail = $(`<div class="panel" id="task-detail"></div>`);
    const h = $(`<h3></h3>`); h.textContent = doc.title;
    detail.appendChild(h);
    const meta = $(`<div class="id"></div>`);
    meta.textContent =
      `${doc.id} · ${doc.status} · branch ${doc.task_branch || "-"}` +
      ` · CI attempts ${doc.ci_attempts || 0}`;
    detail.appendChild(meta);
    if (doc.description) {
      const d = $(`<p style="white-space:pre-wrap"></p>`);
      d.textContent = doc.description; detail.appendChild(d);
    }
    if (doc.pr_id) {
      const prdoc = (await api(`/api/v1/pull-requests`)).pull_requests
        .find(p => p.id === doc.pr_id);
      if (prdoc) {
        const pr = $(`<div class="id"></div>`);
        pr.textContent = `PR ${prdoc.id}: ${prdoc.status} · CI ${
          prdoc.ci_status}`;
        detail.appendChild(pr);
      }
      const diffBtn = $(`<button class="ghost">view diff</button>`);
      diffBtn.onclick = async () => {
        const r = await fetch(`/api/v1/pull-requests/${doc.pr_id}/diff`,
          {headers: authHeaders()});
        const pre = $(`<pre class="code"></pre>`);
        pre.textContent = await r.text();
        detail.appendChild(pre);
      };
      detail.appendChild(diffBtn);
    }
    const rh = $(`<h3 style="margin-top:10px">Design review</h3>`);
    detail.appendChild(rh);
    for (const r of doc.reviews || []) {
      const row = $(`<div class="msg"></div>`);
      row.textContent = `[${r.decision}] ${r.author}: ${r.comment}`;
      detail.appendChild(row);
    }
    if (!(doc.reviews || []).length)
      detail.appendChild($(`<div class="id">no review comments yet</div>`));
    m.appendChild(detail);
    detail.scrollIntoView();
  }
}
