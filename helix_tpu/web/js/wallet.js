/* Wallet: balance/tier, Stripe checkout + subscription, transactions,
 * usage metering. */
import {$, $row, api, esc, render as rerender, toast} from "./core.js";

export async function render(m) {
  const w = await api("/api/v1/wallet").catch(() => ({balance_usd: 0}));
  const sub = await api("/api/v1/wallet/subscription").catch(() => null);
  const stats = $(`<div class="grid3">
    <div class="panel"><div class="statlabel">balance</div>
      <div class="stat">$${(w.balance_usd ?? 0).toFixed(2)}</div></div>
    <div class="panel"><div class="statlabel">tier</div>
      <div class="stat">${esc(w.tier || "free")}</div>
      <div class="id" id="substate"></div></div>
    <div class="panel"><div class="statlabel">top up</div>
      <div class="row"><input id="amt" style="width:90px" value="10">
        <button class="primary" id="tgo">Add</button>
        <button class="ghost" id="sgo">Card…</button></div>
      <div class="row" style="margin-top:6px">
        <button class="ghost" id="subgo">Subscribe to Pro</button></div></div>
  </div>`);
  m.appendChild(stats);
  if (sub)
    stats.querySelector("#substate").textContent =
      sub.active ? `subscription active (${sub.status || "ok"})`
                 : "no subscription";
  stats.querySelector("#tgo").onclick = async () => {
    await api("/api/v1/wallet/topup", {method:"POST", body: JSON.stringify({
      usd: parseFloat(stats.querySelector("#amt").value || "0")})});
    rerender();
  };
  stats.querySelector("#sgo").onclick = async () => {
    // Stripe checkout session for card top-ups; inert unless the
    // operator configured Stripe keys
    const doc = await api("/api/v1/wallet/topup-session", {method:"POST",
      body: JSON.stringify({
        usd: parseFloat(stats.querySelector("#amt").value || "0")})})
      .catch(() => null);
    if (doc?.url) location.href = doc.url;
    else toast("Stripe is not configured on this deployment");
  };
  stats.querySelector("#subgo").onclick = async () => {
    const doc = await api("/api/v1/wallet/subscription-session",
      {method:"POST", body: "{}"}).catch(() => null);
    if (doc?.url) location.href = doc.url;
    else toast("Stripe is not configured on this deployment");
  };
  const tx = $(`<div class="panel"><h3>Transactions</h3><table id="tt"></table></div>`);
  m.appendChild(tx);
  const {transactions} = await api("/api/v1/wallet/transactions")
    .catch(() => ({transactions:[]}));
  const tt = tx.querySelector("#tt");
  tt.innerHTML = `<tr><th>when</th><th>kind</th><th>amount</th><th>note</th></tr>`;
  for (const t of (transactions || []).slice(0, 50)) {
    const tr = $row(`<tr><td>${esc(new Date((t.created_at || 0) * 1000).toLocaleString())}</td>
      <td>${esc(t.kind)}</td><td>$${(t.amount_usd ?? t.usd ?? 0).toFixed(4)}</td><td></td></tr>`);
    tr.lastElementChild.textContent = t.note || t.reference || "";
    tt.appendChild(tr);
  }
  const up = $(`<div class="panel"><h3>Usage</h3><table id="ut"></table></div>`);
  m.appendChild(up);
  const {usage} = await api("/api/v1/usage").catch(() => ({usage:[]}));
  const ut = up.querySelector("#ut");
  ut.innerHTML = `<tr><th>model</th><th>requests</th><th>prompt tokens</th>
    <th>completion tokens</th></tr>`;
  for (const u of usage || [])
    ut.appendChild($row(`<tr><td>${esc(u.model)}</td><td>${u.requests ?? u.calls ?? 0}</td>
      <td>${u.prompt_tokens ?? 0}</td><td>${u.completion_tokens ?? 0}</td></tr>`));
  if (!(usage || []).length)
    ut.appendChild($row(`<tr><td colspan="4" class="id">no usage recorded</td></tr>`));
}
