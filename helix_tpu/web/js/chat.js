/* Chat: streaming session chat against the OpenAI-surface routes. */
import {$, api, authHeaders} from "./core.js";

let sessionId = null;

export async function render(m) {
  const panel = $(`<div class="panel">
    <div class="chat-log" id="log"></div>
    <div class="row" style="margin-top:10px">
      <select id="model"></select>
      <input id="box" class="grow" placeholder="Say something...">
      <button class="primary" id="send">Send</button>
      <button class="ghost" id="newchat">New chat</button>
    </div></div>`);
  m.appendChild(panel);
  const models = await api("/v1/models").catch(() => ({data:[]}));
  const sel = panel.querySelector("#model");
  for (const md of models.data || [])
    sel.appendChild(new Option(md.id, md.id));
  const log = panel.querySelector("#log");
  const add = (role, text) => {
    const d = $(`<div class="msg ${role}"></div>`);
    d.textContent = text; log.appendChild(d);
    log.scrollTop = log.scrollHeight; return d;
  };
  panel.querySelector("#newchat").onclick = () => {
    sessionId = null; log.innerHTML = "";
  };
  const send = async () => {
    const box = panel.querySelector("#box");
    const text = box.value.trim(); if (!text) return;
    box.value = ""; add("user", text);
    const d = add("assistant", "…");
    if (!sessionId) {
      const s = await api("/api/v1/sessions", {method:"POST",
        body: JSON.stringify({name:"web", doc:{model: sel.value}})})
        .catch(() => null);
      if (!s || !s.id) { d.textContent = "error: could not create session"; return; }
      sessionId = s.id;
    }
    const r = await fetch(`/api/v1/sessions/${sessionId}/chat`, {
      method: "POST", headers: authHeaders(),
      body: JSON.stringify({message:text, model: sel.value, stream:true}),
    });
    if (!r.ok) {
      let msg = `HTTP ${r.status}`;
      try { msg = (await r.json()).error?.message || msg; } catch {}
      d.textContent = `error: ${msg}`;
      return;
    }
    d.textContent = "";
    const reader = r.body.getReader();
    const dec = new TextDecoder(); let buf = "";
    for (;;) {
      const {done, value} = await reader.read();
      if (done) break;
      buf += dec.decode(value, {stream:true});
      for (const line of buf.split("\n\n").slice(0, -1)) {
        const p = line.replace(/^data: /, "").trim();
        if (!p || p === "[DONE]") continue;
        try {
          const c = JSON.parse(p);
          const delta = c.choices?.[0]?.delta?.content;
          if (delta) d.textContent += delta;
        } catch {}
      }
      buf = buf.split("\n\n").slice(-1)[0];
    }
  };
  panel.querySelector("#send").onclick = send;
  panel.querySelector("#box").onkeydown = (e) => { if (e.key === "Enter") send(); };
}
