/* Knowledge: sources, re-index, semantic search. */
import {$, $row, api, esc, render as rerender} from "./core.js";

export async function render(m) {
  const top = $(`<div class="panel row">
    <input id="kn" placeholder="name">
    <textarea id="kt" class="grow" placeholder="inline text content" rows="2"></textarea>
    <button class="primary" id="mk">Add knowledge</button></div>`);
  m.appendChild(top);
  top.querySelector("#mk").onclick = async () => {
    await api("/api/v1/knowledge", {method:"POST", body: JSON.stringify({
      name: top.querySelector("#kn").value, text: top.querySelector("#kt").value})});
    rerender();
  };
  const search = $(`<div class="panel row">
    <select id="ksel"></select>
    <input id="kq" class="grow" placeholder="semantic search query">
    <button class="ghost" id="kgo">Search</button></div>`);
  m.appendChild(search);
  const results = $(`<div class="panel" style="display:none"><h3>Results</h3>
    <div id="kr"></div></div>`);
  m.appendChild(results);
  const {knowledge} = await api("/api/v1/knowledge");
  for (const k of knowledge)
    search.querySelector("#ksel").appendChild(new Option(k.name, k.id));

  // bundled metasearch (searx-compatible /api/v1/search)
  const web = $(`<div class="panel"><h3>Web search</h3>
    <div class="row"><input id="wq" class="grow" placeholder="metasearch the web">
      <button class="ghost" id="wgo">Search</button></div>
    <div id="wr" style="margin-top:8px"></div></div>`);
  m.appendChild(web);
  web.querySelector("#wgo").onclick = async () => {
    const out = web.querySelector("#wr");
    out.textContent = "searching...";
    try {
      const data = await api(`/api/v1/search?q=${encodeURIComponent(web.querySelector("#wq").value)}`);
      out.innerHTML = "";
      for (const r of data.results) {
        const d = $(`<div style="margin-bottom:8px"><a target="_blank"></a>
          <div class="id"></div></div>`);
        const a = d.querySelector("a");
        a.href = r.url; a.textContent = r.title || r.url;
        d.querySelector("div").textContent = r.content || "";
        out.appendChild(d);
      }
      if (!data.results.length) out.textContent = "no results";
    } catch (e) { out.textContent = String(e.message || e); }
  };
  search.querySelector("#kgo").onclick = async () => {
    const kid = search.querySelector("#ksel").value;
    if (!kid) return;
    const doc = await api(`/api/v1/knowledge/${kid}/search`, {method:"POST",
      body: JSON.stringify({query: search.querySelector("#kq").value, top_k: 5})});
    results.style.display = "";
    const kr = results.querySelector("#kr");
    kr.innerHTML = "";
    for (const hit of doc.results || []) {
      const d = $(`<div class="card"></div>`);
      d.textContent = `[${(hit.score ?? 0).toFixed(3)}] ${hit.text || hit.chunk || ""}`.slice(0, 400);
      kr.appendChild(d);
    }
    if (!(doc.results || []).length) kr.textContent = "no hits";
  };
  const p = $(`<div class="panel"><table><tr><th>id</th><th>name</th>
    <th>state</th><th>version</th><th></th><th></th></tr></table></div>`);
  for (const k of knowledge) {
    const tr = $row(`<tr><td>${esc(k.id)}</td>
      <td>${esc(k.name)}</td><td><span class="tag ${esc(k.state)}">${esc(k.state)}</span></td>
      <td>${esc(k.version)}</td><td></td><td></td></tr>`);
    const rf = $(`<button class="ghost">refresh</button>`);
    rf.onclick = async () => {
      await api(`/api/v1/knowledge/${k.id}/refresh`, {method:"POST"}); rerender();
    };
    tr.children[4].appendChild(rf);
    const del = $(`<button class="ghost danger">delete</button>`);
    del.onclick = async () => {
      await api(`/api/v1/knowledge/${k.id}`, {method:"DELETE"}); rerender();
    };
    tr.children[5].appendChild(del);
    p.querySelector("table").appendChild(tr);
  }
  m.appendChild(p);
}
