/* JS decoder for the native lossy video codec (native/vidcodec — 'HXV1').
 * Mirrors the C++ decoder: zlib payload -> per-macroblock skip/intra flags
 * -> (run,level) RLE -> dequant -> 8x8 IDCT -> YCbCr 4:2:0 -> RGBA canvas.
 * The browser-side half of the reference's WebCodecs worker
 * (frontend/src/lib/helix-stream/), implemented for our bitstream. */

const QLUMA = [
  16,11,10,16,24,40,51,61, 12,12,14,19,26,58,60,55,
  14,13,16,24,40,57,69,56, 14,17,22,29,51,87,80,62,
  18,22,37,56,68,109,103,77, 24,35,55,64,81,104,113,92,
  49,64,78,87,103,121,120,101, 72,92,95,98,112,100,103,99];
const QCHROMA = [
  17,18,24,47,99,99,99,99, 18,21,26,66,99,99,99,99,
  24,26,56,99,99,99,99,99, 47,66,99,99,99,99,99,99,
  99,99,99,99,99,99,99,99, 99,99,99,99,99,99,99,99,
  99,99,99,99,99,99,99,99, 99,99,99,99,99,99,99,99];
const ZIGZAG = [
  0,1,8,16,9,2,3,10,17,24,32,25,18,11,4,5,
  12,19,26,33,40,48,41,34,27,20,13,6,7,14,21,28,
  35,42,49,56,57,50,43,36,29,22,15,23,30,37,44,51,
  58,59,52,45,38,31,39,46,53,60,61,54,47,55,62,63];

const COS = [];
for (let u = 0; u < 8; u++) {
  const a = u === 0 ? Math.sqrt(0.125) : 0.5;
  COS.push(Array.from({length: 8},
    (_, x) => a * Math.cos((2*x + 1) * u * Math.PI / 16)));
}

function idct8x8(coef, out) {
  const tmp = new Float32Array(64);
  for (let v = 0; v < 8; v++)
    for (let y = 0; y < 8; y++) {
      let s = 0;
      for (let u = 0; u < 8; u++) s += coef[u*8 + v] * COS[u][y];
      tmp[y*8 + v] = s;
    }
  for (let y = 0; y < 8; y++)
    for (let x = 0; x < 8; x++) {
      let s = 0;
      for (let u = 0; u < 8; u++) s += tmp[y*8 + u] * COS[u][x];
      out[y*8 + x] = s;
    }
}

class Reader {
  constructor(buf) { this.b = buf; this.i = 0; this.ok = true; }
  u8() {
    if (this.i >= this.b.length) { this.ok = false; return 0; }
    return this.b[this.i++];
  }
  varint() {
    let v = 0, shift = 0;
    for (;;) {
      if (this.i >= this.b.length || shift > 28) { this.ok = false; return 0; }
      const byte = this.b[this.i++];
      v |= (byte & 0x7f) << shift;
      if (!(byte & 0x80)) break;
      shift += 7;
    }
    return (v >>> 1) ^ -(v & 1);
  }
}

function decodeBlock(br, qbase, qscale, dst, stride, ox, oy) {
  const q = new Float32Array(64);
  let i = 0;
  for (;;) {
    const run = br.u8();
    if (!br.ok) return false;
    if (run === 255) break;
    i += run;
    if (i >= 64) return false;
    q[ZIGZAG[i]] = br.varint();
    i++;
  }
  const deq = new Float32Array(64), rec = new Float32Array(64);
  for (let k = 0; k < 64; k++)
    deq[k] = q[k] * Math.max(qbase[k] * qscale, 1);
  idct8x8(deq, rec);
  for (let y = 0; y < 8; y++)
    for (let x = 0; x < 8; x++) {
      const v = Math.round(rec[y*8 + x] + 128);
      dst[(oy + y) * stride + ox + x] = v < 0 ? 0 : (v > 255 ? 255 : v);
    }
  return true;
}

export class HxvDecoder {
  constructor(w, h) {
    this.sw = w; this.sh = h;
    this.w = Math.ceil(w / 16) * 16;
    this.h = Math.ceil(h / 16) * 16;
    this.mbx = this.w / 16; this.mby = this.h / 16;
    this.Y = new Uint8Array(this.w * this.h);
    this.Cb = new Uint8Array(this.w * this.h / 4).fill(128);
    this.Cr = new Uint8Array(this.w * this.h / 4).fill(128);
    this.haveFrame = false;
    this.frameId = 0;
    this.needKeyframe = false;  // set on P-frame gap; viewer should ask for an I
    this._chain = Promise.resolve(null);
  }

  /* Serialized decode: packets must apply in arrival order, but each
   * decode awaits DecompressionStream — chain them so a small P-frame
   * can never overtake a large keyframe onto the shared planes. */
  decode(packet) {
    this._chain = this._chain.catch(() => null)
      .then(() => this._decode(packet));
    return this._chain;
  }

  async _decode(packet) {
    const dv = new DataView(packet);
    if (dv.getUint32(0, true) !== 0x31565848) return null;  // 'HXV1'
    const type = dv.getUint8(12);
    const fid = dv.getUint32(4, true);
    if (type === 1 && !this.haveFrame) { this.needKeyframe = true; return null; }
    if (type === 1 && fid !== this.frameId + 1) {
      // a P-frame was dropped upstream (server ring buffer under
      // backpressure): our reconstruction has diverged — freeze and ask
      // for a keyframe instead of painting garbage until kf_interval
      this.needKeyframe = true;
      return null;
    }
    const qscale = dv.getFloat32(14, true);
    const comp = new Uint8Array(packet, 22);
    const ds = new DecompressionStream("deflate");
    const stream = new Blob([comp]).stream().pipeThrough(ds);
    const raw = new Uint8Array(await new Response(stream).arrayBuffer());
    const br = new Reader(raw);
    const cw = this.w / 2;
    let codedMbs = 0;
    for (let my = 0; my < this.mby; my++)
      for (let mx = 0; mx < this.mbx; mx++) {
        const flags = br.u8();
        if (!br.ok) return null;
        if (flags === 0) continue;
        codedMbs++;
        const px = mx * 16, py = my * 16;
        for (let by = 0; by < 2; by++)
          for (let bx = 0; bx < 2; bx++)
            if (!decodeBlock(br, QLUMA, qscale, this.Y, this.w,
                             px + bx*8, py + by*8)) return null;
        if (!decodeBlock(br, QCHROMA, qscale, this.Cb, cw, px/2, py/2))
          return null;
        if (!decodeBlock(br, QCHROMA, qscale, this.Cr, cw, px/2, py/2))
          return null;
      }
    this.haveFrame = true;
    this.frameId = fid;
    this.needKeyframe = false;
    // all-skip P-frame: the screen is unchanged — skip the full-frame
    // color conversion + canvas upload entirely
    if (type === 1 && codedMbs === 0) return null;
    // YCbCr -> RGBA
    const img = new ImageData(this.sw, this.sh);
    const d = img.data;
    for (let y = 0; y < this.sh; y++)
      for (let x = 0; x < this.sw; x++) {
        const Y = this.Y[y * this.w + x];
        const cb = this.Cb[(y >> 1) * cw + (x >> 1)] - 128;
        const cr = this.Cr[(y >> 1) * cw + (x >> 1)] - 128;
        const c = (Y - 16) * 298;
        let r = (c + 409*cr + 128) >> 8,
            g = (c - 100*cb - 208*cr + 128) >> 8,
            b = (c + 516*cb + 128) >> 8;
        const o = (y * this.sw + x) * 4;
        d[o]   = r < 0 ? 0 : (r > 255 ? 255 : r);
        d[o+1] = g < 0 ? 0 : (g > 255 ? 255 : g);
        d[o+2] = b < 0 ? 0 : (b > 255 ? 255 : b);
        d[o+3] = 255;
      }
    return img;
  }
}
