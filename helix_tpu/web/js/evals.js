/* Evals: per-app evaluation suites + runs (reference: the evaluations
 * product surface the apps carry). */
import {$, $row, api, esc, setRefresh, tab, toast} from "./core.js";

/* "question => expected substring" lines -> the backend's question docs
 * (assertions[{type: contains}] — expected_contains is NOT a backend
 * field; validate_suite_doc would drop it and every run would pass
 * trivially). */
export function parseQuestions(text) {
  return text.split("\n").map(l => l.trim()).filter(Boolean)
    .map(l => {
      const [q, want] = l.split("=>").map(x => x.trim());
      const doc = {question: q};
      if (want) doc.assertions = [{type: "contains", value: want}];
      return doc;
    });
}

export async function render(m) {
  await renderQuestionSets(m);
  const top = $(`<div class="panel row">
    <span class="id">app</span><select id="app" class="grow"></select></div>`);
  m.appendChild(top);
  const suitePanel = $(`<div class="panel"><h3>Evaluation suites</h3>
    <table id="st"></table>
    <div class="row" style="margin-top:8px">
      <input id="sn" placeholder="suite name">
      <textarea id="sq" class="grow code" rows="3"
        placeholder='questions, one per line: "question => expected substring"'></textarea>
      <button class="primary" id="sgo">Create suite</button></div></div>`);
  m.appendChild(suitePanel);
  const runPanel = $(`<div class="panel"><h3>Runs</h3><table id="rt"></table>
    <pre class="code" id="rd" style="display:none"></pre></div>`);
  m.appendChild(runPanel);

  const {apps} = await api("/api/v1/apps").catch(() => ({apps:[]}));
  const appSel = top.querySelector("#app");
  for (const a of apps) appSel.appendChild(new Option(a.name, a.id));
  if (!apps.length) {
    suitePanel.querySelector("#st").innerHTML =
      `<tr><td class="id">create an app first — suites hang off apps</td></tr>`;
    return;
  }
  appSel.onchange = refresh;

  async function refresh() {
    const appId = appSel.value;
    if (!appId) return;
    const {suites} = await api(
      `/api/v1/apps/${appId}/evaluation-suites`).catch(() => ({suites:[]}));
    const st = suitePanel.querySelector("#st");
    st.innerHTML = `<tr><th>id</th><th>name</th><th>questions</th><th></th><th></th></tr>`;
    for (const s of suites || []) {
      const tr = $row(`<tr><td>${esc(s.id)}</td><td>${esc(s.name)}</td>
        <td>${(s.questions || []).length}</td><td></td><td></td></tr>`);
      const run = $(`<button class="ghost">run</button>`);
      run.onclick = async () => {
        await api(`/api/v1/apps/${appId}/evaluation-suites/${s.id}/runs`,
          {method:"POST", body: "{}"});
        toast("run started");
        loadRuns(s.id);
      };
      tr.children[3].appendChild(run);
      const del = $(`<button class="ghost danger">delete</button>`);
      del.onclick = async () => {
        await api(`/api/v1/apps/${appId}/evaluation-suites/${s.id}`,
          {method:"DELETE"});
        refresh();
      };
      tr.children[4].appendChild(del);
      tr.onclick = (e) => {
        if (e.target.tagName !== "BUTTON") loadRuns(s.id);
      };
      st.appendChild(tr);
    }
    if (!(suites || []).length)
      st.appendChild($row(`<tr><td colspan="5" class="id">no suites for this app</td></tr>`));
    if ((suites || []).length) loadRuns(suites[0].id);
  }

  async function loadRuns(suiteId) {
    const appId = appSel.value;
    const {runs} = await api(
      `/api/v1/apps/${appId}/evaluation-suites/${suiteId}/runs`)
      .catch(() => ({runs:[]}));
    const rt = runPanel.querySelector("#rt");
    rt.innerHTML = `<tr><th>id</th><th>status</th><th>score</th><th>when</th><th></th></tr>`;
    for (const r of (runs || []).slice().reverse()) {
      const score = r.summary
        ? `${r.summary.passed ?? 0}/${r.summary.total ?? 0}` : "-";
      const tr = $row(`<tr><td>${esc(r.id)}</td>
        <td><span class="tag ${esc(r.status)}">${esc(r.status)}</span></td>
        <td>${esc(score)}</td>
        <td>${esc(new Date((r.created_at || 0) * 1000).toLocaleString())}</td>
        <td></td></tr>`);
      const v = $(`<button class="ghost">results</button>`);
      v.onclick = async () => {
        const doc = await api(`/api/v1/apps/${appId}/evaluation-runs/${r.id}`);
        const pre = runPanel.querySelector("#rd");
        pre.style.display = "";
        pre.textContent = JSON.stringify(doc, null, 2);
      };
      tr.lastElementChild.appendChild(v);
      rt.appendChild(tr);
    }
    if (!(runs || []).length)
      rt.appendChild($row(`<tr><td colspan="5" class="id">no runs yet</td></tr>`));
  }

  suitePanel.querySelector("#sgo").onclick = async () => {
    const questions = parseQuestions(suitePanel.querySelector("#sq").value);
    await api(`/api/v1/apps/${appSel.value}/evaluation-suites`, {
      method:"POST", body: JSON.stringify({
        name: suitePanel.querySelector("#sn").value, questions})});
    toast("suite created");
    refresh();
  };
  refresh();
  setRefresh(() => { if (tab === "evals") refresh(); }, 5000);
}

export async function renderQuestionSets(m) {
  const p = $(`<div class="panel"><h3>Question sets</h3>
    <p class="id">Standalone reusable questionnaires; executions run
    through the eval engine.</p>
    <div class="row"><input id="qn" placeholder="set name">
      <textarea id="qq" class="grow code" rows="2"
        placeholder='one per line: "question => expected substring"'></textarea>
      <button class="primary" id="qgo">Create</button></div>
    <table id="qt"></table>
    <div id="qe" style="margin-top:8px"></div></div>`);
  m.appendChild(p);

  async function showExecutions(qs) {
    const qe = p.querySelector("#qe");
    qe.textContent = "loading executions...";
    const {executions} = await api(
      `/api/v1/question-sets/${qs.id}/executions`
    ).catch(() => ({executions: []}));
    qe.innerHTML = `<h3>executions: ${esc(qs.name)}</h3>`;
    for (const ex of executions.slice().reverse()) {
      const sum = ex.summary || {};
      const d = $(`<div class="id"></div>`);
      d.textContent = `${ex.id}  ${ex.status}  ` +
        (sum.total ? `${sum.passed || 0}/${sum.total} passed` : "");
      qe.appendChild(d);
    }
    if (!executions.length) qe.innerHTML += `<div class="id">none yet</div>`;
  }

  async function refresh() {
    const {question_sets} = await api("/api/v1/question-sets")
      .catch(() => ({question_sets: []}));
    const qt = p.querySelector("#qt");
    qt.innerHTML = `<tr><th>name</th><th>questions</th><th></th></tr>`;
    for (const qs of question_sets) {
      const tr = $row(`<tr><td>${esc(qs.name)}</td>
        <td>${(qs.questions || []).length}</td>
        <td><button class="ghost run">execute</button>
            <button class="ghost del">delete</button></td></tr>`);
      tr.querySelector(".run").onclick = async () => {
        await api(`/api/v1/question-sets/${qs.id}/executions`,
                  {method: "POST", body: "{}"});
        showExecutions(qs);
      };
      tr.querySelector("td:first-child").style.cursor = "pointer";
      tr.querySelector("td:first-child").onclick =
        () => showExecutions(qs);
      tr.querySelector(".del").onclick = async () => {
        await api(`/api/v1/question-sets/${qs.id}`, {method: "DELETE"});
        refresh();
      };
      qt.appendChild(tr);
    }
  }
  p.querySelector("#qgo").onclick = async () => {
    const questions = parseQuestions(p.querySelector("#qq").value);
    await api("/api/v1/question-sets", {method: "POST",
      body: JSON.stringify({name: p.querySelector("#qn").value,
                            questions})});
    refresh();
  };
  refresh();
}
