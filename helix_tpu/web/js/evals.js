/* Evals: per-app evaluation suites + runs (reference: the evaluations
 * product surface the apps carry). */
import {$, $row, api, esc, setRefresh, tab, toast} from "./core.js";

export async function render(m) {
  const top = $(`<div class="panel row">
    <span class="id">app</span><select id="app" class="grow"></select></div>`);
  m.appendChild(top);
  const suitePanel = $(`<div class="panel"><h3>Evaluation suites</h3>
    <table id="st"></table>
    <div class="row" style="margin-top:8px">
      <input id="sn" placeholder="suite name">
      <textarea id="sq" class="grow code" rows="3"
        placeholder='questions, one per line: "question => expected substring"'></textarea>
      <button class="primary" id="sgo">Create suite</button></div></div>`);
  m.appendChild(suitePanel);
  const runPanel = $(`<div class="panel"><h3>Runs</h3><table id="rt"></table>
    <pre class="code" id="rd" style="display:none"></pre></div>`);
  m.appendChild(runPanel);

  const {apps} = await api("/api/v1/apps").catch(() => ({apps:[]}));
  const appSel = top.querySelector("#app");
  for (const a of apps) appSel.appendChild(new Option(a.name, a.id));
  if (!apps.length) {
    suitePanel.querySelector("#st").innerHTML =
      `<tr><td class="id">create an app first — suites hang off apps</td></tr>`;
    return;
  }
  appSel.onchange = refresh;

  async function refresh() {
    const appId = appSel.value;
    if (!appId) return;
    const {suites} = await api(
      `/api/v1/apps/${appId}/evaluation-suites`).catch(() => ({suites:[]}));
    const st = suitePanel.querySelector("#st");
    st.innerHTML = `<tr><th>id</th><th>name</th><th>questions</th><th></th><th></th></tr>`;
    for (const s of suites || []) {
      const tr = $row(`<tr><td>${esc(s.id)}</td><td>${esc(s.name)}</td>
        <td>${(s.questions || []).length}</td><td></td><td></td></tr>`);
      const run = $(`<button class="ghost">run</button>`);
      run.onclick = async () => {
        await api(`/api/v1/apps/${appId}/evaluation-suites/${s.id}/runs`,
          {method:"POST", body: "{}"});
        toast("run started");
        loadRuns(s.id);
      };
      tr.children[3].appendChild(run);
      const del = $(`<button class="ghost danger">delete</button>`);
      del.onclick = async () => {
        await api(`/api/v1/apps/${appId}/evaluation-suites/${s.id}`,
          {method:"DELETE"});
        refresh();
      };
      tr.children[4].appendChild(del);
      tr.onclick = (e) => {
        if (e.target.tagName !== "BUTTON") loadRuns(s.id);
      };
      st.appendChild(tr);
    }
    if (!(suites || []).length)
      st.appendChild($row(`<tr><td colspan="5" class="id">no suites for this app</td></tr>`));
    if ((suites || []).length) loadRuns(suites[0].id);
  }

  async function loadRuns(suiteId) {
    const appId = appSel.value;
    const {runs} = await api(
      `/api/v1/apps/${appId}/evaluation-suites/${suiteId}/runs`)
      .catch(() => ({runs:[]}));
    const rt = runPanel.querySelector("#rt");
    rt.innerHTML = `<tr><th>id</th><th>status</th><th>score</th><th>when</th><th></th></tr>`;
    for (const r of (runs || []).slice().reverse()) {
      const score = r.summary
        ? `${r.summary.passed ?? 0}/${r.summary.total ?? 0}` : "-";
      const tr = $row(`<tr><td>${esc(r.id)}</td>
        <td><span class="tag ${esc(r.status)}">${esc(r.status)}</span></td>
        <td>${esc(score)}</td>
        <td>${esc(new Date((r.created_at || 0) * 1000).toLocaleString())}</td>
        <td></td></tr>`);
      const v = $(`<button class="ghost">results</button>`);
      v.onclick = async () => {
        const doc = await api(`/api/v1/apps/${appId}/evaluation-runs/${r.id}`);
        const pre = runPanel.querySelector("#rd");
        pre.style.display = "";
        pre.textContent = JSON.stringify(doc, null, 2);
      };
      tr.lastElementChild.appendChild(v);
      rt.appendChild(tr);
    }
    if (!(runs || []).length)
      rt.appendChild($row(`<tr><td colspan="5" class="id">no runs yet</td></tr>`));
  }

  suitePanel.querySelector("#sgo").onclick = async () => {
    const questions = suitePanel.querySelector("#sq").value.split("\n")
      .map(l => l.trim()).filter(Boolean)
      .map(l => {
        const [q, expect] = l.split("=>").map(x => x.trim());
        return expect ? {question: q, expected_contains: expect}
                      : {question: q};
      });
    await api(`/api/v1/apps/${appSel.value}/evaluation-suites`, {
      method:"POST", body: JSON.stringify({
        name: suitePanel.querySelector("#sn").value, questions})});
    toast("suite created");
    refresh();
  };
  refresh();
  setRefresh(() => { if (tab === "evals") refresh(); }, 5000);
}
