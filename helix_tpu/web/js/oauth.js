/* OAuth connections: provider registry, connect (authorization-code
 * flow), connection status, disconnect. */
import {$, $row, api, esc} from "./core.js";

export async function render(m) {
  await renderServiceConnections(m);
  const p = $(`<div class="panel"><h3>OAuth connections</h3>
    <p class="id">Connect external accounts (GitHub, ...) — agents use the
    tokens for repo skills; knowledge sources use them for SharePoint.</p>
    <table id="ot"></table></div>`);
  m.appendChild(p);

  async function refresh() {
    const {providers} = await api("/api/v1/oauth/providers")
      .catch(() => ({providers:[]}));
    const {connections} = await api("/api/v1/oauth/connections")
      .catch(() => ({connections:[]}));
    const connected = Object.fromEntries(
      (connections || []).map(c => [c.provider || c, c]));
    const ot = p.querySelector("#ot");
    ot.innerHTML = `<tr><th>provider</th><th>status</th><th></th></tr>`;
    for (const pr of providers || []) {
      const name = pr.name || pr;
      const conn = connected[name];
      const tr = $row(`<tr><td>${esc(name)}</td>
        <td><span class="tag ${conn ? "connected" : ""}">${conn ? "connected" : "not connected"}</span></td>
        <td></td></tr>`);
      if (conn) {
        const d = $(`<button class="ghost danger">disconnect</button>`);
        d.onclick = async () => {
          await api(`/api/v1/oauth/connections/${encodeURIComponent(name)}`,
            {method:"DELETE"});
          refresh();
        };
        tr.lastElementChild.appendChild(d);
      } else {
        const c = $(`<button class="ghost">connect</button>`);
        c.onclick = async () => {
          const doc = await api(
            `/api/v1/oauth/connect/${encodeURIComponent(name)}`);
          if (doc.url) location.href = doc.url;
        };
        tr.lastElementChild.appendChild(c);
      }
      ot.appendChild(tr);
    }
    if (!(providers || []).length)
      ot.appendChild($row(`<tr><td colspan="3" class="id">
        no OAuth providers configured (set HELIX_GITHUB_CLIENT_ID/SECRET)
        </td></tr>`));
  }
  refresh();
}

export async function renderServiceConnections(m) {
  const p = $(`<div class="panel"><h3>Service connections</h3>
    <p class="id">Stored forge credentials (tokens encrypted at rest) —
    forge sync and repo import resolve them here.</p>
    <div class="row"><select id="sp"><option>github</option>
      <option>gitlab</option><option>generic</option></select>
      <input id="sn" placeholder="name">
      <input id="st" class="grow" placeholder="token" type="password">
      <button class="primary" id="sgo">Add</button></div>
    <table id="sc"></table></div>`);
  m.appendChild(p);

  async function refresh() {
    const {connections} = await api("/api/v1/service-connections")
      .catch(() => ({connections: []}));
    const sc = p.querySelector("#sc");
    sc.innerHTML = `<tr><th>name</th><th>provider</th><th>api</th><th></th></tr>`;
    for (const c of connections) {
      const tr = $row(`<tr><td>${esc(c.name)}</td><td>${esc(c.provider)}</td>
        <td class="id">${esc(c.api_base || "")}</td>
        <td><button class="ghost del">remove</button></td></tr>`);
      tr.querySelector(".del").onclick = async () => {
        await api(`/api/v1/service-connections/${c.id}`, {method: "DELETE"});
        refresh();
      };
      sc.appendChild(tr);
    }
  }
  p.querySelector("#sgo").onclick = async () => {
    await api("/api/v1/service-connections", {method: "POST",
      body: JSON.stringify({
        provider: p.querySelector("#sp").value,
        name: p.querySelector("#sn").value,
        token: p.querySelector("#st").value,
      })});
    p.querySelector("#st").value = "";
    refresh();
  };
  refresh();
}
