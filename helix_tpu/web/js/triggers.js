/* Triggers: cron + webhook (+ platform adapters via kind). */
import {$, $row, api, esc} from "./core.js";

export async function render(m) {
  const form = $(`<div class="panel row">
    <select id="tk"><option>webhook</option><option>cron</option>
      <option>slack</option><option>teams</option><option>discord</option>
      <option>azure-devops</option><option>crisp</option></select>
    <input id="tname" placeholder="name">
    <input id="tspec" class="grow" placeholder="cron spec (cron only), e.g. */5 * * * *">
    <input id="tapp" placeholder="app id">
    <button class="primary" id="tgo">Create trigger</button></div>`);
  m.appendChild(form);
  const p = $(`<div class="panel"><table id="tt"></table></div>`);
  m.appendChild(p);
  async function refresh() {
    const {triggers} = await api("/api/v1/triggers").catch(() => ({triggers:[]}));
    const tt = p.querySelector("#tt");
    tt.innerHTML = `<tr><th>id</th><th>kind</th><th>name</th><th>detail</th><th></th></tr>`;
    for (const t of triggers || []) {
      const detail = t.kind === "cron"
        ? (t.cron || t.spec || "") : `POST /webhooks/${t.id}`;
      const tr = $row(`<tr><td>${esc(t.id)}</td><td>${esc(t.kind)}</td>
        <td>${esc(t.name)}</td><td>${esc(detail)}</td><td></td></tr>`);
      const del = $(`<button class="ghost danger">delete</button>`);
      del.onclick = async () => {
        await api(`/api/v1/triggers/${t.id}`, {method:"DELETE"}); refresh();
      };
      tr.lastElementChild.appendChild(del);
      tt.appendChild(tr);
    }
    if (!(triggers || []).length)
      tt.appendChild($row(`<tr><td colspan="5" class="id">no triggers</td></tr>`));
  }
  form.querySelector("#tgo").onclick = async () => {
    await api("/api/v1/triggers", {method:"POST", body: JSON.stringify({
      kind: form.querySelector("#tk").value,
      name: form.querySelector("#tname").value,
      cron: form.querySelector("#tspec").value,
      app_id: form.querySelector("#tapp").value})});
    refresh();
  };
  refresh();
}
