/* Projects: boards over spec-task kanbans + attached repos + git browser
 * (reference: frontend/src/components/project). */
import {$, $row, api, esc, render as rerender} from "./core.js";

export async function render(m) {
  const top = $(`<div class="panel row">
    <input id="pn" placeholder="project name">
    <input id="pd" class="grow" placeholder="description">
    <button class="primary" id="mk">Create project</button></div>`);
  m.appendChild(top);
  top.querySelector("#mk").onclick = async () => {
    await api("/api/v1/projects", {method: "POST", body: JSON.stringify({
      name: top.querySelector("#pn").value,
      description: top.querySelector("#pd").value})});
    rerender();
  };

  const {projects} = await api("/api/v1/projects");
  // one round trip wave, not N sequential fetches
  const progress = await Promise.all(projects.map(
    p => api(`/api/v1/projects/${p.id}/tasks-progress`)
      .catch(() => ({total: 0, done: 0, percent: 0}))));
  const list = $(`<div class="panel"><h3>Projects</h3>
    <table><thead><tr><th>name</th><th>labels</th><th>progress</th>
    <th>repos</th><th></th></tr></thead><tbody id="pb"></tbody></table></div>`);
  m.appendChild(list);
  const pb = list.querySelector("#pb");
  projects.forEach((p, i) => {
    const prog = progress[i];
    const tr = $row(`<tr>
      <td>${p.pinned ? "&#9733; " : ""}${esc(p.name)}</td>
      <td>${p.labels.map(esc).join(", ")}</td>
      <td>${prog.done}/${prog.total} (${prog.percent}%)</td>
      <td>${p.repositories.map(r => esc(r.repo) + (r.primary ? "*" : "")).join(", ")}</td>
      <td><button class="ghost pin">pin</button>
          <button class="ghost del">delete</button></td></tr>`);
    tr.querySelector(".pin").onclick = async () => {
      await api(`/api/v1/projects/${p.id}/pin`,
                {method: "POST", body: JSON.stringify({pinned: !p.pinned})});
      rerender();
    };
    tr.querySelector(".del").onclick = async () => {
      await api(`/api/v1/projects/${p.id}`, {method: "DELETE"});
      rerender();
    };
    pb.appendChild(tr);
  });

  // git browser over the control plane's repos
  const repos = (await api("/api/v1/git/repositories")).repos || [];
  const gb = $(`<div class="panel"><h3>Repository browser</h3>
    <div class="row"><select id="gr"></select>
      <input id="gq" class="grow" placeholder="grep pattern (optional)">
      <button class="ghost" id="go">Browse</button></div>
    <div id="gt" style="margin-top:8px"></div></div>`);
  m.appendChild(gb);
  for (const r of repos) gb.querySelector("#gr").appendChild(new Option(r, r));
  gb.querySelector("#go").onclick = async () => {
    const repo = encodeURIComponent(gb.querySelector("#gr").value);
    const q = gb.querySelector("#gq").value.trim();
    const out = gb.querySelector("#gt");
    out.innerHTML = "";
    if (q) {
      const {hits} = await api(
        `/api/v1/git/repositories/${repo}/grep?q=${encodeURIComponent(q)}`);
      for (const h of hits.slice(0, 50)) {
        const d = $(`<div class="id"></div>`);
        d.textContent = `${h.path}:${h.line}: ${h.text}`;
        out.appendChild(d);
      }
      if (!hits.length) out.textContent = "no matches";
      return;
    }
    const {entries} = await api(`/api/v1/git/repositories/${repo}/tree`);
    for (const e of entries) {
      const d = $(`<div class="id"></div>`);
      d.textContent = `${e.type === "tree" ? "dir " : "file"} ${e.path}` +
        (e.type === "blob" ? ` (${e.size}b)` : "");
      if (e.type === "blob") {
        d.style.cursor = "pointer";
        d.onclick = async () => {
          const f = await api(`/api/v1/git/repositories/${repo}/file-content?path=${encodeURIComponent(e.path)}`);
          const pre = $(`<pre style="max-height:300px;overflow:auto"></pre>`);
          pre.textContent = f.content;
          d.after(pre);
        };
      }
      out.appendChild(d);
    }
  };
}
