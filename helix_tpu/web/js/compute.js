/* Compute: autoscaler instances + golden workspace caches + disk
 * pressure (reference: sandbox/compute manager dashboards). */
import {$, $row, api, esc, setRefresh, tab, toast} from "./core.js";

export async function render(m) {
  const computePanel = $(`<div class="panel"><h3>Compute instances (autoscaler)</h3>
    <table id="ct"></table></div>`);
  m.appendChild(computePanel);
  const goldenPanel = $(`<div class="panel"><h3>Golden workspace caches</h3>
    <table id="gt"></table>
    <div class="row" style="margin-top:8px">
      <button class="ghost" id="ggc">Run GC</button>
      <span class="id" id="gp"></span></div></div>`);
  m.appendChild(goldenPanel);

  async function refresh() {
    const {instances} = await api("/api/v1/compute/instances")
      .catch(() => ({instances:[]}));
    const ct = computePanel.querySelector("#ct");
    ct.innerHTML = `<tr><th>id</th><th>provider</th><th>state</th>
      <th>runner</th><th>sandboxes</th></tr>`;
    for (const i of instances || [])
      ct.appendChild($row(`<tr><td>${esc(i.id)}</td>
        <td>${esc(i.provider)} ${esc(i.provider_id)}</td>
        <td><span class="tag ${esc(i.compute_state)}">${esc(i.compute_state)}</span></td>
        <td>${esc(i.runner_id)}</td>
        <td>${i.active_sandboxes}/${i.max_sandboxes}</td></tr>`));
    if (!(instances || []).length)
      ct.appendChild($row(`<tr><td colspan="5" class="id">autoscaler idle or disabled</td></tr>`));

    const {golden} = await api("/api/v1/workspaces/golden")
      .catch(() => ({golden:[]}));
    const gt = goldenPanel.querySelector("#gt");
    gt.innerHTML = `<tr><th>project</th><th>files</th><th>bytes</th>
      <th>promoted</th><th></th></tr>`;
    for (const g of golden || []) {
      const tr = $row(`<tr><td>${esc(g.project)}</td><td>${g.files}</td>
        <td>${(g.bytes / 1e6).toFixed(1)} MB</td>
        <td>${esc(new Date((g.promoted_at || 0) * 1000).toLocaleString())}</td>
        <td></td></tr>`);
      const del = $(`<button class="ghost danger">drop</button>`);
      del.onclick = async () => {
        await api(`/api/v1/workspaces/golden/${encodeURIComponent(g.project)}`,
          {method:"DELETE"});
        refresh();
      };
      tr.lastElementChild.appendChild(del);
      gt.appendChild(tr);
    }
    if (!(golden || []).length)
      gt.appendChild($row(`<tr><td colspan="5" class="id">no golden snapshots</td></tr>`));
    const pressure = await api("/api/v1/workspaces/pressure").catch(() => null);
    if (pressure)
      goldenPanel.querySelector("#gp").textContent =
        `disk ${pressure.used_pct?.toFixed?.(1) ?? pressure.used_pct}% used`;
  }
  goldenPanel.querySelector("#ggc").onclick = async () => {
    const doc = await api("/api/v1/workspaces/gc", {method:"POST"});
    toast(`GC reaped ${doc.reaped ?? 0} workspaces`);
    refresh();
  };
  refresh();
  setRefresh(() => { if (tab === "compute") refresh(); }, 5000);
}
