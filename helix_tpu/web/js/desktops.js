/* Desktop stream viewer: tile-codec or video-codec frames over WS onto a
 * canvas, pointer + keyboard input back
 * (reference: DesktopStreamViewer.tsx + helix-stream WebCodecs worker). */
import {$, api} from "./core.js";
import {HxvDecoder} from "./vidcodec.js";

export async function render(m) {
  const {desktops} = await api("/api/v1/desktops");
  const list = $(`<div class="panel"><h3>Agent desktops</h3>
    <div id="dl"></div>
    <div class="row" style="margin-top:6px">
      <button id="newgui" class="ghost">+ GUI desktop</button>
    </div></div>`);
  m.appendChild(list);
  const dl = list.querySelector("#dl");
  if (!desktops.length) dl.textContent = "No live desktops. They appear while task agents run.";
  for (const d of desktops) {
    const b = $(`<button class="ghost" style="margin:4px"></button>`);
    b.textContent = `${d.name || d.id} [${d.codec || "tiles"}]`;
    b.onclick = () => watch(d);
    dl.appendChild(b);
  }
  list.querySelector("#newgui").onclick = async () => {
    const d = await api("/api/v1/desktops", {method: "POST",
      body: JSON.stringify({kind: "gui", name: "gui-desktop"})});
    watch(d);
  };
  const view = $(`<div class="panel"><canvas id="cv" width="960" height="540" tabindex="0"
      style="outline:none;max-width:100%"></canvas>
    <div class="row" style="margin-top:8px">
      <input id="inp" class="grow" placeholder="type to the agent...">
    </div></div>`);
  m.appendChild(view);
  let inputWs = null, streamWs = null;

  async function watch(d) {
    if (streamWs) { streamWs.close(); streamWs = null; }
    if (inputWs) { inputWs.close(); inputWs = null; }
    const cv = view.querySelector("#cv");
    cv.width = d.width || 960; cv.height = d.height || 540;
    const ctx = cv.getContext("2d");
    ctx.clearRect(0, 0, cv.width, cv.height);
    const vdec = new HxvDecoder(cv.width, cv.height);
    const proto = location.protocol === "https:" ? "wss" : "ws";
    const ws = new WebSocket(`${proto}://${location.host}/api/v1/desktops/${d.id}/ws/stream`);
    ws.binaryType = "arraybuffer";
    streamWs = ws;
    inputWs = new WebSocket(`${proto}://${location.host}/api/v1/desktops/${d.id}/ws/input`);
    let lastKfReq = 0;
    const send = (o) => { if (inputWs?.readyState === 1) inputWs.send(JSON.stringify(o)); };
    ws.onmessage = async (ev) => {
      const dv = new DataView(ev.data);
      const magic = dv.getUint32(0, true);
      if (magic === 0x31565848) {              // 'HXV1' lossy video
        const img = await vdec.decode(ev.data);
        if (img) ctx.putImageData(img, 0, 0);
        else if (vdec.needKeyframe && Date.now() - lastKfReq > 500) {
          // a P-frame was dropped under backpressure: re-sync with an I
          lastKfReq = Date.now();
          send({type: "refresh"});
        }
        return;
      }
      if (magic !== 0x31465848) return;        // 'HXF1' lossless tiles
      const buf = new Uint8Array(ev.data);
      const W = dv.getUint16(8, true), H = dv.getUint16(10, true),
            NT = dv.getUint16(12, true);
      const tiles = [];
      for (let i = 0; i < NT; i++) {
        tiles.push([dv.getUint16(16 + i*4, true), dv.getUint16(18 + i*4, true)]);
      }
      const comp = buf.slice(16 + NT*4);
      const ds = new DecompressionStream("deflate");
      const stream = new Blob([comp]).stream().pipeThrough(ds);
      const raw = new Uint8Array(await new Response(stream).arrayBuffer());
      let off = 0;
      for (const [tx, ty] of tiles) {
        const tw = Math.min(32, W - tx*32), th = Math.min(32, H - ty*32);
        const img = ctx.createImageData(tw, th);
        for (let p = 0; p < tw*th; p++) {     // BGRA -> RGBA
          img.data[p*4]   = raw[off + p*4 + 2];
          img.data[p*4+1] = raw[off + p*4 + 1];
          img.data[p*4+2] = raw[off + p*4];
          img.data[p*4+3] = raw[off + p*4 + 3];
        }
        ctx.putImageData(img, tx*32, ty*32);
        off += tw*th*4;
      }
    };
    const pos = (e) => {
      const r = cv.getBoundingClientRect();
      return {x: Math.round((e.clientX - r.left) * cv.width / r.width),
              y: Math.round((e.clientY - r.top) * cv.height / r.height)};
    };
    cv.onmousemove = (e) => send({type: "pointer", ...pos(e)});
    cv.onmousedown = (e) => { cv.focus();
      send({type: "pointer", ...pos(e), button: 1, state: "down"}); };
    cv.onmouseup = (e) => send({type: "pointer", ...pos(e), button: 1, state: "up"});
    cv.onkeydown = (e) => {
      if (e.key.length === 1) send({type: "text", text: e.key});
      else send({type: "key", key: e.key});
      e.preventDefault();
    };
    view.querySelector("#inp").onkeydown = (e) => {
      if (e.key === "Enter" && inputWs?.readyState === 1) {
        inputWs.send(JSON.stringify({type:"text", text:e.target.value}));
        e.target.value = "";
      }
    };
  }
}
