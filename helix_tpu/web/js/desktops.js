/* Desktop stream viewer: tile-codec frames over WS onto a canvas,
 * keyboard input back (reference: DesktopStreamViewer.tsx). */
import {$, api} from "./core.js";

export async function render(m) {
  const {desktops} = await api("/api/v1/desktops");
  const list = $(`<div class="panel"><h3>Agent desktops</h3><div id="dl"></div></div>`);
  m.appendChild(list);
  const dl = list.querySelector("#dl");
  if (!desktops.length) dl.textContent = "No live desktops. They appear while task agents run.";
  for (const d of desktops) {
    const b = $(`<button class="ghost" style="margin:4px"></button>`);
    b.textContent = d.name || d.id;
    b.onclick = () => watch(d);
    dl.appendChild(b);
  }
  const view = $(`<div class="panel"><canvas id="cv" width="960" height="540"></canvas>
    <div class="row" style="margin-top:8px">
      <input id="inp" class="grow" placeholder="type to the agent...">
    </div></div>`);
  m.appendChild(view);
  let inputWs = null, streamWs = null;
  async function watch(d) {
    if (streamWs) { streamWs.close(); streamWs = null; }
    if (inputWs) { inputWs.close(); inputWs = null; }
    const cv = view.querySelector("#cv");
    cv.width = d.width; cv.height = d.height;
    const ctx = cv.getContext("2d");
    ctx.clearRect(0, 0, cv.width, cv.height);
    const proto = location.protocol === "https:" ? "wss" : "ws";
    const ws = new WebSocket(`${proto}://${location.host}/api/v1/desktops/${d.id}/ws/stream`);
    ws.binaryType = "arraybuffer";
    streamWs = ws;
    inputWs = new WebSocket(`${proto}://${location.host}/api/v1/desktops/${d.id}/ws/input`);
    ws.onmessage = async (ev) => {
      const buf = new Uint8Array(ev.data);
      const dv = new DataView(ev.data);
      if (dv.getUint32(0, true) !== 0x31465848) return;
      // header: magic(4) frame_id(4) w(2) h(2) ntiles(2) kf(1) res(1) = 16
      const W = dv.getUint16(8, true), H = dv.getUint16(10, true),
            NT = dv.getUint16(12, true);
      const tiles = [];
      for (let i = 0; i < NT; i++) {
        tiles.push([dv.getUint16(16 + i*4, true), dv.getUint16(18 + i*4, true)]);
      }
      const comp = buf.slice(16 + NT*4);
      const ds = new DecompressionStream("deflate");
      const stream = new Blob([comp]).stream().pipeThrough(ds);
      const raw = new Uint8Array(await new Response(stream).arrayBuffer());
      let off = 0;
      for (const [tx, ty] of tiles) {
        const tw = Math.min(32, W - tx*32), th = Math.min(32, H - ty*32);
        const img = ctx.createImageData(tw, th);
        for (let p = 0; p < tw*th; p++) {     // BGRA -> RGBA
          img.data[p*4]   = raw[off + p*4 + 2];
          img.data[p*4+1] = raw[off + p*4 + 1];
          img.data[p*4+2] = raw[off + p*4];
          img.data[p*4+3] = raw[off + p*4 + 3];
        }
        ctx.putImageData(img, tx*32, ty*32);
        off += tw*th*4;
      }
    };
    view.querySelector("#inp").onkeydown = (e) => {
      if (e.key === "Enter" && inputWs?.readyState === 1) {
        inputWs.send(JSON.stringify({type:"text", text:e.target.value}));
        e.target.value = "";
      }
    };
  }
}
