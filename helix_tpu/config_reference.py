"""The deployment's environment-variable reference, in one place.

The reference loads one giant envconfig ``ServerConfig`` whose struct
tags generate the ``serve --help`` env reference
(``api/pkg/config/config.go:11-38``, ``serve.go:78,102``).  This module
is the same single source of truth for helix-tpu: every HELIX_* knob the
runtime reads, with description and default — rendered by
``helix-tpu config-reference`` and asserted complete by tests (a knob
read anywhere in the tree must be documented here).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class EnvVar:
    name: str
    description: str
    default: str = ""
    section: str = "general"


ENV_REFERENCE: tuple = (
    # -- server ----------------------------------------------------------
    EnvVar(
        "HELIX_DB_DSN",
        "Control-plane database location: a filesystem path to the "
        "consolidated SQLite file. A postgres:// DSN is recognised and "
        "rejected with a pointer at the SQLite deployment story (the "
        "reference runs GORM/Postgres; we run one-box SQLite with "
        "cross-entity transactions).",
        section="server",
    ),
    # -- accelerator -----------------------------------------------------
    EnvVar(
        "HELIX_BENCH_BATCH",
        "Decode batch size for bench.py's TPU measurement (default 32; "
        "the KV pool is provisioned for 64 at 256 tokens/request).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_PEAK_FLOPS",
        "Peak accelerator FLOP/s used as the denominator of the runner's "
        "helix_mfu_estimate gauge. Unset: the v5e bf16 peak (197e12) on "
        "TPU backends, no MFU gauge elsewhere.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_SPEC_TOKENS",
        "Speculative decoding override for every engine this node "
        "serves: >0 enables prompt-lookup drafting with that many draft "
        "tokens per slot per verify call, 0 forces speculation off even "
        "where a profile enables it. Unset: the profile's "
        "enable_spec_decode/spec_tokens settings apply.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_ASYNC_LOOP",
        "Asynchronous pipelined engine loop override for every engine "
        "this node serves: truthy dispatches device step N+1 against "
        "predicted post-step state while step N executes and emits "
        "tokens through a bounded off-thread stage (greedy and seeded "
        "temp>0 outputs stay bit-identical to the synchronous loop); "
        "0/false forces the synchronous baseline even where a profile "
        "sets engine.enable_async_loop. Watch helix_device_idle_ratio "
        "and the helix_step_host_build_seconds / "
        "helix_step_emit_seconds histograms for the effect. Unset: the "
        "profile setting applies (default off).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_TOKEN_BUCKETS",
        "Comma-separated token-bucket ladder for the unified ragged "
        "device step's prefill segment (e.g. '64,192,512,2048'). Each "
        "admission wave / prefill chunk pads its flat token axis up to "
        "the smallest rung that fits, so the ladder trades compiled "
        "step shapes (one per rung used, watch "
        "helix_compiled_step_shapes) against padding waste (watch "
        "helix_prefill_padding_ratio). The top rung is always clamped "
        "to max_prefill_len. Unset: powers of two from page_size to "
        "max_prefill_len.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_KV_HOST_POOL_BYTES",
        "Host-RAM KV tier budget (bytes) for every engine this node "
        "serves: prefix-cache evictions spill page contents to pinned "
        "host buffers instead of dying (restored + re-adopted when a "
        "later prompt shares the prefix), and running decoders become "
        "preemptible by page swap (Engine.preempt). Overrides a "
        "profile's engine.host_pool_bytes; 0 forces the tier off. "
        "Unset: the profile setting applies (default off).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_ADMISSION_TIMEOUT",
        "Seconds a request may wait for KV pages before it is shed with "
        "a typed 503 (code kv_exhausted, Retry-After) instead of aging "
        "silently in the queue. While admission has been starved longer "
        "than this, NEW arrivals fast-fail the same way before SSE "
        "headers commit. Applies to queued and preempted-parked "
        "requests. Unset: no deadline (requests wait up to the 600 s "
        "queue reaper).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_PREEMPT_STALL_SECONDS",
        "Admission stall threshold for preemption-by-swap: when the "
        "wait queue has been KV-starved this long, the engine loop "
        "swaps the newest/largest running decoder out to the host KV "
        "tier (exact resume later) instead of letting the whole queue "
        "age out. Needs HELIX_KV_HOST_POOL_BYTES > 0. Unset: never "
        "preempt.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_DRAIN_SECONDS",
        "Graceful-shutdown drain window (node agent SIGTERM/SIGINT "
        "path): the heartbeat flips to draining immediately (the router "
        "stops sending new work), in-flight requests keep generating "
        "this many seconds, and whatever is still unfinished at the "
        "deadline is exported as request snapshots to a peer runner "
        "instead of shed (finish -> snapshot+ship -> shed ladder).",
        default="10",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MIGRATION_TIMEOUT",
        "Cross-runner migration timeout in seconds: bounds each "
        "snapshot ship during drain AND how long an imported request "
        "waits for its stream to be claimed via /v1/migrate/resume "
        "before the peer aborts the orphan.",
        default="30",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MIDSTREAM_FAILOVER",
        "Set to 1 to arm the control plane's SSE-aware dispatch path: "
        "a runner death PAST the first streamed byte continues the "
        "client stream on a surviving runner (resume-from-snapshot "
        "after a clean drain, else deterministic replay-from-prompt "
        "with already-delivered text elided) with exactly-once token "
        "delivery for greedy/seeded requests. Unset/0: mid-stream "
        "death surfaces as an in-band error frame (the PR 2 "
        "behaviour).",
        section="server",
    ),
    EnvVar(
        "HELIX_POOL_DISAGG",
        "Set to 1 to enable disaggregated prefill/decode at the control "
        "plane: streaming prompts dispatch to a prefill-pool runner "
        "that computes the prompt, ships the KV snapshot + sampler "
        "state to a decode-pool peer, and the stream resumes there "
        "(greedy and seeded outputs bit-identical to colocated "
        "serving). Every failure rung falls back toward colocated "
        "serving — prefill runner serves locally on a failed ship, the "
        "decode pool re-prefills on a failed handoff. Needs runners "
        "declaring role: prefill and decode (profile role: or "
        "HELIX_POOL_ROLE). Unset/0: colocated serving.",
        section="server",
    ),
    EnvVar(
        "HELIX_POOL_ROLE",
        "This node's disaggregation pool role (prefill | decode | "
        "mixed), heartbeat-federated to the control plane. Beats the "
        "applied profile's role: declaration (the HELIX_SPEC_TOKENS "
        "operator contract). Ordinary traffic avoids prefill-pool "
        "runners while any decode/mixed runner serves the model; the "
        "prefill handoff picks strictly from the prefill pool. Unset: "
        "the profile's role (default mixed).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_XFER_ATTEMPT_TIMEOUT",
        "Per-attempt timeout in seconds for one KV snapshot ship (a "
        "POST /v1/migrate/import to a peer runner) — drain migration "
        "and disaggregated prefill handoffs both obey it, so one slow "
        "peer cannot wedge a drain.",
        default="10",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_XFER_MAX_ATTEMPTS",
        "Rounds over the candidate peer set a KV snapshot ship makes "
        "before giving up (each round tries every model-matching "
        "target once; rounds back off exponentially).",
        default="3",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_XFER_BACKOFF_BASE",
        "Base seconds of the capped exponential backoff between KV "
        "ship rounds (round n sleeps min(base * 2^n, cap)).",
        default="0.1",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_XFER_BACKOFF_CAP",
        "Cap seconds of the KV ship backoff.",
        default="2.0",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_XFER_DEADLINE",
        "Hard total deadline in seconds for one KV snapshot transfer "
        "(all attempts + backoffs + the disagg handler's wait for "
        "prefill completion). Past it the ship is abandoned "
        "(helix_xfer_deadline_exceeded_total) and the request degrades "
        "to local serving. Unset: HELIX_MIGRATION_TIMEOUT.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_ADAPTER_POOL_SLOTS",
        "Continuous multi-LoRA serving override for every engine this "
        "node serves (the HELIX_SPEC_TOKENS contract — beats the "
        "profile's engine.adapter_pool_slots): >=2 slots arm the "
        "batched adapter path (one resident base model serves many "
        "`model@adapter` tenants through a stacked HBM pool, slot 0 "
        "reserved for the zero identity adapter; the pool shape "
        "compiles once at warmup, so publishing an adapter later "
        "needs no restart or recompile), 0 forces it off even where a "
        "profile enables it. Unset: the profile setting applies "
        "(default off). Not supported for mrope (VL) engines; on "
        "multi-host meshes the pool runs on every host (adapter ids "
        "ride the step plan and followers stage residency before the "
        "step), so publish adapters to the leader and followers as a "
        "pair.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_ADAPTER_HOST_POOL_BYTES",
        "Byte budget for the host rung of the adapter residency "
        "ladder (decoded LoRA adapter trees awaiting an HBM pool "
        "slot; LRU over filestore-backed entries — an adapter whose "
        "only copy is the host one is never evicted). Cold adapters "
        "promote filestore -> host on the async prefetch worker and "
        "host -> HBM at admission. Default 268435456 (256 MiB); 0 "
        "disables the bound.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_ADAPTER_PREFETCH",
        "Async adapter prefetch (ISSUE 15): on (default), a cold "
        "adapter's filestore->host load runs on a background worker "
        "kicked at submit/admission, overlapping the request's queue "
        "wait — an engine step never blocks on an adapter load. "
        "0/false forces synchronous loads (debug/tests).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_FILESTORE_KV_DIR",
        "Root directory of the persistent filestore KV tier (the "
        "bottom rung of the residency ladder: HBM -> host RAM -> peer "
        "-> filestore). Freshly prefilled full prefix pages persist "
        "here (content-addressed by prefix-chain digest, namespaced by "
        "model + KV geometry, blake2b-checksummed) and restore across "
        "process restarts — an agent fleet's shared system prompt "
        "survives a rolling deploy without recomputing. Corrupt or "
        "missing blobs degrade to recompute with a typed counter "
        "(helix_filestore_kv_corrupt_total), never an error. Point it "
        "at a shared filesystem to share prefixes across runners. "
        "Unset: tier off. Multi-host meshes arm it too: point the "
        "leader and every follower at the SAME directory — the step "
        "plan carries each admission's cached_tokens and followers "
        "verify their restore matched the leader's.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_FILESTORE_KV_QUOTA_BYTES",
        "Per-tenant write quota for the filestore KV tier in bytes "
        "(the PR 7 tenant identity is charged at write-through). Past "
        "it new blobs are rejected with a typed counter "
        "(helix_filestore_kv_quota_rejects_total); reads are never "
        "gated. 0/unset: unlimited.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MAX_PAGES_PER_SEQ",
        "Per-sequence page-table capacity for EVERY engine this node "
        "serves (operator-beats-profile, the HELIX_SPEC_TOKENS "
        "contract — it also beats the bump derived from a profile's "
        "context_length). On a tiered engine (ctx_hot_pages > 0) this "
        "caps the DEVICE-resident pages one sequence may hold while "
        "max_model_len can exceed it — the demoted cold middle lives "
        "in the host pool; on a fully-resident engine it caps the "
        "whole sequence. Unset: the profile's engine block (default "
        "128).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_CTX_HOT_PAGES",
        "Tiered KV residency for million-token contexts (ISSUE 20): "
        "> 0 keeps that many attention-hot TAIL pages of each long "
        "sequence in HBM and demotes the cold middle to the host pool "
        "(requires HELIX_KV_HOST_POOL_BYTES), streaming it back "
        "through fixed-size chunks folded into the same online-softmax "
        "merge as ring attention — outputs stay bit-identical to fully "
        "resident while peak HBM pages stay bounded. Every restored "
        "page re-verifies its blake2b checksum; a corrupt page is a "
        "typed error, never wrong attention. Applies to every engine "
        "this node serves (operator-beats-profile); 0 forces fully-"
        "resident even where a profile enables tiering. Unset: the "
        "profile's engine block (default 0 = off).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_CTX_TENANT_TOKENS",
        "Per-tenant quota for the context-caching API (ISSUE 20): the "
        "total prompt tokens one tenant may hold across its POST "
        "/v1/context handles. Past it new creations are rejected 429 "
        "with a typed counter (helix_ctx_quota_rejects_total); "
        "resolving existing handles is never gated. 0/unset: "
        "unlimited.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_EXACT_SAMPLING",
        "Set to 1 to force the exact full-vocab top-p sampling path for "
        "every request (default: auto — the 64-candidate MXU fast path "
        "when the nucleus provably fits, exact fallback otherwise).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_SEARCH_ENGINES",
        "JSON list of metasearch engine specs for the bundled searx-"
        "compatible /search endpoint, e.g. "
        '[{"kind": "searx", "name": "sx", "url": "http://host"}, '
        '{"kind": "mediawiki"}, {"kind": "ddg"}]. Empty (default): '
        "/search returns 503 instead of hanging on missing egress.",
        section="knowledge",
    ),
    EnvVar(
        "HELIX_BROWSER_POOL_SIZE",
        "Instances in the crawling/browsing pool (default 2). Each is an "
        "HTTP fetcher + readability extractor; with HELIX_CHROME_BIN set "
        "the pool seam can hold real Chromium sessions instead.",
        section="knowledge",
    ),
    EnvVar(
        "HELIX_CHROME_BIN",
        "Path to a Chromium binary for the CDP browser seam (JS-rendered "
        "crawling). Unset: the JS-less HttpBrowser serves the pool.",
        section="knowledge",
    ),
    EnvVar(
        "HELIX_FILESTORE",
        "Blob store backend: 'local' (default, rooted FS under the data "
        "dir) or 'gcs' (Google Cloud Storage over the JSON API).",
        section="server",
    ),
    EnvVar(
        "HELIX_GCS_BUCKET",
        "Bucket for HELIX_FILESTORE=gcs (required in that mode).",
        section="server",
    ),
    EnvVar(
        "HELIX_GCS_PREFIX",
        "Optional object-key prefix for the GCS filestore.",
        section="server",
    ),
    EnvVar(
        "HELIX_GCS_ENDPOINT",
        "GCS API endpoint override (default "
        "https://storage.googleapis.com); point at fake-gcs-server or an "
        "emulator in tests/dev.",
        section="server",
    ),
    EnvVar(
        "HELIX_GCS_TOKEN",
        "Static bearer token for GCS requests. Unset: the GCE metadata "
        "server is tried (2 s budget), else anonymous (emulators).",
        section="server",
    ),
    EnvVar(
        "HELIX_LICENSE_KEY",
        "Offline-verifiable ed25519-signed license key (HELIX-... "
        "format). Absent or invalid: the deployment runs the community "
        "tier; /api/v1/config/license reports the reason.",
        section="server",
    ),
    EnvVar(
        "HELIX_LICENSE_PUBKEY",
        "Hex ed25519 public key that license signatures must verify "
        "against (default: the built-in issuer key). Self-licensing "
        "deployments run their own issuer with helix_tpu.control.license.",
        section="server",
    ),
    EnvVar(
        "HELIX_PUBLIC_DOMAINS",
        "Comma-separated domains this deployment itself fronts. The "
        "/.well-known/helix-domain-verify route only answers for claims "
        "on these domains — unset (default), it answers for none, so a "
        "user can never self-verify the deployment's own domain and "
        "hijack email auto-join.",
        section="auth",
    ),
    EnvVar(
        "HELIX_DOMAIN_CLAIM_TTL_S",
        "Seconds an UNVERIFIED org-domain claim blocks competing claims "
        "(default 259200 = 72h). Verified claims never expire.",
        section="auth",
    ),
    # -- auth ------------------------------------------------------------
    EnvVar(
        "HELIX_MASTER_KEY",
        "Envelope-encryption master key for user secrets and OAuth "
        "tokens. Unset: a random key is generated and persisted next to "
        "the auth DB (set explicitly in production).",
        section="auth",
    ),
    EnvVar(
        "HELIX_RUNNER_TOKEN",
        "Shared token nodes present on the runner control loop "
        "(heartbeat, assignment poll, reverse-tunnel dial). Empty + "
        "auth_required: runner endpoints fail closed to admin-only.",
        section="auth",
    ),
    EnvVar(
        "HELIX_API_KEY",
        "Bearer key used by the admin CLI verbs (org/knowledge/secret/"
        "runner) when --api-key is not passed; also injected into "
        "sandboxed agent children as their control-plane credential.",
        section="auth",
    ),
    EnvVar(
        "HELIX_API_BASE",
        "Control-plane base URL injected into sandboxed agent children "
        "(their only egress).",
        section="auth",
    ),
    EnvVar(
        "HELIX_OIDC_ISSUER",
        "OIDC issuer URL; set to enable JWT bearer auth (discovery + "
        "JWKS RS256 verification).",
        section="auth",
    ),
    EnvVar(
        "HELIX_OIDC_CLIENT_ID",
        "Audience expected in OIDC tokens.",
        default="helix",
        section="auth",
    ),
    EnvVar(
        "HELIX_OIDC_ADMIN_EMAILS",
        "Comma-separated emails granted platform admin on OIDC "
        "provision (a pure-OIDC deployment's only admin path).",
        section="auth",
    ),
    # -- integrations -----------------------------------------------------
    EnvVar(
        "HELIX_GITHUB_CLIENT_ID",
        "GitHub OAuth app client id (enables the GitHub agent skill).",
        section="integrations",
    ),
    EnvVar(
        "HELIX_GITHUB_CLIENT_SECRET",
        "GitHub OAuth app client secret.",
        section="integrations",
    ),
    EnvVar(
        "HELIX_SLACK_WEBHOOK_URL",
        "Slack incoming-webhook URL for lifecycle notifications.",
        section="integrations",
    ),
    EnvVar(
        "HELIX_DISCORD_WEBHOOK_URL",
        "Discord webhook URL for lifecycle notifications.",
        section="integrations",
    ),
    EnvVar(
        "HELIX_SMTP_HOST",
        "SMTP host for email notifications (enables the email sink).",
        section="integrations",
    ),
    EnvVar("HELIX_SMTP_PORT", "SMTP port.", default="587",
           section="integrations"),
    EnvVar("HELIX_SMTP_FROM", "Email sender.", default="helix@localhost",
           section="integrations"),
    EnvVar("HELIX_SMTP_TO", "Notification recipient.",
           section="integrations"),
    EnvVar("HELIX_SMTP_USER", "SMTP username.", section="integrations"),
    EnvVar("HELIX_SMTP_PASSWORD", "SMTP password.",
           section="integrations"),
    # -- observability ----------------------------------------------------
    EnvVar(
        "HELIX_PING_URL",
        "Version-ping beacon endpoint (anonymous {product, version, ts} "
        "POST, hourly). Unset: no beacon (the default).",
        section="observability",
    ),
    EnvVar(
        "HELIX_PROFILER_DIR",
        "Directory for on-demand jax.profiler captures written by the "
        "runner's POST /admin/profiler (the server picks the filename; "
        "clients never choose paths). Unset: a fresh tempdir per "
        "capture.",
        section="observability",
    ),
    EnvVar(
        "HELIX_TENANT_TOP_K",
        "How many tenants get their own label series per engine in the "
        "per-tenant SLO accounting (helix_tenant_* metrics and the "
        "heartbeat tenants rollup); everyone else folds into one "
        "__other__ bucket via LRU demotion, so /metrics cardinality is "
        "constant under tenant churn.",
        default="8",
        section="observability",
    ),
    EnvVar(
        "HELIX_SLO_BURN_WINDOWS",
        "Fast,slow window seconds for the SLO error-budget burn-rate "
        "gauges (helix_slo_burn_rate / helix_tenant_slo_burn_rate), "
        "e.g. '300,3600'. Burn rate 1.0 = the error budget is spent "
        "exactly as fast as it accrues; >1.0 = the SLO is being "
        "violated.",
        default="300,3600",
        section="observability",
    ),
    EnvVar(
        "HELIX_TRACEMALLOC",
        "Set to 1 to arm tracemalloc at import so the control plane's "
        "heap-profile endpoint sees allocations from process start. "
        "Costs 2-7x on every later jax compile — diagnostics only, "
        "never in production serving.",
        default="0",
        section="observability",
    ),
    # trace federation (ISSUE 18): the push cadence is the heartbeat
    # interval — spans ride the existing beat, so there is no separate
    # interval knob to tune (or forget)
    EnvVar(
        "HELIX_TRACE_FEDERATION",
        "Set to 0/false/off to stop runners pushing completed trace "
        "spans to the control plane inside the heartbeat payload. On "
        "(the default) the cp stitches every host's spans per trace id "
        "and serves the cluster-wide timeline at /v1/debug/traces/"
        "{id}; off, each host only answers for its own spans.",
        default="1",
        section="observability",
    ),
    EnvVar(
        "HELIX_TRACE_EXPORT_BATCH",
        "Maximum spans one heartbeat may carry (and the control "
        "plane's per-batch ingest clamp). Spans beyond the batch wait "
        "for the next beat; the export ring bounds how many can wait.",
        default="256",
        section="observability",
    ),
    EnvVar(
        "HELIX_TRACE_BUFFER",
        "Runner-side pending-export ring size. When the heartbeat "
        "falls behind span production, the OLDEST unsent span is "
        "dropped and counted in helix_trace_dropped_spans_total — "
        "memory stays bounded, loss stays visible.",
        default="2048",
        section="observability",
    ),
    EnvVar(
        "HELIX_TRACE_CP_TRACES",
        "How many federated traces the control plane retains (LRU "
        "beyond that; a dead runner's spans are pruned with the "
        "runner regardless).",
        default="2048",
        section="observability",
    ),
    EnvVar(
        "HELIX_CANARY",
        "Set to 1 to run the continuous correctness-canary scheduler "
        "(obs/canary.py): golden greedy probes mint per serving axis "
        "at profile apply and replay through the real serving path "
        "under the reserved __canary__ tenant, verifying token-level "
        "bit-identity. Off by default — probes consume real device "
        "steps, so the operator opts in the way scored routing is "
        "opted into.",
        default="0",
        section="observability",
    ),
    EnvVar(
        "HELIX_CANARY_INTERVAL",
        "Seconds between canary probe rounds while the runner's "
        "canary health is ok (failing runners reprobe on "
        "HELIX_CANARY_REPROBE_BACKOFF instead).",
        default="60",
        section="observability",
    ),
    EnvVar(
        "HELIX_CANARY_AXES",
        "Comma list restricting which serving axes mint golden probes "
        "(decode, prefix, spec, adapter, int8, resume). Unset: every "
        "axis the engine actually exercises, EXCEPT resume — the "
        "post-migration replay axis only mints when listed "
        "explicitly.",
        section="observability",
    ),
    EnvVar(
        "HELIX_CANARY_FAILURES",
        "Consecutive mismatched probe rounds before the runner's "
        "canary health flips to 'failing' (and the consecutive clean "
        "rounds required to recover from 'reprobing' back to 'ok'). "
        "Latency deviations and probe sheds/timeouts never count — "
        "only token-level bit-identity failures move the rungs.",
        default="2",
        section="observability",
    ),
    EnvVar(
        "HELIX_CANARY_REPROBE_BACKOFF",
        "Seconds a canary-failing runner waits between recovery probe "
        "rounds, so a transiently corrupted runner re-earns 'ok' "
        "without waiting out the full probe interval.",
        default="30",
        section="observability",
    ),
    # -- scheduler (serving/sched.py; README "Scheduling") ---------------
    # HELIX_SCHED_* knobs beat the profile's slo.sched block (the
    # HELIX_SPEC_TOKENS operator-override contract)
    EnvVar(
        "HELIX_SCHED_POLICY",
        "Scheduler policy for every engine this node serves: 'wfq' "
        "turns on strict interactive/batch priority tiers + per-tenant "
        "deficit-weighted fair queueing; 'fifo' forces the baseline "
        "FIFO ordering even where a profile enables wfq. Unset: the "
        "profile's slo.sched.policy applies (default fifo).",
        section="scheduler",
    ),
    EnvVar(
        "HELIX_SCHED_DEFAULT_CLASS",
        "Priority class assumed for requests that carry no (or an "
        "unauthenticated) X-Helix-Class header: 'interactive' or "
        "'batch'. Unset: the profile's slo.sched.default_class "
        "(default interactive).",
        section="scheduler",
    ),
    EnvVar(
        "HELIX_SCHED_TENANT_QUEUE_DEPTH",
        "Bounded per-tenant queues: max queued requests one tenant may "
        "hold before ITS submissions get 429s (per-tenant queue_full), "
        "so a flooding tenant cannot fill the global admission bound "
        "and starve everyone else. Unset: the profile's "
        "slo.sched.max_tenant_queue_depth (default unbounded).",
        section="scheduler",
    ),
    EnvVar(
        "HELIX_SCHED_PREFILL_BUDGET",
        "Adaptive per-step prefill-admission token budget (cap and "
        "initial value) under the wfq policy: halves toward the floor "
        "while the fast-window TTFT/queue-wait burn rate exceeds 1.0, "
        "grows back 1.25x once healthy. Unset: the profile's "
        "slo.sched.prefill_budget_tokens (default unbudgeted).",
        section="scheduler",
    ),
    EnvVar(
        "HELIX_SCHED_PREFILL_BUDGET_MIN",
        "Floor the TTFT-burn feedback loop may shrink the prefill "
        "budget to; admission always makes progress (>= 1 admission "
        "per step) regardless. Unset: the profile's "
        "slo.sched.prefill_budget_min_tokens.",
        default="256",
        section="scheduler",
    ),
    # -- routing (control/router.py; README "Routing & autoscaling") -----
    EnvVar(
        "HELIX_ROUTER_POLICY",
        "Control-plane placement policy: 'scored' closes the loop from "
        "federated heartbeat saturation (hard-avoid runners near KV/"
        "host-pool exhaustion or with a squeezed prefill budget, "
        "soft-prefer low queue depth / occupancy / warm spec "
        "acceptance, steer batch-class traffic off runners whose "
        "tenants are burning SLO budget; stale or missing saturation "
        "scores neutral, never best). Unset or 'rr': the seed "
        "least-loaded/round-robin baseline, bit-for-bit.",
        default="rr",
        section="router",
    ),
    EnvVar(
        "HELIX_ROUTER_KV_AVOID_THRESHOLD",
        "KV occupancy (0..1) at which the scored policy hard-avoids a "
        "runner — routed to only when no alternative exists.",
        default="0.85",
        section="router",
    ),
    EnvVar(
        "HELIX_ROUTER_KV_FULL_THRESHOLD",
        "KV occupancy (0..1) past which a runner is treated as FULL: a "
        "new dispatch there is a guaranteed typed kv_exhausted, so "
        "when EVERY candidate is full the control plane sheds with a "
        "503 code=kv_saturated and an honest Retry-After instead of "
        "dispatching into certain failure.",
        default="0.98",
        section="router",
    ),
    EnvVar(
        "HELIX_ROUTER_HOST_AVOID_THRESHOLD",
        "Host KV tier occupancy (0..1) at which the scored policy "
        "hard-avoids a runner (its spill headroom is nearly gone).",
        default="0.92",
        section="router",
    ),
    EnvVar(
        "HELIX_ROUTER_PREFILL_AVOID_TOKENS",
        "A runner reporting a prefill-admission budget in (0, this] is "
        "hard-avoided: the scheduler's SLO-burn feedback has squeezed "
        "admission to the floor there. 0 in the heartbeat always means "
        "unbudgeted and never triggers the avoid.",
        default="256",
        section="router",
    ),
    EnvVar(
        "HELIX_ROUTER_BURN_STEER_THRESHOLD",
        "Worst-tenant fast-window SLO burn rate above which batch-class "
        "(X-Helix-Class) traffic is steered away from a runner (soft "
        "score penalty, not an avoid).",
        default="1.0",
        section="router",
    ),
    EnvVar(
        "HELIX_PREFIX_AFFINITY",
        "Set to 1 to route requests sharing a prompt head (system "
        "prompt) to the runner whose PrefixCache/host tier already "
        "holds those pages (cp-side bounded LRU of prefix digest -> "
        "runner). Affinity is a hint, not a pin: under the scored "
        "policy it yields to saturation, breakers and drain; under rr "
        "it yields whenever the hinted runner is no longer among the "
        "least-loaded. Unset/0: off.",
        section="router",
    ),
    EnvVar(
        "HELIX_PREFIX_AFFINITY_ENTRIES",
        "Bound on the prefix-affinity LRU (distinct prompt heads "
        "remembered cluster-wide).",
        default="2048",
        section="router",
    ),
    EnvVar(
        "HELIX_ROUTER_CANARY_AVOID",
        "Set to 1 to hard-avoid runners whose federated correctness-"
        "canary health is failing or reprobing (wrong tokens are worse "
        "than slow ones) — under BOTH routing policies. The LAST "
        "runner serving a model is never stranded: it serves with a "
        "warning (counted in "
        "the cp canary route counters, logged with the trace id) "
        "rather than shedding a whole model on a possibly-false-"
        "positive probe. Unset/0: canary health is reported but never "
        "steers.",
        default="0",
        section="router",
    ),
    # -- dispatch robustness (control plane -> runner) -------------------
    EnvVar(
        "HELIX_DISPATCH_MAX_ATTEMPTS",
        "Max runner candidates one inference dispatch tries before "
        "returning 503 runners_exhausted (connect errors and 5xx "
        "received before the first streamed byte fail over to the next "
        "candidate).",
        default="3",
        section="server",
    ),
    EnvVar(
        "HELIX_DISPATCH_BACKOFF_BASE",
        "Base seconds for the capped exponential backoff (with jitter) "
        "between dispatch failover attempts.",
        default="0.05",
        section="server",
    ),
    EnvVar(
        "HELIX_DISPATCH_BACKOFF_CAP",
        "Upper bound in seconds on the per-attempt dispatch backoff.",
        default="1.0",
        section="server",
    ),
    EnvVar(
        "HELIX_DISPATCH_TIMEOUT",
        "Total deadline in seconds for one inference dispatch across "
        "all failover attempts (the remaining budget shrinks with each "
        "retry).",
        default="300",
        section="server",
    ),
    EnvVar(
        "HELIX_INTER_TOKEN_TIMEOUT",
        "Runner-side ceiling in seconds on the gap between consecutive "
        "streamed tokens of one response; a stall past it aborts the "
        "request with a typed 504 (SSE clients get an in-band error "
        "frame).",
        default="300",
        section="server",
    ),
    # -- knowledge --------------------------------------------------------
    EnvVar(
        "HELIX_CRAWLER_ALLOW_PRIVATE",
        "Set to 1 to let the knowledge crawler fetch private/loopback "
        "addresses (intranet docs). Default: refused (SSRF guard).",
        default="0",
        section="knowledge",
    ),
    # -- accelerator ------------------------------------------------------
    EnvVar(
        "JAX_PLATFORMS",
        "JAX platform selection; the control plane and sandbox children "
        "pin 'cpu' (they never touch chips). Serving nodes inherit the "
        "deployment default (tpu).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_BENCH_CHILD",
        "Internal: marks the CPU-fallback bench child process.",
        section="accelerator",
    ),
    # -- multi-host (DCN) serving (serving/multihost_serving.py) ---------
    EnvVar(
        "HELIX_MH_DIGEST",
        "Follower-side emission-digest verification mode for multi-host "
        "plan-broadcast serving: 'strict' (default) treats a rolling "
        "per-step digest mismatch against the leader's plans as lost "
        "lockstep (the follower stops and surfaces the restart ladder), "
        "'warn' logs and counts it (helix-side stats "
        "digest_mismatches), 'off' skips the check.",
        default="strict",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_RING",
        "Capacity (records) of the leader's plan ring buffer. A "
        "follower that falls more than this many records behind cannot "
        "rejoin by replay and must restart from a profile re-apply; "
        "bigger rings buy crash-recovery window at the cost of leader "
        "memory (plans are compact JSON, typically <1 KiB/step).",
        default="4096",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_BACKOFF_BASE",
        "Base seconds of a follower's capped exponential backoff (with "
        "jitter) between retries after a transient plan-feed error "
        "(retry n sleeps ~min(base * 2^n, cap)); fatal conditions "
        "(ring fall-behind, leader restart, divergence) never retry.",
        default="0.05",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_BACKOFF_CAP",
        "Cap seconds of the follower plan-feed retry backoff.",
        default="5.0",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_LAG_STEPS",
        "Leader-side lag ladder threshold (steps): a follower whose "
        "applied step sustains more than this many steps behind the "
        "published plan enters the typed 'lagging' state and the "
        "leader throttles admission (prefill budget pinned to 0, the "
        "PR 8 discipline) until it catches back up to half the "
        "threshold — back-pressure instead of ring overflow.",
        default="64",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_MAX_FOLLOWERS",
        "Bound on follower health entries the leader tracks (and the "
        "size of the helix_mh_follower_* metric family); polls beyond "
        "it are served but not registered (followers_dropped counts "
        "them).",
        default="16",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_FOLLOWER_TTL",
        "Seconds without a poll before the leader marks a registered "
        "follower 'lost' (it stops feeding the lag throttle; a "
        "rejoining poll re-registers it).",
        default="15",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_FOLLOWER_ID",
        "Stable id this follower registers with the leader's health "
        "registry (default: follower-<pid>). Set it per host so lag / "
        "digest telemetry survives process restarts under one name.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_CHECKPOINT_DIR",
        "Shared filestore directory for leader-state checkpoints "
        "(ISSUE 17 failover). Point every host of the mesh at the SAME "
        "path (the PR 14 cluster filestore tier): the leader "
        "checkpoints its host-side queue state there and a standby "
        "promotes from the newest checkpoint. Empty = no "
        "checkpointing, failover degrades to the full resync ladder.",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_CHECKPOINT_SECONDS",
        "Seconds between leader-state checkpoints (captured on the "
        "engine thread at a step boundary, written off-thread through "
        "the filestore). Smaller = fresher takeover boundary, more "
        "filestore writes.",
        default="5",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_CHECKPOINT_KEEP",
        "Newest leader-state checkpoints retained per model; older "
        "ones are pruned after each write.",
        default="3",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_STANDBY",
        "Set to 1 on a follower host to mark it a hot standby (the "
        "profile's multihost.standby beats this): standbys keep a "
        "digest-verified replica and are the preferred "
        "promote_follower target when the leader dies.",
        default="0",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_MH_PROMOTE_AFTER",
        "Standby auto-promotion trigger: after this many CONSECUTIVE "
        "transient plan-feed failures (the leader host is gone, not a "
        "blip) a standby stops retrying and fires its promotion hook. "
        "0 (default) = never self-trigger; promotion is operator- or "
        "node-agent-driven.",
        default="0",
        section="accelerator",
    ),
    # -- multi-host (DCN) training ---------------------------------------
    EnvVar(
        "HELIX_COORDINATOR",
        "Multi-host training: process 0's host:port for the jax "
        "distributed world (gradient all-reduce rides DCN between "
        "hosts).",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_NUM_HOSTS",
        "Multi-host training: total participating host processes.",
        default="1",
        section="accelerator",
    ),
    EnvVar(
        "HELIX_HOST_RANK",
        "Multi-host training: this host's process rank (0-based).",
        default="0",
        section="accelerator",
    ),
    # -- compute autoscaler (GCE provider) -------------------------------
    EnvVar(
        "HELIX_GCE_PROJECT",
        "GCP project for the pool autoscaler's GCE provider. Setting "
        "this together with HELIX_GCE_ZONE switches the autoscaler from "
        "the stub to real instances.",
        section="compute",
    ),
    EnvVar(
        "HELIX_GCE_ZONE",
        "GCE zone runner instances are provisioned in.",
        section="compute",
    ),
    EnvVar(
        "HELIX_GCE_MACHINE_TYPE",
        "Machine type for provisioned runner hosts.",
        default="n2-standard-8",
        section="compute",
    ),
    EnvVar(
        "HELIX_GCE_IMAGE",
        "Boot image for provisioned runner hosts.",
        default="projects/debian-cloud/global/images/family/debian-12",
        section="compute",
    ),
    EnvVar(
        "HELIX_GCE_CONTROL_PLANE",
        "Control-plane URL baked into the instance startup script "
        "(serve-node dials back here over the reverse tunnel).",
        section="compute",
    ),
    EnvVar(
        "GCE_TOKEN",
        "Static OAuth bearer for the GCE API; falls back to the "
        "instance metadata server when unset.",
        section="compute",
    ),
    EnvVar(
        "HELIX_INSTANCE_ID",
        "Compute-row identity an autoscaled host includes in its "
        "heartbeats so the pool manager can bind them to its instance "
        "row (matched by row id or provider id; the GCE startup script "
        "exports the instance hostname). Unset on hand-managed nodes.",
        section="compute",
    ),
    EnvVar(
        "HELIX_AUTOSCALE_FLOOR",
        "Override for the autoscaler's floor (healthy hosts kept alive "
        "at all times); beats the supplied ManagerConfig.",
        section="compute",
    ),
    EnvVar(
        "HELIX_AUTOSCALE_MAX",
        "Override for the autoscaler's max owned hosts (hard ceiling; "
        "0 disables demand/saturation bursts).",
        section="compute",
    ),
    EnvVar(
        "HELIX_AUTOSCALE_QUEUE_HIGH",
        "Cluster-wide queued-request depth (summed over runner "
        "heartbeats) that, sustained for HELIX_AUTOSCALE_SUSTAIN_"
        "SECONDS, provisions another host (0 disables the queue "
        "trigger).",
        section="compute",
    ),
    EnvVar(
        "HELIX_AUTOSCALE_BURN_HIGH",
        "Worst-tenant fast-window SLO burn rate that, sustained, "
        "provisions another host (0 disables the burn trigger).",
        section="compute",
    ),
    EnvVar(
        "HELIX_AUTOSCALE_SUSTAIN_SECONDS",
        "How long a scale-up trigger (and the idle condition for "
        "scale-down victim selection) must hold before the autoscaler "
        "acts — one hot scrape must not provision.",
        default="60",
        section="compute",
    ),
    EnvVar(
        "HELIX_AUTOSCALE_IDLE_SECONDS",
        "Cluster idle duration (zero queued work, tenant burn healthy) "
        "after which the autoscaler drains ONE runner at a time down "
        "toward the floor — announce draining, migrate in-flight "
        "requests to peers (ISSUE 11 ladder), then terminate the host. "
        "0 disables saturation-driven scale-down.",
        section="compute",
    ),
    EnvVar(
        "HELIX_AUTOSCALE_DRAIN_GRACE",
        "Seconds a drain-requested host may linger before it is "
        "terminated anyway (0 = HELIX_DRAIN_SECONDS + 30). Normal "
        "completion is earlier: the host is reclaimed as soon as its "
        "runner leaves the router.",
        section="compute",
    ),
    EnvVar(
        "HELIX_GIT_TOKEN",
        "Internal: carries the forge token from GitHubSync to git's "
        "credential helper via the child environment (never on the "
        "command line).",
        section="integrations",
    ),
    # -- CLI --------------------------------------------------------------
    EnvVar(
        "HELIX_API_URL",
        "Control-plane base URL the CLI verbs talk to when --url is not "
        "passed.",
        default="http://localhost:8080",
        section="cli",
    ),
    EnvVar(
        "HELIX_API_TOKEN",
        "Bearer token the CLI presents to the control plane when "
        "--api-key is not passed.",
        section="cli",
    ),
    # -- server -----------------------------------------------------------
    EnvVar(
        "HELIX_PUBLIC_URL",
        "Externally-reachable base URL of this control plane (used in "
        "links the server hands out: OAuth callbacks, runner dial-back).",
        default="http://localhost:8080",
        section="server",
    ),
    EnvVar(
        "HELIX_EXECUTOR",
        "Spec-task executor backend: empty = in-process sandbox agent; "
        "'ws' = dispatch implementation work to an external runner over "
        "the /ws/external-runner websocket.",
        section="server",
    ),
    EnvVar(
        "HELIX_WS_AGENT",
        "With HELIX_EXECUTOR=ws: agent type requested from external "
        "runners (e.g. claude-code, zed, goose).",
        section="server",
    ),
    # -- knowledge --------------------------------------------------------
    EnvVar(
        "HELIX_ANN_THRESHOLD",
        "Vector-store size (rows) above which similarity search switches "
        "from exact cosine scan to the native HNSW ANN index.",
        default="5000",
        section="knowledge",
    ),
    # -- billing (Stripe rails) ------------------------------------------
    EnvVar(
        "HELIX_STRIPE_SECRET_KEY",
        "Stripe API secret key; setting it enables the billing rails "
        "(checkout sessions, subscriptions, webhooks).",
        section="billing",
    ),
    EnvVar(
        "HELIX_STRIPE_WEBHOOK_SECRET",
        "Stripe webhook signing secret used to verify "
        "/api/v1/stripe/webhook payloads.",
        section="billing",
    ),
    EnvVar(
        "HELIX_STRIPE_PRICE_ID_PRO",
        "Stripe price id for the pro-tier subscription checkout.",
        section="billing",
    ),
    EnvVar(
        "HELIX_STRIPE_API_URL",
        "Stripe API base (tests point it at a fake).",
        default="https://api.stripe.com",
        section="billing",
    ),
    EnvVar(
        "HELIX_APP_URL",
        "User-facing app URL Stripe checkout redirects back to.",
        default="http://localhost:8080",
        section="billing",
    ),
    # -- Anthropic gateway ------------------------------------------------
    EnvVar(
        "HELIX_ANTHROPIC_PROXY_KEY",
        "Upstream Anthropic API key for the native /v1/messages gateway.",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_ANTHROPIC_OAUTH_TOKEN",
        "Claude-subscription OAuth bearer; preferred over the API key "
        "when present (the gateway probes which auth the account has).",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_ANTHROPIC_BASE_URL",
        "Anthropic API base for the direct gateway backend.",
        default="https://api.anthropic.com",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_VERTEX_PROJECT",
        "GCP project id; setting it routes the Anthropic gateway "
        "through Vertex AI model endpoints.",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_VERTEX_REGION",
        "Vertex AI region for Anthropic models.",
        default="us-east5",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_VERTEX_CREDENTIALS",
        "Service-account credentials JSON (inline) for Vertex auth; "
        "falls back to metadata-server tokens when unset.",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_VERTEX_BASE_URL",
        "Override for the Vertex endpoint base (tests point it at a "
        "fake).",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_BEDROCK_ACCESS_KEY",
        "AWS access key id; setting it routes the Anthropic gateway "
        "through Bedrock invoke endpoints.",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_BEDROCK_SECRET_KEY",
        "AWS secret access key for Bedrock SigV4 signing.",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_BEDROCK_SESSION_TOKEN",
        "Optional AWS STS session token for Bedrock.",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_BEDROCK_REGION",
        "AWS region for Bedrock Anthropic models.",
        default="us-east-1",
        section="anthropic",
    ),
    EnvVar(
        "HELIX_BEDROCK_BASE_URL",
        "Override for the Bedrock endpoint base (tests point it at a "
        "fake).",
        section="anthropic",
    ),
)


def render(sections: bool = True) -> str:
    out = []
    cur = None
    for var in ENV_REFERENCE:
        if sections and var.section != cur:
            cur = var.section
            out.append(f"\n[{cur}]")
        default = f" (default: {var.default})" if var.default else ""
        out.append(f"  {var.name}{default}\n      {var.description}")
    return "\n".join(out).strip()
