"""HBM-accounted multi-model residency: in-process hot-swap.

BASELINE.md config 3 ("Llama-3-8B + Phi-3-mini hot-swap on one chip") and
SURVEY.md §7 stage 3: where the reference swaps models by ``docker compose
down/up`` of vLLM containers (weights re-downloaded/re-loaded each time,
minutes), this build keeps models as in-process Engines and swaps by
load/evict against an HBM budget:

- every model's footprint = weight bytes (exact, from the param tree) +
  page-pool bytes (from CacheConfig) + an activation headroom margin;
- ``acquire(name)`` loads on demand, evicting least-recently-used IDLE
  models (never one with in-flight requests) until the budget fits —
  the scheduling decision ``gpu-memory-utilization`` flags approximate in
  vLLM, made exact here by the device layer's HBM numbers;
- eviction stops the engine loop and drops the param/cache references; XLA
  frees the HBM when the arrays die.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

from helix_tpu.serving.registry import ModelRegistry, ServedModel


def tree_bytes(tree) -> int:
    import jax

    return sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(tree)
        if hasattr(x, "size")
    )


def model_param_count(model_cfg) -> int:
    """Architectural parameter count from a config (dense-path weights:
    for MoE this is the ACTIVE-per-token shape, which is also the right
    numerator for decode MFU — each generated token moves ~2 FLOPs per
    active parameter through the MXU)."""
    c = model_cfg
    embed = c.vocab_size * c.hidden_size
    per_layer = (
        c.hidden_size * c.num_heads * c.head_dim        # wq
        + 2 * c.hidden_size * c.num_kv_heads * c.head_dim  # wk, wv
        + c.num_heads * c.head_dim * c.hidden_size      # wo
        + 3 * c.hidden_size * c.intermediate_size       # gate, up, down
        + 2 * c.hidden_size                             # norms
    )
    return embed * (1 if c.tie_word_embeddings else 2) + (
        c.num_layers * per_layer + c.hidden_size
    )


def estimate_model_bytes(
    model_cfg,
    engine_kwargs: dict,
    quantization: Optional[str] = None,
    headroom: float = 0.10,
) -> int:
    """Predict a chat model's HBM footprint from its config BEFORE building:
    weight bytes (arch param count x itemsize) + page-pool bytes + headroom.
    The exact-accounting replacement for the reference's deleted GGUF
    memory-estimation package (``api/pkg/memory/estimate.go`` — 'should not
    be used anymore')."""
    from helix_tpu.engine.engine import EngineConfig
    from helix_tpu.engine.kv_cache import CacheConfig

    c = model_cfg
    n_params = model_param_count(c)
    import jax.numpy as jnp

    itemsize = 1 if quantization == "int8" else jnp.dtype(c.dtype).itemsize
    weight_bytes = n_params * itemsize
    ecfg = EngineConfig(**engine_kwargs) if engine_kwargs else EngineConfig()
    cache_bytes = ecfg.cache_config(dtype=c.dtype).total_bytes(c)
    return int((weight_bytes + cache_bytes) * (1 + headroom))


def host_pool_budget_bytes(default: int = 0) -> int:
    """Operator-declared host-RAM KV tier budget
    (``HELIX_KV_HOST_POOL_BYTES``), the host-side sibling of the HBM
    budget ``CacheConfig.fit_hbm`` sizes the device pool with.  0 =
    tier disabled."""
    import os

    v = os.environ.get("HELIX_KV_HOST_POOL_BYTES", "")
    return int(v) if v else default


def host_tier_pages(model_cfg, cache_cfg, host_budget_bytes: int) -> int:
    """How many spilled pages a host budget holds for this model — the
    ``fit_hbm`` arithmetic applied to the host tier.  The ratio against
    ``cache_cfg.num_pages`` is the effective prefix-cache
    multiplication a system-prompt-heavy fleet gets (the 10-100x
    figure): host RAM is typically 8-16x HBM and a page spills at its
    stored size (int8 pages stay int8)."""
    per_page = cache_cfg.page_bytes(model_cfg)
    return int(host_budget_bytes // per_page) if per_page else 0


def served_model_bytes(m: ServedModel, headroom: float = 0.10) -> int:
    """Footprint of a live ServedModel: weights + KV pages (+headroom)."""
    total = 0
    if m.loop is not None:
        eng = m.loop.engine
        total += tree_bytes(eng.params)
        total += tree_bytes(eng.cache.carry())  # pools + int8 scale pools
    elif m.embedder is not None:
        total += tree_bytes(m.embedder.params)
    return int(total * (1 + headroom))


@dataclasses.dataclass
class Resident:
    model: ServedModel
    bytes: int
    last_used: float
    loads: int = 0


class ResidencyManager:
    """A ModelRegistry whose ``get`` faults models in against an HBM budget."""

    def __init__(
        self,
        hbm_budget_bytes: int,
        build: Callable[[str], ServedModel],
        estimate: Optional[Callable[[str], int]] = None,
        measure: Callable[[ServedModel], int] = served_model_bytes,
    ):
        """``estimate(name)`` predicts a model's footprint BEFORE building it
        so eviction happens first (mandatory on a real chip — build-then-
        evict would OOM HBM).  Without it, acquire builds first and measures
        (fine on CPU/tests, wrong on device)."""
        self.budget = hbm_budget_bytes
        self._build = build
        self._estimate = estimate
        self._measure = measure
        self._resident: dict[str, Resident] = {}
        self._known: set = set()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._loading: set = set()
        self._load_errors: dict[str, BaseException] = {}
        # bytes held for in-flight prefetches so concurrent acquires can't
        # claim the headroom the prefetch just evicted for
        self._reserved: dict[str, int] = {}
        # metrics
        self.evictions = 0
        self.loads = 0
        # model -> last acquire stall / build duration, in seconds
        self.swap_seconds: dict[str, float] = {}
        self.load_seconds: dict[str, float] = {}

    # -- registry-compatible surface --------------------------------------
    def register_name(self, name: str) -> None:
        self._known.add(name)

    def names(self) -> list:
        return sorted(self._known)

    def resident_names(self) -> list:
        return sorted(self._resident)

    def get(self, name: str) -> Optional[ServedModel]:
        if name not in self._known:
            return None
        return self.acquire(name)

    def list(self) -> list:
        with self._lock:
            return [r.model for _, r in sorted(self._resident.items())]

    def used_bytes(self) -> int:
        with self._lock:
            return self.used_bytes_locked()

    def stats(self) -> dict:
        """Consistent snapshot for /metrics (other threads mutate the dicts
        mid-scrape otherwise). used_bytes includes in-flight prefetch
        reservations — the number admission control actually sees."""
        with self._lock:
            return {
                "loads": self.loads,
                "evictions": self.evictions,
                "used_bytes": self.used_bytes_locked(),
                "budget_bytes": self.budget,
                "swap_seconds": dict(self.swap_seconds),
                "load_seconds": dict(self.load_seconds),
            }

    # -- residency ----------------------------------------------------------
    def _is_idle(self, r: Resident) -> bool:
        loop = r.model.loop
        if loop is None:
            return True
        eng = loop.engine
        return not eng.has_work()

    def _evict_until_fits(self, need: int) -> bool:
        """Evict LRU idle models until ``need`` bytes fit. Lock held."""
        while self.used_bytes_locked() + need > self.budget:
            victims = [
                r
                for r in self._resident.values()
                if self._is_idle(r)
            ]
            if not victims:
                return False
            victim = min(victims, key=lambda r: r.last_used)
            self._evict(victim.model.name)
        return True

    def used_bytes_locked(self) -> int:
        return sum(r.bytes for r in self._resident.values()) + sum(
            self._reserved.values()
        )

    def _evict(self, name: str) -> None:
        r = self._resident.pop(name, None)
        if r is None:
            return
        if r.model.loop is not None:
            r.model.loop.stop(join=False)
        self.evictions += 1

    def prefetch(self, name: str) -> bool:
        """Stage ``name``'s weights in the background so the NEXT acquire
        is (near-)free: evict idle models for headroom now, build+load on a
        daemon thread, publish as resident on completion.  The in-flight
        model keeps decoding throughout — nothing stops until an eviction
        is actually required, and busy models are never evicted (SURVEY §7
        hard part #2: swap latency is weights->HBM load time; overlap it
        with serving instead of stalling the requesting call).

        Returns False when overlap is impossible: unknown name, or the
        headroom cannot be freed without evicting a busy model (the
        subsequent ``acquire`` then does the old synchronous swap)."""
        with self._lock:
            r = self._resident.get(name)
            if r is not None:
                # already warm: refresh LRU standing so the model the
                # operator just asked to keep hot isn't the next victim
                r.last_used = time.monotonic()
                return True
            if name not in self._known or name in self._loading:
                return name in self._loading
            if self._estimate is not None:
                need = self._estimate(name)
                if not self._evict_until_fits(need):
                    return False
                self._reserved[name] = need
            self._loading.add(name)
            self._load_errors.pop(name, None)

        def run():
            t0 = time.monotonic()
            try:
                model = self._build(name)
                need = self._measure(model)
                ok = False
                with self._lock:
                    self._reserved.pop(name, None)
                    # measured > estimated: make room, idle victims only
                    ok = self._evict_until_fits(need)
                    if ok:
                        self._resident[name] = Resident(
                            model=model, bytes=need,
                            last_used=time.monotonic(), loads=1,
                        )
                        self.loads += 1
                        self.load_seconds[name] = (
                            time.monotonic() - t0
                        )
                if not ok:
                    if model.loop is not None:
                        model.loop.stop(join=False)
                    raise MemoryError(
                        f"prefetched model '{name}' ({need >> 20} MiB) no "
                        f"longer fits: resident models busy"
                    )
            except BaseException as e:  # noqa: BLE001 — delivered to waiters
                with self._lock:
                    self._load_errors[name] = e
            finally:
                with self._lock:
                    self._reserved.pop(name, None)
                    self._loading.discard(name)
                    self._cond.notify_all()

        threading.Thread(
            target=run, name=f"helix-prefetch-{name}", daemon=True
        ).start()
        return True

    def acquire(self, name: str) -> ServedModel:
        t_enter = time.monotonic()
        with self._lock:
            # a prefetch in flight for this name: wait for it instead of
            # double-building (the wait IS the swap latency)
            waited = False
            while name in self._loading:
                waited = True
                self._cond.wait(timeout=0.5)
            err = self._load_errors.pop(name, None)
            if err is not None:
                if waited:
                    raise err
                # stale failure from an unattended prefetch: a fresh build
                # may well succeed now — log and fall through to one
                import logging

                logging.getLogger(__name__).warning(
                    "dropping stale prefetch failure for %s: %s", name, err
                )
            r = self._resident.get(name)
            if r is not None:
                r.last_used = time.monotonic()
                self.swap_seconds[name] = time.monotonic() - t_enter
                return r.model
            if self._estimate is not None:
                # device path: predict footprint, evict FIRST, then build
                need = self._estimate(name)
                if not self._evict_until_fits(need):
                    raise MemoryError(
                        f"cannot fit model '{name}' ({need >> 20} MiB) in "
                        f"HBM budget {self.budget >> 20} MiB: all resident "
                        f"models busy"
                    )
                model = self._build(name)
                need = max(need, self._measure(model))
            else:
                # host/test path: build first, measure exactly, then evict
                model = self._build(name)
                need = self._measure(model)
                if not self._evict_until_fits(need):
                    if model.loop is not None:
                        model.loop.stop(join=False)
                    raise MemoryError(
                        f"cannot fit model '{name}' ({need >> 20} MiB) in "
                        f"HBM budget {self.budget >> 20} MiB: all resident "
                        f"models busy"
                    )
            self._resident[name] = Resident(
                model=model, bytes=need, last_used=time.monotonic(), loads=1
            )
            self.loads += 1
            # synchronous swap: the requesting call stalled for the whole
            # build+load — exactly the latency prefetch() exists to hide
            swap = time.monotonic() - t_enter
            self.swap_seconds[name] = swap
            self.load_seconds[name] = swap
            return model

    def evict(self, name: str) -> None:
        with self._lock:
            self._evict(name)

    def touch(self, name: str) -> None:
        with self._lock:
            r = self._resident.get(name)
            if r:
                r.last_used = time.monotonic()
