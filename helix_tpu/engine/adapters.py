"""Multi-LoRA adapter serving: tiered residency + batched application
(ISSUE 15).

One resident base model serves MANY LoRA adapters concurrently: requests
address ``model@adapter``, every engine-step row carries an
``adapter_id`` column in the PR 9 per-row metadata, and the unified
ragged step applies adapters with a batched gather-matmul (BGMV-style:
``ops.quant.maybe_dequant_dense`` adds ``scale * (x @ A[g]) @ B[g]`` per
token from a stacked pool) — so a mixed-adapter decode/prefill/spec wave
packs the SAME device call with no new trace families, and the
alternative (one ``merge_lora_into_params`` copy per tenant) stops
costing N× base-model HBM plus a hot-swap compile wave per adapter
change.

Residency mirrors the KV ladder:

- :class:`AdapterPool` — the HBM rung: a fixed-capacity stacked slot
  array per LoRA target (slot 0 is the reserved IDENTITY adapter —
  zeros at scale 0, so adapter-free rows ride the same program and
  greedy outputs stay bit-identical to the pool-less engine), LRU over
  refcount-0 slots, loads counted and timed.  Capacity is compiled into
  the step once (``EngineConfig.adapter_pool_slots``); LOADING an
  adapter later writes values into the same-shaped arrays, so publish →
  serve needs no recompile (warmup covers the adapter slot).
- :class:`AdapterStore` — the host rung (byte-budgeted LRU of decoded
  host trees, ``HELIX_ADAPTER_HOST_POOL_BYTES``) over an optional
  persistent filestore rung (checksummed ``.npz`` blobs under the PR 14
  ``HELIX_FILESTORE_KV_DIR`` root), with an async prefetch worker
  kicked at admission so a cold adapter overlaps its load with the
  queue wait and never stalls an engine step
  (``HELIX_ADAPTER_PREFETCH=0`` forces synchronous loads).

This module is the single owner of the ``helix_adapter_*`` metric
family (``tools/lint_metrics.py`` contract 11): the runner scrape
surface calls :func:`collect_adapter_metrics`, the node agent builds
its heartbeat adapter-residency block with
:func:`adapter_residency_summary`, and the control plane clamps the
runner-supplied block through :func:`validate_adapter_block` — the
contracts 3-10 importer pattern.

jax is imported lazily (inside :class:`AdapterPool`) so control-plane
processes can import this module for sanitisation/validation without
touching the accelerator runtime.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import io
import json
import logging
import os
import re
import threading
import time
from typing import Callable, Optional

import numpy as np

log = logging.getLogger("helix.adapters")

# ``model@adapter`` addressing: the separator and the adapter-id shape.
# Ids are bounded and character-restricted BEFORE they can mint a
# metrics label or become a filestore path component — the PR 7 tenant
# sanitiser rule.  No leading dot (no hidden/parent-dir names), no path
# separators, bounded length.
ADAPTER_SEP = "@"
MAX_ADAPTER_ID_LEN = 64
_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")

# bounds for federation blocks (heartbeats) and /v1/models listings so
# a runner with thousands of published adapters can't bloat either
MAX_RESIDENCY_ENTRIES = 128
MAX_LISTED_ADAPTERS = 32

# per-adapter accounting is top-K bounded like PR 7 tenants: the K most
# recently active adapters get their own label series, the rest fold
# into one __other__ bucket with totals conserved
ADAPTER_TOP_K = 8
OTHER_ADAPTER = "__other__"


def sanitize_adapter_id(value) -> str:
    """Bound a caller-supplied adapter id to the shapes that may mint a
    metrics label or a filestore path component.  Returns "" for
    anything hostile (too long, path-ish, wrong charset, a claim on the
    ``__other__`` fold bucket)."""
    if not isinstance(value, str):
        return ""
    v = value.strip()
    if not v or len(v) > MAX_ADAPTER_ID_LEN or v == OTHER_ADAPTER:
        return ""
    if not _ID_RE.match(v):
        return ""
    return v


def split_model_adapter(name) -> tuple:
    """``"base@adapter"`` -> ``(base, adapter_id, ok)``.

    ``ok`` is False when an ``@`` was present but the adapter id failed
    sanitisation (the caller answers 404, never passes the raw value
    on).  A plain model name returns ``(name, "", True)``."""
    if not isinstance(name, str) or ADAPTER_SEP not in name:
        return name, "", True
    base, _, raw = name.partition(ADAPTER_SEP)
    adapter = sanitize_adapter_id(raw)
    return base, adapter, bool(adapter)


def adapter_prefetch_enabled() -> bool:
    """HELIX_ADAPTER_PREFETCH: 0/false forces synchronous tier loads
    (debug/tests); default on — cold adapters load on a background
    worker overlapped with the queue wait."""
    v = os.environ.get("HELIX_ADAPTER_PREFETCH", "").strip().lower()
    return v not in ("0", "false", "no", "off")


def adapter_host_pool_bytes(default: int = 256 * 1024 * 1024) -> int:
    """HELIX_ADAPTER_HOST_POOL_BYTES: byte budget for the host rung of
    the adapter residency ladder (decoded adapter trees awaiting HBM
    slots).  Default 256 MiB; 0 disables eviction bounds (unbounded)."""
    v = os.environ.get("HELIX_ADAPTER_HOST_POOL_BYTES", "").strip()
    if not v:
        return default
    return int(v)


def adapter_pool_slots_env() -> Optional[int]:
    """HELIX_ADAPTER_POOL_SLOTS: operator-level override for every
    engine this node serves (the HELIX_SPEC_TOKENS contract — beats the
    profile's ``engine.adapter_pool_slots``; 0 forces the batched
    adapter path off).  None = unset, profile applies."""
    v = os.environ.get("HELIX_ADAPTER_POOL_SLOTS", "").strip()
    if not v:
        return None
    return max(0, int(v))


# ---------------------------------------------------------------------------
# adapter specs (host representation)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AdapterSpec:
    """One published adapter, decoded to host numpy: per-target stacked
    ``a [L, fan_in, r]`` / ``b [L, r, fan_out]`` factors (f32) plus the
    serving scale (alpha/rank)."""

    adapter_id: str
    rank: int
    scale: float
    targets: dict                 # {target: {"a": np, "b": np}}
    checksum: str = ""

    @property
    def nbytes(self) -> int:
        return sum(
            int(f["a"].nbytes) + int(f["b"].nbytes)
            for f in self.targets.values()
        )


def pack_lora_tree(adapter_id: str, lora_params: dict,
                   scaling: float) -> AdapterSpec:
    """A training-side LoRA tree (``training.lora`` layout:
    ``{target: {lora_a [L, in, r], lora_b [L, r, out]}}``) as an
    :class:`AdapterSpec` — the train -> publish bridge."""
    targets = {}
    rank = 0
    for t, lp in lora_params.items():
        a = np.asarray(lp["lora_a"], dtype=np.float32)
        b = np.asarray(lp["lora_b"], dtype=np.float32)
        if a.ndim != 3 or b.ndim != 3 or a.shape[-1] != b.shape[-2]:
            raise ValueError(
                f"adapter {adapter_id!r}: target {t!r} factors have "
                f"incompatible shapes {a.shape} x {b.shape}"
            )
        rank = max(rank, a.shape[-1])
        targets[t] = {"a": a, "b": b}
    if not targets:
        raise ValueError(f"adapter {adapter_id!r}: no LoRA targets")
    return AdapterSpec(
        adapter_id=adapter_id, rank=rank, scale=float(scaling),
        targets=targets,
    )


def _spec_checksum(spec: AdapterSpec) -> str:
    h = hashlib.blake2b(digest_size=16)
    h.update(json.dumps(
        {"rank": spec.rank, "scale": spec.scale,
         "targets": sorted(spec.targets)}, sort_keys=True,
    ).encode())
    for t in sorted(spec.targets):
        h.update(np.ascontiguousarray(spec.targets[t]["a"]).tobytes())
        h.update(np.ascontiguousarray(spec.targets[t]["b"]).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# AdapterStore: host rung + persistent filestore rung, async prefetch
# ---------------------------------------------------------------------------


class AdapterStore:
    """Published adapters for ONE base model: a byte-budgeted host LRU
    of decoded :class:`AdapterSpec` trees over an optional checksummed
    filestore directory (the persistent rung — survives restarts and is
    shared by every runner on the filesystem, the PR 14 tier).

    Thread-safe: HTTP publish threads, the async prefetch worker and
    the engine thread all go through one lock.  ``prefetch`` never
    blocks the caller; ``ready`` is the engine's admission gate."""

    def __init__(self, model_name: str, dims: dict, num_layers: int,
                 rank_cap: int, host_budget_bytes: Optional[int] = None,
                 root_dir: str = "", prefetch: Optional[bool] = None):
        self.model_name = model_name
        self.dims = dict(dims)          # {target: (fan_in, fan_out)}
        self.num_layers = int(num_layers)
        self.rank_cap = int(rank_cap)
        self.budget_bytes = (
            adapter_host_pool_bytes() if host_budget_bytes is None
            else int(host_budget_bytes)
        )
        self.root = root_dir or ""
        if self.root:
            os.makedirs(self.root, exist_ok=True)
        self._prefetch_on = (
            adapter_prefetch_enabled() if prefetch is None else bool(prefetch)
        )
        self._lock = threading.Lock()
        self._host: "collections.OrderedDict[str, AdapterSpec]" = (
            collections.OrderedDict()
        )
        self._host_bytes = 0
        # per-id publish generation: bumped by every explicit publish
        # (NOT by blob reads, which restore the same content) — the HBM
        # pool compares this against the generation it loaded so a
        # RE-published adapter reloads instead of serving stale weights
        self._gens: dict = {}
        # ids known to have a filestore blob (written by publish or
        # seen by a successful read): the host-LRU eviction rule checks
        # THIS set, never the filesystem — no I/O under the store lock
        # (the engine thread's ready()/get_resident() share it)
        self._blob_backed: set = set()
        self._inflight: set = set()      # ids with a prefetch in flight
        self._worker: Optional[threading.Thread] = None
        self._queue: "collections.deque" = collections.deque()
        self._wake = threading.Event()
        # counters (plain ints, GIL-atomic reads from scrape threads)
        self.publishes = 0
        self.prefetches = 0
        self.host_evictions = 0
        self.load_errors = 0

    # -- publish -----------------------------------------------------------

    def validate_spec(self, spec: AdapterSpec) -> Optional[str]:
        """None when the spec fits this base model's geometry, else the
        reason (surfaced as an HTTP 400 by the publish endpoint)."""
        if spec.rank > self.rank_cap:
            return (
                f"adapter rank {spec.rank} exceeds the pool rank cap "
                f"{self.rank_cap} (EngineConfig.adapter_rank)"
            )
        for t, f in spec.targets.items():
            want = self.dims.get(t)
            if want is None:
                return (
                    f"target {t!r} is not servable by the batched pool "
                    f"for {self.model_name!r} (pool targets: "
                    f"{sorted(self.dims)})"
                )
            a, b = f["a"], f["b"]
            if a.shape[0] != self.num_layers or (
                a.shape[1], b.shape[2]
            ) != want:
                return (
                    f"target {t!r} factors {a.shape} x {b.shape} do not "
                    f"match model dims L={self.num_layers}, "
                    f"(in, out)={want}"
                )
        return None

    def publish(self, spec: AdapterSpec, persist: bool = True) -> None:
        """Admit a validated spec to the host rung (and write through to
        the filestore rung when configured) — the adapter becomes
        servable without restart or recompile."""
        if sanitize_adapter_id(spec.adapter_id) != spec.adapter_id:
            # enforced at the STORE, not just the HTTP surface: every
            # programmatic publisher goes through here, and the id is
            # about to become a filestore path component
            raise ValueError(
                f"adapter id {spec.adapter_id!r} failed sanitisation "
                "(bounded [A-Za-z0-9._-], no leading dot)"
            )
        err = self.validate_spec(spec)
        if err:
            raise ValueError(err)
        if not spec.checksum:
            spec.checksum = _spec_checksum(spec)
        persisted = False
        if persist and self.root:
            self._write_blob(spec)
            persisted = True
        with self._lock:
            if persisted:
                self._blob_backed.add(spec.adapter_id)
            self._install(spec)
            self._gens[spec.adapter_id] = (
                self._gens.get(spec.adapter_id, 0) + 1
            )
        self.publishes += 1

    def publish_checkpoint(self, adapter_id: str, ckpt_dir: str,
                           scale: Optional[float] = None) -> AdapterSpec:
        """Publish a LoRA SFT checkpoint (``training.checkpoint``
        layout, as written by ``helix-tpu sft --output``): restore,
        pack, validate, admit — the restartless train → publish → serve
        loop."""
        from helix_tpu.training.checkpoint import restore_checkpoint

        restored = restore_checkpoint(ckpt_dir)
        if restored is None:
            raise FileNotFoundError(
                f"adapter checkpoint not found at {ckpt_dir!r}"
            )
        scaling = scale
        if scaling is None:
            scaling = float(restored.get("lora_scaling") or 0) or 1.0
        spec = pack_lora_tree(
            adapter_id, restored["lora_params"], scaling
        )
        self.publish(spec)
        return spec

    # -- host rung ---------------------------------------------------------

    def _install(self, spec: AdapterSpec) -> None:
        """Lock must be held."""
        old = self._host.pop(spec.adapter_id, None)
        if old is not None:
            self._host_bytes -= old.nbytes
        self._host[spec.adapter_id] = spec
        self._host_bytes += spec.nbytes
        if self.budget_bytes > 0:
            # LRU-evict host copies past the byte budget — but only
            # entries the filestore rung can reload (the cached
            # _blob_backed set, NOT an isfile under the lock); an
            # unpersisted adapter's only copy is never dropped
            for aid in list(self._host):
                if self._host_bytes <= self.budget_bytes:
                    break
                if aid == spec.adapter_id or aid not in self._blob_backed:
                    continue
                victim = self._host.pop(aid)
                self._host_bytes -= victim.nbytes
                self.host_evictions += 1

    def generation(self, adapter_id: str) -> int:
        """Publish generation of an adapter (0 = never explicitly
        published in this process — e.g. restored from a blob)."""
        with self._lock:
            return self._gens.get(adapter_id, 0)

    def ready(self, adapter_id: str) -> bool:
        """Host-resident (an HBM load can proceed this step)."""
        with self._lock:
            return adapter_id in self._host

    def contains(self, adapter_id: str) -> bool:
        """Published on ANY rung (host, an in-flight prefetch, or the
        filestore) — the in-memory checks come first so callers that
        already kicked a prefetch never touch the (possibly remote)
        filesystem."""
        with self._lock:
            if adapter_id in self._host or adapter_id in self._inflight:
                return True
        return self._has_blob(adapter_id)

    def get_resident(self, adapter_id: str) -> Optional[AdapterSpec]:
        """Host-rung hit or None — NO filestore fallback, no disk I/O:
        the engine thread's pool-load lookup (a cold adapter defers to
        the async prefetch instead of stalling the step on a blob
        read + checksum)."""
        with self._lock:
            spec = self._host.get(adapter_id)
            if spec is not None:
                self._host.move_to_end(adapter_id)
            return spec

    def get(self, adapter_id: str) -> Optional[AdapterSpec]:
        """Host hit, or a SYNCHRONOUS filestore load (callers that must
        not block use ``get_resident`` / ``ready`` + ``prefetch``
        instead)."""
        spec = self.get_resident(adapter_id)
        if spec is not None:
            return spec
        spec = self._read_blob(adapter_id)
        if spec is not None:
            with self._lock:
                self._blob_backed.add(adapter_id)
                self._install(spec)
        return spec

    def ids(self, bound: int = MAX_LISTED_ADAPTERS) -> list:
        """Published adapter ids across rungs, sorted, bounded — the
        /v1/models listing source."""
        with self._lock:
            out = set(self._host)
        if self.root:
            try:
                for fn in os.listdir(self.root):
                    if fn.endswith(".npz"):
                        aid = sanitize_adapter_id(fn[:-4])
                        if aid:
                            out.add(aid)
            except OSError:
                pass
        return sorted(out)[:bound]

    # -- async prefetch ----------------------------------------------------

    def prefetch(self, adapter_id: str) -> bool:
        """Kick a filestore -> host load on the background worker and
        return immediately (True when the adapter is or may become
        host-resident).  NO filesystem I/O happens on the caller's
        thread — even the blob-existence check runs on the worker, so
        an event-loop or engine-thread caller can never stall on a
        slow/remote filestore.  An id with no blob simply resolves to a
        no-op there.  With prefetch disabled (HELIX_ADAPTER_PREFETCH=0)
        the load happens inline instead."""
        with self._lock:
            if adapter_id in self._host:
                return True
            if adapter_id in self._inflight:
                return True
        if not self.root:
            return False
        if not self._prefetch_on:
            return self.get(adapter_id) is not None
        with self._lock:
            if adapter_id in self._inflight:
                return True
            self._inflight.add(adapter_id)
            self._queue.append(adapter_id)
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._prefetch_loop,
                    name="adapter-prefetch", daemon=True,
                )
                self._worker.start()
        self._wake.set()
        self.prefetches += 1
        return True

    def _prefetch_loop(self) -> None:
        while True:
            self._wake.wait(timeout=1.0)
            self._wake.clear()
            while True:
                with self._lock:
                    if not self._queue:
                        break
                    aid = self._queue.popleft()
                try:
                    spec = self._read_blob(aid)
                    if spec is not None:
                        with self._lock:
                            self._blob_backed.add(aid)
                            self._install(spec)
                except Exception:  # noqa: BLE001 — the tier degrades, never dies
                    self.load_errors += 1
                    log.exception("adapter prefetch failed for %s", aid)
                finally:
                    with self._lock:
                        self._inflight.discard(aid)

    # -- filestore rung ----------------------------------------------------

    def _blob_path(self, adapter_id: str) -> str:
        return os.path.join(self.root, f"{adapter_id}.npz")

    def _has_blob(self, adapter_id: str) -> bool:
        return bool(self.root) and os.path.isfile(
            self._blob_path(adapter_id)
        )

    def _write_blob(self, spec: AdapterSpec) -> None:
        arrays = {}
        for t, f in spec.targets.items():
            arrays[f"a__{t}"] = f["a"]
            arrays[f"b__{t}"] = f["b"]
        meta = json.dumps({
            "adapter_id": spec.adapter_id, "rank": spec.rank,
            "scale": spec.scale, "checksum": spec.checksum,
            "model": self.model_name,
        })
        buf = io.BytesIO()
        np.savez(buf, __meta__=np.frombuffer(
            meta.encode(), dtype=np.uint8
        ), **arrays)
        path = self._blob_path(spec.adapter_id)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(buf.getvalue())
        os.replace(tmp, path)

    def _read_blob(self, adapter_id: str) -> Optional[AdapterSpec]:
        if not self._has_blob(adapter_id):
            return None
        try:
            with np.load(self._blob_path(adapter_id)) as z:
                meta = json.loads(bytes(z["__meta__"]).decode())
                targets = {}
                for k in z.files:
                    if k.startswith("a__"):
                        t = k[3:]
                        targets[t] = {
                            "a": z[f"a__{t}"], "b": z[f"b__{t}"],
                        }
            spec = AdapterSpec(
                adapter_id=adapter_id, rank=int(meta["rank"]),
                scale=float(meta["scale"]), targets=targets,
                checksum=str(meta.get("checksum", "")),
            )
            # checksum verified BEFORE the spec can reach a pool slot —
            # a corrupt blob is a typed miss (recompute/prefetch path),
            # never wrong weights
            if spec.checksum and _spec_checksum(spec) != spec.checksum:
                self.load_errors += 1
                log.warning(
                    "dropping corrupt adapter blob %s (checksum "
                    "mismatch)", adapter_id,
                )
                return None
            if self.validate_spec(spec) is not None:
                self.load_errors += 1
                return None
            return spec
        except Exception:  # noqa: BLE001 — a bad blob is a miss, not a crash
            self.load_errors += 1
            log.exception("unreadable adapter blob %s", adapter_id)
            return None

    def stats(self) -> dict:
        with self._lock:
            resident = len(self._host)
            used = self._host_bytes
        return {
            "host_resident": resident,
            "host_used_bytes": used,
            "host_budget_bytes": self.budget_bytes,
            "publishes": self.publishes,
            "prefetches": self.prefetches,
            "host_evictions": self.host_evictions,
            "load_errors": self.load_errors,
        }


def default_adapter_store(model_cfg, engine_cfg) -> "AdapterStore":
    """The store an Engine builds for itself when the pool is enabled:
    geometry from the model config, host budget + prefetch from the
    documented env knobs, and the persistent rung under the PR 14
    filestore root (``HELIX_FILESTORE_KV_DIR``) when one is set."""
    from helix_tpu.training.lora import _target_dims

    dims = _target_dims(model_cfg)
    targets = tuple(
        t for t in engine_cfg.adapter_targets if t in dims
    )
    root = ""
    fs = os.environ.get("HELIX_FILESTORE_KV_DIR", "")
    if fs:
        ns = re.sub(r"[^A-Za-z0-9._-]", "_", model_cfg.name or "model")
        root = os.path.join(fs, "adapters", ns)
    return AdapterStore(
        model_cfg.name or "model",
        {t: dims[t] for t in targets},
        model_cfg.num_layers,
        engine_cfg.adapter_rank,
        root_dir=root,
    )


# ---------------------------------------------------------------------------
# AdapterPool: the HBM rung (stacked slots grafted into the ragged step)
# ---------------------------------------------------------------------------


class AdapterPool:
    """Fixed-capacity device-resident adapter slots for one engine.

    Per LoRA target the pool holds stacked factors shaped for the
    layer-scanned forward (leading ``num_layers`` dim like every other
    stacked weight): ``a [L, N, fan_in, R]``, ``b [L, N, R, fan_out]``,
    plus one shared per-slot scale ``[L, N]``.  Slot 0 is the reserved
    identity adapter (zero factors, zero scale): a row whose metadata
    carries adapter id 0 adds an exact ``0.0`` to every projection, so
    adapter-free traffic through the pool-enabled program emits
    greedy-bit-identical tokens.

    Loading writes one slot of each array (``.at[:, n].set``) — same
    shapes, same dtypes, so the compiled step never retraces on adapter
    churn.  Slots are LRU over refcount-0 entries; an engine holds one
    ref per live request (admission → finish, parked requests
    included), so a serving adapter can never be evicted out from
    under its rows."""

    def __init__(self, model_cfg, targets: tuple, rank: int, slots: int,
                 dtype=None):
        import jax.numpy as jnp

        from helix_tpu.training.lora import _target_dims

        if slots < 2:
            raise ValueError(
                f"adapter_pool_slots ({slots}) must be >= 2 (slot 0 is "
                "the reserved identity adapter)"
            )
        dims = _target_dims(model_cfg)
        self.targets = tuple(t for t in targets if t in dims)
        if not self.targets:
            raise ValueError(
                f"no usable adapter targets in {targets} for "
                f"{model_cfg.name}"
            )
        self.rank = int(rank)
        self.slots = int(slots)
        L = model_cfg.num_layers
        dt = dtype or jnp.float32
        self._a = {
            t: jnp.zeros((L, self.slots, dims[t][0], self.rank), dt)
            for t in self.targets
        }
        self._b = {
            t: jnp.zeros((L, self.slots, self.rank, dims[t][1]), dt)
            for t in self.targets
        }
        self._scale = jnp.zeros((L, self.slots), jnp.float32)
        self._slot_of: dict = {}        # adapter id -> slot index
        self._lru: "collections.OrderedDict[str, None]" = (
            collections.OrderedDict()
        )
        self._refs: dict = {}           # adapter id -> live request count
        self._gen_loaded: dict = {}     # adapter id -> publish generation
        self.version = 0                # bumps per load/evict (graft cache)
        # counters
        self.loads = 0
        self.evictions = 0
        self.load_seconds = 0.0
        # bounded per-adapter activity accounting (rows applied in
        # device steps): top-K most recently active + __other__, totals
        # conserved — the PR 7 tenant rule, so the labelled series
        # count stays constant under adapter churn
        self._rows: "collections.OrderedDict[str, int]" = (
            collections.OrderedDict()
        )
        self._rows_other = 0
        self.rows_applied_total = 0
        self._lock = threading.Lock()

    # -- graft surface (engine dispatch path) ------------------------------

    def entries(self) -> dict:
        """Per-target pool entries to merge into ``params["layers"]``:
        the forward's layer scan slices the leading L dim exactly like
        the base weights, and ``maybe_dequant_dense`` picks the
        ``lora_pool_*`` keys up per projection."""
        return {
            t: {
                "lora_pool_a": self._a[t],
                "lora_pool_b": self._b[t],
                "lora_pool_scale": self._scale,
            }
            for t in self.targets
        }

    def hbm_bytes(self) -> int:
        return sum(
            int(a.nbytes) for a in self._a.values()
        ) + sum(int(b.nbytes) for b in self._b.values())

    # -- residency ---------------------------------------------------------

    def resident(self, adapter_id: str) -> bool:
        with self._lock:
            return adapter_id in self._slot_of

    def resident_ids(self) -> list:
        with self._lock:
            return sorted(self._slot_of)

    def slot_for(self, adapter_id: str) -> Optional[int]:
        with self._lock:
            return self._slot_of.get(adapter_id)

    def acquire(self, adapter_id: str,
                lookup: Callable[[str], Optional[AdapterSpec]],
                generation: Optional[int] = None) -> Optional[int]:
        """Pin ``adapter_id`` into an HBM slot for one request.

        Resident: refcount++ and return the slot.  Host-ready (``lookup``
        yields a spec): load into a free or LRU refcount-0 slot and
        return it.  Otherwise None — the caller defers admission and
        kicks a prefetch; the engine step never blocks on a cold
        adapter.

        ``generation`` is the store's publish generation: a resident
        slot loaded from an OLDER generation reloads in place when no
        live request pins it (re-publish serves the new weights on the
        next admission); pinned slots keep serving the weights their
        live rows were conditioned on, and reload once the refs
        drain."""
        with self._lock:
            slot = self._slot_of.get(adapter_id)
            if slot is not None:
                stale = (
                    generation is not None
                    and self._gen_loaded.get(adapter_id) != generation
                    and self._refs.get(adapter_id, 0) <= 0
                )
                if not stale:
                    self._refs[adapter_id] = (
                        self._refs.get(adapter_id, 0) + 1
                    )
                    self._lru.move_to_end(adapter_id)
                    return slot
        spec = lookup(adapter_id)
        if spec is None:
            return None
        with self._lock:
            slot = self._slot_of.get(adapter_id)
            refresh = slot is not None
            if refresh and (
                generation is None
                or self._gen_loaded.get(adapter_id) == generation
                or self._refs.get(adapter_id, 0) > 0
            ):
                # raced: another thread loaded/refreshed it already (or
                # a live request pinned the old weights mid-check)
                self._refs[adapter_id] = self._refs.get(adapter_id, 0) + 1
                self._lru.move_to_end(adapter_id)
                return slot
            if not refresh:
                slot = self._free_slot_locked()
                if slot is None:
                    return None    # every slot pinned by live requests
            t0 = time.monotonic()
            self._load_locked(slot, spec)
            self.load_seconds += time.monotonic() - t0
            self._slot_of[adapter_id] = slot
            self._lru[adapter_id] = None
            self._lru.move_to_end(adapter_id)
            self._refs[adapter_id] = 1
            if generation is not None:
                self._gen_loaded[adapter_id] = generation
            self.loads += 1
            self.version += 1
            return slot

    def release(self, adapter_id: str) -> None:
        with self._lock:
            n = self._refs.get(adapter_id, 0) - 1
            if n > 0:
                self._refs[adapter_id] = n
            else:
                self._refs.pop(adapter_id, None)

    def _free_slot_locked(self) -> Optional[int]:
        used = set(self._slot_of.values())
        for s in range(1, self.slots):   # slot 0 = identity, never used
            if s not in used:
                return s
        # LRU-evict a refcount-0 resident (its slot data stays garbage
        # until overwritten; no live row can carry its id)
        for aid in list(self._lru):
            if self._refs.get(aid, 0) <= 0:
                s = self._slot_of.pop(aid)
                self._lru.pop(aid, None)
                self._gen_loaded.pop(aid, None)
                self.evictions += 1
                self.version += 1
                return s
        return None

    def _load_locked(self, slot: int, spec: AdapterSpec) -> None:
        import jax.numpy as jnp

        for t in self.targets:
            f = spec.targets.get(t)
            a_host = np.zeros(self._a[t].shape[0:1] + self._a[t].shape[2:],
                              np.float32)
            b_host = np.zeros(self._b[t].shape[0:1] + self._b[t].shape[2:],
                              np.float32)
            if f is not None:
                r = f["a"].shape[-1]
                a_host[:, :, :r] = f["a"]
                b_host[:, :r, :] = f["b"]
            dt = self._a[t].dtype
            self._a[t] = self._a[t].at[:, slot].set(
                jnp.asarray(a_host, dtype=dt)
            )
            self._b[t] = self._b[t].at[:, slot].set(
                jnp.asarray(b_host, dtype=dt)
            )
        self._scale = self._scale.at[:, slot].set(
            jnp.float32(spec.scale)
        )

    # -- bounded per-adapter activity --------------------------------------

    def note_rows(self, counts: dict) -> None:
        """Bank device-step rows per adapter id (top-K + __other__,
        totals conserved — constant /metrics cardinality under adapter
        churn)."""
        with self._lock:
            for aid, n in counts.items():
                n = int(n)
                if n <= 0:
                    continue
                self.rows_applied_total += n
                if aid in self._rows:
                    self._rows[aid] += n
                    self._rows.move_to_end(aid)
                elif len(self._rows) < ADAPTER_TOP_K:
                    self._rows[aid] = n
                    self._rows.move_to_end(aid)
                else:
                    # demote the stalest tracked adapter into __other__
                    # (sums conserved), then track the newcomer
                    old_id, old_n = self._rows.popitem(last=False)
                    self._rows_other += old_n
                    self._rows[aid] = n

    def rows_applied(self) -> dict:
        with self._lock:
            out = dict(self._rows)
            if self._rows_other:
                out[OTHER_ADAPTER] = self._rows_other
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": self.slots,
                "resident": len(self._slot_of),
                "pinned": sum(
                    1 for v in self._refs.values() if v > 0
                ),
                "loads": self.loads,
                "evictions": self.evictions,
                "load_seconds": round(self.load_seconds, 6),
                "rows_applied": self.rows_applied_total,
                "hbm_bytes": self.hbm_bytes(),
            }


# ---------------------------------------------------------------------------
# metrics + federation (the single helix_adapter_* owner — lint contract 11)
# ---------------------------------------------------------------------------


def collect_adapter_metrics(c, loop, labels: dict) -> None:
    """Runner-side adapter series for one engine loop (called from the
    runner's scrape surface — the importer pattern).  No-op when the
    engine serves without a pool."""
    eng = loop.engine
    pool = getattr(eng, "adapter_pool", None)
    if pool is None:
        return
    st = pool.stats()
    c.gauge(
        "helix_adapter_pool_slots", st["slots"], labels,
        help="HBM adapter-pool slot capacity (slot 0 = identity)",
    )
    c.gauge(
        "helix_adapter_resident", st["resident"], labels,
        help="Adapters currently resident in the HBM pool",
    )
    c.gauge(
        "helix_adapter_pool_bytes", st["hbm_bytes"], labels,
        help="HBM bytes held by the stacked adapter pool",
    )
    c.counter(
        "helix_adapter_loads_total", st["loads"], labels,
        help="Adapter loads into an HBM pool slot",
    )
    c.counter(
        "helix_adapter_evictions_total", st["evictions"], labels,
        help="LRU evictions of refcount-0 adapters from the HBM pool",
    )
    c.counter(
        "helix_adapter_load_seconds_total", st["load_seconds"], labels,
        help="Cumulative host->HBM adapter load time",
    )
    for aid, n in sorted(pool.rows_applied().items()):
        c.counter(
            "helix_adapter_rows_applied_total", n,
            {**labels, "adapter": aid},
            help="Device-step rows served per adapter (top-K bounded "
                 "+ __other__)",
        )
    store = getattr(eng, "adapter_store", None)
    if store is None:
        return
    sst = store.stats()
    c.counter(
        "helix_adapter_publishes_total", sst["publishes"], labels,
        help="Adapters published (train -> publish -> serve)",
    )
    c.counter(
        "helix_adapter_prefetches_total", sst["prefetches"], labels,
        help="Async filestore->host adapter prefetches kicked",
    )
    c.counter(
        "helix_adapter_host_evictions_total", sst["host_evictions"],
        labels,
        help="Host-tier adapter evictions (filestore-backed only)",
    )
    c.counter(
        "helix_adapter_load_errors_total", sst["load_errors"], labels,
        help="Corrupt/unreadable adapter blobs dropped at load",
    )
    c.gauge(
        "helix_adapter_host_pool_used_bytes", sst["host_used_bytes"],
        labels,
        help="Host-tier bytes held by decoded adapter trees",
    )
    c.gauge(
        "helix_adapter_host_pool_budget_bytes",
        sst["host_budget_bytes"], labels,
        help="Host-tier adapter byte budget "
             "(HELIX_ADAPTER_HOST_POOL_BYTES)",
    )


def adapter_residency_summary(models) -> list:
    """The heartbeat adapter-residency block: bounded, sorted
    ``model@adapter`` ids currently resident in any live engine's HBM
    pool — the control plane's adapter-affinity signal.  ``models`` is
    the node agent's lock-free live-model snapshot."""
    out = []
    for m in models:
        loop = getattr(m, "loop", None)
        pool = getattr(getattr(loop, "engine", None), "adapter_pool",
                       None)
        if pool is None:
            continue
        name = getattr(m, "name", "")
        for aid in pool.resident_ids():
            out.append(f"{name}{ADAPTER_SEP}{aid}")
            if len(out) >= MAX_RESIDENCY_ENTRIES:
                return sorted(out)
    return sorted(out)


def validate_adapter_block(raw) -> list:
    """Clamp a runner-supplied heartbeat adapters block: a bounded list
    of sanitised ``model@adapter`` strings — malformed blocks degrade
    to [] and never reject the heartbeat (the PR 4/7 validator rule)."""
    if not isinstance(raw, (list, tuple)):
        return []
    out = []
    for entry in raw:
        if not isinstance(entry, str) or ADAPTER_SEP not in entry:
            continue
        base, _, aid = entry.partition(ADAPTER_SEP)
        aid = sanitize_adapter_id(aid)
        if not base or not aid or len(base) > 256:
            continue
        out.append(f"{base}{ADAPTER_SEP}{aid}")
        if len(out) >= MAX_RESIDENCY_ENTRIES:
            break
    return sorted(set(out))
