"""Paged KV cache: device-side page pool + host-side allocator.

The TPU replacement for vLLM's PagedAttention block manager (which the
reference rides inside its CUDA containers — ``SURVEY.md`` §2.2).  Design:

- Device state is two arrays per model, ``k_pages``/``v_pages`` of shape
  ``[num_layers, num_pages, page_size, kv_heads, head_dim]`` — statically
  shaped so every jitted step reuses one executable.  The layer dim leads so
  the model's ``lax.scan`` slices per-layer views.  ``[kv_heads, head_dim]``
  are minormost so ONE token's K (the KV-write scatter's update block) is
  contiguous in the default row-major layout — with heads ahead of pages the
  scatter preferred a transposed layout and XLA relaid the whole multi-GiB
  pool out and back *inside the decode loop* (the r3 profiler trace showed
  ~40% of each decode window in those copies).  A ``(layer, page)`` slice is
  a contiguous ``[page_size, kv_heads, head_dim]`` block — the DMA unit the
  Pallas decode kernel streams HBM->VMEM (one DMA per page for ALL heads).
- The page pool shards over the mesh on the kv-head axis (follows tensor
  parallelism; pages axis stays unsharded so any page can host any sequence).
- Allocation/free is pure host Python (a free list) — it never appears in a
  traced function; the device only ever sees page-table *arrays*.
- Writes take the model's stacked fresh KV ``[L, B, S, KVH, D]`` and one
  scatter places all layers/tokens; slot -> (page, offset) math happens on
  host or in cheap integer ops.

HBM cost per page = ``2 * L * page_size * KVH * D * itemsize`` — the unit the
residency manager (``engine/residency.py``) budgets with, replacing the
reference's GPU VRAM accounting.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from helix_tpu.models.common import ModelConfig


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    num_pages: int
    page_size: int = 16
    max_pages_per_seq: int = 128
    # Page-pool storage dtype.  "int8" stores K/V codes at 1 byte/elem
    # plus per-(slot, kv-head) f32 scale pools — page bytes drop to
    # (D + 4) / (2 * D) of bf16, so ``fit_hbm`` admits ~1.94x the pages
    # at head_dim 128 (the decode-throughput lever: batch is page-bound).
    dtype: str = "bfloat16"

    @property
    def quantized(self) -> bool:
        return self.dtype == "int8"

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.max_pages_per_seq

    def page_bytes(self, model: ModelConfig) -> int:
        per_elem = (
            2
            * model.num_layers
            * self.page_size
            * model.num_kv_heads
        )
        total = per_elem * model.head_dim * jnp.dtype(self.dtype).itemsize
        if self.quantized:
            # f32 scale per (token slot, kv head), for K and V pools
            total += per_elem * 4
        return total

    def total_bytes(self, model: ModelConfig) -> int:
        return self.num_pages * self.page_bytes(model)

    @classmethod
    def fit_hbm(
        cls,
        model: ModelConfig,
        hbm_budget_bytes: int,
        page_size: int = 16,
        max_pages_per_seq: int = 128,
        dtype: str = "bfloat16",
    ) -> "CacheConfig":
        """Size the page pool to an HBM budget (what's left after weights) —
        the accounting the reference does per-GPU with
        ``--gpu-memory-utilization`` on vLLM, done natively here.
        ``dtype="int8"`` budgets codes + scale pools, admitting
        ``2*D/(D+4)`` (~1.94x at head_dim 128) the bf16 pages."""
        probe = cls(num_pages=1, page_size=page_size,
                    max_pages_per_seq=max_pages_per_seq, dtype=dtype)
        per_page = probe.page_bytes(model)
        num_pages = max(hbm_budget_bytes // per_page, 0)
        return cls(
            num_pages=int(num_pages),
            page_size=page_size,
            max_pages_per_seq=max_pages_per_seq,
            dtype=dtype,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """Device page pool (a pytree — passes through jit with donation).

    With an int8 pool the per-(slot, head) f32 scale pools ``k_scale`` /
    ``v_scale`` (shape ``[L, N, P, KVH]``) ride along; they are ``None``
    for full-precision pools so the pytree structure itself encodes the
    storage mode (jit re-traces on the structural change, no static flag
    needed).
    """

    k_pages: jax.Array  # [L, N, P, KVH, D]
    v_pages: jax.Array
    k_scale: Optional[jax.Array] = None  # [L, N, P, KVH] f32 (int8 pools)
    v_scale: Optional[jax.Array] = None

    @classmethod
    def create(
        cls,
        model: ModelConfig,
        cache: CacheConfig,
        mesh=None,
    ) -> "PagedKVCache":
        shape = (
            model.num_layers,
            cache.num_pages,
            cache.page_size,
            model.num_kv_heads,
            model.head_dim,
        )
        sshape = shape[:-1]
        dtype = jnp.dtype(cache.dtype)
        if mesh is not None:
            from helix_tpu.parallel.sharding import logical_sharding

            # leading L follows the pp layer sharding: each pipeline
            # group holds ONLY its own layers' KV pages (KV dominates
            # serving HBM; replicating it would forfeit most of pp's
            # capacity win). Meshes without pp prune it to replicated.
            sharding = logical_sharding(
                mesh, ("layers", "pages", None, "cache_heads", None)
            )
            zeros = jax.jit(
                lambda: jnp.zeros(shape, dtype), out_shardings=(sharding)
            )
            k = zeros()
            v = zeros()
            if cache.quantized:
                ssharding = logical_sharding(
                    mesh, ("layers", "pages", None, "cache_heads")
                )
                szeros = jax.jit(
                    lambda: jnp.zeros(sshape, jnp.float32),
                    out_shardings=(ssharding),
                )
                return cls(
                    k_pages=k, v_pages=v, k_scale=szeros(),
                    v_scale=szeros(),
                )
        else:
            k = jnp.zeros(shape, dtype)
            v = jnp.zeros(shape, dtype)
            if cache.quantized:
                return cls(
                    k_pages=k,
                    v_pages=v,
                    k_scale=jnp.zeros(sshape, jnp.float32),
                    v_scale=jnp.zeros(sshape, jnp.float32),
                )
        return cls(k_pages=k, v_pages=v)

    @property
    def num_layers(self):
        return self.k_pages.shape[0]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def layer_view(self, layer: int):
        return self.k_pages[layer], self.v_pages[layer]

    def carry(self):
        """The pytree threaded through decode scans / prefill xs: pools
        plus scale pools when quantized (leaves all carry a leading L)."""
        if self.k_scale is None:
            return (self.k_pages, self.v_pages)
        return (self.k_pages, self.v_pages, self.k_scale, self.v_scale)

    @classmethod
    def from_carry(cls, carry) -> "PagedKVCache":
        if len(carry) == 2:
            return cls(k_pages=carry[0], v_pages=carry[1])
        return cls(
            k_pages=carry[0], v_pages=carry[1],
            k_scale=carry[2], v_scale=carry[3],
        )


def write_kv(
    cache: PagedKVCache,
    k_new: jax.Array,  # [L, B, S, KVH, D]
    v_new: jax.Array,
    pages: jax.Array,   # [B, S] int32 — destination page per token
    offsets: jax.Array, # [B, S] int32 — offset within page
    valid: jax.Array,   # [B, S] bool — False for padding tokens
) -> PagedKVCache:
    """Scatter fresh KV into the pool in one op.

    Padding tokens are routed to a reserved scratch page (page 0 is kept as
    the engine's garbage page) so the scatter stays fully dense.

    Int8 pools quantize here (per-slot-per-head absmax scales) and scatter
    the f32 scale rows into the scale pools with the same fused index.
    """
    L, B, S, KVH, D = k_new.shape
    Lp, P, ps, KVHp, Dp = cache.k_pages.shape
    # Scatter at ONE fused token index (page*page_size + offset) into a
    # [L, P*ps, KVH, D] view of the pool.  One update block = a token's
    # [KVH, D] — contiguous under the pool's default row-major layout, so
    # XLA keeps that layout (a (page, offset) two-index scatter, or a pool
    # with heads ahead of pages, makes layout assignment flip the pool and
    # copy multi-GiB temporaries).  The reshapes are bitcasts (pages and
    # offset are adjacent, contiguous dims).
    flat_idx = jnp.where(
        valid, pages * ps + offsets, 0
    ).reshape(-1)
    k_sc = v_sc = None
    if cache.quantized:
        from helix_tpu.ops.quant import quantize_kv

        k_new, k_sc = quantize_kv(k_new)   # int8 + [L, B, S, KVH] f32
        v_new, v_sc = quantize_kv(v_new)
    kf = k_new.reshape(L, B * S, KVH, D).astype(cache.k_pages.dtype)
    vf = v_new.reshape(L, B * S, KVH, D).astype(cache.v_pages.dtype)
    k_pages = (
        cache.k_pages.reshape(Lp, P * ps, KVHp, Dp)
        .at[:, flat_idx]
        .set(kf, mode="drop", unique_indices=False)
        .reshape(Lp, P, ps, KVHp, Dp)
    )
    v_pages = (
        cache.v_pages.reshape(Lp, P * ps, KVHp, Dp)
        .at[:, flat_idx]
        .set(vf, mode="drop", unique_indices=False)
        .reshape(Lp, P, ps, KVHp, Dp)
    )
    if not cache.quantized:
        return PagedKVCache(k_pages=k_pages, v_pages=v_pages)
    k_scale = (
        cache.k_scale.reshape(Lp, P * ps, KVHp)
        .at[:, flat_idx]
        .set(k_sc.reshape(L, B * S, KVH), mode="drop",
             unique_indices=False)
        .reshape(Lp, P, ps, KVHp)
    )
    v_scale = (
        cache.v_scale.reshape(Lp, P * ps, KVHp)
        .at[:, flat_idx]
        .set(v_sc.reshape(L, B * S, KVH), mode="drop",
             unique_indices=False)
        .reshape(Lp, P, ps, KVHp)
    )
    return PagedKVCache(
        k_pages=k_pages, v_pages=v_pages,
        k_scale=k_scale, v_scale=v_scale,
    )


class PageAllocator:
    """Host-side free-list allocator for the page pool.

    Page 0 is reserved as the garbage page that padding writes land on
    (``write_kv``), so it is never handed out.
    """

    def __init__(self, num_pages: int, max_pages_per_seq: int):
        self.num_pages = num_pages
        self.max_pages_per_seq = max_pages_per_seq
        self._free = list(range(num_pages - 1, 0, -1))  # page 0 reserved
        self._owned: dict[str, list[int]] = {}
        self.peak_used = 0   # high-water mark of occupied pages (metrics)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        """Occupied pages (incl. prefix-cache-owned); garbage page 0 is
        outside both used and free."""
        return self.num_pages - 1 - len(self._free)

    def pages_needed(self, num_tokens: int, page_size: int) -> int:
        return -(-num_tokens // page_size)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, seq_id: str, n: int) -> list[int]:
        if len(self._free) < n:
            raise MemoryError(
                f"page pool exhausted: want {n}, have {len(self._free)}"
            )
        got = [self._free.pop() for _ in range(n)]
        if self.used_pages > self.peak_used:
            self.peak_used = self.used_pages
        self._owned.setdefault(seq_id, []).extend(got)
        if len(self._owned[seq_id]) > self.max_pages_per_seq:
            raise MemoryError(f"sequence {seq_id} exceeds max_pages_per_seq")
        return got

    def seq_pages(self, seq_id: str) -> list[int]:
        return list(self._owned.get(seq_id, []))

    def free(self, seq_id: str) -> None:
        pages = self._owned.pop(seq_id, [])
        self._free.extend(reversed(pages))

    def detach(self, seq_id: str, pages: list) -> None:
        """Remove ``pages`` from the sequence's ownership WITHOUT freeing
        them — the prefix cache adopts them; they re-enter the free list
        only through give_back() on eviction."""
        drop = set(pages)
        owned = self._owned.get(seq_id)
        if owned:
            self._owned[seq_id] = [p for p in owned if p not in drop]

    def give_back(self, pages: list) -> None:
        """Return cache-evicted pages to the free list."""
        self._free.extend(pages)


def slot_to_page_offset(slots: jax.Array, page_table, page_size: int):
    """(page, offset) for absolute slot indices given per-seq page tables.

    ``slots``: [B, S] absolute token positions; ``page_table``: [B, maxP].
    Decode callers pass ``positions[:, None]`` for S=1.
    """
    page_idx = slots // page_size
    offsets = slots % page_size
    pages = jnp.take_along_axis(page_table, page_idx, axis=-1)
    return pages.astype(jnp.int32), offsets.astype(jnp.int32)


class PrefixCache:
    """Automatic prefix caching: content-hashed full pages of prompt KV
    shared across requests (vLLM's APC — the reference serves through
    vLLM where this is the flagship TTFT feature for shared system
    prompts; SURVEY.md §2.2).

    Pages enter the cache when a request's prompt finishes prefilling
    (``adopt``) and are then OWNED by the cache: the allocator's ``free``
    no longer returns them (they are detached from the request), and they
    go back to the free list only via LRU eviction under allocation
    pressure.  A later request whose prompt starts with the same page
    contents ``acquire``s them (refcount++) and skips prefilling those
    tokens entirely — attention reads them as history through the page
    table, which is safe because decode only ever writes pages PAST the
    shared prefix.

    Hash chain: h_i = blake2b(h_{i-1} || tokens[i*ps:(i+1)*ps]) — a page
    matches only when its entire prefix matches, so a page table can be
    stitched from the longest cached run.
    """

    def __init__(self):
        self._entries: dict[bytes, list] = {}   # digest -> [page, refs, tick]
        self._by_page: dict[int, bytes] = {}
        self._tick = 0
        self.hits = 0          # pages served from cache
        self.misses = 0        # full pages prefilled fresh
        self.evicted_pages = 0  # pages LRU-evicted under allocation pressure

    @staticmethod
    def page_hashes(tokens, page_size: int, max_pages: int) -> list:
        """Chain digests for the first ``max_pages`` FULL pages."""
        import hashlib

        out = []
        prev = b""
        for i in range(max_pages):
            chunk = tokens[i * page_size:(i + 1) * page_size]
            if len(chunk) < page_size:
                break
            h = hashlib.blake2b(digest_size=16)
            h.update(prev)
            h.update(np.asarray(chunk, np.int32).tobytes())
            prev = h.digest()
            out.append(prev)
        return out

    def match_len(self, hashes: list) -> int:
        """Longest cached prefix (pages), without acquiring."""
        n = 0
        for h in hashes:
            if h not in self._entries:
                break
            n += 1
        return n

    def acquire(self, hashes: list) -> list:
        """Claim the longest cached prefix; returns its pages (refs++).
        Does NOT touch the hit/miss counters — a claim can still fail on
        page pressure and be released; the engine records hits only for
        admissions that actually start (record_claim)."""
        pages = []
        self._tick += 1
        for h in hashes:
            e = self._entries.get(h)
            if e is None:
                break
            e[1] += 1
            e[2] = self._tick
            pages.append(e[0])
        return pages

    def record_claim(self, hit_pages: int, total_pages: int) -> None:
        """Stats for ONE admitted request: pages served from cache vs
        full pages prefilled fresh."""
        self.hits += hit_pages
        self.misses += total_pages - hit_pages

    def release(self, pages: list) -> None:
        for p in pages:
            h = self._by_page.get(p)
            if h is None:
                continue
            e = self._entries.get(h)
            if e is not None and e[1] > 0:
                e[1] -= 1

    def adopt(self, hashes: list, pages: list) -> list:
        """Transfer ownership of a finished prompt's fresh full pages to
        the cache (refs=1 for the adopting request).  Pages whose hash is
        already cached (a concurrent duplicate prefilled its own copy)
        are NOT adopted — the caller keeps them and they free normally.
        Returns the adopted pages."""
        adopted = []
        self._tick += 1
        for h, p in zip(hashes, pages):
            if h in self._entries or p in self._by_page:
                continue
            self._entries[h] = [p, 1, self._tick]
            self._by_page[p] = h
            adopted.append(p)
        return adopted

    def evict(self, n: int) -> list:
        """Free up to ``n`` pages from refcount-0 entries, LRU first.
        NOTE: evicting entry i invalidates the hash CHAIN below it for
        future matches, but match_len stops at the first missing digest,
        so correctness holds — later entries just become unreachable and
        age out the same way."""
        if n <= 0:
            return []
        victims = sorted(
            (e for e in self._entries.values() if e[1] == 0),
            key=lambda e: e[2],
        )[:n]
        freed = []
        for e in victims:
            page = e[0]
            h = self._by_page.pop(page)
            del self._entries[h]
            freed.append(page)
        self.evicted_pages += len(freed)
        return freed

    @property
    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "pages": len(self._by_page),
            "hits": self.hits,
            "misses": self.misses,
            "evicted_pages": self.evicted_pages,
        }
