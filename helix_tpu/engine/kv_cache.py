"""Paged KV cache: device-side page pool + host-side allocator.

The TPU replacement for vLLM's PagedAttention block manager (which the
reference rides inside its CUDA containers — ``SURVEY.md`` §2.2).  Design:

- Device state is two arrays per model, ``k_pages``/``v_pages`` of shape
  ``[num_layers, num_pages, page_size, kv_heads, head_dim]`` — statically
  shaped so every jitted step reuses one executable.  The layer dim leads so
  the model's ``lax.scan`` slices per-layer views.  ``[kv_heads, head_dim]``
  are minormost so ONE token's K (the KV-write scatter's update block) is
  contiguous in the default row-major layout — with heads ahead of pages the
  scatter preferred a transposed layout and XLA relaid the whole multi-GiB
  pool out and back *inside the decode loop* (the r3 profiler trace showed
  ~40% of each decode window in those copies).  A ``(layer, page)`` slice is
  a contiguous ``[page_size, kv_heads, head_dim]`` block — the DMA unit the
  Pallas decode kernel streams HBM->VMEM (one DMA per page for ALL heads).
- The page pool shards over the mesh on the kv-head axis (follows tensor
  parallelism; pages axis stays unsharded so any page can host any sequence).
- Allocation/free is pure host Python (a free list) — it never appears in a
  traced function; the device only ever sees page-table *arrays*.
- Writes take the model's stacked fresh KV ``[L, B, S, KVH, D]`` and one
  scatter places all layers/tokens; slot -> (page, offset) math happens on
  host or in cheap integer ops.

HBM cost per page = ``2 * L * page_size * KVH * D * itemsize`` — the unit the
residency manager (``engine/residency.py``) budgets with, replacing the
reference's GPU VRAM accounting.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from helix_tpu.models.common import ModelConfig


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    num_pages: int
    page_size: int = 16
    max_pages_per_seq: int = 128
    # Page-pool storage dtype.  "int8" stores K/V codes at 1 byte/elem
    # plus per-(slot, kv-head) f32 scale pools — page bytes drop to
    # (D + 4) / (2 * D) of bf16, so ``fit_hbm`` admits ~1.94x the pages
    # at head_dim 128 (the decode-throughput lever: batch is page-bound).
    dtype: str = "bfloat16"

    @property
    def quantized(self) -> bool:
        return self.dtype == "int8"

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.max_pages_per_seq

    def page_bytes(self, model: ModelConfig) -> int:
        per_elem = (
            2
            * model.num_layers
            * self.page_size
            * model.num_kv_heads
        )
        total = per_elem * model.head_dim * jnp.dtype(self.dtype).itemsize
        if self.quantized:
            # f32 scale per (token slot, kv head), for K and V pools
            total += per_elem * 4
        return total

    def total_bytes(self, model: ModelConfig) -> int:
        return self.num_pages * self.page_bytes(model)

    @classmethod
    def fit_hbm(
        cls,
        model: ModelConfig,
        hbm_budget_bytes: int,
        page_size: int = 16,
        max_pages_per_seq: int = 128,
        dtype: str = "bfloat16",
    ) -> "CacheConfig":
        """Size the page pool to an HBM budget (what's left after weights) —
        the accounting the reference does per-GPU with
        ``--gpu-memory-utilization`` on vLLM, done natively here.
        ``dtype="int8"`` budgets codes + scale pools, admitting
        ``2*D/(D+4)`` (~1.94x at head_dim 128) the bf16 pages."""
        probe = cls(num_pages=1, page_size=page_size,
                    max_pages_per_seq=max_pages_per_seq, dtype=dtype)
        per_page = probe.page_bytes(model)
        num_pages = max(hbm_budget_bytes // per_page, 0)
        return cls(
            num_pages=int(num_pages),
            page_size=page_size,
            max_pages_per_seq=max_pages_per_seq,
            dtype=dtype,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """Device page pool (a pytree — passes through jit with donation).

    With an int8 pool the per-(slot, head) f32 scale pools ``k_scale`` /
    ``v_scale`` (shape ``[L, N, P, KVH]``) ride along; they are ``None``
    for full-precision pools so the pytree structure itself encodes the
    storage mode (jit re-traces on the structural change, no static flag
    needed).
    """

    k_pages: jax.Array  # [L, N, P, KVH, D]
    v_pages: jax.Array
    k_scale: Optional[jax.Array] = None  # [L, N, P, KVH] f32 (int8 pools)
    v_scale: Optional[jax.Array] = None

    @classmethod
    def create(
        cls,
        model: ModelConfig,
        cache: CacheConfig,
        mesh=None,
    ) -> "PagedKVCache":
        shape = (
            model.num_layers,
            cache.num_pages,
            cache.page_size,
            model.num_kv_heads,
            model.head_dim,
        )
        sshape = shape[:-1]
        dtype = jnp.dtype(cache.dtype)
        if mesh is not None:
            from helix_tpu.parallel.sharding import logical_sharding

            # leading L follows the pp layer sharding: each pipeline
            # group holds ONLY its own layers' KV pages (KV dominates
            # serving HBM; replicating it would forfeit most of pp's
            # capacity win). Meshes without pp prune it to replicated.
            sharding = logical_sharding(
                mesh, ("layers", "pages", None, "cache_heads", None)
            )
            zeros = jax.jit(
                lambda: jnp.zeros(shape, dtype), out_shardings=(sharding)
            )
            k = zeros()
            v = zeros()
            if cache.quantized:
                ssharding = logical_sharding(
                    mesh, ("layers", "pages", None, "cache_heads")
                )
                szeros = jax.jit(
                    lambda: jnp.zeros(sshape, jnp.float32),
                    out_shardings=(ssharding),
                )
                return cls(
                    k_pages=k, v_pages=v, k_scale=szeros(),
                    v_scale=szeros(),
                )
        else:
            k = jnp.zeros(shape, dtype)
            v = jnp.zeros(shape, dtype)
            if cache.quantized:
                return cls(
                    k_pages=k,
                    v_pages=v,
                    k_scale=jnp.zeros(sshape, jnp.float32),
                    v_scale=jnp.zeros(sshape, jnp.float32),
                )
        return cls(k_pages=k, v_pages=v)

    @property
    def num_layers(self):
        return self.k_pages.shape[0]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def layer_view(self, layer: int):
        return self.k_pages[layer], self.v_pages[layer]

    def carry(self):
        """The pytree threaded through decode scans / prefill xs: pools
        plus scale pools when quantized (leaves all carry a leading L)."""
        if self.k_scale is None:
            return (self.k_pages, self.v_pages)
        return (self.k_pages, self.v_pages, self.k_scale, self.v_scale)

    @classmethod
    def from_carry(cls, carry) -> "PagedKVCache":
        if len(carry) == 2:
            return cls(k_pages=carry[0], v_pages=carry[1])
        return cls(
            k_pages=carry[0], v_pages=carry[1],
            k_scale=carry[2], v_scale=carry[3],
        )


def write_kv(
    cache: PagedKVCache,
    k_new: jax.Array,  # [L, B, S, KVH, D]
    v_new: jax.Array,
    pages: jax.Array,   # [B, S] int32 — destination page per token
    offsets: jax.Array, # [B, S] int32 — offset within page
    valid: jax.Array,   # [B, S] bool — False for padding tokens
) -> PagedKVCache:
    """Scatter fresh KV into the pool in one op.

    Padding tokens are routed to a reserved scratch page (page 0 is kept as
    the engine's garbage page) so the scatter stays fully dense.

    Int8 pools quantize here (per-slot-per-head absmax scales) and scatter
    the f32 scale rows into the scale pools with the same fused index.
    """
    L, B, S, KVH, D = k_new.shape
    Lp, P, ps, KVHp, Dp = cache.k_pages.shape
    # Scatter at ONE fused token index (page*page_size + offset) into a
    # [L, P*ps, KVH, D] view of the pool.  One update block = a token's
    # [KVH, D] — contiguous under the pool's default row-major layout, so
    # XLA keeps that layout (a (page, offset) two-index scatter, or a pool
    # with heads ahead of pages, makes layout assignment flip the pool and
    # copy multi-GiB temporaries).  The reshapes are bitcasts (pages and
    # offset are adjacent, contiguous dims).
    flat_idx = jnp.where(
        valid, pages * ps + offsets, 0
    ).reshape(-1)
    k_sc = v_sc = None
    if cache.quantized:
        from helix_tpu.ops.quant import quantize_kv

        k_new, k_sc = quantize_kv(k_new)   # int8 + [L, B, S, KVH] f32
        v_new, v_sc = quantize_kv(v_new)
    kf = k_new.reshape(L, B * S, KVH, D).astype(cache.k_pages.dtype)
    vf = v_new.reshape(L, B * S, KVH, D).astype(cache.v_pages.dtype)
    k_pages = (
        cache.k_pages.reshape(Lp, P * ps, KVHp, Dp)
        .at[:, flat_idx]
        .set(kf, mode="drop", unique_indices=False)
        .reshape(Lp, P, ps, KVHp, Dp)
    )
    v_pages = (
        cache.v_pages.reshape(Lp, P * ps, KVHp, Dp)
        .at[:, flat_idx]
        .set(vf, mode="drop", unique_indices=False)
        .reshape(Lp, P, ps, KVHp, Dp)
    )
    if not cache.quantized:
        return PagedKVCache(k_pages=k_pages, v_pages=v_pages)
    k_scale = (
        cache.k_scale.reshape(Lp, P * ps, KVHp)
        .at[:, flat_idx]
        .set(k_sc.reshape(L, B * S, KVH), mode="drop",
             unique_indices=False)
        .reshape(Lp, P, ps, KVHp)
    )
    v_scale = (
        cache.v_scale.reshape(Lp, P * ps, KVHp)
        .at[:, flat_idx]
        .set(v_sc.reshape(L, B * S, KVH), mode="drop",
             unique_indices=False)
        .reshape(Lp, P, ps, KVHp)
    )
    return PagedKVCache(
        k_pages=k_pages, v_pages=v_pages,
        k_scale=k_scale, v_scale=v_scale,
    )


class PageAllocator:
    """Host-side free-list allocator for the page pool.

    Page 0 is reserved as the garbage page that padding writes land on
    (``write_kv``), so it is never handed out.

    Invariants, enforced loudly (ISSUE 6): ``used + free == num_pages - 1``
    after every operation, ``free()`` of a sequence that owns nothing is
    an error (double-free / typo'd seq id), ``give_back()`` of a page
    already on the free list is an error, and ``allocate()`` either
    fully succeeds or changes nothing — a partial failure can never
    orphan pages.
    """

    def __init__(self, num_pages: int, max_pages_per_seq: int):
        self.num_pages = num_pages
        self.max_pages_per_seq = max_pages_per_seq
        self._free = list(range(num_pages - 1, 0, -1))  # page 0 reserved
        self._free_set = set(self._free)   # O(1) double-give_back guard
        self._owned: dict[str, list[int]] = {}
        self.peak_used = 0   # high-water mark of occupied pages (metrics)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        """Occupied pages (incl. prefix-cache-owned); garbage page 0 is
        outside both used and free."""
        return self.num_pages - 1 - len(self._free)

    def pages_needed(self, num_tokens: int, page_size: int) -> int:
        return -(-num_tokens // page_size)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, seq_id: str, n: int) -> list[int]:
        """All-or-nothing: every failure path is checked BEFORE any page
        leaves the free list, so a raising allocate leaves no orphans."""
        if n < 0:
            raise ValueError(f"allocate({seq_id!r}, {n}): negative count")
        if n == 0:
            return []
        if len(self._free) < n:
            raise MemoryError(
                f"page pool exhausted: want {n}, have {len(self._free)}"
            )
        if len(self._owned.get(seq_id, ())) + n > self.max_pages_per_seq:
            raise MemoryError(f"sequence {seq_id} exceeds max_pages_per_seq")
        got = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(got)
        if self.used_pages > self.peak_used:
            self.peak_used = self.used_pages
        self._owned.setdefault(seq_id, []).extend(got)
        return got

    def seq_pages(self, seq_id: str) -> list[int]:
        return list(self._owned.get(seq_id, []))

    def owns(self, seq_id: str) -> bool:
        """Does this sequence currently own any pages?  Callers with a
        legitimately-maybe-unallocated sequence (a request aborted while
        still queued) guard ``free()`` with this instead of relying on a
        silent no-op that would also mask real double-frees."""
        return seq_id in self._owned

    def free(self, seq_id: str) -> None:
        if seq_id not in self._owned:
            raise KeyError(
                f"free() of sequence {seq_id!r} that owns no pages "
                "(double free, or never allocated?)"
            )
        pages = self._owned.pop(seq_id)
        self._free.extend(reversed(pages))
        self._free_set.update(pages)

    def detach(self, seq_id: str, pages: list) -> None:
        """Remove ``pages`` from the sequence's ownership WITHOUT freeing
        them — the prefix cache adopts them; they re-enter the free list
        only through give_back() on eviction."""
        drop = set(pages)
        owned = self._owned.get(seq_id)
        if owned:
            self._owned[seq_id] = [p for p in owned if p not in drop]

    def give_back(self, pages: list) -> None:
        """Return cache-evicted pages to the free list."""
        dup = self._free_set.intersection(pages)
        if dup:
            raise ValueError(
                f"give_back() of already-free page(s) {sorted(dup)}"
            )
        self._free.extend(pages)
        self._free_set.update(pages)


def slot_to_page_offset(slots: jax.Array, page_table, page_size: int):
    """(page, offset) for absolute slot indices given per-seq page tables.

    ``slots``: [B, S] absolute token positions; ``page_table``: [B, maxP].
    Decode callers pass ``positions[:, None]`` for S=1.
    """
    page_idx = slots // page_size
    offsets = slots % page_size
    pages = jnp.take_along_axis(page_table, page_idx, axis=-1)
    return pages.astype(jnp.int32), offsets.astype(jnp.int32)


class PrefixCache:
    """Automatic prefix caching: content-hashed full pages of prompt KV
    shared across requests (vLLM's APC — the reference serves through
    vLLM where this is the flagship TTFT feature for shared system
    prompts; SURVEY.md §2.2).

    Pages enter the cache when a request's prompt finishes prefilling
    (``adopt``) and are then OWNED by the cache: the allocator's ``free``
    no longer returns them (they are detached from the request), and they
    go back to the free list only via LRU eviction under allocation
    pressure.  A later request whose prompt starts with the same page
    contents ``acquire``s them (refcount++) and skips prefilling those
    tokens entirely — attention reads them as history through the page
    table, which is safe because decode only ever writes pages PAST the
    shared prefix.

    Hash chain: h_i = blake2b(h_{i-1} || tokens[i*ps:(i+1)*ps]) — a page
    matches only when its entire prefix matches, so a page table can be
    stitched from the longest cached run.
    """

    def __init__(self):
        self._entries: dict[bytes, list] = {}   # digest -> [page, refs, tick]
        self._by_page: dict[int, bytes] = {}
        self._tick = 0
        self.hits = 0          # pages served from cache
        self.misses = 0        # full pages prefilled fresh
        self.evicted_pages = 0  # pages LRU-evicted under allocation pressure

    @staticmethod
    def page_hashes(tokens, page_size: int, max_pages: int) -> list:
        """Chain digests for the first ``max_pages`` FULL pages."""
        import hashlib

        out = []
        prev = b""
        for i in range(max_pages):
            chunk = tokens[i * page_size:(i + 1) * page_size]
            if len(chunk) < page_size:
                break
            h = hashlib.blake2b(digest_size=16)
            h.update(prev)
            h.update(np.asarray(chunk, np.int32).tobytes())
            prev = h.digest()
            out.append(prev)
        return out

    def match_len(self, hashes: list) -> int:
        """Longest cached prefix (pages), without acquiring."""
        n = 0
        for h in hashes:
            if h not in self._entries:
                break
            n += 1
        return n

    def acquire(self, hashes: list) -> list:
        """Claim the longest cached prefix; returns its pages (refs++).
        Does NOT touch the hit/miss counters — a claim can still fail on
        page pressure and be released; the engine records hits only for
        admissions that actually start (record_claim)."""
        pages = []
        self._tick += 1
        for h in hashes:
            e = self._entries.get(h)
            if e is None:
                break
            e[1] += 1
            e[2] = self._tick
            pages.append(e[0])
        return pages

    def record_claim(self, hit_pages: int, total_pages: int) -> None:
        """Stats for ONE admitted request: pages served from cache vs
        full pages prefilled fresh."""
        self.hits += hit_pages
        self.misses += total_pages - hit_pages

    def release(self, pages: list) -> None:
        for p in pages:
            h = self._by_page.get(p)
            if h is None:
                continue
            e = self._entries.get(h)
            if e is not None and e[1] > 0:
                e[1] -= 1

    def adopt(self, hashes: list, pages: list) -> list:
        """Transfer ownership of a finished prompt's fresh full pages to
        the cache (refs=1 for the adopting request).  Pages whose hash is
        already cached (a concurrent duplicate prefilled its own copy)
        are NOT adopted — the caller keeps them and they free normally.
        Returns the adopted pages."""
        adopted = []
        self._tick += 1
        for h, p in zip(hashes, pages):
            if h in self._entries or p in self._by_page:
                continue
            self._entries[h] = [p, 1, self._tick]
            self._by_page[p] = h
            adopted.append(p)
        return adopted

    def evict(self, n: int) -> list:
        """Free up to ``n`` pages from refcount-0 entries, LRU first.
        Returns the freed page ids (see ``evict_entries`` for the
        digest-carrying variant the host spill tier feeds on)."""
        return [p for _, p in self.evict_entries(n)]

    def evict_entries(self, n: int) -> list:
        """Free up to ``n`` pages from refcount-0 entries, LRU first;
        returns ``[(digest, page), ...]`` so the caller can demote the
        page CONTENTS to a host tier keyed by the same chain digest a
        future ``match_len`` would look up.
        NOTE: evicting entry i invalidates the hash CHAIN below it for
        future matches, but match_len stops at the first missing digest,
        so correctness holds — later entries just become unreachable and
        age out the same way."""
        if n <= 0:
            return []
        victims = sorted(
            (e for e in self._entries.values() if e[1] == 0),
            key=lambda e: e[2],
        )[:n]
        freed = []
        for e in victims:
            page = e[0]
            h = self._by_page.pop(page)
            del self._entries[h]
            freed.append((h, page))
        self.evicted_pages += len(freed)
        return freed

    @property
    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "pages": len(self._by_page),
            "hits": self.hits,
            "misses": self.misses,
            "evicted_pages": self.evicted_pages,
        }


# ---------------------------------------------------------------------------
# Host-RAM page tier (ISSUE 6): spill instead of die
# ---------------------------------------------------------------------------


def _page_checksum(arrays: dict) -> bytes:
    """Content digest over a page's host buffers, in a fixed field order.
    Spilled int8 pools checksum the raw codes + scale rows, so a
    restore is verified bit-exact in the STORED representation."""
    h = hashlib.blake2b(digest_size=16)
    for field in ("k", "v", "k_scale", "v_scale"):
        a = arrays.get(field)
        if a is not None:
            h.update(np.ascontiguousarray(a).tobytes())
    return h.digest()


def page_checksum(arrays: dict) -> bytes:
    """Public content digest over one page's host buffers (the
    ``gather_pages`` field layout) — the host tier verifies restores
    with it and request snapshots (ISSUE 11) stamp/verify every shipped
    page with the same digest, so a page is checked identically whether
    it crossed a process boundary or just the PCIe bus."""
    return _page_checksum(arrays)


class ColdPageError(RuntimeError):
    """A tiered sequence's demoted cold-middle page failed checksum
    verification (or vanished from the host pool) at stream time.

    Unlike a prefix-cache restore miss — which truncates the chain and
    recomputes, correct by construction — a cold-middle page has no
    recompute path mid-decode: the tokens it holds were already
    conditioned on.  The ONLY safe outcome is a typed failure for this
    request; attending garbage KV would silently corrupt every
    subsequent token."""


class _HostPage:
    """One spilled page: host copies of its K/V (+ int8 scale rows).

    ``arrays`` may still hold device arrays whose host copy is in
    flight (``copy_to_host_async`` issued at spill time — the engine
    thread never blocks on the D2H transfer); ``_finalize`` converts to
    numpy and stamps the checksum on first use."""

    __slots__ = (
        "key", "arrays", "nbytes", "pinned", "tick", "checksum", "ready",
        "device",
    )

    def __init__(self, key, arrays: dict, nbytes: int, pinned: bool,
                 tick: int):
        self.key = key
        self.arrays = arrays
        self.nbytes = nbytes
        self.pinned = pinned
        self.tick = tick
        self.checksum: Optional[bytes] = None
        self.ready = False
        self.device: Optional[dict] = None   # prefetched device handles


class HostPagePool:
    """Byte-budgeted host-RAM tier under the device page pool.

    Two key spaces share one budget:

    - **prefix pages** keyed by the ``PrefixCache`` chain digest:
      ``PrefixCache`` evictions demote here instead of dying, and a
      later admission whose prompt chains onto a host-resident digest
      restores the page into fresh device pages (10-100x the effective
      prefix cache for system-prompt-heavy fleets);
    - **preempted sequences** keyed by ``("seq", request_id, table_pos)``
      and PINNED: a swapped-out decoder's private pages must survive
      until resume or abort, so prefix-spill pressure can never evict
      them.

    Unpinned entries LRU-evict to fit the budget.  Every entry carries a
    content checksum verified at restore (and at prefetch) — a corrupt
    host buffer is detected, dropped, and surfaces as a counter + a
    cache miss (prefix pages) or a resume failure (preempted pages),
    never as silently wrong KV.

    Engine-thread owned; the counters and occupancy ints are plain
    GIL-atomic reads for the /metrics and heartbeat threads.
    """

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._entries: dict = {}
        self._pending: list = []   # keys spilled but not yet finalized
        self._tick = 0
        self._bytes = 0
        # counters (monotonic; scraped as helix_kv_* series)
        self.spilled_pages = 0      # pages demoted device -> host
        self.restored_pages = 0     # pages promoted host -> device
        self.evicted_pages = 0      # unpinned pages LRU-dropped for budget
        self.corrupt_pages = 0      # checksum failures detected at restore
        self.alloc_failures = 0     # spills dropped: budget/fault

    # -- occupancy (GIL-atomic reads, any thread) ---------------------------

    @property
    def used_bytes(self) -> int:
        return self._bytes

    @property
    def pages(self) -> int:
        return len(self._entries)

    @property
    def occupancy(self) -> float:
        return self._bytes / self.budget_bytes if self.budget_bytes else 0.0

    def stats(self) -> dict:
        return {
            "pages": len(self._entries),
            "used_bytes": self._bytes,
            "budget_bytes": self.budget_bytes,
            "spilled_pages": self.spilled_pages,
            "restored_pages": self.restored_pages,
            "evicted_pages": self.evicted_pages,
            "corrupt_pages": self.corrupt_pages,
            "alloc_failures": self.alloc_failures,
        }

    # -- write side (engine thread) -----------------------------------------

    @staticmethod
    def _fault(op: str) -> Optional[dict]:
        from helix_tpu.testing import faults

        inj = faults.active()
        return inj.host_pool_fault(op) if inj is not None else None

    def put(self, key, arrays: dict, pinned: bool = False) -> bool:
        """Adopt one page's buffers (device arrays fresh off a gather, or
        numpy).  Device arrays get ``copy_to_host_async`` issued here so
        the D2H copy overlaps whatever the engine does next; numpy
        conversion + checksum happen lazily on first use.  Returns False
        (and counts ``alloc_failures``) when the page cannot fit."""
        fault = self._fault("spill")
        if fault is not None and fault.get("mode") == "alloc_fail":
            self.alloc_failures += 1
            return False
        nbytes = sum(
            int(a.nbytes) for a in arrays.values() if a is not None
        )
        old = self._entries.get(key)
        if old is not None:
            self._drop(key)
        if nbytes > self.budget_bytes or not self._evict_for(nbytes):
            # a failed RE-spill must not destroy the previously valid
            # host copy (same digest = same content) — put it back; it
            # fit before and only evictions happened since
            if (
                old is not None
                and self._bytes + old.nbytes <= self.budget_bytes
            ):
                self._entries[key] = old
                self._bytes += old.nbytes
            self.alloc_failures += 1
            return False
        for a in arrays.values():
            copy_async = getattr(a, "copy_to_host_async", None)
            if copy_async is not None:
                try:
                    copy_async()
                except Exception:  # noqa: BLE001 — fallback: lazy blocking fetch
                    pass
        self._tick += 1
        self._entries[key] = _HostPage(key, arrays, nbytes, pinned,
                                       self._tick)
        self._bytes += nbytes
        self._pending.append(key)
        self.spilled_pages += 1
        return True

    def drain_pending(self) -> None:
        """Finalize spills whose async D2H copies have had time to land
        (called once per engine step): converts the stored device
        arrays to numpy and stamps checksums, RELEASING the device
        buffers.  Without this, a cold spilled prefix that is never
        re-read would pin its HBM gather buffers for the life of the
        pool — the 'host' tier must not hold device memory beyond ~one
        step."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        for key in pending:
            e = self._entries.get(key)
            if e is not None:
                self._finalize(e)

    def _evict_for(self, nbytes: int) -> bool:
        """LRU-drop unpinned entries until ``nbytes`` fit; False when the
        pinned set alone exceeds the headroom."""
        while self._bytes + nbytes > self.budget_bytes:
            victims = [e for e in self._entries.values() if not e.pinned]
            if not victims:
                return False
            victim = min(victims, key=lambda e: e.tick)
            self._drop(victim.key)
            self.evicted_pages += 1
        return True

    def _drop(self, key) -> None:
        e = self._entries.pop(key, None)
        if e is not None:
            self._bytes -= e.nbytes

    def discard(self, key) -> None:
        """Remove an entry without restore accounting (aborted preempted
        request, prefix page superseded on device)."""
        self._drop(key)

    # -- read side (engine thread) ------------------------------------------

    def contains(self, key) -> bool:
        """Presence check only — never blocks on an in-flight D2H copy
        (the admission loop chains digests through this every step)."""
        return key in self._entries

    @staticmethod
    def _finalize(e: _HostPage) -> None:
        if e.ready:
            return
        e.arrays = {
            f: (None if a is None else np.asarray(a))
            for f, a in e.arrays.items()
        }
        e.checksum = _page_checksum(e.arrays)
        e.ready = True

    def get(self, key) -> Optional[dict]:
        """Fetch one page's host buffers for restore, checksum-verified.
        Returns None on a miss OR a detected corruption (the entry is
        dropped and counted — the caller treats it as a cache miss /
        resume failure, never as usable KV)."""
        e = self._entries.get(key)
        if e is None:
            return None
        fault = self._fault("restore")
        if fault is not None:
            if fault.get("mode") == "slow":
                time.sleep(float(fault.get("delay", 0.05)))
            elif fault.get("mode") == "corrupt":
                self._finalize(e)
                k = np.array(e.arrays["k"])   # detached copy, then flip
                k.view(np.uint8).reshape(-1)[0] ^= 0xFF
                e.arrays = {**e.arrays, "k": k}
        self._finalize(e)
        if _page_checksum(e.arrays) != e.checksum:
            self._drop(key)
            self.corrupt_pages += 1
            return None
        self._tick += 1
        e.tick = self._tick
        return e.arrays

    def prefetch(self, key) -> bool:
        """Start the host->device upload for a page expected to restore
        soon (admission saw the digest while the request was still
        queue-blocked): ``jax.device_put`` is async, so the upload
        overlaps the queue wait and the eventual restore consumes the
        in-flight handles.  Verification happens here — a corrupt page
        is dropped now, before any device write."""
        e = self._entries.get(key)
        if e is None:
            return False
        if e.device is not None:
            return True
        arrays = self.get(key)
        if arrays is None:
            return False
        e.device = {
            f: (None if a is None else jax.device_put(a))
            for f, a in arrays.items()
        }
        return True

    def release_device(self, key) -> None:
        """Drop a prefetched entry's device handles (the host copy
        stays).  Prefetch targets HBM — the resource the machine is by
        definition short of when this tier is active — so uploads whose
        admission never materialised (request shed, chain truncated)
        must be let go, not retained until LRU eviction."""
        e = self._entries.get(key)
        if e is not None:
            e.device = None

    def take_restored(self, key) -> Optional[dict]:
        """Claim a page for device restore: verified buffers (device
        handles when prefetched, else host numpy), removed from the pool
        and counted as restored."""
        e = self._entries.get(key)
        if e is None:
            return None
        if e.device is not None:
            out = e.device
        else:
            out = self.get(key)
            if out is None:
                return None
        self._drop(key)
        self.restored_pages += 1
        return out


def gather_pages(cache: PagedKVCache, page_ids: list) -> list:
    """Slice ``page_ids`` out of the device pool as per-page array dicts
    (``[L, page_size, KVH, D]`` each, scale rows ``[L, page_size, KVH]``
    when quantized).  One fused gather per field, then cheap per-page
    slices — the result arrays are fresh buffers, safe to hand to
    ``HostPagePool.put`` while later steps donate the pool."""
    idx = jnp.asarray(np.asarray(page_ids, np.int32))
    k = cache.k_pages[:, idx]
    v = cache.v_pages[:, idx]
    ks = cache.k_scale[:, idx] if cache.k_scale is not None else None
    vs = cache.v_scale[:, idx] if cache.v_scale is not None else None
    out = []
    for i in range(len(page_ids)):
        out.append(
            {
                "k": k[:, i],
                "v": v[:, i],
                "k_scale": None if ks is None else ks[:, i],
                "v_scale": None if vs is None else vs[:, i],
            }
        )
    return out


@functools.lru_cache(maxsize=32)
def _build_page_restore_fn(n: int, quantized: bool):
    """One donated scatter writes ``n`` whole pages back into the pool
    (host->device restore).  Cached per (bucketed n, storage mode) so
    restores reuse one executable; padding rows target the garbage
    page 0."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def fn(carry, idx, k_new, v_new, k_sc, v_sc):
        k_pages = carry[0].at[:, idx].set(k_new)
        v_pages = carry[1].at[:, idx].set(v_new)
        if not quantized:
            return (k_pages, v_pages)
        return (
            k_pages,
            v_pages,
            carry[2].at[:, idx].set(k_sc),
            carry[3].at[:, idx].set(v_sc),
        )

    return fn


def restore_pages(
    cache: PagedKVCache, page_ids: list, entries: list
) -> PagedKVCache:
    """Write spilled page contents into freshly allocated device pages.

    ``entries[i]`` (from ``HostPagePool.take_restored``) lands in pool
    page ``page_ids[i]``.  The batch is bucketed to a power of two
    (bounded compile shapes, same scheme as chunked prefill) and written
    by ONE donated scatter; prefetched device handles upload nothing
    here — ``jnp.stack`` just fuses the already-resident pages."""
    if not page_ids:
        return cache
    n = len(page_ids)
    bucket = 1
    while bucket < n:
        bucket *= 2
    idx = np.zeros((bucket,), np.int32)   # padding targets garbage page 0
    idx[:n] = page_ids
    quantized = cache.quantized

    def stack(field):
        parts = [e[field] for e in entries]
        parts += [jnp.zeros_like(parts[0])] * (bucket - n)
        return jnp.stack(parts, axis=1)   # [L, bucket, ...]

    k_new = stack("k")
    v_new = stack("v")
    k_sc = stack("k_scale") if quantized else None
    v_sc = stack("v_scale") if quantized else None
    fn = _build_page_restore_fn(bucket, quantized)
    carry = fn(cache.carry(), jnp.asarray(idx), k_new, v_new, k_sc, v_sc)
    return PagedKVCache.from_carry(carry)
