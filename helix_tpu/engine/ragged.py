"""Host-side metadata for the unified ragged device step.

The engine compiles ONE device-step entry point per (model, backend,
token-bucket) — ``engine._build_ragged_step_fn`` — and every caller
(packed/cache-hit prefill, chunked prefill, plain decode, the mixed
step, spec-verify) is a thin metadata builder over it.  This module owns
the host-side pieces of that contract:

- the **token-bucket ladder**: the prefill segment's flat token axis is
  padded to a rung so XLA compiles O(log max_prefill_len) shapes, not
  one per prompt length.  Default: powers of two from ``page_size`` to
  ``max_prefill_len``; ``HELIX_TOKEN_BUCKETS`` overrides with an
  explicit comma-separated ladder (finer rungs trade a few extra
  compiles for less padding — the padding-ratio gauge shows whether it
  paid off).
- :class:`PrefillPlan` — accumulates prefill **rows** (one per admitted
  prompt / in-flight chunk) and finalizes them into the device arrays
  the unified step consumes: flat tokens + positions + segment ids + KV
  write destinations, and per-row (t0, q_len, hist, table, end,
  sampling, key).
- the **compiled-shape registry** — every distinct (token-bucket,
  has-history) entry point the unified builder traces is recorded per
  model key, so ``helix_compiled_step_shapes`` can report the shape-zoo
  collapse instead of asserting it.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

import numpy as np


def parse_token_buckets(
    spec: Optional[str], page_size: int, cap: int
) -> tuple:
    """The prefill token-bucket ladder, ascending, capped at ``cap``.

    ``spec`` (from ``HELIX_TOKEN_BUCKETS``) is a comma-separated list of
    rung sizes; invalid entries raise (a typo'd ladder must not silently
    become the default).  ``None``/empty: powers of two from
    ``page_size`` up to ``cap``.  The top rung is always ``cap`` so any
    admissible chunk has a home."""
    if spec:
        rungs = sorted(
            {min(int(tok), cap) for tok in spec.split(",") if tok.strip()}
        )
        if not rungs or any(r <= 0 for r in rungs):
            raise ValueError(
                f"HELIX_TOKEN_BUCKETS {spec!r}: rungs must be positive ints"
            )
    else:
        rungs = []
        b = page_size
        while b < cap:
            rungs.append(b)
            b *= 2
    if not rungs or rungs[-1] != cap:
        rungs.append(cap)
    return tuple(rungs)


def bucket_tokens(n: int, ladder: tuple) -> int:
    """Smallest rung >= n (callers guarantee n <= ladder[-1])."""
    for b in ladder:
        if b >= n:
            return b
    return ladder[-1]


# ---------------------------------------------------------------------------
# compiled-shape registry (feeds helix_compiled_step_shapes)
# ---------------------------------------------------------------------------

_SHAPES: dict = {}           # model key -> set of shape tuples
_SHAPES_LOCK = threading.Lock()


def note_step_shape(model_key, shape: tuple) -> None:
    """Record one distinct compiled device-step entry point for a model.
    Called from the unified builder on cache miss (and from the VL
    prefill path per bucket), so the count IS the number of live traced
    step programs."""
    with _SHAPES_LOCK:
        _SHAPES.setdefault(model_key, set()).add(shape)


def compiled_step_shapes(model_key) -> int:
    with _SHAPES_LOCK:
        return len(_SHAPES.get(model_key, ()))


def step_shape_set(model_key) -> frozenset:
    """Snapshot of the distinct compiled step shapes for a model key.
    The multihost parity tests diff this across a leader run and a
    follower replay: a plan-driven follower must trace ZERO shapes of
    its own (same model key -> same registry entry, so the assertion is
    'no new members after replay')."""
    with _SHAPES_LOCK:
        return frozenset(_SHAPES.get(model_key, ()))


@dataclasses.dataclass
class PrefillRow:
    req: object                 # engine.Request (None for warmup rows)
    table: np.ndarray           # full page table row [maxP]
    start: int                  # pages-resident history tokens
    rem: int                    # fresh tokens this call
    tokens: list                # the rem token ids
    key: np.ndarray             # [2] u32 sampling sub-key
    sampling: object            # SamplingParams
    t0: int = 0                 # assigned at finalize
    adapter: int = 0            # multi-LoRA pool slot (0 = identity)


class PrefillPlan:
    """One call's prefill segment: rows packed back-to-back on a flat
    token axis, finalized to a ladder rung.

    The unification win lives here: cache-hit prompts (nonzero
    ``start``), cold packed prompts and the in-flight chunk all share
    ONE segment instead of one padded call each — padding is charged
    once, ``rung - sum(rem)``, by the engine's ``_charge_padding``."""

    def __init__(self, page_size: int, max_pages: int, max_rows: int):
        self.page_size = page_size
        self.max_pages = max_pages
        self.max_rows = max_rows
        self.rows: list = []
        self.used = 0

    def fits(self, rem: int, cap: int) -> bool:
        return len(self.rows) < self.max_rows and self.used + rem <= cap

    def add(self, req, table, start: int, rem: int, tokens, key,
            sampling, adapter: int = 0) -> None:
        row = PrefillRow(
            req=req, table=np.asarray(table), start=int(start),
            rem=int(rem), tokens=list(tokens), key=key, sampling=sampling,
            t0=self.used, adapter=int(adapter),
        )
        self.rows.append(row)
        self.used += row.rem

    @property
    def has_hist(self) -> bool:
        return any(r.start > 0 for r in self.rows)

    def finalize(self, rung: int):
        """Device arrays for the unified step's prefill inputs.

        Returns a dict of host arrays (the engine asarray's them):
        ``tokens/pos/seg/pages/offsets/aids [1, rung]``, per-row
        ``t0/qlen/hist/ends [R]`` and ``tables [R, maxP]``, plus the
        rows' sampling params and keys.  ``aids`` carries each token's
        multi-LoRA pool slot (0 = identity — padding and adapter-free
        rows contribute an exact zero delta in the batched gather-
        matmul)."""
        R = self.max_rows
        ps = self.page_size
        tokens = np.zeros((1, rung), np.int32)
        pos = np.zeros((1, rung), np.int32)
        seg = np.zeros((1, rung), np.int32)
        pages = np.zeros((1, rung), np.int32)
        offsets = np.zeros((1, rung), np.int32)
        aids = np.zeros((1, rung), np.int32)
        t0 = np.zeros((R,), np.int32)
        qlen = np.zeros((R,), np.int32)
        hist = np.zeros((R,), np.int32)
        ends = np.zeros((R,), np.int32)
        tables = np.zeros((R, self.max_pages), np.int32)
        keys = np.zeros((R, 2), np.uint32)
        for j, row in enumerate(self.rows):
            sl = slice(row.t0, row.t0 + row.rem)
            tokens[0, sl] = row.tokens
            abs_pos = np.arange(row.start, row.start + row.rem)
            pos[0, sl] = abs_pos
            seg[0, sl] = j + 1
            # clamp like the device paths: real rows never exceed their
            # table (admission caps max_len), warmup's garbage-page rows
            # may — they write page 0 regardless
            pages[0, sl] = row.table[
                np.minimum(abs_pos // ps, len(row.table) - 1)
            ]
            offsets[0, sl] = abs_pos % ps
            aids[0, sl] = row.adapter
            t0[j] = row.t0
            qlen[j] = row.rem
            hist[j] = row.start
            ends[j] = row.t0 + row.rem - 1
            tables[j, : len(row.table)] = row.table
            keys[j] = row.key
        # unused rows park at the segment end (ascending-start contract)
        t0[len(self.rows):] = self.used
        return {
            "tokens": tokens, "pos": pos, "seg": seg,
            "pages": pages, "offsets": offsets, "aids": aids,
            "t0": t0, "qlen": qlen, "hist": hist, "ends": ends,
            "tables": tables, "keys": keys,
        }

    def finalize_device(self, rung: int):
        """``finalize`` + the host->device upload, in one place.

        The engine calls this at DISPATCH time so the conversion (and
        the transfers jax issues for it) overlap whatever device step is
        already in flight — the async engine loop's double-buffered
        metadata upload.  Plan building itself stays pure host work and
        may run against the loop's PREDICTED post-step state; nothing
        here reads device values."""
        import jax.numpy as jnp

        return {k: jnp.asarray(v) for k, v in self.finalize(rung).items()}
