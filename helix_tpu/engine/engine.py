"""The serving engine: continuous batching over a paged KV cache.

Replaces the reference's per-model vLLM container (``SURVEY.md`` §2.2, §7
stage 2).  One ``Engine`` owns one model's weights + page pool on a mesh
slice and exposes token-level ``add_request`` / ``step`` — the OpenAI HTTP
surface (``helix_tpu.serving``) sits on top, the multi-model residency
manager (``helix_tpu.engine.residency``) creates/destroys Engines per the
active profile.

Execution model (all shapes static, everything jitted once per bucket):

- **Prefill**: one request per call, prompt padded to a power-of-two bucket;
  flash attention over its own K/V; fresh K/V scattered into the request's
  pages; last-token logits sampled for the first generated token.
- **Decode**: one fused step for all ``max_decode_batch`` slots — forward
  (paged attention over each slot's page table) + KV write + penalty +
  sampling inside a single jit; inactive slots ride along pointed at the
  garbage page.
- **Mixed step**: while a long prompt chunk-prefills, the chunk and every
  active decode slot run in ONE device call per engine step (ragged row
  lengths over the shared page pool) — decode never stalls during
  admission and never pays a second dispatch.
- **Int8 KV** (``EngineConfig.kv_cache_dtype="int8"``): pages store codes
  + per-(slot, head) f32 scales; ~2x the cached tokens per HBM byte, with
  in-register dequant in the paged kernel.
- **Speculative decoding** (``EngineConfig.enable_spec_decode``): the host
  drafts up to ``spec_tokens`` continuation tokens per slot via
  prompt-lookup n-grams (``engine/spec.py`` — no draft model), and ONE
  device call scores all k+1 positions per slot against its ragged paged
  history, accepting the longest prefix the model's own sampling agrees
  with — accepted tokens cost no extra forward pass, and a per-slot
  acceptance EMA degrades the worst case back to the plain fused window.
- Host side keeps plain-Python queues, a page allocator, and per-request
  state; nothing dynamic ever crosses into traced code.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import itertools
import logging
import os
import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from helix_tpu.engine import ragged as ragged_meta
from helix_tpu.engine.kv_cache import (
    CacheConfig,
    ColdPageError,
    PageAllocator,
    PagedKVCache,
    slot_to_page_offset,
    write_kv,
)
from helix_tpu.engine.ragged import PrefillPlan, bucket_tokens
from helix_tpu.engine.sampling import (
    SamplingParams,
    SamplingState,
    apply_penalties,
    sample,
    split_keys,
)
from helix_tpu.models.common import ModelConfig
from helix_tpu.models.llama import forward
from helix_tpu.obs import trace as obs_trace
from helix_tpu.obs.slo import ANON_TENANT
from helix_tpu.ops.attention import attention as full_attention
from helix_tpu.ops.paged import ragged_paged_attention


class FinishReason(str, enum.Enum):
    STOP = "stop"
    LENGTH = "length"
    ABORT = "abort"


@dataclasses.dataclass
class Request:
    id: str
    prompt_tokens: list
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    stop_token_ids: tuple = ()
    # --- multimodal (Qwen2-VL family) ---
    image_embeds: Optional[object] = None    # [N_img_tokens, E] device array
    image_positions: Optional[list] = None   # indices of image tokens in prompt
    positions3: Optional[object] = None      # np [3, S] mrope position streams
    mrope_delta: int = 0                     # decode-time stream offset
    # mutable state
    output_tokens: list = dataclasses.field(default_factory=list)
    finished: bool = False
    finish_reason: Optional[FinishReason] = None
    slot: Optional[int] = None
    max_len: Optional[int] = None   # page-capacity cap set at admission
    submit_time: float = dataclasses.field(default_factory=time.monotonic)
    admitted_time: Optional[float] = None   # slot claimed (queue wait ends)
    first_token_time: Optional[float] = None
    # end-to-end trace identity (obs.trace): minted at the OpenAI
    # endpoint, carried through dispatch into engine-level spans; empty
    # string = untraced (span recording is then a no-op)
    trace_id: str = ""
    # tenant identity (obs.slo): auth-resolved at the control plane,
    # adopted from X-Helix-Tenant by the OpenAI surface — feeds the
    # bounded per-tenant accounting and the admission audit trail
    tenant: str = ANON_TENANT
    # priority class (serving/sched.py): "interactive" | "batch";
    # "" lets the engine loop stamp the profile's default at submit
    sched_class: str = ""
    # multi-LoRA adapter id (engine/adapters.py): sanitised at the
    # OpenAI surface from `model@adapter` addressing; "" = base model.
    # The engine resolves it to an HBM pool slot at admission (deferred
    # — never blocking a step — while the adapter is cold) and holds
    # one pool ref until finish
    adapter: str = ""
    cached_tokens: int = 0          # prompt tokens served by prefix cache
    preempt_count: int = 0          # times swapped out (bounds thrash)
    # force full device residency even on a tiered engine: context-cache
    # creation prefills (serving/context_cache.py) must keep every page
    # resident so the prefix cache / filestore can adopt them
    ctx_pin: bool = False
    _page_hashes: Optional[list] = None

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_tokens) + len(self.output_tokens)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_decode_batch: int = 8
    page_size: int = 16
    num_pages: int = 2048
    max_pages_per_seq: int = 128
    max_prefill_len: int = 2048   # chunk size: longer prompts prefill in
    # max_prefill_len-sized chunks appended to the same page table across
    # engine steps, interleaved with decode (vLLM --max-model-len analogue:
    # the true prompt limit is max_model_len / page capacity, not this)
    max_model_len: Optional[int] = None  # None = page capacity
    attn_backend: Optional[str] = None   # None = auto (pallas on TPU)
    eos_token_ids: tuple = ()
    # Decode steps fused into ONE jit call (lax.scan) between host syncs.
    # Steady-state decode then fetches tokens to host once per WINDOW, not
    # once per token — the lever that matters when the host↔device link
    # has real latency (the axon relay costs ~28 ms per device_get; at
    # n=1 that round trip, not the chip, set the r3 bench's 172 tok/s).
    # vLLM calls the same idea "multi-step scheduling".  The engine drops
    # to single steps while admission/chunked-prefill work is pending and
    # near per-request token caps, so semantics are unchanged; streaming
    # consumers see tokens in bursts of at most this many.
    decode_steps_per_sync: int = 1
    # Adaptive streaming cadence: with at most this many active slots the
    # engine syncs EVERY step so interactive chats stream per-token; the
    # fused window only engages once the batch is big enough that
    # amortising the host round trip beats per-token latency (round-3
    # verdict weak #5 — bursty cadence is the wrong default for chat).
    adaptive_sync_max_streams: int = 2
    # Automatic prefix caching (vLLM APC): full prompt pages are content-
    # hashed and shared across requests — a request whose prompt starts
    # with an already-cached prefix skips prefilling those tokens (the
    # shared-system-prompt TTFT lever).  Pages stay read-only by
    # construction: the shareable prefix is capped at the prompt's FULL
    # pages below its last token, and decode writes only past the prompt.
    enable_prefix_cache: bool = True
    # KV page-pool storage dtype: "auto" stores at the model dtype;
    # "int8" stores codes + per-(slot, head) f32 scales, halving page
    # bytes (CacheConfig.fit_hbm then admits ~1.94x the pages at
    # head_dim 128) with dequantization in-register inside the paged
    # decode kernel.  vLLM analogue: --kv-cache-dtype fp8/int8.
    kv_cache_dtype: str = "auto"   # auto | bfloat16 | float32 | int8
    # Ragged mixed prefill/decode step: while a long prompt chunk-
    # prefills, pack the chunk AND every active decode slot into ONE
    # device call per engine step (the decode rows walk their ragged page
    # tables in the paged kernel, the chunk attends its gathered history
    # — same pool, same traced program).  Decode keeps emitting a token
    # every engine step during long-prompt admission without paying two
    # serialized dispatches; vLLM v1 calls this a mixed batch.
    enable_mixed_step: bool = True
    # Speculative decoding (engine/spec.py): draft up to spec_tokens
    # continuation tokens per slot on the HOST (prompt-lookup n-grams —
    # no draft model), then score all k+1 positions in ONE device call
    # (a short ragged chunk per slot over its paged history) and accept
    # the longest draft prefix the model agrees with.  Each accepted
    # token is a decode forward pass the request never runs.  Sampling
    # at every verified position draws from the request's own
    # SamplingParams tiers, so the output distribution is exactly the
    # non-speculative one (greedy is bit-identical); a per-slot
    # acceptance EMA turns speculation off for slots whose drafts keep
    # missing, so the worst case degenerates to the existing fused
    # window.  Not supported for mrope (VL) or MoE models (expert
    # capacity is shared across the verify chunk, which would perturb
    # routing vs plain decode) — the engine logs and disables there.
    enable_spec_decode: bool = False
    spec_tokens: int = 4
    # Asynchronous pipelined engine loop (serving/engine_loop.py): while
    # device step N executes, the loop dispatches step N+1 against
    # PREDICTED post-step state (positions/budgets advanced at dispatch
    # — the device advances every active row by the full window whether
    # or not the host later discards an overrun, so the prediction is
    # exact for everything but EOS, whose overrun tokens are discarded
    # exactly like fused-window overruns always were) and emits step
    # N-1's tokens through a bounded off-thread emission stage.  The
    # pipeline engages only for plain fused-decode steps in steady state
    # (no admissions, no chunked prefill, no parked preemptions, state
    # clean) and degrades to the synchronous loop everywhere else —
    # including for the WHOLE engine when speculative decoding is
    # enabled (a drafter conditioning on host-lagged sequences would
    # gut acceptance; spec already amortizes host syncs via its fused
    # verify+tail) — so greedy AND seeded temp>0 outputs are
    # bit-identical with the knob on or off.  Node-level override:
    # HELIX_ASYNC_LOOP (operator-beats-profile, 0 forces off).
    enable_async_loop: bool = False
    # Continuous multi-LoRA serving (engine/adapters.py): >= 2 turns on
    # the batched adapter path — a fixed-capacity stacked HBM pool of
    # LoRA factors (slot 0 reserved for the zero identity adapter) is
    # grafted into the unified ragged step, every device-step row
    # carries its adapter slot in the per-row metadata, and the
    # projections add scale * (x @ A[g]) @ B[g] per token via a batched
    # gather-matmul — so N tenants' adapters serve against ONE resident
    # base model with no per-tenant model copies, no hot-swap compile
    # waves, and no new trace families (the pool shape is compiled once
    # at warmup; loading an adapter later writes values into the same
    # arrays).  0 = off (seed behaviour; `adapter:` profile merging
    # still works as the single-adapter fallback).  Node-level
    # override: HELIX_ADAPTER_POOL_SLOTS.  Unsupported for mrope (VL)
    # models — the single-shot VL prefill does not thread per-token
    # adapter ids.
    adapter_pool_slots: int = 0
    # pool-wide rank capacity: adapters with smaller rank zero-pad
    # (exact — zero rows of A and zero columns of B contribute nothing)
    adapter_rank: int = 16
    # LoRA targets the pool serves (must cover every published
    # adapter's targets; attention-only by default — MoE FFNs are not
    # adaptable, dense FFN targets can be added per profile)
    adapter_targets: tuple = ("wq", "wk", "wv", "wo")
    # Host-RAM KV tier (engine/kv_cache.HostPagePool): byte budget for
    # spilled pages.  >0 turns the tier on: PrefixCache evictions demote
    # page contents to host buffers instead of dying (restored into
    # fresh device pages when a later prompt chains onto the digest —
    # the 10-100x effective-prefix-cache lever for system-prompt-heavy
    # fleets), and Engine.preempt can swap a running slot's private
    # pages + sampling state out and exactly resume it later
    # (preemption-by-swap; the graceful-degradation lever under KV
    # exhaustion).  0 = no host tier (seed behaviour: evictions free,
    # preemption unavailable).  Node-level override:
    # HELIX_KV_HOST_POOL_BYTES.
    host_pool_bytes: int = 0
    # Tiered KV residency for long contexts (ISSUE 20): > 0 turns on
    # streamed chunked attention — a sequence keeps only its last
    # ctx_hot_pages full pages (plus the partially written head page and
    # any shared prefix) resident in the device pool; the cold middle
    # demotes to the host tier page by page as decode/prefill advances,
    # and every device step attends it from staged fixed-size chunks via
    # the ring-attention online-softmax combine.  Context length is then
    # bounded by the PAGE TABLE WIDTH (max_pages_per_seq * page_size),
    # not the physical pool — the million-token-context lever.  Requires
    # host_pool_bytes > 0; greedy and seeded outputs are bit-identical
    # with tiering on vs fully resident.  Node-level override:
    # HELIX_CTX_HOT_PAGES.  0 = off (seed behaviour).
    ctx_hot_pages: int = 0
    # Pages per staged cold chunk: each chunk gathers this many demoted
    # pages from the host tier (checksum-verified per page) into one
    # partial-attention block.  Larger chunks = fewer merge steps and
    # fewer compiled chunk-count buckets, more transient HBM per step.
    ctx_stream_pages: int = 4

    def cache_config(self, dtype: str = "bfloat16") -> CacheConfig:
        kv_dtype = (
            dtype
            if self.kv_cache_dtype in ("auto", None, "")
            else self.kv_cache_dtype
        )
        return CacheConfig(
            num_pages=self.num_pages,
            page_size=self.page_size,
            max_pages_per_seq=self.max_pages_per_seq,
            dtype=kv_dtype,
        )


def _bucket(n: int, lo: int, hi: int) -> int:
    b = lo
    while b < n:
        b *= 2
    return min(b, hi) if b <= hi else hi


# ---------------------------------------------------------------------------
# Host-side PRNG key derivation (no device round trips — see _request_key)
# ---------------------------------------------------------------------------

_M64 = (1 << 64) - 1
_SEED_DOMAIN = 0xA076_1D64_78BD_642F  # seeded-request key domain


def _splitmix64(x: int) -> int:
    x = (x + 0x9E37_79B9_7F4A_7C15) & _M64
    z = ((x ^ (x >> 30)) * 0xBF58_476D_1CE4_E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D0_49BB_1331_11EB) & _M64
    return z ^ (z >> 31)


def _host_key(x: int) -> np.ndarray:
    """uint32[2] threefry key data from a 64-bit state."""
    z = _splitmix64(x)
    return np.array([z >> 32, z & 0xFFFF_FFFF], np.uint32)


def _host_split(key: np.ndarray, n: int = 2) -> list:
    """Derive n child keys from a host key, deterministically."""
    base = (int(key[0]) << 32) | int(key[1])
    return [_host_key(base ^ (0xD6E8_FEB8_6659_FD93 * (i + 1))) for i in
            range(n)]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DecodeState:
    """Device-resident per-slot decode state.

    Steady-state decode never uploads anything from the host: last
    tokens, positions, page tables, RNG keys, and the output-token
    histogram (for presence/frequency penalties) all live on device and
    are advanced inside the fused step.  The host re-syncs the state only
    when the slot set changes (admission / completion) via one jitted
    merge (``_rebuild_state``) that preserves the device-evolving
    pieces (keys, histograms) of surviving slots.
    """

    last_token: jax.Array    # [B] i32
    positions: jax.Array     # [B] i32
    page_tables: jax.Array   # [B, P] i32
    active: jax.Array        # [B] i32
    mrope_delta: jax.Array   # [B] i32
    keys: jax.Array          # [B, 2] u32 — per-slot PRNG keys
    token_counts: jax.Array  # [B, V] i32 — output-token histogram
    adapter_slots: jax.Array  # [B] i32 — multi-LoRA pool slot (0 = none)
    sampling: SamplingState


@functools.partial(jax.jit, donate_argnums=(0,))
def _rebuild_state(
    old: DecodeState, last_token, positions, page_tables, active,
    mrope_delta, new_keys, keep, adapter_slots, sampling,
) -> DecodeState:
    B = last_token.shape[0]
    keepc = keep[:, None] > 0
    # fresh slots start their histogram with the prefill-sampled first
    # token (it is output token #1 for penalty purposes)
    fresh = jnp.zeros_like(old.token_counts)
    fresh = fresh.at[jnp.arange(B), jnp.clip(last_token, 0)].add(
        ((keep == 0) & (active > 0)).astype(fresh.dtype)
    )
    return DecodeState(
        last_token=last_token,
        positions=positions,
        page_tables=page_tables,
        active=active,
        mrope_delta=mrope_delta,
        keys=jnp.where(keepc, old.keys, new_keys),
        token_counts=jnp.where(keepc, old.token_counts, fresh),
        adapter_slots=adapter_slots,
        sampling=sampling,
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _override_token_counts(state: DecodeState, slot, counts) -> DecodeState:
    """Replace ONE slot's device-resident output-token histogram — the
    exact-resume path restores the penalty state a preempted request had
    evolved on device (``_rebuild_state``'s fresh-slot histogram only
    seeds the first token, which would skew presence/frequency penalties
    after a swap-in)."""
    return dataclasses.replace(
        state, token_counts=state.token_counts.at[slot].set(counts)
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _patch_first_token(state: DecodeState, slot, tok) -> DecodeState:
    """Seed ONE fresh slot's device-resident last_token + histogram from
    a still-on-device first-token handle (deferred chunk-final fetch):
    ``_rebuild_state`` seeded the slot from the host mirror's placeholder
    0, so move that histogram count to the real token and set last_token
    — the decode step that follows in the same engine step then conditions
    on the true first token without the host ever fetching it alone."""
    counts = state.token_counts.at[slot, 0].add(-1)
    counts = counts.at[slot, tok].add(1)
    return dataclasses.replace(
        state,
        last_token=state.last_token.at[slot].set(tok),
        token_counts=counts,
    )


@dataclasses.dataclass
class PendingStep:
    """One dispatched-but-not-reconciled device step.

    ``step_dispatch`` builds metadata, issues the (async) device call and
    returns one of these; ``step_complete`` performs the step's SINGLE
    host fetch and the post-fetch bookkeeping (emits, stop conditions,
    slot frees).  ``rows`` snapshots the slot occupants at dispatch so a
    completion that runs after the slot set changed (async pipeline:
    step N+1 completes after step N's finishes freed slots) can never
    attribute tokens to a later occupant — a row whose slot no longer
    holds the same request discards its tokens, exactly the fused-window
    overrun contract."""

    kind: str                   # "decode" | "spec" | "mixed"
    rows: list                  # [(slot_index, Request)] at dispatch
    handles: tuple              # device arrays the completion fetches
    n: int = 1                  # fused window size (decode)
    n_extra: int = 0            # fused tail length (spec)
    draft_len: Optional[np.ndarray] = None   # [B] (spec)
    # deferred chunk-final first tokens: [(Request, [R] device handle)],
    # fetched inside this step's one device_get instead of their own
    pending_first: list = dataclasses.field(default_factory=list)
    st: Optional[dict] = None   # mixed: the in-flight chunking record
    final: bool = False         # mixed: this chunk completes the prompt


@dataclasses.dataclass
class PreemptedSeq:
    """A decoder swapped out to host RAM, parked for exact resume.

    Private page CONTENTS live in the engine's ``HostPagePool`` keyed
    ``("seq", req.id, table_pos)`` and pinned; this record keeps the
    book-keeping needed to rebuild the slot bit-identically: the table
    layout (shared prefix pages keep their device page ids — their
    refcounts stay held while parked), decode position, last token,
    the evolved PRNG key, and the output-token histogram."""

    req: "Request"
    table: np.ndarray           # first n_pages entries of the page table
    private_pos: list           # table indices whose pages were spilled
    position: int
    last_token: int
    mrope_delta: int
    key: np.ndarray             # evolved per-slot PRNG key, [2] u32
    counts: np.ndarray          # output-token histogram, [V] i32
    preempted_at: float = dataclasses.field(default_factory=time.monotonic)
    # imported-snapshot path (ISSUE 11): page contents carried INLINE
    # (already checksum-verified at import) instead of through the host
    # pool — a migrated-in request must park and resume even on engines
    # whose host tier is off.  None = the PR 6 host-pool path.
    entries: Optional[list] = None


# ---------------------------------------------------------------------------
# portable request snapshots (ISSUE 11): export / migrate / import
# ---------------------------------------------------------------------------

SNAPSHOT_VERSION = 1


class SnapshotError(ValueError):
    """A request snapshot that must not touch the engine: wrong version,
    incompatible KV geometry, or a failed page checksum.  ``code`` is the
    typed discriminator surfaced to HTTP callers."""

    def __init__(self, message: str, code: str = "snapshot_invalid"):
        super().__init__(message)
        self.code = code


@dataclasses.dataclass
class RequestSnapshot:
    """One in-flight request as a first-class, portable object.

    Everything a peer engine with the same weights needs to continue the
    generation bit-identically: prompt + already-emitted token ids, the
    device-evolved sampler state captured via the PR 6 preempt path
    (evolved PRNG key, output-token penalty histogram, decode position),
    the sequence's KV pages in their STORED representation (raw int8
    codes + scales for quantized pools — restore is bit-exact), and the
    tenant/trace/sched-class identity so accounting follows the request
    across runners.  ``pages`` hold numpy array dicts (the
    ``gather_pages`` field layout); ``page_checksums`` are blake2b
    digests over the stored representation, verified by
    ``import_request`` BEFORE any allocator mutation."""

    version: int
    model: str
    request_id: str
    prompt_tokens: list
    output_tokens: list
    sampling: dict              # dataclasses.asdict(SamplingParams)
    stop_token_ids: list
    tenant: str
    trace_id: str
    sched_class: str
    max_len: Optional[int]
    preempt_count: int
    # device-evolved decode state; position None = the request never
    # reached a slot (queued / mid-chunk) and replays from the prompt
    position: Optional[int]
    last_token: Optional[int]
    mrope_delta: int
    key: Optional[list]         # evolved PRNG key, two uint32 words
    token_counts: dict          # SPARSE {token_id: count} histogram
    # KV geometry the importer validates before anything else
    page_size: int
    num_layers: int
    kv_heads: int
    head_dim: int
    kv_dtype: str
    pages: list                 # [{k, v, k_scale, v_scale}, ...] numpy
    page_checksums: list        # blake2b hex digest per page
    # table capacity the peer must allocate (>= len(pages)): only pages
    # holding WRITTEN KV ship — wire size scales with progress, not
    # max_tokens — and the importer backs the table's tail with fresh
    # (content-irrelevant) pages up to this count
    total_pages: int = 0
    # multi-LoRA adapter id (ISSUE 15): the importer re-resolves it
    # against ITS residency ladder, so a migrated adapter request keeps
    # decoding through the same adapter on the peer; "" = base model
    # (absent on pre-ISSUE-15 wire snapshots — default keeps them valid)
    adapter: str = ""

    @property
    def has_kv(self) -> bool:
        return self.position is not None and bool(self.pages)

    def kv_bytes(self) -> int:
        return sum(
            int(a.nbytes)
            for p in self.pages
            for a in p.values()
            if a is not None
        )


# Compiled step functions are cached at module level keyed by the static
# configuration, NOT per Engine instance — two Engines serving the same
# architecture (or the same Engine recreated by a profile swap) reuse one
# executable.  Combined with jax's persistent compilation cache this makes
# profile hot-swap cheap (SURVEY.md §7 hard part #2).
#
# Since the ragged unification there is ONE such builder for the whole
# device step (``_build_ragged_step_fn``, keyed only on the prefill
# token-bucket at runtime) — packed/cache-hit prefill, chunked prefill,
# plain decode, the mixed step and spec-verify are host-side metadata
# builders over it.  The VL single-shot prefill (image-bucket shapes) and
# the embed splice are the only other compiled entry points;
# ``tools/lint_metrics.py`` contract 6 fails the build if a new lru-cached
# step builder appears outside this set.
def _mesh_sp(mesh) -> int:
    if mesh is not None and "sp" in mesh.axis_names:
        return mesh.shape["sp"]
    return 0


def _gather_history(layer_cache, idx, B: int, Hs: int):
    """Gather a slot-history window from the paged pool: ``idx`` indexes
    pages ([m] for the single-sequence chunk path, [B, m] for the batched
    verify path), reshaped token-major to [B, Hs, KVH, D].  Int8 pools
    dequantize in-register with the per-(slot, head) scales right after
    the gather (the gather itself moved 1 byte/elem) — the ONE recipe
    shared by chunk prefill, the mixed step, and speculative verify."""
    kp, vp = layer_cache[0], layer_cache[1]   # [N, P, KVH, D]
    _, P, KVH, D = kp.shape
    kh = kp[idx].reshape(B, Hs, KVH, D)
    vh = vp[idx].reshape(B, Hs, KVH, D)
    if len(layer_cache) == 4:
        ks, vs = layer_cache[2], layer_cache[3]
        kh = kh.astype(jnp.float32) * ks[idx].reshape(B, Hs, KVH)[..., None]
        vh = vh.astype(jnp.float32) * vs[idx].reshape(B, Hs, KVH)[..., None]
    return kh, vh


@functools.lru_cache(maxsize=64)
def _build_prefill_fn_mrope(model_cfg: ModelConfig, page_size: int, backend):
    """Qwen2-VL-family prefill: takes spliced input embeddings + 3-stream
    mrope positions; masking/KV-writes stay sequence-indexed."""
    from helix_tpu.models.qwen2_vl import text_forward_mrope

    cfg = model_cfg

    @functools.partial(jax.jit, donate_argnums=(1,))
    def prefill_fn(
        params, cache, tokens, embeds, positions3, page_table, length,
        sampling, key,
    ):
        B, S = tokens.shape  # B == 1
        positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
        valid = positions < length
        seg = valid.astype(jnp.int32)

        def attn_fn(q, k, v, layer_cache, pos):
            return full_attention(
                q, k, v,
                causal=True,
                q_positions=pos,
                kv_positions=pos,
                q_segment_ids=seg,
                kv_segment_ids=seg,
                backend=backend,
            )

        logits, (k_new, v_new) = text_forward_mrope(
            params, cfg, tokens, positions3,
            attn_fn=attn_fn,
            input_embeds=embeds,
            mrope_sections=cfg.mrope_sections,
            seq_positions=positions,
        )
        pages, offsets = slot_to_page_offset(positions, page_table, page_size)
        cache = write_kv(cache, k_new, v_new, pages, offsets, valid)
        last = logits[jnp.arange(B), length - 1]
        token = sample(last, sampling, key[None])
        return cache, token

    return prefill_fn


@functools.lru_cache(maxsize=16)
def _build_embed_splice_fn(model_cfg: ModelConfig):
    """tokens [1,S] + padded image embeds [N, E] + their target indices ->
    spliced input embeddings (bucketed on N by the caller)."""
    cfg = model_cfg

    @jax.jit
    def splice(params, tokens, img_embeds, img_pos, n_img):
        from helix_tpu.ops.quant import embed_lookup

        emb = embed_lookup(params["embed"], tokens, jnp.dtype(cfg.dtype))
        S = tokens.shape[1]
        idx = jnp.where(
            jnp.arange(img_embeds.shape[0]) < n_img, img_pos, S + 1
        )
        emb = emb[0].at[idx].set(
            img_embeds.astype(emb.dtype), mode="drop"
        )[None]
        return emb

    return splice


@functools.lru_cache(maxsize=1)
def _layout_pin():
    """Row-major layout pin, or None on jax versions without
    ``with_layout_constraint`` (the pin is a TPU-only layout-assignment
    hint; without it the decode loop still computes correctly, XLA may
    just relay the pool on TPU builds that lack the API)."""
    try:
        from jax.experimental.layout import Layout, with_layout_constraint
    except ImportError:
        # loud once: on TPU this pin is what prevents the r3 pool-relayout
        # OOM, so its absence must not degrade silently into an
        # unexplained mid-serving HBM blowup
        logging.getLogger(__name__).warning(
            "jax.experimental.layout.with_layout_constraint unavailable "
            "in this jax build — decode runs without the page-pool "
            "layout pin (correct everywhere; on TPU, XLA may relay the "
            "pool and cost pool-sized HBM temporaries per decode call)"
        )
        return None

    def pin(x):
        return with_layout_constraint(
            x, Layout(major_to_minor=tuple(range(x.ndim)))
        )

    return pin


def _pin_default_layout(cache):
    # Keep the page pools in their argument (row-major) layout through
    # the scan carry: without the pin, XLA:TPU's layout assignment
    # favours the KV scatter and relaids BOTH pools at the loop
    # boundary — two pool-sized HLO-temp copies per call, which alone
    # OOMed the 8B bench config (r3: +4 GiB on a 16 GiB chip).
    pin = _layout_pin()
    if pin is None:
        return cache
    from helix_tpu.engine.kv_cache import PagedKVCache

    return PagedKVCache(
        k_pages=pin(cache.k_pages),
        v_pages=pin(cache.v_pages),
        k_scale=None if cache.k_scale is None else pin(cache.k_scale),
        v_scale=None if cache.v_scale is None else pin(cache.v_scale),
    )


def _ragged_attn_call(q, k, v, caches, lyr, t0, q_len, hist, tables,
                      backend, cold=None):
    """One ragged-op invocation from inside a forward pass: unpack the
    pool carry (with optional int8 scale pools) and flatten the token
    grid onto the op's flat row axis.  ``cold`` (tiered KV residency)
    carries the staged cold-middle chunks plus each row's demoted token
    span — the op excludes the span from the hot gather and merges the
    chunks' online-softmax stats instead."""
    kp, vp = caches[0], caches[1]
    ks = caches[2] if len(caches) == 4 else None
    vs = caches[3] if len(caches) == 4 else None
    Bq, Sq, H, D = q.shape
    KVH = k.shape[-2]
    tkw = {}
    if cold is not None:
        (c_k, c_v, c_ks, c_vs, c_row, c_len, lo, hi) = cold
        tkw = dict(
            span_lo=lo, span_hi=hi, cold_k=c_k, cold_v=c_v,
            cold_row=c_row, cold_len=c_len,
            cold_k_scale=c_ks, cold_v_scale=c_vs,
        )
    out = ragged_paged_attention(
        q.reshape(Bq * Sq, H, D),
        k.reshape(Bq * Sq, KVH, D),
        v.reshape(Bq * Sq, KVH, D),
        kp, vp, lyr, t0, q_len, hist, tables,
        backend=backend, k_scale=ks, v_scale=vs, **tkw,
    )
    return out.reshape(Bq, Sq, H, D)


def _ring_chunk_attention(q, k, v, caches, lyr, p_pos, p_seg, p_hist,
                          p_tables, mesh, page_size, hist_pages):
    """Sequence-parallel chunk-vs-history attention over the ICI ring
    (``sp`` mesh axis > 1): each chip holds a KV shard and ``ppermute``
    rotates shards — contexts beyond one chip's activation budget
    prefill sequence-parallel.  Ring attention has no segment ids, so
    the engine keeps history-attending rows ALONE in their call on sp
    meshes (padding KV slots get a sentinel position instead).

    ``hist_pages`` is the STATIC pow2-bucketed history capacity (part of
    the builder key, like the pre-unification chunk path): the gather
    and the ring payload scale with actual history, not max context."""
    from helix_tpu.parallel.ring_attention import ring_attention

    layer_view = tuple(c[lyr] for c in caches)
    Hs = hist_pages * page_size
    kh, vh = _gather_history(layer_view, p_tables[0, :hist_pages], 1, Hs)
    k_all = jnp.concatenate([kh.astype(k.dtype), k], axis=1)
    v_all = jnp.concatenate([vh.astype(v.dtype), v], axis=1)
    kv_pos_hist = jnp.arange(Hs)[None]
    kseg_hist = (kv_pos_hist < p_hist[0]).astype(jnp.int32)
    kv_pos = jnp.concatenate([kv_pos_hist, p_pos], axis=1)
    kseg = jnp.concatenate(
        [kseg_hist, (p_seg > 0).astype(jnp.int32)], axis=1
    )
    kv_pos_m = jnp.where(kseg > 0, kv_pos, 1 << 30)
    return ring_attention(
        q, k_all, v_all, mesh,
        q_positions=p_pos,
        kv_positions=kv_pos_m,
        causal=True,
    )


def _tail_decode_step(params, cache, state: DecodeState, *, cfg, backend,
                      page_size, use_adapters: bool = False):
    """Traced body of ONE plain decode step over every slot: each active
    slot is a one-token row over its ragged paged history.  This is the
    fused-window TAIL of the unified step (scanned ``n_extra`` times
    inside the same jit so a multi-token window still costs one host
    sync), bit-compatible with the pre-unification ``_decode_one_step``:
    same penalty → key-split → sample order, same garbage-page routing
    for parked slots."""
    B = state.last_token.shape[0]
    L, KVH, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
    kdt = jnp.dtype(cfg.dtype)
    tokens = state.last_token[:, None]
    pos2d = state.positions[:, None]
    active = state.active
    t0 = jnp.arange(B, dtype=jnp.int32)
    q_len = (active > 0).astype(jnp.int32)
    hist = state.positions * active
    kacc0 = jnp.zeros((L, B, 1, KVH, D), kdt)
    vacc0 = jnp.zeros((L, B, 1, KVH, D), kdt)

    def attn_fn(q, k, v, carry_cache, pos):
        (caches, kacc, vacc), lyr = carry_cache
        out = _ragged_attn_call(
            q, k, v, caches, lyr, t0, q_len, hist, state.page_tables,
            backend,
        )
        return out, (caches, kacc.at[lyr].set(k), vacc.at[lyr].set(v))

    carry0 = (cache.carry(), kacc0, vacc0)
    if cfg.mrope_sections is not None:
        from helix_tpu.models.qwen2_vl import text_forward_mrope

        # past the prompt, all three streams advance together at a
        # per-request constant offset from the sequence index
        pos3 = jnp.broadcast_to(
            (state.positions + state.mrope_delta)[None, :, None],
            (3, B, 1),
        )
        logits, (pc, kacc, vacc) = text_forward_mrope(
            params, cfg, tokens, pos3,
            attn_fn=attn_fn,
            carry_caches=carry0,
            mrope_sections=cfg.mrope_sections,
            seq_positions=pos2d,
        )
    else:
        logits, (pc, kacc, vacc) = forward(
            params, cfg, tokens, pos2d,
            attn_fn=attn_fn,
            carry_caches=carry0,
            # inactive slots never consume expert capacity: outputs
            # are independent of batch-mates (decode is dropless too)
            moe_token_mask=(active > 0)[:, None],
            adapter_ids=(
                state.adapter_slots[:, None] if use_adapters else None
            ),
        )
    cache = PagedKVCache.from_carry(pc)
    pages, offsets = slot_to_page_offset(pos2d, state.page_tables,
                                         page_size)
    cache = write_kv(cache, kacc, vacc, pages, offsets,
                     (active > 0)[:, None])
    penalised = apply_penalties(
        logits[:, 0], state.token_counts,
        state.sampling.presence, state.sampling.frequency,
    )
    carry_keys, step_keys = split_keys(state.keys)
    token = sample(penalised, state.sampling, step_keys)
    new_state = DecodeState(
        last_token=token,
        positions=state.positions + active,   # inactive slots stay parked
        page_tables=state.page_tables,
        active=active,
        mrope_delta=state.mrope_delta,
        keys=carry_keys,
        token_counts=state.token_counts.at[jnp.arange(B), token].add(
            active
        ),
        adapter_slots=state.adapter_slots,
        sampling=state.sampling,
    )
    return cache, new_state, token


@functools.lru_cache(maxsize=256)
def _build_ragged_step_fn(
    model_cfg: ModelConfig, page_size: int, backend, mesh,
    token_bucket: int, has_hist: bool, prefill_rows: int,
    state_width: int, n_tail_max: int, ring_hist_pages: int = 0,
    adapter_slots: int = 0, cold_chunks: int = 0, cold_ct: int = 0,
):
    """THE unified device step: ONE compiled entry point serves every
    caller, keyed at runtime only on the prefill token-bucket.

    One call runs, in one jit:

    1. **Prefill segment** (``token_bucket`` > 0): a flat token axis of
       up to ``prefill_rows`` ragged rows — cold packed prompts,
       prefix-cache hits (their remainder attends the shared pages via
       ``hist``) and the in-flight long-prompt chunk all share it.  One
       forward, one ``write_kv`` scatter, one batched first-token
       sample.  ``has_hist`` statically selects between pure packed
       self-attention (no pool reads — the cold common case) and the
       ragged paged op; an ``sp`` mesh routes single-row history chunks
       through ring attention instead.
    2. **State segment**: every decode slot is a ``state_width``-token
       row — its last sampled token plus up to ``state_width - 1``
       host-drafted speculative tokens (``draft_len[b]`` of them; 0 = a
       plain decode step, -1 = the slot sits this call out, e.g. during
       an admission wave).  Verification is in-call: every live position
       samples from the slot's OWN SamplingParams with the penalty
       histogram evolved along the drafted prefix ("sample from target
       and compare" IS rejection sampling for a point-mass draft, so the
       output distribution is exactly non-speculative and greedy is
       bit-identical); the longest agreeing prefix is kept and
       positions/last_token/histogram roll back INSIDE the call.
       Rejected drafts' KV lands only in the slot's private page tail
       and is overwritten by the next step.  Key splits are consumed
       only at live positions, so a plain step costs exactly one split —
       the same key stream plain decode always had.
    3. **Fused tail**: ``n_extra`` (DYNAMIC — no shape per window size)
       plain decode steps scanned onto the rolled-back state inside the
       same jit, so one host sync still yields a full
       ``decode_steps_per_sync`` window.

    Pre-unification this was six lru-cached builders × their bucket
    grids (packed buckets, chunk C×hist pairs, mixed pairs, per-window
    decode scans, verify width×hist×tail triples).  Now the compiled
    set is O(|token ladder|); ``engine/ragged.py``'s registry records
    each entry for the ``helix_compiled_step_shapes`` gauge.
    """
    ragged_meta.note_step_shape(
        (model_cfg, page_size, backend, mesh),
        ("ragged", token_bucket, has_hist, prefill_rows,
         ring_hist_pages, cold_chunks),
    )
    # adapter_slots is an ENGINE-WIDE constant (EngineConfig), not a
    # per-call shape axis: every existing trace family gains exactly
    # one variant, so the compiled-shape count is unchanged vs the
    # pool-less engine (the tentpole's no-new-trace-families contract;
    # adapter LOADS write values into the same-shaped pool arrays and
    # never retrace)
    use_adapters = adapter_slots > 0
    cfg = model_cfg
    is_moe = cfg.num_experts > 0
    is_mrope = cfg.mrope_sections is not None
    Cb = token_bucket
    W = state_width
    # sp meshes run single-row chunks (cold first chunk included —
    # sharding the 32k chunk's self-attention is the point) through
    # ring attention; multi-row packed waves keep segment-masked
    # full attention like the pre-unification packed path
    use_ring = _mesh_sp(mesh) > 1 and Cb > 0 and prefill_rows == 1
    if is_mrope and Cb > 0:
        raise ValueError(
            "mrope prompts prefill through the VL single-shot builder, "
            "never the ragged prefill segment"
        )

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def step_fn(params, cache, state: DecodeState, pargs, drafts,
                draft_len, n_extra, cold=None):
        B = state.last_token.shape[0]
        L, KVH, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
        kdt = jnp.dtype(cfg.dtype)
        drops = None
        # tiered KV residency (ISSUE 20): staged cold-middle chunks plus
        # the per-row demoted token spans — one slab shared by the
        # prefill segment (rows = plan rows, via c_prow) and the state
        # segment (rows = decode slots, via c_srow); a chunk owned by
        # neither mapping carries row -1 and masks to an exact zero
        # contribution
        if cold_chunks > 0:
            (c_k, c_v, c_ks, c_vs, c_prow, c_srow, c_len,
             p_span_lo, p_span_hi, s_span_lo, s_span_hi) = cold
            p_cold = (c_k, c_v, c_ks, c_vs, c_prow, c_len,
                      p_span_lo, p_span_hi)
            s_cold = (c_k, c_v, c_ks, c_vs, c_srow, c_len,
                      s_span_lo, s_span_hi)
        else:
            p_cold = None
            s_cold = None

        # ---- 1. prefill segment --------------------------------------
        if Cb > 0:
            if use_adapters:
                (p_tokens, p_pos, p_seg, p_pages, p_offsets, p_t0,
                 p_qlen, p_hist, p_tables, p_ends, p_sampling, p_keys,
                 p_aids) = pargs
            else:
                (p_tokens, p_pos, p_seg, p_pages, p_offsets, p_t0,
                 p_qlen, p_hist, p_tables, p_ends, p_sampling,
                 p_keys) = pargs
                p_aids = None
            kacc0 = jnp.zeros((L, 1, Cb, KVH, D), kdt)
            vacc0 = jnp.zeros((L, 1, Cb, KVH, D), kdt)

            def p_attn(q, k, v, carry_cache, pos):
                (caches, kacc, vacc), lyr = carry_cache
                if use_ring:
                    out = _ring_chunk_attention(
                        q, k, v, caches, lyr, p_pos, p_seg, p_hist,
                        p_tables, mesh, page_size, ring_hist_pages,
                    )
                elif has_hist:
                    out = _ragged_attn_call(
                        q, k, v, caches, lyr, p_t0, p_qlen, p_hist,
                        p_tables, backend, cold=p_cold,
                    )
                else:
                    # cold rows only: packed self-attention, no pool
                    # reads — bit-compatible with the pre-unification
                    # packed-prefill path
                    out = full_attention(
                        q, k, v,
                        causal=True,
                        q_positions=p_pos,
                        kv_positions=p_pos,
                        q_segment_ids=p_seg,
                        kv_segment_ids=p_seg,
                        backend=backend,
                    )
                return out, (caches, kacc.at[lyr].set(k),
                             vacc.at[lyr].set(v))

            res = forward(
                params, cfg, p_tokens, p_pos,
                attn_fn=p_attn,
                carry_caches=(cache.carry(), kacc0, vacc0),
                moe_token_mask=p_seg > 0,
                return_moe_stats=is_moe,
                adapter_ids=p_aids,
            )
            if is_moe:
                logits_p, (pc, kacc, vacc), moe_stats = res
                drops = moe_stats["dropped"]
            else:
                logits_p, (pc, kacc, vacc) = res
            cache = write_kv(
                PagedKVCache.from_carry(pc), kacc, vacc, p_pages,
                p_offsets, p_seg > 0,
            )
            last = logits_p[0, p_ends]      # [R, V] — each row's last token
            p_first = sample(last, p_sampling, p_keys)
        else:
            p_first = jnp.zeros((0,), jnp.int32)

        # ---- 2. state segment (decode / verify rows) -----------------
        tokens_s = jnp.concatenate(
            [state.last_token[:, None], drafts], axis=1
        )                                                    # [B, W]
        pos_s = state.positions[:, None] + jnp.arange(W)[None]
        act = state.active > 0
        live = (jnp.arange(W)[None] <= draft_len[:, None]) & act[:, None]
        s_t0 = jnp.arange(B, dtype=jnp.int32) * W
        # rows sitting this call out (draft_len -1: admission waves,
        # standalone chunk steps) get q_len 0 so the kernel skips their
        # page-pool sweep entirely — an admission wave must not cost a
        # wasted decode step per active slot
        s_qlen = jnp.where(act & (draft_len >= 0), W, 0).astype(jnp.int32)
        s_hist = state.positions * state.active
        kacc0s = jnp.zeros((L, B, W, KVH, D), kdt)
        vacc0s = jnp.zeros((L, B, W, KVH, D), kdt)

        def s_attn(q, k, v, carry_cache, pos):
            (caches, kacc, vacc), lyr = carry_cache
            out = _ragged_attn_call(
                q, k, v, caches, lyr, s_t0, s_qlen, s_hist,
                state.page_tables, backend, cold=s_cold,
            )
            return out, (caches, kacc.at[lyr].set(k),
                         vacc.at[lyr].set(v))

        carry0 = (cache.carry(), kacc0s, vacc0s)
        if is_mrope:
            from helix_tpu.models.qwen2_vl import text_forward_mrope

            pos3 = jnp.broadcast_to(
                (pos_s + state.mrope_delta[:, None])[None], (3, B, W)
            )
            logits_s, (pc2, kaccs, vaccs) = text_forward_mrope(
                params, cfg, tokens_s, pos3,
                attn_fn=s_attn,
                carry_caches=carry0,
                mrope_sections=cfg.mrope_sections,
                seq_positions=pos_s,
            )
        else:
            logits_s, (pc2, kaccs, vaccs) = forward(
                params, cfg, tokens_s, pos_s,
                attn_fn=s_attn,
                carry_caches=carry0,
                moe_token_mask=live,
                adapter_ids=(
                    jnp.broadcast_to(
                        state.adapter_slots[:, None], (B, W)
                    )
                    if use_adapters else None
                ),
            )
        cache = PagedKVCache.from_carry(pc2)
        pages_s, offs_s = slot_to_page_offset(
            pos_s, state.page_tables, page_size
        )
        cache = write_kv(cache, kaccs, vaccs, pages_s, offs_s, live)

        # position-by-position penalised sampling (cheap [B, V] ops):
        # the histogram carries the drafted prefix forward so position
        # j's penalties match plain decode having emitted j tokens.
        # Splits are consumed ONLY at live positions — a plain step
        # (draft_len 0) advances the key stream exactly once.
        def samp_body(carry, j):
            counts, keys = carry
            pen = apply_penalties(
                logits_s[:, j], counts,
                state.sampling.presence, state.sampling.frequency,
            )
            carry_keys, step_keys = split_keys(keys)
            tok = sample(pen, state.sampling, step_keys)
            lj = live[:, j]
            tok = jnp.where(lj, tok, 0)
            keys = jnp.where(lj[:, None], carry_keys, keys)
            counts = counts.at[jnp.arange(B), tok].add(
                lj.astype(counts.dtype)
            )
            return (counts, keys), tok

        (counts, keys), sampled = jax.lax.scan(
            samp_body, (state.token_counts, state.keys), jnp.arange(W)
        )
        sampled = sampled.T                                  # [B, W]

        # acceptance: longest prefix of draws agreeing with the drafts
        if W > 1:
            in_draft = jnp.arange(W - 1)[None, :] < draft_len[:, None]
            agree = jnp.where(
                in_draft, sampled[:, : W - 1] == drafts, True
            )
            prefix = jnp.cumprod(agree.astype(jnp.int32), axis=1)
            n_acc = jnp.sum(prefix * in_draft.astype(jnp.int32), axis=1)
        else:
            n_acc = jnp.zeros((B,), jnp.int32)
        emit = jnp.where(live[:, 0], n_acc + 1, 0)           # [B]

        # roll back past the accepted length: positions/last_token/
        # histogram come out exactly as ``emit`` plain decode steps
        new_last = jnp.take_along_axis(
            sampled, jnp.maximum(emit - 1, 0)[:, None], axis=1
        )[:, 0]
        discard = (jnp.arange(W)[None, :] >= emit[:, None]) & live
        counts = counts.at[jnp.arange(B)[:, None], sampled].add(
            -discard.astype(counts.dtype)
        )
        new_state = DecodeState(
            last_token=jnp.where(emit > 0, new_last, state.last_token),
            positions=state.positions + emit,
            page_tables=state.page_tables,
            active=state.active,
            mrope_delta=state.mrope_delta,
            keys=keys,
            token_counts=counts,
            adapter_slots=state.adapter_slots,
            sampling=state.sampling,
        )

        # ---- 3. fused plain-decode tail (dynamic length) -------------
        if n_tail_max > 0:
            buf0 = jnp.zeros((n_tail_max, B), jnp.int32)

            def tail_body(t, carry):
                c, st, buf = carry
                c, st, tok = _tail_decode_step(
                    params, c, st, cfg=cfg, backend=backend,
                    page_size=page_size, use_adapters=use_adapters,
                )
                return _pin_default_layout(c), st, buf.at[t].set(tok)

            cache, new_state, extra = jax.lax.fori_loop(
                0, n_extra, tail_body,
                (_pin_default_layout(cache), new_state, buf0),
            )
        else:
            extra = jnp.zeros((0, B), jnp.int32)
        return cache, new_state, p_first, sampled, emit, extra, drops

    return step_fn



class Engine:
    """Single-model serving engine on one mesh slice."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        params,
        cfg: EngineConfig,
        mesh=None,
        rng_seed: int = 0,
    ):
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        # chunked prefill assumes chunk/history shapes are page-aligned
        # powers of two (flash block divisibility + exact history gather)
        q, ps = cfg.max_prefill_len, cfg.page_size
        while q > ps and q % 2 == 0:
            q //= 2
        if q != ps:
            raise ValueError(
                f"max_prefill_len ({cfg.max_prefill_len}) must be "
                f"page_size ({ps}) times a power of two"
            )
        if cfg.kv_cache_dtype not in (
            "auto", None, "", "bfloat16", "float32", "int8"
        ):
            raise ValueError(
                f"unsupported kv_cache_dtype {cfg.kv_cache_dtype!r} "
                "(expected auto | bfloat16 | float32 | int8)"
            )
        self.cache_cfg = cfg.cache_config(dtype=model_cfg.dtype)
        self.cache = PagedKVCache.create(model_cfg, self.cache_cfg, mesh)
        self.allocator = PageAllocator(
            self.cache_cfg.num_pages, self.cache_cfg.max_pages_per_seq
        )
        B = cfg.max_decode_batch
        self.slots: list[Optional[Request]] = [None] * B
        self.waiting: list[Request] = []
        self._requests: dict[str, Request] = {}
        # host mirrors of device-visible per-slot state
        self._last_token = np.zeros((B,), np.int32)
        self._positions = np.zeros((B,), np.int32)
        self._mrope_delta = np.zeros((B,), np.int32)
        self._page_tables = np.zeros(
            (B, self.cache_cfg.max_pages_per_seq), np.int32
        )
        self._slot_keys = np.zeros((B, 2), np.uint32)   # per-slot carry keys
        self._state_dirty = True
        self._changed_slots: set[int] = set()  # admitted/freed since sync
        self._dstate: Optional[DecodeState] = None
        self._chunking: Optional[dict] = None  # in-flight chunked prefill
        from helix_tpu.engine.kv_cache import PrefixCache

        self.prefix_cache = (
            PrefixCache() if cfg.enable_prefix_cache else None
        )
        self._shared_pages: dict[str, list] = {}  # req id -> cache pages
        # host-RAM KV tier (ISSUE 6): spilled prefix pages + swapped-out
        # decoders, byte-budgeted; None = tier off (evictions free pages,
        # preemption unavailable)
        from helix_tpu.engine.kv_cache import HostPagePool

        self.host_pool = (
            HostPagePool(cfg.host_pool_bytes)
            if cfg.host_pool_bytes > 0
            else None
        )
        # tiered KV residency (ISSUE 20): demoted cold-middle pages live
        # in the host pool keyed ("ctx", req_id, page_idx); each tiered
        # slot keeps a ledger {lo, hi, top, rid, table} — [lo, hi) is the
        # demoted span (pages zeroed in the table), top the high-water of
        # allocated device pages.  _cold_staged caches the assembled +
        # device_put chunk slab between steps so prefetch overlaps H2D
        # with the in-flight step's compute.
        if cfg.ctx_hot_pages > 0:
            if self.host_pool is None:
                raise ValueError(
                    "ctx_hot_pages > 0 requires host_pool_bytes > 0: "
                    "demoted cold pages live in the host page pool"
                )
            if model_cfg.mrope_sections is not None:
                raise ValueError(
                    "tiered KV residency is not supported for mrope (VL) "
                    "models"
                )
            if _mesh_sp(mesh) > 1:
                raise ValueError(
                    "tiered KV residency is not supported with sequence "
                    "parallelism (ring attention owns the history split)"
                )
            if cfg.ctx_stream_pages < 1:
                raise ValueError(
                    f"ctx_stream_pages ({cfg.ctx_stream_pages}) must be "
                    ">= 1"
                )
        self._tiered: dict[int, dict] = {}
        self._cold_staged: Optional[dict] = None
        self.num_ctx_stream_chunks = 0
        self.num_ctx_demoted_pages = 0
        self.preempted: list[PreemptedSeq] = []   # parked, resume FIFO
        self._resume_failures: list = []          # (req, reason) for the loop
        # scheduler delegation (serving/sched.py): the loop wires these.
        # on_admit fires once per confirmed admission (_try_claim
        # success) — the fair-share charge point; victim_policy, when
        # set, orders preempt_for_pressure candidates (None keeps the
        # builtin newest-admission/largest-footprint pick);
        # prefill_budget caps NEW prefill-admission tokens per step
        # (None = unbudgeted — the historical behaviour)
        self.on_admit: Optional[Callable[[Request], None]] = None
        self.victim_policy: Optional[Callable[[list], list]] = None
        self.prefill_budget: Optional[int] = None
        self._budget_left: Optional[int] = None
        # plan-broadcast hooks (serving/multihost_serving.py): a leader
        # wraps step_dispatch with a PlanRecorder that captures host
        # decisions (admits+cached_tokens, resumes, drafts, budget,
        # queue pressure) as data; a follower steps under a PlanDrive
        # that pins the same decisions to the leader's plan.  Both are
        # duck-typed so the engine never imports the serving layer.
        self._plan_recorder = None
        self._plan_drive = None
        self._slot_count_overrides: dict[int, np.ndarray] = {}
        # deferred chunk-final first tokens (ISSUE 13): the final chunk's
        # sampled token stays on device — _sync_state patches the slot's
        # DecodeState from the handle and the emit joins the decode
        # step's single device_get (one host round trip per step, not
        # two).  _inflight_out counts dispatched-not-yet-reconciled
        # tokens per request so the async loop's predicted dispatch
        # computes budgets/headroom against post-step state.
        self._pending_first: list = []           # [(req, [R] dev handle)]
        self._pending_first_ids: set = set()
        self._pending_token_patches: dict[int, object] = {}
        self._inflight_out: dict[str, int] = {}
        self._prefetched: set = set()   # digests with in-flight device puts
        self._key_base = _splitmix64(0x8E1_1C9 ^ (rng_seed & _M64))
        self._key_nonce = 0
        self._step_counter = itertools.count()
        self._backend = cfg.attn_backend
        # metrics
        import collections as _collections

        self.num_prefill_tokens = 0
        self.num_decode_tokens = 0
        # every token handed to a subscriber (decode + prefill first
        # tokens) — the numerator of goodput tokens/s
        self.num_generated_tokens = 0
        # prefill-bucket padding: tokens of forward-pass work spent on
        # zeros because prompts round up to power-of-two buckets (the
        # padding-waste axis of the ragged-paged-attention analysis)
        self.num_prefill_padding_tokens = 0
        # requests admitted to a slot (flight-recorder admission deltas)
        self.num_admitted = 0
        # request-level prefix-cache outcomes, counted at claim time
        # (page-level hit/miss pools live on PrefixCache itself)
        self.prefix_cache_hits = 0
        self.prefix_cache_misses = 0
        # ragged mixed steps taken (chunk prefill + decode in ONE call)
        self.num_mixed_steps = 0
        # --- speculative decoding (engine/spec.py) ---
        # host-side prompt-lookup drafter + per-request acceptance EMA;
        # None = speculation off (config, or an unsupported model family)
        self.spec = None
        if cfg.enable_spec_decode:
            if cfg.spec_tokens < 1:
                raise ValueError(
                    f"spec_tokens ({cfg.spec_tokens}) must be >= 1 when "
                    "enable_spec_decode is set"
                )
            if (
                model_cfg.mrope_sections is not None
                or model_cfg.num_experts > 0
            ):
                # mrope decode needs 3-stream positions the verify chunk
                # does not thread; MoE expert capacity is shared across
                # the chunk, which would perturb routing vs plain decode
                logging.getLogger(__name__).warning(
                    "speculative decoding is not supported for %s models"
                    " — running plain decode",
                    "mrope (VL)" if model_cfg.mrope_sections is not None
                    else "MoE",
                )
            else:
                from helix_tpu.engine.spec import SpecConfig, SpecDecoder

                self.spec = SpecDecoder(
                    SpecConfig(spec_tokens=cfg.spec_tokens)
                )
        # --- continuous multi-LoRA serving (ISSUE 15) ---
        # batched adapter pool (engine/adapters.py): one resident base
        # model, many per-tenant adapters — requests carry an adapter
        # id, every device-step row carries its pool slot, and the
        # unified step applies scale * (x @ A) @ B per token via a
        # batched gather-matmul.  None = off (config, or an unsupported
        # model family).  adapter_store is the host/filestore residency
        # ladder below the pool (built by default; the node agent may
        # re-wire a custom one post-construction like kv_filestore).
        self.adapter_pool = None
        self.adapter_store = None
        self._adapter_refs: dict[str, str] = {}   # req id -> adapter id
        self._slot_adapters = np.zeros((B,), np.int32)
        if cfg.adapter_pool_slots > 0:
            if model_cfg.mrope_sections is not None:
                logging.getLogger(__name__).warning(
                    "batched multi-LoRA serving is not supported for "
                    "mrope (VL) models — running without an adapter pool"
                )
            elif cfg.adapter_pool_slots < 2:
                # slot 0 is the reserved identity adapter, so one slot
                # can serve nothing — degrade to off (warn) instead of
                # failing the whole model's profile apply
                logging.getLogger(__name__).warning(
                    "adapter_pool_slots=%d leaves no usable slots "
                    "(slot 0 is the reserved identity) — running "
                    "without an adapter pool; set >= 2 to serve "
                    "adapters", cfg.adapter_pool_slots,
                )
            else:
                from helix_tpu.engine.adapters import (
                    AdapterPool,
                    default_adapter_store,
                )

                self.adapter_pool = AdapterPool(
                    model_cfg, cfg.adapter_targets, cfg.adapter_rank,
                    cfg.adapter_pool_slots,
                    dtype=jnp.dtype(model_cfg.dtype),
                )
                self.adapter_store = default_adapter_store(
                    model_cfg, cfg
                )
        self._grafted_params = None    # (pool.version, params) cache
        # --- unified ragged step (ISSUE 10) ---
        # ONE compiled device-step entry point serves packed/cache-hit
        # prefill, chunked prefill, plain decode, the mixed step and
        # spec-verify; at runtime it is keyed only on the prefill
        # token-bucket ladder below (HELIX_TOKEN_BUCKETS overrides the
        # power-of-two default with finer rungs → less padding, a few
        # more compiles).
        self._token_ladder = ragged_meta.parse_token_buckets(
            os.environ.get("HELIX_TOKEN_BUCKETS"),
            self.cache_cfg.page_size,
            cfg.max_prefill_len,
        )
        # fused-window tail capacity (static buffer; actual tail length
        # is a DYNAMIC argument, so every window size shares one trace)
        self._n_tail_max = max(0, cfg.decode_steps_per_sync - 1)
        W = self._spec_width()
        self._zero_drafts = np.zeros((B, W - 1), np.int32)
        self._zero_rows = np.zeros((B,), np.int32)     # plain decode rows
        self._inert_rows = np.full((B,), -1, np.int32)  # state rows sit out
        self._shape_key = (
            model_cfg, self.cache_cfg.page_size, self._backend, mesh,
        )
        # verify calls issued, drafts proposed, drafts accepted
        self.num_spec_steps = 0
        self.num_spec_drafted_tokens = 0
        self.num_spec_accepted_tokens = 0
        # device-side decode steps (each fused window of n counts n):
        # decode_tokens / (device_steps * batch) is exact slot utilization
        self.num_decode_device_steps = 0
        # device-step CALLS issued (one per unified ragged step / VL
        # prefill): (prefill + decode tokens) / calls is the
        # tokens-per-device-step figure the ragged unification moves
        self.num_device_calls = 0
        # KV tiering (ISSUE 6): swap-out/swap-in of running decoders and
        # cumulative host->device restore time (bench's restore-latency
        # numerator; page-level spill/restore pools live on host_pool)
        self.num_preemptions = 0
        self.num_resumes = 0
        self.restore_seconds = 0.0
        # portable request snapshots (ISSUE 11): export/import counters
        # feed the helix_migrations_* series and the migration bench
        self.num_snapshots_exported = 0
        self.num_snapshots_imported = 0
        # disaggregated prefill/decode (ISSUE 14): snapshots exported at
        # prefill completion for a decode-pool peer (a subset of
        # num_snapshots_exported)
        self.num_prefill_exports = 0
        # persistent filestore KV tier (ISSUE 14): the bottom rung of
        # the residency ladder (HBM -> host RAM -> peer -> filestore).
        # Wired post-construction (serving.kv_filestore.filestore_for_
        # engine) like on_admit; None = tier off.  filestore_restored_
        # pages counts pages adopted FROM it (cross-restart prefix hits).
        self.kv_filestore = None
        self.filestore_restored_pages = 0
        # MoE routing assignments dropped to expert-capacity overflow
        # during prefill (those tokens silently rode the residual stream);
        # device scalars accumulate un-fetched and drain lazily so the
        # prefill hot path never blocks on a drop-counter device_get
        self._moe_dropped = 0
        self._moe_drop_handles: list = []
        self.recent_ttfts: "_collections.deque" = _collections.deque(
            maxlen=200
        )   # seconds; feeds /metrics p50/p95

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    @property
    def kv_pages_used(self) -> int:
        """Occupied pages in the pool (prefix-cache-owned pages count as
        used: they hold live KV).  Page 0 (garbage) is excluded from
        both sides, so used/capacity is a true occupancy ratio."""
        return self.allocator.used_pages

    @property
    def kv_pages_capacity(self) -> int:
        return max(1, self.cache_cfg.num_pages - 1)

    @property
    def _resident_context_cap(self) -> int:
        """Context limit for a fully device-resident sequence: the
        profile's max_model_len capped by per-sequence page capacity AND
        the physical pool size (a prompt that can never allocate must be
        rejected, not queued forever)."""
        cap = min(
            self.cache_cfg.max_seq_len,
            (self.cache_cfg.num_pages - 1) * self.cache_cfg.page_size,
        )
        if self.cfg.max_model_len is not None:
            cap = min(cap, self.cfg.max_model_len)
        return cap

    @property
    def max_context_len(self) -> int:
        """Hard prompt+generation limit.  With tiered KV residency on
        (ctx_hot_pages > 0 and a host pool) the physical-pool term drops:
        only the hot tail must fit in HBM, the cold middle streams from
        host RAM — capacity is the per-sequence page-table width (and the
        profile's max_model_len)."""
        if self.cfg.ctx_hot_pages > 0 and self.host_pool is not None:
            cap = self.cache_cfg.max_seq_len
            if self.cfg.max_model_len is not None:
                cap = min(cap, self.cfg.max_model_len)
            return cap
        return self._resident_context_cap

    def validate_request(self, req: Request) -> Optional[str]:
        """Admission pre-check, safe from any thread; None = acceptable."""
        plen = len(req.prompt_tokens)
        if plen + 1 > self.max_context_len:
            return (
                f"prompt ({plen} tokens) exceeds the model context limit "
                f"{self.max_context_len}"
            )
        if (
            self.model_cfg.mrope_sections is not None
            and plen > self.cfg.max_prefill_len
        ):
            # VL prefill is single-shot (image splice shapes); text models
            # prefill arbitrarily long prompts in chunks
            return (
                f"vision prompt ({plen} tokens) exceeds max_prefill_len "
                f"{self.cfg.max_prefill_len}"
            )
        if not req.prompt_tokens:
            return "empty prompt"
        if getattr(req, "adapter", ""):
            if self.adapter_pool is None:
                return (
                    f"adapter '{req.adapter}' requested but this engine "
                    "serves without an adapter pool "
                    "(EngineConfig.adapter_pool_slots)"
                )
            if (
                self.adapter_store is not None
                and not self.adapter_pool.resident(req.adapter)
                and not self.adapter_store.contains(req.adapter)
            ):
                return (
                    f"adapter '{req.adapter}' is not published for "
                    f"model '{self.model_cfg.name}'"
                )
        return None

    def add_request(self, req: Request) -> None:
        err = self.validate_request(req)
        if err:
            raise ValueError(err)
        self._requests[req.id] = req
        self.waiting.append(req)

    def abort(self, req_id: str) -> None:
        req = self._requests.get(req_id)
        if req is None or req.finished:
            return
        self._finish(req, FinishReason.ABORT)

    def get_request(self, req_id: str) -> Optional[Request]:
        """Live view of a submitted request (engine-thread callers: the
        quarantine path in EngineLoop inspects admission recency)."""
        return self._requests.get(req_id)

    def has_work(self) -> bool:
        return (
            bool(self.waiting)
            or bool(self.preempted)
            or any(s is not None for s in self.slots)
        )

    def reap_stuck(self, max_queue_seconds: float = 600.0) -> list:
        """Abort requests stuck in the wait queue beyond a budget (page
        starvation under a long-running batch).  The engine-side analogue of
        the reference's auto-wake-stuck-interactions loop (SURVEY.md §5).
        Returns the aborted requests."""
        now = time.monotonic()
        stuck = [
            r for r in list(self.waiting)
            if now - r.submit_time > max_queue_seconds
        ]
        for r in stuck:
            self._finish(r, FinishReason.ABORT)
        return stuck

    def warmup(self, chunked: bool = True) -> None:
        """Compile the unified ragged step's shape ladder ahead of
        traffic (profile-apply time), so first-token latency excludes
        XLA compilation.  Drives one real tiny request through the
        public path (pages are allocated and freed normally) — that
        alone compiles the decode-only entry point, and with it EVERY
        fused-window size and spec-verify width (both are dynamic
        arguments of the one trace, not shape families) — then walks the
        prefill token-bucket ladder against the garbage page.

        Pre-unification this compiled packed buckets + per-window decode
        scans + verify (width × history × tail) triples + chunk/mixed
        (C × history) pairs; the whole zoo is now O(|token ladder|)
        entry points (a ragged final chunk may still compile one extra
        small single-row shape at request time)."""
        if self.model_cfg.mrope_sections is not None:
            return  # VL prefill shape depends on image buckets; skip
        req = Request(
            id="__warmup__",
            prompt_tokens=[0] * min(4, self.cache_cfg.page_size),
            sampling=SamplingParams(max_tokens=2),
        )
        self.add_request(req)
        while self.has_work():
            self.step()
        # the warmup token's latency is XLA compile time, not serving
        # latency — keep it out of the TTFT percentiles
        self.recent_ttfts.clear()
        self._sync_state()
        ps = self.cache_cfg.page_size
        maxP = self.cache_cfg.max_pages_per_seq
        B = self.cfg.max_decode_batch
        can_chunk = (
            chunked and self.max_context_len > self.cfg.max_prefill_len
        )
        hist_variants = [False]
        if self.prefix_cache is not None or can_chunk:
            # cache-hit waves / chunk continuations attend history
            hist_variants.append(True)

        def drive(rung: int, with_hist: bool, rows: int) -> None:
            # one dummy row filling the rung exactly; its table is all
            # garbage-page zeros, so reads see garbage (discarded) and
            # writes land on page 0 — nothing real advances
            plan = PrefillPlan(ps, maxP, rows)
            plan.add(
                None, np.zeros((maxP,), np.int32),
                ps if with_hist else 0, rung, [0] * rung,
                _host_key(0), SamplingParams(),
            )
            self._ragged_step(
                plan=plan, draft_len=self._inert_rows, n_extra=0,
            )

        for rung in self._token_ladder:
            for hh in hist_variants:
                drive(rung, hh, B)
        if can_chunk:
            # the dominant per-chunk shapes: full chunks run single-row
            # at the top rung — the FIRST chunk of a cold long prompt
            # has no history, every later chunk does, and the mixed
            # step shares both traces (the state segment rides along in
            # every entry point)
            drive(self.cfg.max_prefill_len, False, 1)
            drive(self.cfg.max_prefill_len, True, 1)

    def step(self) -> list[tuple[Request, int]]:
        """Admit + prefill waiting requests, then one decode step.

        Long prompts prefill one chunk per engine step, so decode slots
        keep producing tokens while a 32k prompt works through its chunks
        (no head-of-line stall for already-running requests).  When both
        a chunk AND active decode slots are pending, the ragged mixed
        step packs them into ONE device call (``enable_mixed_step``).

        Returns [(request, new_token_id), ...] for tokens produced this step.

        ``step()`` is exactly ``step_complete(step_dispatch())`` — the
        async engine loop (ISSUE 13) calls the halves itself so the host
        phase of step N+1 overlaps the device phase of step N.
        """
        emitted, pend = self.step_dispatch()
        if pend is not None:
            try:
                # stage the NEXT step's cold chunks while this step's
                # device work is still in flight: the gathers/device_puts
                # are async and enqueue after the dispatched step on the
                # device stream, so H2D traffic overlaps compute
                self.prefetch_cold()
                self.step_complete(pend, emitted)
            except Exception:
                # roll the predicted-state advance back before the
                # failure propagates: quarantine bisection and plan
                # followers retry through this wrapper, and a retry
                # against mirrors claiming (position p+n, last_token at
                # p-1) would silently skip/mis-condition n tokens
                self.discard_pending(pend)
                raise
        return emitted

    def step_dispatch(self) -> tuple[list, Optional[PendingStep]]:
        """The HOST phase of one engine step: admission, plan building,
        metadata upload and the (async) device dispatch.  Returns
        ``(emitted_so_far, pending)`` — ``pending`` carries the device
        handles; nothing here blocks on the device except the admission
        wave's batched first-token fetch (conservative fallback: steps
        with admissions reconcile synchronously)."""
        emitted: list[tuple[Request, int]] = []
        if self.host_pool is not None:
            # release the HBM gather buffers of spills from EARLIER
            # steps (their async D2H copies have landed by now) —
            # step-entry so every step shape drains, including the
            # early-returning mixed step
            self.host_pool.drain_pending()
        # per-step prefill-admission budget (scheduler feedback loop):
        # refreshed every step; admission charges it in _try_claim
        self._budget_left = self.prefill_budget
        if self._plan_drive is not None:
            # follower: the budget is the leader's decision, not ours
            self._budget_left = self._plan_drive.budget
        elif self._plan_recorder is not None:
            self._plan_recorder.budget = self._budget_left
        self._admit(emitted)
        if self._chunking is not None and self._chunking["req"].finished:
            self._chunking = None    # aborted mid-prefill
        decode_ready = any(
            self._slot_active(i) for i in range(len(self.slots))
        )
        if (
            self._chunking is not None
            and decode_ready
            and self.cfg.enable_mixed_step
        ):
            return emitted, self._mixed_dispatch()
        if self._chunking is not None:
            self._chunk_dispatch()
        # re-check: a chunk that just completed activates its slot and
        # decodes its second token this same step (pre-mixed behaviour);
        # its deferred first token rides that step's single device_get
        if any(self._slot_active(i) for i in range(len(self.slots))):
            # speculate when the drafter has something to verify; any
            # step it doesn't (no n-gram hit, EMA-disabled slots, no
            # headroom) falls straight through to the plain fused window
            pend = None
            if self.spec is not None:
                pend = self._spec_dispatch()
            if pend is None:
                pend = self._decode_dispatch()
            return emitted, pend
        # nothing decodable (admission-only step, or a chunk whose
        # request aborted between activation and decode): any deferred
        # first token must still land — conservative synchronous flush
        self._flush_pending_first(emitted)
        return emitted, None

    def step_complete(self, pend: PendingStep, emitted=None) -> list:
        """The RECONCILE phase: the step's one host fetch plus every
        host-visible effect (emits, stop conditions, slot frees).  The
        async loop calls this AFTER dispatching the next step, so the
        fetch blocks only for the device time the host work did not
        already cover."""
        emitted = [] if emitted is None else emitted
        if pend.kind == "decode":
            self._decode_complete(pend, emitted)
        elif pend.kind == "spec":
            self._spec_complete(pend, emitted)
        else:
            self._mixed_complete(pend, emitted)
        return emitted

    def pipeline_ready(self) -> bool:
        """True when the NEXT dispatch can safely run against predicted
        post-step state while a step is still in flight: plain
        fused-decode steady state only.  Admission waves, chunked
        prefill, speculation (its per-slot advance depends on acceptance
        counts the host has not seen), parked preemptions and any dirty
        slot state (the rebuild uploads host mirrors that are only
        accurate at reconcile points) all force the loop back to the
        synchronous dispatch->complete ordering."""
        if (
            self._state_dirty
            or self._dstate is None
            or self.waiting
            or self._chunking is not None
            or self.preempted
            or self.spec is not None
            or self._pending_first
            # tiered slots gather pages for demotion between steps — the
            # gathers must order against a RECONCILED cache handle, so
            # tiering keeps the loop on the synchronous path
            or self._tiered
        ):
            return False
        # every active slot must have headroom for at least one more
        # predicted token: a slot whose in-flight window exhausts its
        # budget or page allocation is about to FINISH at the reconcile,
        # and dispatching past that point would trip the headroom
        # invariant (or waste a whole discarded step) — reconcile first
        for i, req in enumerate(self.slots):
            if req is None or not self._slot_active(i):
                continue
            pend = self._pending_out(req)
            if (
                req.sampling.max_tokens - len(req.output_tokens) - pend
                <= 0
                or (req.max_len or self.cache_cfg.max_seq_len)
                - req.num_tokens - pend <= 0
            ):
                return False
        return True

    def discard_pending(self, pend: PendingStep) -> None:
        """Forget an in-flight dispatch whose completion failed or will
        never run (step-failure path): host bookkeeping only — every
        slot is marked changed so the next ``_sync_state`` re-uploads
        the mirrors rather than trusting device state the failed step
        may have left behind."""
        if pend.kind == "decode":
            # roll back the predicted-position advance: the mirror's
            # last_token is still the last RECONCILED token (position
            # p-1), so the retry must re-decode from p — leaving the
            # dispatch-time p+n in place would re-sync a (position,
            # last_token) pair that never existed and silently skip n
            # tokens from the client's stream
            for i, r in pend.rows:
                if self.slots[i] is r:
                    self._positions[i] -= pend.n
        for _i, r in pend.rows:
            self._inflight_out.pop(r.id, None)
        self._pending_token_patches.clear()
        self._pending_first = []
        self._pending_first_ids.clear()
        for req, tok in pend.pending_first:
            if req.finished or req.slot is None:
                continue
            # the chunk call that sampled this deferred first token
            # SUCCEEDED — only the decode completion failed.  Put it
            # back so the retry re-seeds the slot from the handle and
            # still emits token #1; dropping it would condition the
            # retried stream on the placeholder mirror (0) and silently
            # lose the prompt's first sampled token.
            self._pending_first.append((req, tok))
            self._pending_first_ids.add(req.id)
            self._pending_token_patches[req.slot] = tok[0]
        self._state_dirty = True
        self._changed_slots.update(range(len(self.slots)))

    def _pending_out(self, req: Request) -> int:
        """Tokens this request has in flight (dispatched, not yet
        reconciled) plus a deferred chunk-final first token — the
        correction every budget/headroom read applies so a predicted
        dispatch can never overrun max_tokens or the allocated pages."""
        return self._inflight_out.get(req.id, 0) + (
            1 if req.id in self._pending_first_ids else 0
        )

    def _take_pending_first(self) -> list:
        pf, self._pending_first = self._pending_first, []
        self._pending_first_ids.clear()
        return pf

    def _finish_first_emit(self, req: Request, first_token: int,
                           emitted) -> None:
        """Deferred chunk-final emit, after its handle was fetched as
        part of the step's batched device_get."""
        if req.finished:
            return   # aborted after activation: the token is moot
        if req.slot is not None:
            self._last_token[req.slot] = first_token
            # a patch not yet consumed by _sync_state is superseded by
            # the now-accurate mirror (a stale patch after the mirror
            # write would double-count the histogram seed)
            self._pending_token_patches.pop(req.slot, None)
        self._emit(req, first_token, emitted)

    def _flush_pending_first(self, emitted) -> None:
        """Conservative fallback when no same-step decode fetch will
        carry the deferred first token: fetch it alone (today's
        behaviour)."""
        pf = self._take_pending_first()
        if not pf:
            return
        for req, tok in pf:
            self._finish_first_emit(req, int(np.asarray(tok)[0]), emitted)
        self._drain_moe_drops()   # the fetch above synced the device

    def _request_key(self, req: Request) -> np.ndarray:
        """Root PRNG key for one request: derived from its seed when given,
        else from the engine stream counter.

        Keys are derived ON HOST (splitmix64 -> two uint32 words used as
        threefry key data).  The previous ``jax.random.split`` chain cost a
        device dispatch + a blocking fetch PER REQUEST — through the axon
        relay (~70 ms/round-trip) admission of a 32-request burst spent
        ~3 s of device IDLE in key bookkeeping (the r3 TTFT).  Any distinct
        uint32 pair is a valid threefry key; determinism contracts hold:
        a seeded request's key depends only on its seed (reproducible
        across engines and batchmates), unseeded requests get the engine
        counter stream.
        """
        if req.sampling.seed is not None:
            return _host_key(_SEED_DOMAIN ^ (req.sampling.seed & _M64))
        self._key_nonce += 1
        return _host_key(self._key_base ^ self._key_nonce)

    def _slot_active(self, i: int) -> bool:
        """Occupied and decodable (not mid-chunked-prefill)."""
        s = self.slots[i]
        if s is None:
            return False
        return self._chunking is None or s is not self._chunking["req"]

    def generate(
        self, prompts: Sequence[Sequence[int]], sampling: SamplingParams
    ) -> list[list[int]]:
        """Blocking convenience wrapper (tests, bench)."""
        reqs = [
            Request(
                id=f"gen-{i}",
                prompt_tokens=list(p),
                sampling=sampling,
                stop_token_ids=tuple(self.cfg.eos_token_ids),
            )
            for i, p in enumerate(prompts)
        ]
        for r in reqs:
            self.add_request(r)
        while self.has_work():
            self.step()
        return [r.output_tokens for r in reqs]

    # ------------------------------------------------------------------
    # admission + prefill
    # ------------------------------------------------------------------

    def _prompt_hashes(self, req: Request) -> list:
        """Chain digests for the prompt's shareable full pages, capped at
        (plen-1)//ps: the page holding the LAST prompt token is never
        shared so sampling always has at least one token to prefill."""
        if getattr(req, "_page_hashes", None) is None:
            from helix_tpu.engine.kv_cache import PrefixCache

            ps = self.cache_cfg.page_size
            cap = (len(req.prompt_tokens) - 1) // ps
            req._page_hashes = PrefixCache.page_hashes(
                req.prompt_tokens, ps, cap
            )
        return req._page_hashes

    def _ensure_pages(self, need: int) -> bool:
        """can_allocate, with prefix-cache LRU eviction as the backstop.

        With a host tier, eviction SPILLS instead of destroying: the
        page contents demote to host buffers keyed by the same chain
        digest ``match_len`` looks up, so a later prompt sharing the
        prefix restores them instead of re-prefilling (the effective
        prefix cache grows from HBM-pages to host-budget-pages)."""
        if self.allocator.can_allocate(need):
            return True
        if self.prefix_cache is not None:
            entries = self.prefix_cache.evict_entries(
                need - self.allocator.free_pages
            )
            if entries:
                if self.host_pool is not None:
                    self._spill_prefix_pages(entries)
                self.allocator.give_back([p for _, p in entries])
        return self.allocator.can_allocate(need)

    def _spill_prefix_pages(self, entries: list) -> None:
        """Demote evicted prefix pages (``[(digest, page), ...]``) to the
        host tier.  The gather result is fresh device buffers with their
        D2H copies issued asynchronously inside ``put`` — the engine
        thread never blocks on the transfer.  A page the pool rejects
        (budget, injected alloc_fail) is simply lost, exactly as before
        the tier existed."""
        from helix_tpu.engine.kv_cache import gather_pages

        arrays = gather_pages(self.cache, [p for _, p in entries])
        for (digest, _page), page_arrays in zip(entries, arrays):
            self.host_pool.put(digest, page_arrays)

    # ------------------------------------------------------------------
    # continuous multi-LoRA serving (ISSUE 15)
    # ------------------------------------------------------------------

    def publish_adapter(self, adapter_id: str, lora_params: dict,
                        scaling: float) -> None:
        """Publish a trained LoRA tree for ``model@adapter_id`` serving
        — validated against this model's geometry, admitted to the
        host/filestore residency ladder, servable without restart or
        recompile (the pool shape was compiled at warmup)."""
        from helix_tpu.engine.adapters import pack_lora_tree

        if self.adapter_pool is None or self.adapter_store is None:
            raise ValueError(
                "adapter serving is off for this engine "
                "(EngineConfig.adapter_pool_slots)"
            )
        self.adapter_store.publish(
            pack_lora_tree(adapter_id, lora_params, scaling)
        )

    def _adapter_ready(self, req: Request) -> bool:
        """Can this request's adapter reach an HBM slot THIS step?
        Resident or host-resident = yes; otherwise the async
        filestore->host prefetch is (re-)kicked and admission defers —
        a cold adapter overlaps its load with the queue wait and never
        blocks an engine step."""
        aid = getattr(req, "adapter", "")
        if not aid or self.adapter_pool is None:
            return True
        if self.adapter_pool.resident(aid):
            return True
        if self.adapter_store is None:
            return False
        if self.adapter_store.ready(aid):
            return True
        self.adapter_store.prefetch(aid)
        return False

    def ensure_adapter_resident(self, adapter_id: str) -> bool:
        """Synchronously stage an adapter onto the host rung so the NEXT
        admission/resume can pin it without deferring.  Plan followers
        call this before stepping (the leader only broadcasts a request
        once it actually admitted it, so the adapter must load NOW, not
        via the async prefetch the leader's queue wait amortized)."""
        if not adapter_id or self.adapter_pool is None:
            return not adapter_id
        if self.adapter_pool.resident(adapter_id):
            return True
        if self.adapter_store is None:
            return False
        if self.adapter_store.ready(adapter_id):
            return True
        return self.adapter_store.get(adapter_id) is not None

    def _acquire_adapter(self, req: Request) -> Optional[int]:
        """Pin the request's adapter into an HBM pool slot (idempotent
        per request — one ref held admission -> finish, parked requests
        included, so a serving adapter can never be evicted under its
        rows).  None = not loadable this step (cold, or every slot
        pinned): the caller defers."""
        aid = getattr(req, "adapter", "")
        if not aid:
            return 0
        if self.adapter_pool is None:
            return None
        if req.id in self._adapter_refs:
            return self.adapter_pool.slot_for(aid)
        if self.adapter_store is not None:
            # host-resident specs ONLY: this runs on the engine thread,
            # and a filestore fallback here would be a blocking blob
            # read + checksum stalling every in-flight decode — a cold
            # adapter defers (the caller kicks the async prefetch)
            lookup = self.adapter_store.get_resident
            gen = self.adapter_store.generation(aid)
        else:
            lookup, gen = (lambda _id: None), None
        slot = self.adapter_pool.acquire(aid, lookup, generation=gen)
        if slot is not None:
            self._adapter_refs[req.id] = aid
        return slot

    def _release_adapter(self, req: Request) -> None:
        aid = self._adapter_refs.pop(req.id, None)
        if aid is not None and self.adapter_pool is not None:
            self.adapter_pool.release(aid)

    def _graft_params(self):
        """The model params with the adapter pool's stacked slot arrays
        merged into each targeted layer entry (shallow dict copies —
        the arrays themselves are the pool's).  Cached per pool
        version: loads/evictions swap values, never shapes, so the
        compiled step never retraces on adapter churn."""
        if self.adapter_pool is None:
            return self.params
        cached = self._grafted_params
        if cached is not None and cached[0] == self.adapter_pool.version:
            return cached[1]
        merged = dict(self.params)
        layers = dict(merged["layers"])
        for t, entry in self.adapter_pool.entries().items():
            layers[t] = {**layers[t], **entry}
        merged["layers"] = layers
        self._grafted_params = (self.adapter_pool.version, merged)
        return merged

    def _note_adapter_rows(self, plan, draft_len) -> None:
        """Bank this device call's rows per adapter id (bounded top-K
        accounting on the pool) — host-side dict math only."""
        pool = self.adapter_pool
        if pool is None:
            return
        counts: dict = {}
        if plan is not None:
            for row in plan.rows:
                if row.adapter and row.req is not None:
                    aid = getattr(row.req, "adapter", "")
                    if aid:
                        counts[aid] = counts.get(aid, 0) + 1
        if draft_len is not None:
            dl = np.asarray(draft_len)
            for i, req in enumerate(self.slots):
                if (
                    req is not None
                    and i < len(dl)
                    and dl[i] >= 0
                    and self._slot_active(i)
                    and getattr(req, "adapter", "")
                ):
                    counts[req.adapter] = counts.get(req.adapter, 0) + 1
        if counts:
            pool.note_rows(counts)

    def _try_claim(self, req: Request, use_cache: bool = False):
        """Allocate pages + a slot for one waiting request; returns its
        page table or None when resources are unavailable.

        With ``use_cache`` the longest cached prefix is acquired from the
        prefix cache and stitched in front of freshly allocated pages.
        When the chain continues into the HOST tier, those pages are
        restored into freshly allocated device pages here (their uploads
        were typically prefetched while the request sat queue-blocked,
        so the device_put overlapped the wait) and re-adopted into the
        device prefix cache.  ``req.cached_tokens`` records how many
        prompt tokens are already resident (page-aligned)."""
        free_slots = [i for i, s in enumerate(self.slots) if s is None]
        if not free_slots:
            return None
        adapter_slot = 0
        if getattr(req, "adapter", ""):
            # pin the adapter into an HBM pool slot BEFORE any page/slot
            # mutation — a cold adapter defers the whole claim (the ref,
            # once held, survives queue waits and parks until finish)
            got = self._acquire_adapter(req)
            if got is None:
                return None
            adapter_slot = got
        plen = len(req.prompt_tokens)
        ps = self.cache_cfg.page_size
        maxP = self.cache_cfg.max_pages_per_seq
        limit = min(plen + req.sampling.max_tokens, self.max_context_len)
        need = min(self.allocator.pages_needed(limit, ps), maxP)
        k = 0
        hashes: list = []
        if use_cache and self.prefix_cache is not None:
            hashes = self._prompt_hashes(req)
            k = self.prefix_cache.match_len(hashes)
        # tiered KV residency (ISSUE 20): a sequence longer than hot tail
        # + one stream chunk admits with only its FIRST dispatch's pages;
        # _tiered_prep grows the table lazily each step and demotes pages
        # behind the hot tail to the host pool.  ctx_pin rows (context-
        # cache creation prefills) stay fully resident.
        tiered = (
            self.cfg.ctx_hot_pages > 0
            and self.host_pool is not None
            and not getattr(req, "ctx_pin", False)
            and need > k + self.cfg.ctx_hot_pages + self.cfg.ctx_stream_pages
        )
        if (
            self.cfg.ctx_hot_pages > 0
            and self.host_pool is not None
            and not tiered
        ):
            # short (or pinned) rows on a tiered engine stay fully
            # resident, so they must fit the physical pool exactly as on
            # a non-tiered engine
            limit = min(limit, self._resident_context_cap)
            need = min(self.allocator.pages_needed(limit, ps), maxP)
        if tiered:
            # cover exactly the first dispatch: the first prefill chunk
            # for long prompts, else the whole prompt plus one decode
            # token (wave admissions dispatch before any prep pass runs)
            if plen > self.cfg.max_prefill_len:
                first = min(limit, self.cfg.max_prefill_len)
            else:
                first = min(limit, plen + 1)
            need_now = min(
                need, max(k, self.allocator.pages_needed(first, ps))
            )
        else:
            need_now = need
        shared: list = []
        if use_cache and self.prefix_cache is not None:
            if not self._ensure_pages(need_now - k):
                return None   # blocked retry: no acquire, no stat churn
            shared = self.prefix_cache.acquire(hashes)
        need_new = need_now - len(shared)
        if not self._ensure_pages(need_new):
            if shared:
                self.prefix_cache.release(shared)
            return None
        slot = free_slots[0]
        pages = shared + self.allocator.allocate(req.id, need_new)
        req.slot = slot
        req.admitted_time = time.monotonic()   # queue wait ends here
        restored = 0
        if use_cache and self.host_pool is not None and hashes:
            restored = self._restore_host_prefix(req, hashes, shared, pages)
        if use_cache and self.kv_filestore is not None and hashes:
            # the persistent rung below the host tier (ISSUE 14): the
            # chain's continuation may survive on the filestore across
            # restarts — verified blobs restore and re-adopt exactly
            # like host pages; a corrupt/missing blob truncates the
            # chain and the remainder prefills (never an error)
            restored += self._restore_filestore_prefix(
                req, hashes, len(shared) + restored, pages
            )
        req.cached_tokens = (len(shared) + restored) * self.cache_cfg.page_size
        if self._plan_recorder is not None:
            # leader: this admission is final — broadcast the full
            # request identity plus the cached_tokens the prefix /
            # filestore rungs restored (followers verify, so a
            # leader-local disk hit can never silently desync replay)
            self._plan_recorder.note_admit(req)
        if self._plan_drive is not None:
            want = self._plan_drive.cached_tokens.get(req.id)
            if want is not None and want != req.cached_tokens:
                raise RuntimeError(
                    f"plan-follow divergence: request {req.id} restored "
                    f"{req.cached_tokens} cached prompt tokens locally "
                    f"but the leader's plan recorded {want} — the "
                    "prefix/filestore rungs drifted between hosts "
                    "(point both hosts at the same filestore dir)"
                )
        self.num_admitted += 1
        if self._budget_left is not None:
            # charge the uncached prefill work this admission injects
            self._budget_left -= max(
                1, len(req.prompt_tokens) - req.cached_tokens
            )
        if self.on_admit is not None:
            try:
                self.on_admit(req)
            except Exception:  # noqa: BLE001 — policy hooks never fail admission
                logging.getLogger(__name__).exception(
                    "on_admit hook failed for request %s", req.id
                )
        if self.prefix_cache is not None:
            # request-level outcome: did THIS admission reuse any cached
            # prefix pages?  (page-level pools are record_claim below)
            if shared or restored:
                self.prefix_cache_hits += 1
            else:
                self.prefix_cache_misses += 1
        if use_cache and self.prefix_cache is not None:
            self.prefix_cache.record_claim(
                len(shared) + restored, len(hashes)
            )
        if shared:
            self._shared_pages.setdefault(req.id, []).extend(shared)
        # pages round up to page granularity; the model context limit
        # still binds exactly.  Tiered rows keep the full logical limit —
        # their tables grow lazily, so page count is not a length cap.
        if tiered:
            req.max_len = limit
        else:
            req.max_len = min(len(pages) * ps, self.max_context_len)
        self.slots[slot] = req
        self._slot_adapters[slot] = adapter_slot
        table = np.zeros((maxP,), np.int32)
        table[: len(pages)] = pages
        self._page_tables[slot] = table
        if tiered:
            # lo == hi == cached prefix pages: the restored/shared head
            # is never demoted (prefix-cache shares it), keeping the
            # cold span contiguous past it.  ``table`` is the object
            # prefill plans alias, so lazy growth/demotion lands in
            # already-built plans before finalize_device reads them.
            self._tiered[slot] = {
                "lo": req.cached_tokens // ps,
                "hi": req.cached_tokens // ps,
                "top": len(pages),
                "rid": req.id,
                "table": table,
            }
        return table

    def _restore_host_prefix(
        self, req: Request, hashes: list, shared: list, pages: list
    ) -> int:
        """Promote the host-resident continuation of the prefix chain
        into this request's freshly allocated device pages.

        Walks digests past the device-matched head, claims each page
        from the host pool (checksum-verified; a corrupt or concurrently
        evicted entry truncates the chain — the remainder prefills
        normally, correct by construction), writes the batch back with
        one donated scatter, and re-adopts the pages into the device
        prefix cache so the NEXT sharer hits in HBM."""
        k = len(shared)
        entries: list = []
        digests: list = []
        # a tiered claim may have allocated fewer pages than the digest
        # chain is long — restore only what has a device target
        while k + len(entries) < min(len(hashes), len(pages)):
            h = hashes[k + len(entries)]
            if not self.host_pool.contains(h):
                break
            e = self.host_pool.take_restored(h)
            self._prefetched.discard(h)   # consumed (or dropped corrupt)
            if e is None:   # corrupt (detected + dropped) — chain ends
                break
            entries.append(e)
            digests.append(h)
        if not entries:
            return 0
        from helix_tpu.engine.kv_cache import restore_pages

        t0 = time.monotonic()
        targets = pages[k:k + len(entries)]
        self.cache = restore_pages(self.cache, targets, entries)
        self.restore_seconds += time.monotonic() - t0
        if self.prefix_cache is not None:
            adopted = self.prefix_cache.adopt(digests, targets)
            if adopted:
                # same ownership transfer as _adopt_prompt_pages: the
                # cache owns them, the request holds one ref until finish
                self.allocator.detach(req.id, adopted)
                self._shared_pages.setdefault(req.id, []).extend(adopted)
        return len(entries)

    def _cached_prefix_pages(self, req: Request) -> int:
        """Resident prefix length in pages across the tiers this engine
        can restore from (device chain, its host-spilled continuation,
        then the persistent filestore rung) — the admission router's
        signal that a prompt's remainder must attend history."""
        if self.prefix_cache is None:
            return 0
        hashes = self._prompt_hashes(req)
        k = self.prefix_cache.match_len(hashes)
        if self.host_pool is not None:
            while k < len(hashes) and self.host_pool.contains(hashes[k]):
                k += 1
        if self.kv_filestore is not None:
            while k < len(hashes) and self.kv_filestore.contains(
                hashes[k]
            ):
                k += 1
        return k

    def _restore_filestore_prefix(
        self, req: Request, hashes: list, k: int, pages: list
    ) -> int:
        """Promote the filestore-resident continuation of the prefix
        chain (digests past position ``k``) into this request's freshly
        allocated device pages — the cross-restart sibling of
        ``_restore_host_prefix``.  Every blob is checksum-verified by
        ``KVFilestore.get`` BEFORE anything touches the pool; a missing
        or corrupt blob truncates the chain (typed counter) and the
        remainder prefills normally.  Restored pages re-adopt into the
        device prefix cache so the NEXT sharer hits in HBM."""
        entries: list = []
        digests: list = []
        while (
            k + len(entries) < len(hashes)
            and k + len(entries) < len(pages)
        ):
            e = self.kv_filestore.get(hashes[k + len(entries)])
            if e is None:   # miss or corrupt — chain ends, recompute
                break
            entries.append(e)
            digests.append(hashes[k + len(entries) - 1])
        if not entries:
            return 0
        from helix_tpu.engine.kv_cache import restore_pages

        t0 = time.monotonic()
        targets = pages[k:k + len(entries)]
        self.cache = restore_pages(self.cache, targets, entries)
        self.restore_seconds += time.monotonic() - t0
        self.filestore_restored_pages += len(entries)
        if self.prefix_cache is not None:
            adopted = self.prefix_cache.adopt(digests, targets)
            if adopted:
                self.allocator.detach(req.id, adopted)
                self._shared_pages.setdefault(req.id, []).extend(adopted)
        return len(entries)

    def _prefetch_host_prefix(self, req: Request) -> None:
        """Start host->device uploads for the waiting head's host-resident
        prefix pages while it is still resource-blocked: ``device_put``
        is async, so the transfer rides the queue wait (the same
        host/device overlap recipe as spec drafting) and the eventual
        ``_restore_host_prefix`` consumes in-flight handles instead of
        paying the upload at admission time.

        Device handles are bounded to ONE in-flight chain: a new wave
        (different waiting head) releases the previous wave's uploads —
        prefetch borrows HBM from a machine that is out of it, so
        handles whose admission never happened (request shed, chain
        superseded) must not linger until LRU eviction."""
        if self.host_pool is None or self.prefix_cache is None:
            return
        hashes = self._prompt_hashes(req)
        k = self.prefix_cache.match_len(hashes)
        chain = []
        while k < len(hashes) and self.host_pool.contains(hashes[k]):
            chain.append(hashes[k])
            k += 1
        for stale in self._prefetched - set(chain):
            self.host_pool.release_device(stale)
        self._prefetched = set()
        for h in chain:
            if not self.host_pool.prefetch(h):
                break
            self._prefetched.add(h)

    def _admit(self, emitted) -> None:
        # Long prompts that cannot start THIS step (another chunked prefill
        # already in flight) are set aside rather than blocking the queue:
        # short prompts behind them still admit while decode keeps running.
        # They go back at the queue head afterwards, so FIFO order among
        # long prompts is preserved.  Resource exhaustion (no slot/pages)
        # still blocks FIFO — bypassing there would let a stream of short
        # prompts starve a long prompt of the very pages it is waiting for.
        if any(r.finished for r in self.waiting):
            # purge aborted-while-queued requests ANYWHERE in the queue,
            # not just at the head: a finished request deep in the list
            # would otherwise keep counting against queue-depth/token
            # bounds (and the scheduler's per-tenant queues) until
            # admission happened to reach it
            self.waiting[:] = [r for r in self.waiting if not r.finished]
        deferred: list[Request] = []
        pending: list = []   # (batch, first_tokens device handle) per call
        try:
            self._admit_inner(emitted, deferred, pending)
        finally:
            if pending:
                self._finish_packed_admissions(pending, emitted)
            if deferred:
                self.waiting[:0] = deferred
        if self.preempted:
            # swapped-out decoders resume AFTER the wait queue got its
            # chance at the freed pages (they were preempted FOR that
            # queue — resume-first would re-grab the pages and starve it);
            # the loop's admission deadline backstops a park that never
            # clears
            self._try_resume()
        if not self.waiting and self._prefetched:
            # the queue unblocked without consuming the prefetched chain
            # (head admitted fresh, shed, or aborted): let its device
            # uploads go — no future wave would release them otherwise
            for h in self._prefetched:
                self.host_pool.release_device(h)
            self._prefetched = set()

    def _admit_inner(self, emitted, deferred: list, pending: list) -> None:
        while self.waiting:
            if (
                self._budget_left is not None
                and self._budget_left <= 0
            ):
                # per-step prefill-admission budget spent (scheduler
                # TTFT-burn feedback): stop admitting; decode keeps
                # running and the next step gets a fresh budget.  The
                # budget starts >= 1, so the first admission of a step
                # always proceeds — a shrunken budget throttles, it can
                # never wedge admission.
                return
            if self.waiting[0].finished:   # aborted while queued
                self.waiting.pop(0)
                continue
            req = self.waiting[0]
            if not self._adapter_ready(req):
                # cold adapter: its filestore->host prefetch was just
                # (re-)kicked — set the request aside like a blocked
                # long prompt so everything behind it keeps admitting
                # and the engine step never waits on the load
                deferred.append(self.waiting.pop(0))
                continue
            plen = len(req.prompt_tokens)
            needs_chunking = plen > self.cfg.max_prefill_len
            is_mrope = self.model_cfg.mrope_sections is not None
            if not needs_chunking and not is_mrope:
                # short text prompts — cold AND prefix-cache hits — pack
                # into ONE ragged prefill segment (a hit row's remainder
                # attends the shared pages via its per-row history
                # length; pre-unification each hit paid its own padded
                # chunk call).  First tokens stay on device until the
                # whole wave is admitted (one fetch per wave, not per
                # call — each fetch is a full relay round trip).
                if not self._admit_wave(pending):
                    # resource wait: overlap it with the host->device
                    # uploads the eventual claim will consume
                    self._prefetch_host_prefix(req)
                    return
                continue
            if needs_chunking and self._chunking is not None:
                # one chunked prefill in flight at a time — set this long
                # prompt aside so the shorts behind it are not head-of-line
                # blocked (VERDICT r2 weak #6)
                deferred.append(self.waiting.pop(0))
                continue
            table = self._try_claim(req, use_cache=not is_mrope)
            if table is None:
                if not is_mrope:
                    self._prefetch_host_prefix(req)
                return  # resource wait; decode will free pages
            self.waiting.pop(0)
            slot = req.slot
            if needs_chunking:
                # defer to _chunk_dispatch: one chunk per engine step, decode
                # interleaves; the slot stays inactive until the prompt is
                # fully cached.  A prefix-cache hit starts past the
                # resident pages: those tokens are never prefilled again.
                self._chunking = {
                    "req": req, "table": table, "next": req.cached_tokens,
                    "key": self._request_key(req), "slot": slot,
                }
                self._state_dirty = True
                self._changed_slots.add(slot)
                continue
            first_token = self._prefill(req, table, slot=slot)
            req.first_token_time = time.monotonic()
            self.recent_ttfts.append(
                req.first_token_time - req.submit_time
            )
            self._positions[slot] = plen
            self._mrope_delta[slot] = req.mrope_delta
            self._last_token[slot] = first_token
            self._state_dirty = True
            self._changed_slots.add(slot)
            self._emit(req, int(first_token), emitted)

    def _admit_wave(self, pending: list) -> int:
        """Claim as many waiting short text prompts as fit one ragged
        prefill segment and prefill them in ONE unified step.  Cold
        prompts and prefix-cache hits pack the same flat token axis — a
        hit row's remainder attends the shared pages through its per-row
        history length, so hit bursts no longer serialize through padded
        one-request chunk calls.  Returns requests admitted (0 =
        blocked on resources).

        First tokens are NOT fetched here: the device handle is appended
        to ``pending`` and ``_finish_packed_admissions`` fetches the whole
        admission wave in one host round trip."""
        C_cap = self.cfg.max_prefill_len
        ps = self.cache_cfg.page_size
        maxP = self.cache_cfg.max_pages_per_seq
        B = self.cfg.max_decode_batch
        # MoE: one request per call — expert capacity is a shared field
        # across the whole segment, so co-packed requests would perturb
        # each other's routing (and the KV the prefix cache adopts).
        # The admission loop still issues the calls in one wave with one
        # batched token fetch.
        max_pack = 1 if self.model_cfg.num_experts > 0 else B
        sp_ring = _mesh_sp(self.mesh) > 1
        plan = PrefillPlan(ps, maxP, B)
        batch: list = []
        waves: list = []   # closed (plan, batch) pairs

        def flush():
            nonlocal plan, batch
            if batch:
                waves.append((plan, batch))
            plan = PrefillPlan(ps, maxP, B)
            batch = []

        admitted_any = False
        adapter_deferred: list = []
        while self.waiting:
            req = self.waiting[0]
            if req.finished:
                self.waiting.pop(0)
                continue
            if not self._adapter_ready(req):
                # cold adapter mid-wave: defer (prefetch already
                # kicked), keep packing the rest of the queue
                adapter_deferred.append(self.waiting.pop(0))
                continue
            plen = len(req.prompt_tokens)
            if plen > C_cap:
                break   # long prompt: the outer admission loop chunks it
            if len(batch) >= max_pack:
                flush()
            if (
                (batch or waves or admitted_any)
                and self._budget_left is not None
                and self._budget_left <= 0
            ):
                # budget spent mid-wave: close with what fit (the first
                # claim of a step is always admitted)
                break
            cache_match = 0
            if self.prefix_cache is not None:
                cache_match = self._cached_prefix_pages(req)
            if sp_ring and batch and (cache_match or plan.has_hist):
                # ring attention has no segment ids: a history-attending
                # row runs alone in its call on sp meshes
                flush()
            table = self._try_claim(req, use_cache=cache_match > 0)
            if table is None:
                break
            self.waiting.pop(0)
            admitted_any = True
            start = req.cached_tokens   # 0 unless prefix-cache hit
            rem = plen - start
            if batch and not plan.fits(rem, C_cap):
                flush()
            carry, sub = _host_split(self._request_key(req))
            self._slot_keys[req.slot] = carry
            plan.add(
                req, table, start, rem,
                req.prompt_tokens[start:plen], sub, req.sampling,
                adapter=int(self._slot_adapters[req.slot]),
            )
            batch.append((req, table))
        if adapter_deferred:
            # back at the queue head: FIFO among deferred adapters is
            # preserved and the next admission pass re-checks readiness
            self.waiting[:0] = adapter_deferred
        flush()
        admitted = 0
        for wave_plan, wave_batch in waves:
            first_tokens, _, _, _, drops = self._ragged_step(
                plan=wave_plan, draft_len=self._inert_rows, n_extra=0,
            )
            pending.append((wave_batch, first_tokens, drops))
            admitted += len(wave_batch)
        return admitted

    def _finish_packed_admissions(self, pending: list, emitted) -> None:
        """Fetch every admission wave's first tokens in ONE host round
        trip and complete the per-request bookkeeping."""
        if len(pending) == 1:
            batch0, tok0, _ = pending[0]
            flat = np.asarray(tok0)[: len(batch0)]
        else:
            flat = np.asarray(
                jnp.concatenate(
                    [t[: len(b)] for b, t, _ in pending], axis=0
                )
            )
        for _, _, drops in pending:
            self._note_moe_drops(drops)
        # the token fetch above synced the device: draining is free here
        self._drain_moe_drops()
        now = time.monotonic()
        i = 0
        for batch, _, _ in pending:
            for req, _table in batch:
                first_token = int(flat[i])
                i += 1
                slot = req.slot
                req.first_token_time = now
                self.recent_ttfts.append(now - req.submit_time)
                self._positions[slot] = len(req.prompt_tokens)
                self._mrope_delta[slot] = 0
                self._last_token[slot] = first_token
                self._state_dirty = True
                self._changed_slots.add(slot)
                self.num_prefill_tokens += (
                    len(req.prompt_tokens) - req.cached_tokens
                )
                self._adopt_prompt_pages(
                    req, self._page_tables[slot]
                )
                self._emit(req, first_token, emitted)

    def _note_moe_drops(self, drops) -> None:
        """Queue a prefill call's MoE capacity-overflow count (device
        scalar; None for dense models) WITHOUT fetching it — a blocking
        device_get here would serialize every chunk dispatch (the axon
        relay costs ~28 ms per fetch).  The queue drains on the ENGINE
        thread at prefill-completion points, where the device work is
        already host-synced."""
        if drops is None:
            return
        self._moe_drop_handles.append(drops)

    def _drain_moe_drops(self) -> None:
        """Fold queued drop counts into the host counter in one stacked
        fetch.  Engine-thread only (prefill completion paths): the
        /metrics scrape thread must never block on a device sync, so the
        property below just reads the plain int."""
        if not self._moe_drop_handles:
            return
        handles, self._moe_drop_handles = self._moe_drop_handles, []
        n = int(np.asarray(jnp.stack(handles)).sum())
        if n <= 0:
            return
        self._moe_dropped += n
        # surfaced instead of silently riding the residual stream
        # (ADVICE r5)
        logging.getLogger(__name__).info(
            "moe prefill dropped %d routing assignments to capacity "
            "overflow (engine total %d)", n, self._moe_dropped,
        )

    @property
    def moe_dropped_tokens(self) -> int:
        """Total MoE prefill routing assignments dropped to expert-
        capacity overflow.  Lock-free plain-int read (GIL-atomic), safe
        from the metrics thread; at most one un-drained prefill wave
        behind the device."""
        return self._moe_dropped

    def _chunk_plan(self, st) -> tuple:
        """ONE ragged row for the in-flight long prefill's next chunk:
        the row's history length is simply the chunk start (no history
        bucketing — the ragged op walks exactly the pages in use), so
        chunked prefill compiles one single-row shape per token-bucket
        rung instead of one per (chunk, history) pair."""
        req: Request = st["req"]
        plen = len(req.prompt_tokens)
        start = st["next"]
        end = min(start + self.cfg.max_prefill_len, plen)
        rem = end - start
        st["key"], sub = _host_split(st["key"])
        plan = PrefillPlan(
            self.cache_cfg.page_size, self.cache_cfg.max_pages_per_seq, 1
        )
        plan.add(
            req, st["table"], start, rem,
            req.prompt_tokens[start:end], sub, req.sampling,
            adapter=int(self._slot_adapters[st["slot"]]),
        )
        return plan, rem, end

    def _finish_chunk(self, st, first_token, emitted) -> None:
        """Prompt fully cached: activate the slot with the first sampled
        token (shared by the standalone chunk step and the mixed step).

        ``first_token`` is either a host int (mixed step — its fetch was
        folded into the step's one device_get) or the chunk step's [R]
        DEVICE handle, in which case the fetch DEFERS: _sync_state seeds
        the slot's device state from the handle and the emit joins the
        same-step decode fetch, so a long-prompt chunk cascade costs one
        host round trip per step, not two."""
        req: Request = st["req"]
        self._adopt_prompt_pages(req, st["table"])
        slot = st["slot"]
        self._chunking = None
        req.first_token_time = time.monotonic()
        self.recent_ttfts.append(
            req.first_token_time - req.submit_time
        )
        self._positions[slot] = len(req.prompt_tokens)
        self._mrope_delta[slot] = req.mrope_delta
        self._slot_keys[slot] = _host_split(st["key"])[0]
        self._state_dirty = True
        self._changed_slots.add(slot)
        if isinstance(first_token, (int, np.integer)):
            self._last_token[slot] = first_token
            # the caller fetched the first token already: device is
            # synced, so folding the queued chunk drop counts is free
            self._drain_moe_drops()
            self._emit(req, int(first_token), emitted)
            return
        # deferred: placeholder mirror, device-side patch at the next
        # _sync_state, emit at the next batched fetch
        self._last_token[slot] = 0
        self._pending_token_patches[slot] = first_token[0]
        self._pending_first.append((req, first_token))
        self._pending_first_ids.add(req.id)

    # per-request cap on prefill_chunk spans: a 128k prompt would
    # otherwise flood its own trace's span budget and evict the decode/
    # emit summary spans recorded later (the spans a slow-request
    # investigation actually needs)
    _MAX_CHUNK_SPANS = 32

    def _should_trace_chunk(self, st: dict, req: Request, end: int) -> bool:
        """First _MAX_CHUNK_SPANS chunks + always the final chunk."""
        n = st.get("chunk_spans", 0)
        if n < self._MAX_CHUNK_SPANS or end >= len(req.prompt_tokens):
            st["chunk_spans"] = n + 1
            return True
        return False

    def _chunk_dispatch(self) -> None:
        """Dispatch ONE chunk of the in-flight long prefill (called once
        per engine step so decode interleaves).  Pure dispatch: non-final
        chunks fetch nothing at all, and the final chunk's first token
        defers into the same-step decode fetch (``_finish_chunk``)."""
        st = self._chunking
        req: Request = st["req"]
        if req.finished:   # aborted mid-prefill
            self._chunking = None
            return
        t0 = time.monotonic()
        plan, rem, end = self._chunk_plan(st)
        token, _, _, _, drops = self._ragged_step(
            plan=plan, draft_len=self._inert_rows, n_extra=0,
        )
        self._note_moe_drops(drops)
        self.num_prefill_tokens += rem
        st["next"] = end
        if req.trace_id and self._should_trace_chunk(st, req, end):
            # host-side step attribution (device work is async; the final
            # chunk's span absorbs the sync when the first token is read)
            obs_trace.default_store().record(
                req.trace_id, "prefill_chunk", t0, time.monotonic(),
                plane="engine", request_id=req.id,
                chunk_end=end, tokens=rem,
            )
        if end < len(req.prompt_tokens):
            return
        self._finish_chunk(st, token, None)

    def _mixed_dispatch(self) -> Optional[PendingStep]:
        """Ragged mixed step: ONE device call advances every active decode
        slot one token AND the in-flight long prefill one chunk — decode
        never stalls (and never pays a second dispatch) while a long
        prompt is being admitted."""
        st = self._chunking
        req: Request = st["req"]
        if self._state_dirty or self._dstate is None:
            self._sync_state()
        # same headroom invariant as the decode step, for the fused step
        table_cap = (
            self.cache_cfg.max_pages_per_seq * self.cache_cfg.page_size
        )
        for i in range(len(self.slots)):
            if self._slot_active(i) and self._positions[i] + 1 > table_cap:
                raise RuntimeError(
                    f"decode step overruns page-table capacity: slot {i} "
                    f"at position {self._positions[i]} — headroom "
                    f"invariant violated"
                )
        rows = [
            (i, r) for i, r in enumerate(self.slots)
            if r is not None and self._slot_active(i)
        ]
        t0 = time.monotonic()
        plan, rem, end = self._chunk_plan(st)
        token, sampled, _, _, drops = self._ragged_step(
            plan=plan, draft_len=self._zero_rows, n_extra=0,
        )
        self.num_mixed_steps += 1
        self.num_decode_device_steps += 1
        self._note_moe_drops(drops)
        self.num_prefill_tokens += rem
        st["next"] = end
        if req.trace_id and self._should_trace_chunk(st, req, end):
            obs_trace.default_store().record(
                req.trace_id, "prefill_chunk", t0, time.monotonic(),
                plane="engine", request_id=req.id,
                chunk_end=end, tokens=rem, mixed=True,
            )
        return PendingStep(
            kind="mixed", rows=rows, handles=(sampled, token), st=st,
            final=end >= len(req.prompt_tokens),
            # a deferred chunk-final first token re-queued by a failed
            # step can cross into a mixed retry (a NEW prompt started
            # chunking): it must ride THIS step's fetch or its request
            # would emit token #2 before token #1
            pending_first=self._take_pending_first(),
        )

    def _mixed_complete(self, p: PendingStep, emitted) -> None:
        sampled, token = p.handles
        firsts = tuple(tok for _r, tok in p.pending_first)
        if p.final:
            # chunk-final token folded into the step's ONE device_get
            # (previously its own np.asarray fetch — a second host
            # round trip on every long-prompt completion step)
            fetched = jax.device_get((sampled, token) + firsts)
            next_np, tok_np = fetched[0], fetched[1]
            first_np = fetched[2:]
        else:
            fetched = jax.device_get((sampled,) + firsts)
            next_np, tok_np = fetched[0], None
            first_np = fetched[1:]
        if p.pending_first:
            for (req, _h), t_np in zip(p.pending_first, first_np):
                self._finish_first_emit(req, int(t_np[0]), emitted)
            self._drain_moe_drops()   # the fetch above synced the device
        # decode emissions first (the chunking slot is still parked here)
        for i, r in p.rows:
            if self.slots[i] is not r or r.finished:
                continue
            self._positions[i] += 1
            self._last_token[i] = next_np[i, 0]
            self.num_decode_tokens += 1
            self._emit(r, int(next_np[i, 0]), emitted)
        if p.final:
            self._finish_chunk(p.st, int(tok_np[0]), emitted)

    def _prefill(
        self, req: Request, page_table: np.ndarray, slot: Optional[int] = None
    ) -> int:
        """VL (mrope) single-shot prefill.  Text prompts never come here:
        short ones pack through ``_admit_wave`` and long ones chunk
        through ``_chunk_dispatch``."""
        assert self.model_cfg.mrope_sections is not None
        plen = len(req.prompt_tokens)
        bucket = _bucket(
            max(plen, self.cache_cfg.page_size),
            self.cache_cfg.page_size,
            self.cfg.max_prefill_len,
        )
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :plen] = req.prompt_tokens
        self._charge_padding(bucket, plen)
        ragged_meta.note_step_shape(
            self._shape_key, ("mrope_prefill", bucket)
        )
        length = np.int32(plen)
        # per-request PRNG stream: seeded requests reproduce exactly
        # regardless of batch-mates; the carry half becomes the slot's
        # device-resident key for decode
        carry, sub = _host_split(self._request_key(req))
        if slot is not None:
            self._slot_keys[slot] = carry
        sampling = SamplingState.from_params([req.sampling])
        embeds = self._splice_embeds(req, tokens, bucket)
        pos3 = np.zeros((3, 1, bucket), np.int32)
        if req.positions3 is not None:
            pos3[:, 0, :plen] = np.asarray(req.positions3)[:, :plen]
        else:
            pos3[:, 0, :plen] = np.arange(plen)[None]
        fn = _build_prefill_fn_mrope(
            self.model_cfg, self.cache_cfg.page_size, self._backend
        )
        self.num_device_calls += 1
        self.cache, token = fn(
            self.params, self.cache, jnp.asarray(tokens), embeds,
            jnp.asarray(pos3), jnp.asarray(page_table)[None],
            jnp.asarray(length), sampling, sub,
        )
        self.num_prefill_tokens += plen
        return int(token[0])

    def _splice_embeds(self, req: Request, tokens: np.ndarray, bucket: int):
        """Embed-lookup the prompt and splice image embeddings in (bucketed
        on the image-token count so VL prefill compiles a handful of shapes)."""
        splice = _build_embed_splice_fn(self.model_cfg)
        E = self.model_cfg.hidden_size
        if req.image_embeds is None:
            img = jnp.zeros((1, E), jnp.dtype(self.model_cfg.dtype))
            pos = jnp.full((1,), bucket + 1, jnp.int32)
            n = jnp.int32(0)
        else:
            n_img = req.image_embeds.shape[0]
            nb = _bucket(max(n_img, 1), 16, 1 << 16)
            img = jnp.zeros((nb, E), jnp.dtype(self.model_cfg.dtype))
            img = img.at[:n_img].set(jnp.asarray(req.image_embeds))
            posn = np.full((nb,), bucket + 1, np.int32)
            posn[:n_img] = req.image_positions
            pos = jnp.asarray(posn)
            n = jnp.int32(n_img)
        return splice(self.params, jnp.asarray(tokens), img, pos, n)

    # ------------------------------------------------------------------
    # decode
    # ------------------------------------------------------------------

    def _sync_state(self) -> None:
        """One jitted merge uploads the host mirrors after the slot set
        changed; device-evolving pieces (RNG keys, penalty histograms) of
        surviving slots are preserved on device."""
        B = self.cfg.max_decode_batch
        V = self.model_cfg.vocab_size
        P = self.cache_cfg.max_pages_per_seq
        active = np.array(
            [1 if self._slot_active(i) else 0 for i in range(len(self.slots))],
            np.int32,
        )
        sampling = SamplingState.from_params(
            [
                (s.sampling if s is not None else SamplingParams())
                for s in self.slots
            ]
        )
        if self._dstate is None:
            self._dstate = DecodeState(
                last_token=jnp.zeros((B,), jnp.int32),
                positions=jnp.zeros((B,), jnp.int32),
                page_tables=jnp.zeros((B, P), jnp.int32),
                active=jnp.zeros((B,), jnp.int32),
                mrope_delta=jnp.zeros((B,), jnp.int32),
                keys=jnp.zeros((B, 2), jnp.uint32),
                token_counts=jnp.zeros((B, V), jnp.int32),
                adapter_slots=jnp.zeros((B,), jnp.int32),
                sampling=sampling,
            )
        keep = np.array(
            [
                1 if (s is not None and i not in self._changed_slots) else 0
                for i, s in enumerate(self.slots)
            ],
            np.int32,
        )
        self._dstate = _rebuild_state(
            self._dstate,
            jnp.asarray(self._last_token),
            jnp.asarray(self._positions),
            jnp.asarray(self._page_tables),
            jnp.asarray(active),
            jnp.asarray(self._mrope_delta),
            jnp.asarray(self._slot_keys),
            jnp.asarray(keep),
            jnp.asarray(self._slot_adapters),
            sampling,
        )
        self._changed_slots.clear()
        self._state_dirty = False
        if self._slot_count_overrides:
            # resumed slots: re-inject the saved output-token histogram
            # over the fresh-slot reset the rebuild just applied
            for slot, counts in sorted(self._slot_count_overrides.items()):
                self._dstate = _override_token_counts(
                    self._dstate, jnp.int32(slot), jnp.asarray(counts)
                )
            self._slot_count_overrides.clear()
        if self._pending_token_patches:
            # deferred chunk-final first tokens: seed the fresh slot's
            # last_token + histogram from the still-on-device handle —
            # the rebuild above used the placeholder mirror (0)
            for slot, tok in sorted(self._pending_token_patches.items()):
                self._dstate = _patch_first_token(
                    self._dstate, jnp.int32(slot), tok
                )
            self._pending_token_patches.clear()

    def _decode_window(self) -> int:
        """Fused decode steps to run before the next host sync.

        Single steps whenever responsiveness or safety needs them:
        pending admissions / an in-flight chunked prefill (they interleave
        per engine step), or any active slot within a window of its token
        budget or page capacity (the device keeps writing KV until the
        window ends, so the window must never overrun either).  Otherwise
        the largest power of two <= decode_steps_per_sync that every
        active slot can absorb (power-of-two bucketing bounds the number
        of compiled variants).
        """
        n_max = self.cfg.decode_steps_per_sync
        if n_max <= 1 or self._chunking is not None or self._cold_active():
            # cold-middle rows stream staged chunks through the primary
            # attention call only — the fused tail re-gathers history
            # without the cold stats, so tiered steps stay single-token
            return 1
        n_active = sum(
            1 for i in range(len(self.slots)) if self._slot_active(i)
        )
        if n_active <= self.cfg.adaptive_sync_max_streams:
            return 1   # interactive: stream per-token
        cap = n_max
        # queue pressure as the device sees it.  A plan follower pins
        # this bit to the leader's value: its own queue drains exactly
        # at each plan boundary, so reading it locally would diverge
        # from the leader's (non-empty) queue and change the fused
        # window — a different compiled shape mid-collective.
        blocked = bool(self.waiting)
        if self._plan_drive is not None:
            blocked = self._plan_drive.queue_blocked
        elif self._plan_recorder is not None:
            self._plan_recorder.queue_blocked = blocked
        if blocked:
            # Admission already ran this step, so a non-empty queue means
            # admission is RESOURCE-blocked — forcing single steps would
            # not admit anything sooner, it would just re-impose the
            # per-token host round trip on the whole running batch (the
            # regression this feature exists to fix).  A short window is
            # still worth it: slots can finish mid-window (EOS), and the
            # host only sees that — and can re-admit — at the window
            # boundary, so cap the queued-work turnover latency at 4
            # steps instead of n_max.
            cap = min(cap, 4)
        for i, req in enumerate(self.slots):
            if req is None or not self._slot_active(i):
                continue
            # in-flight tokens (async pipeline / deferred chunk-final)
            # count against budget and page room: the predicted dispatch
            # must never overrun what the reconcile will reveal
            pend = self._pending_out(req)
            budget = (
                req.sampling.max_tokens - len(req.output_tokens) - pend
            )
            room = (
                (req.max_len or self.cache_cfg.max_seq_len)
                - req.num_tokens - pend
            )
            cap = min(cap, budget, room)
        if cap <= 1:
            return 1
        n = 1
        while n * 2 <= cap:
            n *= 2
        return n

    # ------------------------------------------------------------------
    # tiered KV residency: streamed cold-middle attention (ISSUE 20)
    # ------------------------------------------------------------------

    @property
    def kv_cold_pages(self) -> int:
        """Demoted cold-middle pages currently host-resident across all
        tiered slots — the saturation gauge for how much context lives
        past HBM."""
        return sum(
            led["hi"] - led["lo"] for led in self._tiered.values()
        )

    def _cold_active(self) -> bool:
        """True when any tiered slot has a non-empty demoted span (the
        next step must stream cold chunks)."""
        return any(
            led["hi"] > led["lo"] for led in self._tiered.values()
        )

    def _ensure_tiered_pages(self, slot: int, led: dict,
                             upto_tokens: int) -> None:
        """Grow a tiered slot's page table to cover ``upto_tokens``
        written positions.  Fresh pages land at the table's high-water
        mark — both in the ledger's aliased table (already-built plan
        rows see them) and the engine's [B, maxP] mirror."""
        ps = self.cache_cfg.page_size
        maxP = self.cache_cfg.max_pages_per_seq
        need = min(self.allocator.pages_needed(upto_tokens, ps), maxP)
        if need <= led["top"]:
            return
        n_new = need - led["top"]
        if not self._ensure_pages(n_new):
            # demotion runs before growth each step, so the steady-state
            # footprint is hot tail + one growth margin; failing THAT
            # means the pool is undersized for the admitted mix
            raise MemoryError(
                f"tiered slot {slot} cannot grow its page table by "
                f"{n_new} page(s) — device pool exhausted even after "
                "cold demotion"
            )
        pages = self.allocator.allocate(led["rid"], n_new)
        for i, pg in enumerate(pages):
            led["table"][led["top"] + i] = pg
            self._page_tables[slot][led["top"] + i] = pg
        led["top"] = need
        # dirty WITHOUT marking the slot changed: page tables re-upload
        # from the host mirror unconditionally, while the slot's
        # device-evolved PRNG key stream and penalty histogram must
        # survive (a changed-slot rebuild would reset both — seeded
        # sampling would silently replay the key stream)
        self._state_dirty = True

    def _demote_slot(self, slot: int, led: dict, written: int) -> None:
        """Move fully written pages behind the hot tail to the host pool
        (checksummed, pinned) and zero their table entries.  ``written``
        is the number of KV positions already written for this slot —
        only pages wholly below ``written - ctx_hot_pages * page_size``
        demote, so the hot tail always stays device-resident."""
        ps = self.cache_cfg.page_size
        target = min(
            written // ps - self.cfg.ctx_hot_pages,
            self.cache_cfg.max_pages_per_seq,
        )
        if target <= led["hi"]:
            return
        from helix_tpu.engine.kv_cache import gather_pages

        idxs = list(range(led["hi"], target))
        page_ids = [int(led["table"][i]) for i in idxs]
        arrays = gather_pages(self.cache, page_ids)
        for idx, page, page_arrays in zip(idxs, page_ids, arrays):
            # pinned: cold pages are the ONLY copy of mid-history KV —
            # prefix-spill pressure must never evict them
            if not self.host_pool.put(
                ("ctx", led["rid"], idx), page_arrays, pinned=True
            ):
                break   # host budget full: stop demoting, keep resident
            self.allocator.detach(led["rid"], [page])
            self.allocator.give_back([page])
            led["table"][idx] = 0
            self._page_tables[slot][idx] = 0
            led["hi"] = idx + 1
            self.num_ctx_demoted_pages += 1
            # table-only change: see _ensure_tiered_pages — never reset
            # the slot's device key stream / histogram over a demotion
            self._state_dirty = True

    def _tiered_prep(self, n_extra: int) -> None:
        """Per-dispatch residency pass for every tiered slot: demote
        pages that fell behind the hot tail, then grow the table to
        cover this step's writes.  Demote-first frees the pages growth
        is about to claim, bounding the per-slot device footprint at
        hot tail + stream margin."""
        for slot in sorted(self._tiered):
            req = self.slots[slot]
            if req is None:
                continue
            led = self._tiered[slot]
            chunking = (
                self._chunking is not None
                and self._chunking.get("slot") == slot
            )
            if chunking:
                written = int(self._chunking["next"])
                upto = min(
                    len(req.prompt_tokens),
                    written + self.cfg.max_prefill_len,
                )
            else:
                written = int(self._positions[slot])
                upto = written + self._spec_width() + int(n_extra)
            upto = min(
                upto,
                req.max_len or self.cache_cfg.max_seq_len,
                self.cache_cfg.max_seq_len,
            )
            self._demote_slot(slot, led, written)
            self._ensure_tiered_pages(slot, led, upto)

    def _cold_spans(self) -> list:
        """Ordered ``(slot, rid, lo, hi)`` for every tiered slot with a
        non-empty demoted span — the staging order, ascending by slot so
        the chunk-fold merge order is deterministic."""
        spans = []
        for slot in sorted(self._tiered):
            led = self._tiered[slot]
            if led["hi"] > led["lo"] and self.slots[slot] is not None:
                spans.append((slot, led["rid"], led["lo"], led["hi"]))
        return spans

    def _refresh_cold_staged(self) -> Optional[dict]:
        """Assemble (or reuse) the staged cold-chunk slab for the
        current demoted spans: host gathers from the pool (checksum
        verified — a corrupt page raises ``ColdPageError``), packed into
        ``[L, nCb, Ct, KVH, D]`` chunk arrays and ``device_put`` as ONE
        async upload.  Keyed on the exact span set, so ``prefetch_cold``
        can build it while the previous step is still on device and the
        dispatch reuses the in-flight handles."""
        spans = self._cold_spans()
        if not spans:
            self._cold_staged = None
            return None
        key = tuple((rid, lo, hi) for _s, rid, lo, hi in spans)
        staged = self._cold_staged
        if staged is not None and staged["key"] == key:
            return staged
        sp = self.cfg.ctx_stream_pages
        ps = self.cache_cfg.page_size
        groups = []   # (rid, [page entries], valid tokens) per chunk
        for _slot, rid, lo, hi in spans:
            for c0 in range(lo, hi, sp):
                c1 = min(c0 + sp, hi)
                entries = []
                for idx in range(c0, c1):
                    e = self.host_pool.get(("ctx", rid, idx))
                    if e is None:
                        raise ColdPageError(
                            f"cold KV page {idx} of request {rid} "
                            "failed checksum verification on restore — "
                            "refusing to attend corrupt history"
                        )
                    entries.append(e)
                groups.append((rid, entries, (c1 - c0) * ps))
        nC = len(groups)
        nCb = 1
        while nCb < nC:
            nCb *= 2
        e0 = groups[0][1][0]
        L, _ps, KVH, D = np.asarray(e0["k"]).shape
        Ct = sp * ps
        kdt = np.asarray(e0["k"]).dtype
        quant = self.cache_cfg.quantized
        ck = np.zeros((L, nCb, Ct, KVH, D), kdt)
        cv = np.zeros((L, nCb, Ct, KVH, D), kdt)
        lens = np.zeros((nCb,), np.int32)
        cks = np.zeros((L, nCb, Ct, KVH), np.float32) if quant else None
        cvs = np.zeros((L, nCb, Ct, KVH), np.float32) if quant else None
        owners = []
        for j, (rid, entries, n_tok) in enumerate(groups):
            ck[:, j, :n_tok] = np.concatenate(
                [np.asarray(e["k"]) for e in entries], axis=1
            )
            cv[:, j, :n_tok] = np.concatenate(
                [np.asarray(e["v"]) for e in entries], axis=1
            )
            lens[j] = n_tok
            owners.append(rid)
            if quant:
                cks[:, j, :n_tok] = np.concatenate(
                    [np.asarray(e["k_scale"], np.float32)
                     for e in entries], axis=1
                )
                cvs[:, j, :n_tok] = np.concatenate(
                    [np.asarray(e["v_scale"], np.float32)
                     for e in entries], axis=1
                )
        self._cold_staged = {
            "key": key,
            "owners": tuple(owners),
            "lens": lens,
            "nCb": nCb,
            "ct": Ct,
            "k": jax.device_put(ck),
            "v": jax.device_put(cv),
            "ks": None if cks is None else jax.device_put(cks),
            "vs": None if cvs is None else jax.device_put(cvs),
        }
        return self._cold_staged

    def _finalize_cold(self, staged: dict, plan, n_rows: int):
        """Bind the staged slab to THIS dispatch's row axes: per-chunk
        owner rows for the prefill segment (plan row index) and the
        state segment (decode slot), plus each row's demoted token span.
        A chunk whose owner appears in neither axis keeps row -1 and
        masks to zero (admission waves during another row's chunked
        prefill).  Returns ``(cold_arg, cold_chunks, cold_ct)``."""
        nCb = staged["nCb"]
        B = len(self.slots)
        spans = self._cold_spans()
        rid_prow: dict = {}
        if plan is not None:
            for j, r in enumerate(plan.rows):
                if r.req is not None:
                    rid_prow[r.req.id] = j
        ps = self.cache_cfg.page_size
        prow = np.full((nCb,), -1, np.int32)
        srow = np.full((nCb,), -1, np.int32)
        p_lo = np.zeros((max(n_rows, 0),), np.int32)
        p_hi = np.zeros((max(n_rows, 0),), np.int32)
        s_lo = np.zeros((B,), np.int32)
        s_hi = np.zeros((B,), np.int32)
        span_by_rid = {}
        for slot, rid, lo, hi in spans:
            span_by_rid[rid] = (slot, lo, hi)
            j = rid_prow.get(rid)
            if j is not None and j < n_rows:
                p_lo[j] = lo * ps
                p_hi[j] = hi * ps
            if self._slot_active(slot):
                s_lo[slot] = lo * ps
                s_hi[slot] = hi * ps
        for c, rid in enumerate(staged["owners"]):
            got = span_by_rid.get(rid)
            if got is None:
                continue
            slot = got[0]
            j = rid_prow.get(rid)
            if j is not None and j < n_rows:
                prow[c] = j
            if self._slot_active(slot):
                srow[c] = slot
        if not (prow >= 0).any() and not (srow >= 0).any():
            # nothing in THIS dispatch attends cold history (e.g. an
            # admission wave while another row owns every span) — skip
            # the cold argument so the call keeps its legacy trace
            return None
        self.num_ctx_stream_chunks += len(staged["owners"])
        cold_arg = (
            staged["k"], staged["v"], staged["ks"], staged["vs"],
            jnp.asarray(prow), jnp.asarray(srow),
            jnp.asarray(staged["lens"]),
            jnp.asarray(p_lo), jnp.asarray(p_hi),
            jnp.asarray(s_lo), jnp.asarray(s_hi),
        )
        return cold_arg, nCb, staged["ct"]

    def prefetch_cold(self) -> None:
        """Stage the NEXT dispatch's cold chunks while the current step
        is still in flight: demotion gathers and the slab's ``device_put``
        are async — they enqueue after the dispatched step on the device
        stream, so the H2D traffic overlaps its compute and the next
        ``_ragged_step`` finds the handles already uploaded.  Called by
        ``step()`` / the async loop between dispatch and complete."""
        if not self._tiered:
            return
        for slot in sorted(self._tiered):
            req = self.slots[slot]
            if req is None:
                continue
            led = self._tiered[slot]
            if (
                self._chunking is not None
                and self._chunking.get("slot") == slot
            ):
                written = int(self._chunking["next"])
            else:
                written = int(self._positions[slot])
            self._demote_slot(slot, led, written)
        self._refresh_cold_staged()

    # ------------------------------------------------------------------
    # preemption-by-swap (ISSUE 6)
    # ------------------------------------------------------------------

    def preempt(self, req_id: str) -> bool:
        """Swap a running decoder out to host RAM and park it for exact
        resume: private page contents + the device-evolved sampler state
        (PRNG key stream, output-token histogram) move to the host tier,
        the slot and pages free, and the request joins ``preempted``.

        Shared prefix pages stay in the device prefix cache with their
        refcounts held — they are shared (typically the hot system
        prompt), so swapping them would free nothing for anyone else and
        would break other holders' tables.

        Returns False when the request is not preemptible right now
        (no host tier, unknown/finished/queued request, mid-chunk
        prefill) or the host budget cannot take its pages — the caller
        degrades to the next rung of the ladder (shed)."""
        if self.host_pool is None:
            return False
        req = self._requests.get(req_id)
        if req is None or req.finished or req.slot is None:
            return False
        slot = req.slot
        if not self._slot_active(slot):
            return False   # mid-chunked-prefill: nothing decodable to park
        if slot in self._tiered:
            # a tiered row's cold pages already live in the host pool
            # under ("ctx", ...) keys — swap-out would double-spill and
            # resume could not rebuild the demoted table; shed instead
            return False
        # capture the device-evolving sampler state AFTER making the
        # device copy current — bit-exact resume needs the key stream
        # and penalty histogram exactly where the last step left them
        if self._state_dirty or self._dstate is None:
            self._sync_state()
        key = np.asarray(self._dstate.keys[slot])
        counts = np.asarray(self._dstate.token_counts[slot])
        shared = self._shared_pages.get(req_id, [])
        owned = self.allocator.seq_pages(req_id)
        n_pages = len(owned) + len(shared)
        table = np.array(self._page_tables[slot][:n_pages])
        private = set(owned)
        private_pos = [
            i for i in range(n_pages) if int(table[i]) in private
        ]
        from helix_tpu.engine.kv_cache import gather_pages

        page_ids = [int(table[i]) for i in private_pos]
        arrays = gather_pages(self.cache, page_ids) if page_ids else []
        put_keys = []
        for pos, page_arrays in zip(private_pos, arrays):
            k = ("seq", req_id, pos)
            # pinned: prefix-spill pressure must never evict a parked
            # decoder's pages out from under its resume
            if not self.host_pool.put(k, page_arrays, pinned=True):
                for kk in put_keys:   # roll back: preemption is atomic
                    self.host_pool.discard(kk)
                return False
            put_keys.append(k)
        self.preempted.append(
            PreemptedSeq(
                req=req,
                table=table,
                private_pos=private_pos,
                position=int(self._positions[slot]),
                last_token=int(self._last_token[slot]),
                mrope_delta=int(self._mrope_delta[slot]),
                key=key,
                counts=counts,
            )
        )
        if self.allocator.owns(req_id):
            self.allocator.free(req_id)
        self.slots[slot] = None
        req.slot = None
        self._state_dirty = True
        self._changed_slots.add(slot)
        self.num_preemptions += 1
        logging.getLogger(__name__).info(
            "preempted request %s: %d private page(s) swapped to host, "
            "%d shared prefix page(s) kept resident",
            req_id, len(private_pos), len(shared),
        )
        return True

    def preempt_for_pressure(self) -> Optional[str]:
        """Pick and preempt the degradation-ladder victim.

        With a ``victim_policy`` wired (the scheduler's ladder: lowest
        class, then most-over-fair-share tenant, then newest) the
        policy's preference order is walked; otherwise the builtin pick
        applies — the NEWEST admission (least sunk decode work),
        breaking ties toward the largest page footprint (frees the most
        for the starved queue).  Requests already swapped twice are
        exempt — bounded thrash.  Returns the preempted request id, or
        None."""
        cands = [
            (req, i)
            for i, req in enumerate(self.slots)
            if req is not None
            and self._slot_active(i)
            and req.preempt_count < 2
        ]
        if self.victim_policy is not None and cands:
            try:
                ordered = list(
                    self.victim_policy([req for req, _i in cands])
                )
            except Exception:  # noqa: BLE001 — a policy bug degrades, not kills
                logging.getLogger(__name__).exception(
                    "victim_policy failed; falling back to builtin pick"
                )
                ordered = []
            for req in ordered:
                if req.finished or req.slot is None:
                    continue
                if self.preempt(req.id):
                    req.preempt_count += 1
                    return req.id
            if ordered:
                return None   # the policy's candidates all declined
        while cands:
            req, i = max(
                cands,
                key=lambda c: (
                    c[0].admitted_time or 0.0,
                    len(self.allocator.seq_pages(c[0].id))
                    + len(self._shared_pages.get(c[0].id, ())),
                ),
            )
            if self.preempt(req.id):
                req.preempt_count += 1
                return req.id
            cands.remove((req, i))
        return None

    # ------------------------------------------------------------------
    # portable request snapshots (ISSUE 11)
    # ------------------------------------------------------------------

    def _snapshot_pages(self, table, n_pages: int, private_pos=None,
                        req_id: str = "") -> Optional[tuple]:
        """Gather the sequence's pages as host numpy dicts, in table
        order, with their stored-representation checksums.  Pages listed
        in ``private_pos`` are read from the host pool (a parked
        decoder's swapped-out pages — already spilled, verified at get);
        everything else gathers from the device pool.  Returns
        (pages, checksums) or None when a host copy failed verification
        (the caller degrades to shed — never exports wrong KV)."""
        from helix_tpu.engine.kv_cache import gather_pages, page_checksum

        private = set(private_pos or ())
        device_pos = [i for i in range(n_pages) if i not in private]
        gathered = {}
        if device_pos:
            page_ids = [int(table[i]) for i in device_pos]
            arrays = gather_pages(self.cache, page_ids)
            for pos, page_arrays in zip(device_pos, arrays):
                gathered[pos] = {
                    f: (None if a is None else np.asarray(a))
                    for f, a in page_arrays.items()
                }
        for pos in sorted(private):
            host = self.host_pool.get(("seq", req_id, pos))
            if host is None:   # corrupt or evicted: cannot export exactly
                return None
            gathered[pos] = host
        pages = [gathered[i] for i in range(n_pages)]
        checksums = [page_checksum(p).hex() for p in pages]
        return pages, checksums

    def _snapshot_base(self, req: Request) -> dict:
        return {
            "version": SNAPSHOT_VERSION,
            "model": self.model_cfg.name,
            "request_id": req.id,
            "prompt_tokens": [int(t) for t in req.prompt_tokens],
            "output_tokens": [int(t) for t in req.output_tokens],
            "sampling": dataclasses.asdict(req.sampling),
            "stop_token_ids": [int(t) for t in req.stop_token_ids],
            "tenant": req.tenant,
            "trace_id": req.trace_id,
            "sched_class": req.sched_class,
            "adapter": getattr(req, "adapter", ""),
            "max_len": req.max_len,
            "preempt_count": req.preempt_count,
            "page_size": self.cache_cfg.page_size,
            "num_layers": self.model_cfg.num_layers,
            "kv_heads": self.model_cfg.num_kv_heads,
            "head_dim": self.model_cfg.head_dim,
            "kv_dtype": self.cache_cfg.dtype,
        }

    def export_request(self, req_id: str) -> Optional[RequestSnapshot]:
        """Build a portable snapshot of one live request (engine thread).

        Three shapes, mirroring where the request is in its life:

        - **decoding in a slot**: full KV export — the device-evolved
          sampler state is captured via the PR 6 preempt recipe (sync
          the device copy, read the slot's key + penalty histogram) and
          every table page gathers to host with a checksum;
        - **parked preempted**: private pages come from the host pool
          (verified), shared prefix pages from the device;
        - **queued / mid-chunk-prefill**: no KV state — the snapshot
          replays from the prompt on the peer (no token was emitted
          yet, so exactly-once delivery holds trivially).

        Returns None for requests that cannot be exported (unknown,
        finished, VL — image embeds are device arrays bound to this
        runner — or a parked page that failed verification).  The caller
        owns the request's local teardown; export itself mutates
        nothing."""
        req = self._requests.get(req_id)
        if req is None or req.finished:
            return None
        if req.image_embeds is not None or req.positions3 is not None:
            return None   # VL requests pin device-resident image state
        if req.slot is not None and req.slot in self._tiered:
            # tiered rows have demoted pages only this engine's host
            # pool holds — a snapshot gathered from the device table
            # would carry holes; migration of cold-middle rows is out
            # of scope (the caller degrades to shed/replay)
            return None
        base = self._snapshot_base(req)
        parked = next(
            (st for st in self.preempted if st.req is req), None
        )
        if parked is not None:
            if parked.entries is not None:
                # imported-and-not-yet-resumed: the verified pages are
                # already inline (every table position is private)
                from helix_tpu.engine.kv_cache import page_checksum

                pages = list(parked.entries)
                checksums = [page_checksum(p).hex() for p in pages]
            else:
                snapped = self._snapshot_pages(
                    parked.table, len(parked.table),
                    private_pos=parked.private_pos, req_id=req.id,
                )
                if snapped is None:
                    return None
                pages, checksums = snapped
            base["total_pages"] = len(parked.table)
            counts = parked.counts
            state = dict(
                position=int(parked.position),
                last_token=int(parked.last_token),
                mrope_delta=int(parked.mrope_delta),
                key=[int(parked.key[0]), int(parked.key[1])],
            )
        elif req.slot is not None and self._slot_active(req.slot):
            slot = req.slot
            # capture the device-evolving sampler state AFTER making the
            # device copy current — the same bit-exactness rule as
            # ``preempt``: the key stream and penalty histogram must be
            # exactly where the last step left them
            if self._state_dirty or self._dstate is None:
                self._sync_state()
            key = np.asarray(self._dstate.keys[slot])
            counts = np.asarray(self._dstate.token_counts[slot])
            n_alloc = len(self.allocator.seq_pages(req.id)) + len(
                self._shared_pages.get(req.id, ())
            )
            # ship only pages holding WRITTEN KV (token slots
            # 0..num_tokens-2 — the newest token's KV lands during the
            # NEXT step): admission allocated capacity for max_tokens up
            # front, and shipping that mostly-uninitialized tail would
            # scale the wire bytes with the budget, not the progress
            ps = self.cache_cfg.page_size
            n_resident = min(n_alloc, -(-req.num_tokens // ps))
            snapped = self._snapshot_pages(
                self._page_tables[slot], n_resident
            )
            if snapped is None:
                return None
            pages, checksums = snapped
            base["total_pages"] = n_alloc
            state = dict(
                position=int(self._positions[slot]),
                last_token=int(self._last_token[slot]),
                mrope_delta=int(self._mrope_delta[slot]),
                key=[int(key[0]), int(key[1])],
            )
        else:
            # queued, or mid-chunk prefill (partial KV is not worth
            # shipping: no token emitted, replay is exact by definition)
            base["output_tokens"] = []
            pages, checksums, counts = [], [], None
            state = dict(
                position=None, last_token=None, mrope_delta=0, key=None,
            )
        sparse: dict = {}
        if counts is not None:
            nz = np.nonzero(counts)[0]
            sparse = {int(i): int(counts[i]) for i in nz}
        self.num_snapshots_exported += 1
        return RequestSnapshot(
            **base, **state, token_counts=sparse,
            pages=pages, page_checksums=checksums,
        )

    def export_prefill(self, req_id: str) -> Optional[RequestSnapshot]:
        """Disaggregated prefill/decode handoff (ISSUE 14): snapshot a
        request as soon as its prefill has completed — the first token
        is sampled and every prompt page holds written KV — so a
        decode-pool peer can import it (``import_request``'s
        validate-checksums-before-mutation path) and continue the
        generation bit-identically as an ordinary admission wave.

        Ships *before* meaningful decode happens: the caller invokes
        this the moment output tokens exist.  Refuses requests whose
        prefill has not finished (nothing to hand off — the peer
        replaying from the prompt would be cheaper than shipping) and
        requests whose export would carry no KV.  Export itself mutates
        nothing; the caller tears the local request down only after the
        ship is CONFIRMED, so a failed transfer degrades to local
        decode — never a lost request."""
        req = self._requests.get(req_id)
        if req is None or req.finished or not req.output_tokens:
            return None
        snap = self.export_request(req_id)
        if snap is None or not snap.has_kv:
            return None
        self.num_prefill_exports += 1
        return snap

    def import_request(self, snap: RequestSnapshot) -> Request:
        """Re-admit a snapshot on this engine (engine thread).

        Validation is strictly BEFORE mutation: version, KV geometry
        (page size / layers / heads / head dim / storage dtype must
        match — bit-identical continuation is the contract, not
        best-effort), then EVERY page checksum against the stored
        representation.  Only then does the request enter the engine —
        KV-carrying snapshots park on the ``preempted`` list with their
        verified pages INLINE and re-admit through ``_try_resume`` as a
        plain admission wave (exactly the PR 6 local-resume path, so the
        continuation is bit-identical); plain snapshots join the wait
        queue like any fresh request.  Raises ``SnapshotError`` (typed)
        without touching allocator or queue state on any failure."""
        if snap.version != SNAPSHOT_VERSION:
            raise SnapshotError(
                f"snapshot version {snap.version} != engine version "
                f"{SNAPSHOT_VERSION}",
                code="snapshot_unsupported",
            )
        existing = self._requests.get(snap.request_id)
        if existing is not None and not existing.finished:
            raise SnapshotError(
                f"request {snap.request_id!r} is already live here",
                code="snapshot_duplicate",
            )
        samp = dict(snap.sampling)
        samp["stop"] = tuple(samp.get("stop", ()) or ())
        req = Request(
            id=snap.request_id,
            prompt_tokens=list(snap.prompt_tokens),
            sampling=SamplingParams(**samp),
            stop_token_ids=tuple(snap.stop_token_ids),
            output_tokens=list(snap.output_tokens),
            trace_id=snap.trace_id,
            tenant=snap.tenant,
            sched_class=snap.sched_class,
            adapter=getattr(snap, "adapter", "") or "",
            preempt_count=int(snap.preempt_count),
        )
        err = self.validate_request(req)
        if err:
            raise SnapshotError(err, code="snapshot_invalid")
        if not snap.has_kv:
            if snap.output_tokens:
                raise SnapshotError(
                    "snapshot carries emitted tokens but no KV state — "
                    "it cannot be continued exactly",
                    code="snapshot_corrupt",
                )
            self._requests[req.id] = req
            self.waiting.append(req)
            self.num_snapshots_imported += 1
            return req
        cc = self.cache_cfg
        geometry = (
            ("page_size", snap.page_size, cc.page_size),
            ("num_layers", snap.num_layers, self.model_cfg.num_layers),
            ("kv_heads", snap.kv_heads, self.model_cfg.num_kv_heads),
            ("head_dim", snap.head_dim, self.model_cfg.head_dim),
            ("kv_dtype", snap.kv_dtype, cc.dtype),
        )
        for field, theirs, ours in geometry:
            if theirs != ours:
                raise SnapshotError(
                    f"KV geometry mismatch on {field}: snapshot has "
                    f"{theirs!r}, this engine has {ours!r}",
                    code="snapshot_incompatible",
                )
        n = len(snap.pages)
        if n != len(snap.page_checksums) or n == 0:
            raise SnapshotError(
                "page/checksum count mismatch", code="snapshot_corrupt"
            )
        n_total = max(n, int(snap.total_pages or n))
        if n_total > cc.max_pages_per_seq or n_total > cc.num_pages - 1:
            raise SnapshotError(
                f"snapshot needs {n_total} pages; this engine caps a "
                f"sequence at "
                f"{min(cc.max_pages_per_seq, cc.num_pages - 1)}",
                code="snapshot_incompatible",
            )
        # the shipped pages must COVER every written KV slot (tokens
        # 0..num_tokens-2): fewer means the continuation would attend
        # garbage — refuse rather than diverge
        written = max(0, len(req.prompt_tokens) + len(req.output_tokens) - 1)
        if n * cc.page_size < written:
            raise SnapshotError(
                f"{n} shipped page(s) cannot cover {written} written "
                "KV slot(s)",
                code="snapshot_corrupt",
            )
        from helix_tpu.engine.kv_cache import page_checksum

        quantized = cc.quantized
        kshape = (
            self.model_cfg.num_layers, cc.page_size,
            self.model_cfg.num_kv_heads, self.model_cfg.head_dim,
        )
        entries = []
        for arrays, digest in zip(snap.pages, snap.page_checksums):
            entry = {
                f: arrays.get(f)
                for f in ("k", "v", "k_scale", "v_scale")
            }
            if entry["k"] is None or entry["v"] is None:
                raise SnapshotError(
                    "page missing k/v buffers", code="snapshot_corrupt"
                )
            if tuple(entry["k"].shape) != kshape:
                raise SnapshotError(
                    f"page shape {tuple(entry['k'].shape)} != pool page "
                    f"shape {kshape}",
                    code="snapshot_incompatible",
                )
            if quantized != (entry["k_scale"] is not None):
                raise SnapshotError(
                    "snapshot storage mode does not match the pool "
                    "(int8 scales present/absent)",
                    code="snapshot_incompatible",
                )
            if page_checksum(entry).hex() != digest:
                raise SnapshotError(
                    "page failed checksum verification — refusing to "
                    "restore corrupt KV",
                    code="snapshot_corrupt",
                )
            entries.append(entry)
        V = self.model_cfg.vocab_size
        counts = np.zeros((V,), np.int32)
        for tok, cnt in snap.token_counts.items():
            t = int(tok)
            if not 0 <= t < V:
                raise SnapshotError(
                    f"histogram token id {t} outside vocab {V}",
                    code="snapshot_incompatible",
                )
            counts[t] = int(cnt)
        if snap.key is None or len(snap.key) != 2:
            raise SnapshotError(
                "missing sampler key", code="snapshot_corrupt"
            )
        limit = min(n_total * cc.page_size, self.max_context_len)
        req.max_len = min(int(snap.max_len or limit), limit)
        req.cached_tokens = 0
        st = PreemptedSeq(
            req=req,
            table=np.zeros((n_total,), np.int32),  # rewritten at resume
            private_pos=list(range(n_total)),
            position=int(snap.position),
            last_token=int(snap.last_token),
            mrope_delta=int(snap.mrope_delta),
            key=np.asarray(snap.key, np.uint32),
            counts=counts,
            entries=entries,
        )
        self._requests[req.id] = req
        self.preempted.append(st)
        self.num_snapshots_imported += 1
        return req

    def _discard_preempted(self, st: PreemptedSeq) -> None:
        st.entries = None
        if self.host_pool is None:
            return   # imported-snapshot park: nothing lives in the pool
        for pos in st.private_pos:
            self.host_pool.discard(("seq", st.req.id, pos))

    def _try_resume(self) -> None:
        """Swap parked decoders back in, FIFO, while a slot + pages are
        available.  Restored pages are bit-identical to what was spilled
        (checksummed both ways), the PRNG key and penalty histogram
        rejoin the device state exactly, so a greedy or seeded
        continuation matches an unpreempted run token for token."""
        while self.preempted:
            st = self.preempted[0]
            req = st.req
            if req.finished:   # aborted while parked
                self.preempted.pop(0)
                self._discard_preempted(st)
                continue
            if self._plan_drive is not None:
                # follower: resume exactly the requests the leader
                # resumed, in plan order — local slot/page headroom may
                # transiently differ mid-plan and must not decide
                drv = self._plan_drive.resumes
                if not drv or drv[0] != req.id:
                    return
            free_slots = [
                i for i, s in enumerate(self.slots) if s is None
            ]
            n_private = len(st.private_pos)
            # _ensure_pages, not bare can_allocate: refcount-0 prefix
            # cache pages must LRU-evict (spilling to the host tier when
            # armed) for a parked resume exactly as they do for a fresh
            # admission — otherwise a pool whose free list is mostly
            # cache-owned wedges every parked/imported request
            if not free_slots or not self._ensure_pages(n_private):
                return
            resume_adapter = 0
            if getattr(req, "adapter", ""):
                # ordinary preemptions keep their adapter ref parked
                # (idempotent re-acquire); imported snapshots pin it
                # here — a cold adapter keeps the park FIFO waiting
                # while the prefetch overlaps (never blocks the step)
                got = self._acquire_adapter(req)
                if got is None:
                    self._adapter_ready(req)   # (re-)kick the prefetch
                    return
                resume_adapter = got
            # claim + verify every host copy BEFORE touching allocator
            # state: a corrupt page means the sequence cannot be
            # reconstructed bit-exactly — fail the request loudly, never
            # resume wrong KV.  One pass (checksum verified inside
            # take_restored); a mid-chain failure aborts the whole
            # resume, so a None can never reach restore_pages.
            # Imported snapshots (ISSUE 11) carry their pages INLINE,
            # verified once at import — no pool round trip.
            t0 = time.monotonic()
            if st.entries is not None:
                entries = st.entries
            else:
                entries = []
                for pos in st.private_pos:
                    e = self.host_pool.take_restored(("seq", req.id, pos))
                    if e is None:
                        break
                    entries.append(e)
            if st.entries is None and len(entries) != n_private:
                self.preempted.pop(0)
                self._discard_preempted(st)
                self._resume_failures.append(
                    (
                        req,
                        "kv_restore_corrupt: a swapped-out page failed "
                        "checksum verification on resume",
                    )
                )
                self._finish(req, FinishReason.ABORT)
                continue
            new_pages = self.allocator.allocate(req.id, n_private)
            from helix_tpu.engine.kv_cache import restore_pages

            # imported snapshots ship only the WRITTEN head of the
            # table; the tail pages just allocated stay as-is (their
            # contents are overwritten before they are ever attended)
            self.cache = restore_pages(
                self.cache, new_pages[: len(entries)], entries
            )
            st.entries = None   # inline page buffers are on device now
            table = np.array(st.table)
            for pos, pg in zip(st.private_pos, new_pages):
                table[pos] = pg
            slot = free_slots[0]
            self.slots[slot] = req
            req.slot = slot
            self._slot_adapters[slot] = resume_adapter
            row = np.zeros((self.cache_cfg.max_pages_per_seq,), np.int32)
            row[: len(table)] = table
            self._page_tables[slot] = row
            self._positions[slot] = st.position
            self._last_token[slot] = st.last_token
            self._mrope_delta[slot] = st.mrope_delta
            # the evolved key re-enters through the host mirror (the
            # changed-slot rebuild takes keys from it); the histogram
            # needs the explicit device override applied at next sync
            self._slot_keys[slot] = st.key
            self._slot_count_overrides[slot] = st.counts
            self._state_dirty = True
            self._changed_slots.add(slot)
            self.num_resumes += 1
            if self._plan_recorder is not None:
                self._plan_recorder.resumes.append(req.id)
            if self._plan_drive is not None:
                self._plan_drive.resumes.pop(0)
            self.restore_seconds += time.monotonic() - t0
            self.preempted.pop(0)
            logging.getLogger(__name__).info(
                "resumed request %s into slot %d (%d page(s) restored)",
                req.id, slot, n_private,
            )

    def drain_resume_failures(self) -> list:
        """(request, reason) pairs for resumes that failed verification —
        the engine loop turns them into typed client error events."""
        out, self._resume_failures = self._resume_failures, []
        return out

    # ------------------------------------------------------------------
    # speculative decoding (engine/spec.py + the unified ragged step)
    # ------------------------------------------------------------------

    @property
    def spec_acceptance_ratio(self) -> float:
        """Lifetime accepted/drafted ratio (0.0 before any draft)."""
        d = self.num_spec_drafted_tokens
        return self.num_spec_accepted_tokens / d if d else 0.0

    def spec_disabled_slots(self) -> int:
        """Live requests currently EMA-disabled from speculating."""
        return self.spec.disabled_count() if self.spec is not None else 0

    def _spec_width(self) -> int:
        """State-segment token width: spec_tokens + 1 (the bonus
        position) when speculation is on, 1 otherwise — EXACT on every
        backend.  The ragged kernel tiles 8-token query blocks
        internally, so pallas no longer buckets the verify width up to a
        page_size multiple (pre-unification a k=4 draft padded every
        verify call to 16 positions at page_size 16), and the history
        length is a per-row runtime value rather than a compile-shape
        bucket."""
        return 1 if self.spec is None else self.cfg.spec_tokens + 1

    def _spec_extra_steps(self) -> int:
        """Fused-window tail for a verify call: plain decode steps
        scanned onto the rolled-back state inside the same jit, so a
        spec sync never yields fewer tokens per host round trip than
        the plain window would have.  Starts from ``_decode_window()``
        (which owns the chunking/adaptive-streaming/queued-work gates)
        and shrinks while any active slot lacks headroom for the worst
        case: ``spec_tokens + 1`` verify positions plus the tail."""
        n = self._decode_window()
        if n <= 1:
            return 0
        k1 = self.cfg.spec_tokens + 1
        table_cap = (
            self.cache_cfg.max_pages_per_seq * self.cache_cfg.page_size
        )
        for i, req in enumerate(self.slots):
            if req is None or not self._slot_active(i):
                continue
            pend = self._pending_out(req)
            h = min(
                req.sampling.max_tokens - len(req.output_tokens) - pend,
                (req.max_len or self.cache_cfg.max_seq_len)
                - req.num_tokens - pend,
                table_cap - int(self._positions[i]),
            )
            while n > 1 and k1 + n - 1 > h:
                n //= 2
            if n <= 1:
                return 0
        return n - 1

    def _spec_dispatch(self) -> Optional[PendingStep]:
        """One speculative decode step: draft per slot on the host, then
        verify every slot's drafts in ONE device call.  Returns None
        when no slot drafted anything (the caller then runs the plain
        fused-window decode — speculation never makes a step slower than
        the baseline path, it only substitutes for it)."""
        k = self.cfg.spec_tokens
        ps = self.cache_cfg.page_size
        B = self.cfg.max_decode_batch
        width = self._spec_width()
        table_cap = self.cache_cfg.max_pages_per_seq * ps
        drafts = np.zeros((B, width - 1), np.int32)
        draft_len = np.zeros((B,), np.int32)
        if self._plan_drive is not None:
            # follower: drafts are DATA from the leader's plan — the
            # local drafter (whose n-gram history and EMA gating are
            # host state) never runs, so the verify call is built from
            # the exact tokens the leader verified
            for slot, toks in self._plan_drive.drafts:
                drafts[slot, : len(toks)] = toks
                draft_len[slot] = len(toks)
            if not draft_len.any():
                return None
            return self._spec_dispatch_tail(drafts, draft_len)
        for i, req in enumerate(self.slots):
            if req is None or not self._slot_active(i):
                continue
            if req.id in self._pending_first_ids:
                # deferred chunk-final first token: the host-visible
                # sequence lags the device by one token, so a draft
                # would condition on the wrong suffix — sit this call
                # out (the verify would just reject it anyway)
                continue
            pos = int(self._positions[i])
            # headroom: the verify call writes KV for pos..pos+L, so the
            # draft must fit the slot's allocated pages (max_len) and is
            # not worth proposing past the remaining token budget
            pend = self._pending_out(req)
            budget = (
                req.sampling.max_tokens - len(req.output_tokens) - pend
            )
            room = (
                (req.max_len or self.cache_cfg.max_seq_len)
                - req.num_tokens - pend
            )
            cap = min(k, budget - 1, room - 1, table_cap - pos - 1)
            if cap <= 0:
                continue
            toks = self.spec.draft(
                req.id, req.prompt_tokens + req.output_tokens, cap
            )
            if not toks:
                continue
            # Stale-KV safety invariant: drafted (possibly rejected) KV
            # lands only in the slot's PRIVATE page tail past the last
            # prompt token — the prefix cache shares only full pages
            # strictly below it, so a rejected draft can never corrupt
            # KV another request reads.  Rollback is then just resetting
            # host length + DecodeState; the next step overwrites the
            # same (page, offset) slots.
            plen = len(req.prompt_tokens)
            n_shared = len(self._shared_pages.get(req.id, ()))
            assert pos >= plen and n_shared * ps <= max(plen - 1, 0), (
                f"speculative KV write would touch shared pages: slot "
                f"{i} at position {pos}, prompt {plen} tokens, "
                f"{n_shared} shared pages of {ps}"
            )
            drafts[i, : len(toks)] = toks
            draft_len[i] = len(toks)
        if not draft_len.any():
            return None
        if self._plan_recorder is not None:
            self._plan_recorder.drafts = [
                (i, [int(t) for t in drafts[i, : int(draft_len[i])]])
                for i in range(B) if draft_len[i] > 0
            ]
        return self._spec_dispatch_tail(drafts, draft_len)

    def _spec_dispatch_tail(self, drafts, draft_len) -> PendingStep:
        """The device half of a spec step: identical for a leader's
        host-drafted tokens and a follower's plan-carried ones."""
        rows = [
            (i, r) for i, r in enumerate(self.slots)
            if r is not None and self._slot_active(i)
        ]
        n_extra = self._spec_extra_steps()
        _, sampled, emit, extra, _ = self._ragged_step(
            drafts=drafts, draft_len=draft_len, n_extra=n_extra,
        )
        self.num_spec_steps += 1
        # ONE device call for verify + the fused-window tail: with
        # accepted drafts, decode_tokens / device_steps exceeds 1 per
        # slot — that ratio IS the speculation win (tokens per forward)
        self.num_decode_device_steps += 1 + n_extra
        return PendingStep(
            kind="spec", rows=rows, handles=(sampled, emit, extra),
            n_extra=n_extra, draft_len=draft_len,
            pending_first=self._take_pending_first(),
        )

    def _spec_complete(self, p: PendingStep, emitted) -> None:
        sampled, emit, extra = p.handles
        firsts = tuple(tok for _r, tok in p.pending_first)
        fetched = jax.device_get((sampled, emit, extra) + firsts)
        sampled_np, emit_np, extra_np = fetched[0], fetched[1], fetched[2]
        if p.pending_first:
            for (req, _h), tok_np in zip(p.pending_first, fetched[3:]):
                self._finish_first_emit(req, int(tok_np[0]), emitted)
            self._drain_moe_drops()   # the fetch above synced the device
        draft_len = p.draft_len
        for i, req in p.rows:
            if self.slots[i] is not req:
                continue
            e = int(emit_np[i])
            L = int(draft_len[i])
            if L:
                acc = min(e - 1, L)
                self.num_spec_drafted_tokens += L
                self.num_spec_accepted_tokens += acc
                self.spec.observe(req.id, L, acc)
            for j in range(e):
                if self.slots[i] is not req or req.finished:
                    break   # finished mid-verify: discard the overrun
                self._positions[i] += 1
                self._last_token[i] = sampled_np[i, j]
                self.num_decode_tokens += 1
                self._emit(req, int(sampled_np[i, j]), emitted)
        # fused-window tail tokens (same contract as the plain decode
        # window: finished slots discard the overrun)
        for s in range(p.n_extra):
            for i, req in p.rows:
                if self.slots[i] is not req or req.finished:
                    continue
                self._positions[i] += 1
                self._last_token[i] = extra_np[s, i]
                self.num_decode_tokens += 1
                self._emit(req, int(extra_np[s, i]), emitted)

    def _decode_dispatch(self) -> PendingStep:
        n = self._decode_window()
        # Headroom invariant, checked loudly on host: the KV write clamps
        # its page-table index, so a slot whose position can reach table
        # capacity inside this window would silently corrupt offset 0 of
        # its last page instead of failing (ADVICE r3).  The window logic
        # above must make this impossible; verify it.
        table_cap = self.cache_cfg.max_pages_per_seq * self.cache_cfg.page_size
        for i in range(len(self.slots)):
            if self._slot_active(i) and self._positions[i] + n > table_cap:
                raise RuntimeError(
                    f"decode window overruns page-table capacity: slot {i} "
                    f"at position {self._positions[i]} + {n} steps > "
                    f"{table_cap} — headroom invariant violated"
                )
        rows = [
            (i, r) for i, r in enumerate(self.slots)
            if r is not None and self._slot_active(i)
        ]
        # plain decode IS the unified step with zero drafts: position 0
        # of each active row samples this step's token, and the fused
        # tail advances the remaining n-1 window steps in the same jit
        _, sampled, _, extra, _ = self._ragged_step(
            draft_len=self._zero_rows, n_extra=n - 1,
        )
        self.num_decode_device_steps += n
        # Predicted-state advance: the DEVICE moves every dispatched row
        # forward by the full window whether or not the host later
        # discards an overrun, so the position mirror advances at
        # dispatch — this is what lets the async loop build step N+1's
        # metadata before step N's tokens are on host.  Completion only
        # fetches, emits and applies stop conditions.
        for i, r in rows:
            self._positions[i] += n
            self._inflight_out[r.id] = self._inflight_out.get(r.id, 0) + n
        return PendingStep(
            kind="decode", rows=rows, handles=(sampled, extra), n=n,
            pending_first=self._take_pending_first(),
        )

    def _decode_complete(self, p: PendingStep, emitted) -> None:
        sampled, extra = p.handles
        firsts = tuple(tok for _r, tok in p.pending_first)
        fetched = jax.device_get((sampled, extra) + firsts)
        sampled_np, extra_np = fetched[0], fetched[1]
        if p.pending_first:
            # deferred chunk-final first tokens land in the SAME host
            # round trip as the decode window (ISSUE 13 satellite)
            for (req, _h), tok_np in zip(p.pending_first, fetched[2:]):
                self._finish_first_emit(req, int(tok_np[0]), emitted)
            self._drain_moe_drops()   # the fetch above synced the device
        for _i, r in p.rows:
            left = self._inflight_out.get(r.id, 0) - p.n
            if left > 0:
                self._inflight_out[r.id] = left
            else:
                self._inflight_out.pop(r.id, None)
        for i, r in p.rows:
            if self.slots[i] is not r or r.finished:
                continue  # finished/evicted mid-flight: discard the overrun
            self._last_token[i] = sampled_np[i, 0]
            self.num_decode_tokens += 1
            self._emit(r, int(sampled_np[i, 0]), emitted)
        for s in range(p.n - 1):
            for i, r in p.rows:
                if self.slots[i] is not r or r.finished:
                    continue
                self._last_token[i] = extra_np[s, i]
                self.num_decode_tokens += 1
                self._emit(r, int(extra_np[s, i]), emitted)

    # ------------------------------------------------------------------
    # the unified ragged device step (ISSUE 10)
    # ------------------------------------------------------------------

    def _charge_padding(self, bucket: int, used: int) -> None:
        """THE padding formula: every prefill caller rounds its token
        axis up to a compile bucket, and the difference is forward-pass
        work spent on zeros.  One site (plus the VL single-shot path)
        so ``helix_prefill_padding_*`` can never drift between
        callers."""
        self.num_prefill_padding_tokens += max(0, int(bucket) - int(used))

    @property
    def compiled_step_shapes(self) -> int:
        """Distinct compiled device-step entry points live for this
        model (unified ragged shapes + VL prefill buckets), from the
        module-level registry — the shape-zoo collapse, observable."""
        return ragged_meta.compiled_step_shapes(self._shape_key)

    def _ragged_step(self, plan=None, drafts=None, draft_len=None,
                     n_extra: int = 0):
        """Issue ONE unified device step: the optional prefill plan's
        ragged rows + the decode-state segment (+ a fused plain-decode
        tail of ``n_extra`` steps).  Every device-step caller routes
        here; the compiled entry point is keyed only on the prefill
        token-bucket (plus the has-history / row-capacity variants the
        plan implies).  Returns ``(p_first, sampled, emit, extra,
        drops)`` device handles."""
        if self._tiered:
            # tiered rows: demote pages behind the hot tail, then grow
            # tables to cover this step's writes — BEFORE the state sync
            # so the uploaded mirrors carry the post-demotion tables.
            # Plan rows alias the same table ndarrays (plan.add stores
            # np.asarray(table)), so mutations land in already-built
            # plans before finalize_device below reads them.
            self._tiered_prep(n_extra)
        if self._state_dirty or self._dstate is None:
            self._sync_state()
        if drafts is None:
            drafts = self._zero_drafts
        if draft_len is None:
            draft_len = self._inert_rows
        pool_slots = (
            self.adapter_pool.slots if self.adapter_pool is not None
            else 0
        )
        if plan is not None and plan.rows:
            rung = bucket_tokens(plan.used, self._token_ladder)
            self._charge_padding(rung, plan.used)
            # host->device conversion happens HERE, at dispatch time:
            # under the async loop this step's metadata uploads overlap
            # the previous step's device execution (double-buffered
            # metadata — jax issues the transfers asynchronously)
            a = plan.finalize_device(rung)
            sampling = SamplingState.from_params(
                [r.sampling for r in plan.rows]
                + [SamplingParams()] * (plan.max_rows - len(plan.rows))
            )
            pargs = (
                a["tokens"], a["pos"], a["seg"], a["pages"],
                a["offsets"], a["t0"], a["qlen"], a["hist"],
                a["tables"], a["ends"], sampling, a["keys"],
            )
            if pool_slots:
                # one more per-row metadata column: each token's
                # adapter pool slot (0 = identity)
                pargs = pargs + (a["aids"],)
            rows = plan.max_rows
            has_hist = plan.has_hist
        else:
            rung, rows, has_hist, pargs = 0, 0, False, ()
        ring_hist = 0
        if rows == 1 and _mesh_sp(self.mesh) > 1:
            # ring chunks gather a STATIC pow2-bucketed history capacity
            # (smallest pow2 multiple of the chunk cap covering the
            # start — the pre-unification chunk scheme), so the ring
            # payload scales with actual history, not max context
            start = max((r.start for r in plan.rows), default=0)
            if start > 0:
                hist_tokens = self.cfg.max_prefill_len
                while hist_tokens < start:
                    hist_tokens *= 2
                ring_hist = min(
                    hist_tokens // self.cache_cfg.page_size,
                    self.cache_cfg.max_pages_per_seq,
                )
        cold_arg = None
        cold_chunks = 0
        cold_ct = 0
        if self._tiered:
            staged = self._refresh_cold_staged()
            if staged is not None:
                bound = self._finalize_cold(staged, plan, rows)
                if bound is not None:
                    cold_arg, cold_chunks, cold_ct = bound
        fn = _build_ragged_step_fn(
            self.model_cfg, self.cache_cfg.page_size, self._backend,
            self.mesh, rung, has_hist, rows, self._spec_width(),
            self._n_tail_max, ring_hist, pool_slots,
            cold_chunks, cold_ct,
        )
        self.num_device_calls += 1
        self._note_adapter_rows(plan, draft_len)
        (self.cache, self._dstate, p_first, sampled, emit, extra,
         drops) = fn(
            self._graft_params(), self.cache, self._dstate, pargs,
            jnp.asarray(drafts), jnp.asarray(draft_len),
            jnp.int32(n_extra), cold_arg,
        )
        return p_first, sampled, emit, extra, drops

    # ------------------------------------------------------------------
    # completion
    # ------------------------------------------------------------------

    def _emit(self, req: Request, token: int, emitted: list) -> None:
        req.output_tokens.append(token)
        self.num_generated_tokens += 1
        emitted.append((req, token))
        stop_ids = set(req.stop_token_ids) | set(self.cfg.eos_token_ids)
        if token in stop_ids:
            self._finish(req, FinishReason.STOP)
        elif len(req.output_tokens) >= req.sampling.max_tokens:
            self._finish(req, FinishReason.LENGTH)
        elif req.num_tokens >= (req.max_len or self.cache_cfg.max_seq_len):
            self._finish(req, FinishReason.LENGTH)

    def _adopt_prompt_pages(self, req: Request, table) -> None:
        """After a prompt is fully resident, hand its fresh full pages to
        the prefix cache so the next request with the same prefix skips
        them.  Pages acquired FROM the cache are already shared; only the
        newly prefilled full pages transfer ownership (detached from the
        allocator so request teardown can't free them out from under a
        future sharer)."""
        if req.slot is not None and req.slot in self._tiered:
            # tiered tables grow lazily and demote — prompt pages may
            # already be host-resident, so neither prefix adoption nor
            # filestore write-through can gather them from the device
            return
        if self.prefix_cache is None:
            return
        hashes = self._prompt_hashes(req)
        if not hashes:
            return
        ps = self.cache_cfg.page_size
        k_shared = req.cached_tokens // ps
        fresh_hashes = hashes[k_shared:]
        if not fresh_hashes:
            return
        fresh_pages = [
            int(table[i]) for i in range(k_shared, len(hashes))
        ]
        adopted = self.prefix_cache.adopt(fresh_hashes, fresh_pages)
        if adopted:
            self.allocator.detach(req.id, adopted)
            # the request keeps USING them (refcount 1 held on its
            # behalf); release on finish
            self._shared_pages.setdefault(req.id, []).extend(adopted)
        if self.kv_filestore is not None:
            # write-through to the persistent rung (ISSUE 14): freshly
            # prefilled full pages persist so a restarted process (or a
            # brand-new decode-pool runner on the shared filesystem)
            # serves this prefix without recomputing it.  Quota'd per
            # tenant; a rejected write is a counter, never an error.
            self._store_filestore_pages(req, fresh_hashes, fresh_pages)

    def _store_filestore_pages(
        self, req: Request, hashes: list, pages: list
    ) -> None:
        """Persist freshly prefilled full prefix pages to the filestore
        tier.  One device gather for the not-yet-stored subset; runs at
        adoption time (the prefill device call has completed, so the
        gathered buffers hold the written KV).  The gather returns NEW
        device buffers (safe against page reuse), and the engine thread
        only dispatches it — the D2H fetch, encode, and disk write run
        on the store's background writer (``put_async``), so the tier
        never stalls the step loop."""
        from helix_tpu.engine.kv_cache import gather_pages

        want = [
            (h, p) for h, p in zip(hashes, pages)
            if not self.kv_filestore.contains(h)
        ]
        if not want:
            return
        try:
            arrays = gather_pages(self.cache, [p for _h, p in want])
            tenant = getattr(req, "tenant", "")
            for (h, _p), page_arrays in zip(want, arrays):
                self.kv_filestore.put_async(h, page_arrays, tenant=tenant)
        except Exception:  # noqa: BLE001 — the tier degrades, never fails serving
            logging.getLogger(__name__).exception(
                "KV filestore write-through failed for request %s",
                req.id,
            )

    def _finish(self, req: Request, reason: FinishReason) -> None:
        req.finished = True
        req.finish_reason = reason
        if req.slot is not None:
            led = self._tiered.pop(req.slot, None)
            if led is not None:
                # drop the cold pages' host residency and any staged
                # chunk slab that references them
                for idx in range(led["lo"], led["hi"]):
                    self.host_pool.discard(("ctx", led["rid"], idx))
                self._cold_staged = None
            self.slots[req.slot] = None
            self._state_dirty = True
            self._changed_slots.add(req.slot)
            req.slot = None
        if req in self.waiting:   # aborted before admission
            self.waiting.remove(req)
        for st in list(self.preempted):   # aborted while parked
            if st.req is req:
                self.preempted.remove(st)
                self._discard_preempted(st)
        shared = self._shared_pages.pop(req.id, None)
        if shared and self.prefix_cache is not None:
            self.prefix_cache.release(shared)
        if self.spec is not None:
            self.spec.forget(req.id)
        self._release_adapter(req)
        if self.allocator.owns(req.id):
            self.allocator.free(req.id)
