"""The serving engine: continuous batching over a paged KV cache.

Replaces the reference's per-model vLLM container (``SURVEY.md`` §2.2, §7
stage 2).  One ``Engine`` owns one model's weights + page pool on a mesh
slice and exposes token-level ``add_request`` / ``step`` — the OpenAI HTTP
surface (``helix_tpu.serving``) sits on top, the multi-model residency
manager (``helix_tpu.engine.residency``) creates/destroys Engines per the
active profile.

Execution model (all shapes static, everything jitted once per bucket):

- **Prefill**: one request per call, prompt padded to a power-of-two bucket;
  flash attention over its own K/V; fresh K/V scattered into the request's
  pages; last-token logits sampled for the first generated token.
- **Decode**: one fused step for all ``max_decode_batch`` slots — forward
  (paged attention over each slot's page table) + KV write + penalty +
  sampling inside a single jit; inactive slots ride along pointed at the
  garbage page.
- Host side keeps plain-Python queues, a page allocator, and per-request
  state; nothing dynamic ever crosses into traced code.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import itertools
import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from helix_tpu.engine.kv_cache import (
    CacheConfig,
    PageAllocator,
    PagedKVCache,
    slot_to_page_offset,
    write_kv,
)
from helix_tpu.engine.sampling import (
    SamplingParams,
    SamplingState,
    sample,
)
from helix_tpu.models.common import ModelConfig
from helix_tpu.models.llama import forward
from helix_tpu.ops.attention import attention as full_attention
from helix_tpu.ops.paged import paged_decode_attention


class FinishReason(str, enum.Enum):
    STOP = "stop"
    LENGTH = "length"
    ABORT = "abort"


@dataclasses.dataclass
class Request:
    id: str
    prompt_tokens: list
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    stop_token_ids: tuple = ()
    # mutable state
    output_tokens: list = dataclasses.field(default_factory=list)
    finished: bool = False
    finish_reason: Optional[FinishReason] = None
    slot: Optional[int] = None
    max_len: Optional[int] = None   # page-capacity cap set at admission
    submit_time: float = dataclasses.field(default_factory=time.monotonic)
    first_token_time: Optional[float] = None

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_tokens) + len(self.output_tokens)


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_decode_batch: int = 8
    page_size: int = 16
    num_pages: int = 2048
    max_pages_per_seq: int = 128
    max_prefill_len: int = 2048
    attn_backend: Optional[str] = None   # None = auto (pallas on TPU)
    eos_token_ids: tuple = ()

    def cache_config(self, dtype: str = "bfloat16") -> CacheConfig:
        return CacheConfig(
            num_pages=self.num_pages,
            page_size=self.page_size,
            max_pages_per_seq=self.max_pages_per_seq,
            dtype=dtype,
        )


def _bucket(n: int, lo: int, hi: int) -> int:
    b = lo
    while b < n:
        b *= 2
    return min(b, hi) if b <= hi else hi


# Compiled step functions are cached at module level keyed by the static
# configuration, NOT per Engine instance — two Engines serving the same
# architecture (or the same Engine recreated by a profile swap) reuse one
# executable.  Combined with jax's persistent compilation cache this makes
# profile hot-swap cheap (SURVEY.md §7 hard part #2).
@functools.lru_cache(maxsize=64)
def _build_prefill_fn(model_cfg: ModelConfig, page_size: int, backend):
    cfg = model_cfg

    @functools.partial(jax.jit, donate_argnums=(1,))
    def prefill_fn(params, cache, tokens, page_table, length, sampling, key):
        B, S = tokens.shape  # B == 1
        positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
        valid = positions < length
        seg = valid.astype(jnp.int32)

        def attn_fn(q, k, v, layer_cache, pos):
            return full_attention(
                q, k, v,
                causal=True,
                q_positions=pos,
                kv_positions=pos,
                q_segment_ids=seg,
                kv_segment_ids=seg,
                backend=backend,
            )

        logits, (k_new, v_new) = forward(
            params, cfg, tokens, positions, attn_fn=attn_fn
        )
        pages, offsets = slot_to_page_offset(positions, page_table, page_size)
        cache = write_kv(cache, k_new, v_new, pages, offsets, valid)
        last = logits[jnp.arange(B), length - 1]  # [B, V] f32
        token = sample(last, sampling, key)
        return cache, token

    return prefill_fn


@functools.lru_cache(maxsize=64)
def _build_decode_fn(model_cfg: ModelConfig, page_size: int, backend):
    cfg = model_cfg

    @functools.partial(jax.jit, donate_argnums=(1,))
    def decode_fn(
        params, cache, last_token, positions, page_tables, active,
        sampling, key,
    ):
        tokens = last_token[:, None]                      # [B, 1]
        pos2d = positions[:, None]                        # [B, 1]

        def attn_fn(q, k, v, layer_cache, pos):
            kp, vp = layer_cache
            out = paged_decode_attention(
                q[:, 0],
                kp,
                vp,
                page_tables,
                positions,
                k_new=k[:, 0],
                v_new=v[:, 0],
                backend=backend,
            )
            return out[:, None]

        logits, (k_new, v_new) = forward(
            params, cfg, tokens, pos2d,
            attn_fn=attn_fn,
            layer_caches=(cache.k_pages, cache.v_pages),
        )
        pages, offsets = slot_to_page_offset(pos2d, page_tables, page_size)
        cache = write_kv(
            cache, k_new, v_new, pages, offsets, active[:, None] > 0
        )
        token = sample(logits[:, 0], sampling, key)
        return cache, token

    return decode_fn


class Engine:
    """Single-model serving engine on one mesh slice."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        params,
        cfg: EngineConfig,
        mesh=None,
        rng_seed: int = 0,
    ):
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        self.cache_cfg = cfg.cache_config(dtype=model_cfg.dtype)
        self.cache = PagedKVCache.create(model_cfg, self.cache_cfg, mesh)
        self.allocator = PageAllocator(
            self.cache_cfg.num_pages, self.cache_cfg.max_pages_per_seq
        )
        B = cfg.max_decode_batch
        self.slots: list[Optional[Request]] = [None] * B
        self.waiting: list[Request] = []
        self._requests: dict[str, Request] = {}
        # host mirrors of device-visible per-slot state
        self._last_token = np.zeros((B,), np.int32)
        self._positions = np.zeros((B,), np.int32)
        self._page_tables = np.zeros(
            (B, self.cache_cfg.max_pages_per_seq), np.int32
        )
        self._sampling_dirty = True
        self._sampling_state: Optional[SamplingState] = None
        self._key = jax.random.PRNGKey(rng_seed)
        self._step_counter = itertools.count()
        self._backend = cfg.attn_backend
        # metrics
        self.num_prefill_tokens = 0
        self.num_decode_tokens = 0

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def add_request(self, req: Request) -> None:
        if len(req.prompt_tokens) > self.cfg.max_prefill_len:
            raise ValueError(
                f"prompt ({len(req.prompt_tokens)} tokens) exceeds "
                f"max_prefill_len {self.cfg.max_prefill_len}"
            )
        self._requests[req.id] = req
        self.waiting.append(req)

    def abort(self, req_id: str) -> None:
        req = self._requests.get(req_id)
        if req is None or req.finished:
            return
        self._finish(req, FinishReason.ABORT)

    def has_work(self) -> bool:
        return bool(self.waiting) or any(s is not None for s in self.slots)

    def step(self) -> list[tuple[Request, int]]:
        """Admit + prefill waiting requests, then one decode step.

        Returns [(request, new_token_id), ...] for tokens produced this step.
        """
        emitted: list[tuple[Request, int]] = []
        self._admit(emitted)
        if any(s is not None for s in self.slots):
            emitted.extend(self._decode_step())
        return emitted

    def generate(
        self, prompts: Sequence[Sequence[int]], sampling: SamplingParams
    ) -> list[list[int]]:
        """Blocking convenience wrapper (tests, bench)."""
        reqs = [
            Request(
                id=f"gen-{i}",
                prompt_tokens=list(p),
                sampling=sampling,
                stop_token_ids=tuple(self.cfg.eos_token_ids),
            )
            for i, p in enumerate(prompts)
        ]
        for r in reqs:
            self.add_request(r)
        while self.has_work():
            self.step()
        return [r.output_tokens for r in reqs]

    # ------------------------------------------------------------------
    # admission + prefill
    # ------------------------------------------------------------------

    def _admit(self, emitted) -> None:
        while self.waiting:
            if self.waiting[0].finished:   # aborted while queued
                self.waiting.pop(0)
                continue
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                return
            req = self.waiting[0]
            plen = len(req.prompt_tokens)
            need = self.allocator.pages_needed(
                plen + req.sampling.max_tokens, self.cache_cfg.page_size
            )
            need = min(need, self.cache_cfg.max_pages_per_seq)
            if not self.allocator.can_allocate(need):
                return  # head-of-line blocking; decode will free pages
            self.waiting.pop(0)
            slot = free_slots[0]
            pages = self.allocator.allocate(req.id, need)
            req.slot = slot
            req.max_len = len(pages) * self.cache_cfg.page_size
            self.slots[slot] = req
            table = np.zeros((self.cache_cfg.max_pages_per_seq,), np.int32)
            table[: len(pages)] = pages
            self._page_tables[slot] = table
            first_token = self._prefill(req, table)
            req.first_token_time = time.monotonic()
            self._positions[slot] = plen
            self._last_token[slot] = first_token
            self._sampling_dirty = True
            self._emit(req, int(first_token), emitted)

    def _prefill(self, req: Request, page_table: np.ndarray) -> int:
        plen = len(req.prompt_tokens)
        bucket = _bucket(
            max(plen, self.cache_cfg.page_size),
            self.cache_cfg.page_size,
            self.cfg.max_prefill_len,
        )
        fn = self._get_prefill_fn(bucket)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :plen] = req.prompt_tokens
        length = np.int32(plen)
        self._key, sub = jax.random.split(self._key)
        sampling = SamplingState.from_params([req.sampling])
        self.cache, token = fn(
            self.params,
            self.cache,
            jnp.asarray(tokens),
            jnp.asarray(page_table)[None],
            jnp.asarray(length),
            sampling,
            sub,
        )
        self.num_prefill_tokens += plen
        return int(token[0])

    def _get_prefill_fn(self, bucket: int):
        return _build_prefill_fn(
            self.model_cfg, self.cache_cfg.page_size, self._backend
        )

    # ------------------------------------------------------------------
    # decode
    # ------------------------------------------------------------------

    def _decode_step(self) -> list[tuple[Request, int]]:
        B = self.cfg.max_decode_batch
        active = np.array(
            [1 if s is not None else 0 for s in self.slots], np.int32
        )
        if self._sampling_dirty:
            params_list = [
                (s.sampling if s is not None else SamplingParams())
                for s in self.slots
            ]
            self._sampling_state = SamplingState.from_params(params_list)
            self._sampling_dirty = False
        fn = self._get_decode_fn()
        self._key, sub = jax.random.split(self._key)
        self.cache, next_tokens = fn(
            self.params,
            self.cache,
            jnp.asarray(self._last_token),
            jnp.asarray(self._positions),
            jnp.asarray(self._page_tables),
            jnp.asarray(active),
            self._sampling_state,
            sub,
        )
        next_np = np.asarray(next_tokens)
        emitted: list[tuple[Request, int]] = []
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            self._positions[i] += 1
            self._last_token[i] = next_np[i]
            self.num_decode_tokens += 1
            self._emit(req, int(next_np[i]), emitted)
        return emitted

    def _get_decode_fn(self):
        return _build_decode_fn(
            self.model_cfg, self.cache_cfg.page_size, self._backend
        )

    # ------------------------------------------------------------------
    # completion
    # ------------------------------------------------------------------

    def _emit(self, req: Request, token: int, emitted: list) -> None:
        req.output_tokens.append(token)
        emitted.append((req, token))
        stop_ids = set(req.stop_token_ids) | set(self.cfg.eos_token_ids)
        if token in stop_ids:
            self._finish(req, FinishReason.STOP)
        elif len(req.output_tokens) >= req.sampling.max_tokens:
            self._finish(req, FinishReason.LENGTH)
        elif req.num_tokens >= (req.max_len or self.cache_cfg.max_seq_len):
            self._finish(req, FinishReason.LENGTH)

    def _finish(self, req: Request, reason: FinishReason) -> None:
        req.finished = True
        req.finish_reason = reason
        if req.slot is not None:
            self.slots[req.slot] = None
            self._sampling_dirty = True
            req.slot = None
        if req in self.waiting:   # aborted before admission
            self.waiting.remove(req)
        self.allocator.free(req.id)
