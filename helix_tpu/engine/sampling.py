"""Token sampling, fully vectorised per batch slot.

OpenAI-surface parameters (temperature / top_p / presence & frequency
penalties / seed — the knobs the reference forwards to vLLM via request
JSON) are carried as per-slot arrays inside one jitted step: different
requests in a continuous batch sample with different settings without
re-tracing.

Strategy: restrict to the top ``TOPK_BOUND`` logits (lax.top_k), apply
temperature / top-k / top-p masking inside that subset, then one categorical
draw.  Bounding the candidate set keeps the per-step cost O(B * TOPK_BOUND)
instead of O(B * vocab) for the sort that exact top-p would need.

Randomness is per-slot: each request carries its own PRNG key (seeded from
``SamplingParams.seed`` when given), split on-device every step — a seeded
request is reproducible regardless of what else shares the batch.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

TOPK_BOUND = 64


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Host-side request sampling settings (OpenAI semantics)."""

    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0              # 0 = disabled
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    max_tokens: int = 256
    stop: tuple = ()
    seed: Optional[int] = None

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SamplingState:
    """Per-slot device arrays consumed by the jitted sampler."""

    temperature: jax.Array   # [B] f32 (0 = greedy)
    top_p: jax.Array         # [B] f32
    top_k: jax.Array         # [B] i32 (0 = disabled)
    presence: jax.Array      # [B] f32
    frequency: jax.Array     # [B] f32

    @classmethod
    def from_params(cls, params_list) -> "SamplingState":
        import numpy as np

        return cls(
            temperature=jnp.asarray(
                np.array([p.temperature for p in params_list], np.float32)
            ),
            top_p=jnp.asarray(np.array([p.top_p for p in params_list], np.float32)),
            top_k=jnp.asarray(np.array([p.top_k for p in params_list], np.int32)),
            presence=jnp.asarray(
                np.array([p.presence_penalty for p in params_list], np.float32)
            ),
            frequency=jnp.asarray(
                np.array([p.frequency_penalty for p in params_list], np.float32)
            ),
        )


def sample(
    logits: jax.Array,        # [B, V] f32
    state: SamplingState,
    keys: jax.Array,          # [B, 2] u32 — one PRNG key per slot
) -> jax.Array:
    """Draw one token per slot. Greedy slots (temperature==0) take argmax."""
    B, V = logits.shape
    k = min(TOPK_BOUND, V)
    # lax.top_k lowers to a FULL vocab sort on TPU (~4 ms/step at 128k
    # vocab, the single most expensive op in the r3 decode trace).  Greedy
    # needs only an exact argmax (a cheap reduction); the sampled path uses
    # the TPU-native approximate top-k (aggregate_to_topk sorts the k
    # survivors descending, which the top-p prefix logic needs).  At the
    # default 0.95 recall a true candidate beyond rank ~55 can occasionally
    # be dropped — immaterial for sampling, and small vocabs (tests, CPU)
    # stay exact via the top_k fallback.
    if V > 4 * TOPK_BOUND:
        top_logits, top_idx = jax.lax.approx_max_k(logits, k)
    else:
        top_logits, top_idx = jax.lax.top_k(logits, k)      # [B, k] desc
    exact_greedy = jnp.argmax(logits, axis=-1).astype(top_idx.dtype)

    temp = jnp.maximum(state.temperature, 1e-6)[:, None]
    scaled = top_logits / temp

    # per-row top-k: keep ranks < top_k (0 disables)
    ranks = jnp.arange(k)[None, :]
    topk = jnp.where(state.top_k[:, None] > 0, state.top_k[:, None], k)
    mask = ranks < topk

    # top-p: keep the smallest prefix whose prob mass >= top_p
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (cum - probs) < state.top_p[:, None]  # always keeps rank 0
    mask = mask & keep_p

    masked = jnp.where(mask, scaled, -jnp.inf)
    draw = jax.vmap(jax.random.categorical)(keys, masked)   # [B]
    sampled = jnp.take_along_axis(top_idx, draw[:, None], axis=-1)[:, 0]
    return jnp.where(
        state.temperature == 0.0, exact_greedy, sampled
    ).astype(jnp.int32)


def split_keys(keys: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[B, 2] u32 -> (carry [B, 2], step [B, 2]), all on-device."""
    both = jax.vmap(lambda k: jax.random.split(k, 2))(keys)  # [B, 2, 2]
    return both[:, 0], both[:, 1]


def apply_penalties(
    logits: jax.Array,          # [B, V]
    token_counts: jax.Array,    # [B, V] int32 — output-token histogram
    presence: jax.Array,        # [B]
    frequency: jax.Array,       # [B]
) -> jax.Array:
    """OpenAI presence/frequency penalties from an output-token histogram
    (vLLM semantics: generated tokens only)."""
    present = (token_counts > 0).astype(logits.dtype)
    return (
        logits
        - presence[:, None] * present
        - frequency[:, None] * token_counts.astype(logits.dtype)
    )
