"""Token sampling, fully vectorised per batch slot.

OpenAI-surface parameters (temperature / top_p / presence & frequency
penalties / seed — the knobs the reference forwards to vLLM via request
JSON) are carried as per-slot arrays inside one jitted step: different
requests in a continuous batch sample with different settings without
re-tracing.

Strategy (three tiers, all inside one jitted step):

1. **Window** (common case): restrict to the top ``TOPK_BOUND`` logits,
   apply temperature / top-k / top-p masking inside that subset, one
   categorical draw.  Token probabilities are computed against the
   FULL-vocab softmax denominator, so nucleus membership is exact whenever
   the nucleus fits the window.  Per-step cost O(B * TOPK_BOUND).
2. **Full categorical** (``top_p >= 1`` and ``top_k`` disabled, i.e. the
   OpenAI defaults, whenever the window does not hold ``top_p`` of the
   mass): one Gumbel-max draw over the full vocab — exact, no sort.
3. **Full sort** (adversarial: ``top_p`` below 1 but past the window's
   mass, or ``top_k > TOPK_BOUND``): full-vocab descending sort + exact
   nucleus prefix.  Entered via ``lax.cond`` only when some slot needs it,
   so the common decode step never pays the O(V log V) sort.

Together the tiers make sampling EXACT with respect to OpenAI/vLLM top-p
semantics — the window is an optimisation, never a truncation (round-3
verdict weak #4).  The one remaining approximation is *which* 64
candidates tier 1 sees: on TPU the window comes from ``approx_max_k``
(~0.95 recall on the tail of the 64) because exact ``lax.top_k`` lowers to
a full-vocab sort (~4 ms/step at 128k vocab).  Slots that escalate to
tiers 2/3 are exact regardless.  Set ``HELIX_EXACT_SAMPLING=1`` (read at
trace time) or pass ``exact=True`` to force the exact window everywhere —
the determinism contract then strengthens from per-build to
per-semantics: a seeded request reproduces across JAX versions and
hardware that order ties identically.

Randomness is per-slot: each request carries its own PRNG key (seeded from
``SamplingParams.seed`` when given), split on-device every step — a seeded
request is reproducible regardless of what else shares the batch.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax
import jax.numpy as jnp

TOPK_BOUND = 64


def _exact_default() -> bool:
    return os.environ.get("HELIX_EXACT_SAMPLING", "") not in ("", "0")


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Host-side request sampling settings (OpenAI semantics)."""

    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0              # 0 = disabled
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    max_tokens: int = 256
    stop: tuple = ()
    seed: Optional[int] = None

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SamplingState:
    """Per-slot device arrays consumed by the jitted sampler."""

    temperature: jax.Array   # [B] f32 (0 = greedy)
    top_p: jax.Array         # [B] f32
    top_k: jax.Array         # [B] i32 (0 = disabled)
    presence: jax.Array      # [B] f32
    frequency: jax.Array     # [B] f32

    @classmethod
    def from_params(cls, params_list) -> "SamplingState":
        import numpy as np

        return cls(
            temperature=jnp.asarray(
                np.array([p.temperature for p in params_list], np.float32)
            ),
            top_p=jnp.asarray(np.array([p.top_p for p in params_list], np.float32)),
            top_k=jnp.asarray(np.array([p.top_k for p in params_list], np.int32)),
            presence=jnp.asarray(
                np.array([p.presence_penalty for p in params_list], np.float32)
            ),
            frequency=jnp.asarray(
                np.array([p.frequency_penalty for p in params_list], np.float32)
            ),
        )


def sample(
    logits: jax.Array,        # [B, V] f32
    state: SamplingState,
    keys: jax.Array,          # [B, 2] u32 — one PRNG key per slot
    exact: Optional[bool] = None,
) -> jax.Array:
    """Draw one token per slot. Greedy slots (temperature==0) take argmax.

    ``exact`` (default: the ``HELIX_EXACT_SAMPLING`` env, read at trace
    time) forces the exact ``lax.top_k`` candidate window; see module
    docstring for the tiering and determinism contract.
    """
    if exact is None:
        exact = _exact_default()
    B, V = logits.shape
    k = min(TOPK_BOUND, V)
    # lax.top_k lowers to a FULL vocab sort on TPU (~4 ms/step at 128k
    # vocab, the single most expensive op in the r3 decode trace).  Greedy
    # needs only an exact argmax (a cheap reduction); the sampled path uses
    # the TPU-native approximate top-k (aggregate_to_topk sorts the k
    # survivors descending, which the top-p prefix logic needs) unless
    # ``exact`` asks for the sort.
    if V > 4 * TOPK_BOUND and not exact:
        top_logits, top_idx = jax.lax.approx_max_k(logits, k)
    else:
        top_logits, top_idx = jax.lax.top_k(logits, k)      # [B, k] desc
    exact_greedy = jnp.argmax(logits, axis=-1).astype(top_idx.dtype)

    temp = jnp.maximum(state.temperature, 1e-6)[:, None]
    scaled_full = logits.astype(jnp.float32) / temp         # [B, V]
    # full-vocab softmax denominator: window probabilities below are TRUE
    # probabilities, so the top-p prefix is the true nucleus whenever it
    # fits the window
    log_z = jax.nn.logsumexp(scaled_full, axis=-1, keepdims=True)
    scaled = top_logits / temp

    # per-row top-k: keep ranks < top_k (0 disables)
    ranks = jnp.arange(k)[None, :]
    topk = jnp.where(state.top_k[:, None] > 0, state.top_k[:, None], k)
    mask = ranks < topk

    # top-p: keep the smallest prefix whose (true) prob mass >= top_p
    probs = jnp.exp(scaled - log_z)                          # [B, k]
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (cum - probs) < state.top_p[:, None]  # always keeps rank 0
    mask = mask & keep_p

    masked = jnp.where(mask, scaled, -jnp.inf)
    draw = jax.vmap(jax.random.categorical)(keys, masked)   # [B]
    sampled = jnp.take_along_axis(top_idx, draw[:, None], axis=-1)[:, 0]

    # ---- escalation: slots whose candidate set extends past the window
    nongreedy = state.temperature > 0.0
    window_mass = cum[:, -1]
    topk_in_window = (state.top_k > 0) & (state.top_k <= k)
    # window insufficient: the nucleus wants more mass than the window
    # holds AND top_k does not already cut the candidate set to <= k
    full_needed = nongreedy & (window_mass < state.top_p) & ~topk_in_window
    open_ended = (state.top_p >= 1.0) & (state.top_k == 0)
    cat_needed = full_needed & open_ended      # tier 2: no truncation at all
    sort_needed = full_needed & ~open_ended    # tier 3: true sorted prefix

    def _tier2(s):
        # exact categorical over the whole vocab — Gumbel-max, no sort
        full = jax.vmap(jax.random.categorical)(keys, scaled_full)
        return jnp.where(cat_needed, full.astype(s.dtype), s)

    def _tier3(s):
        sorted_logits, sorted_idx = jax.lax.top_k(scaled_full, V)
        p_s = jnp.exp(sorted_logits - log_z)
        cum_s = jnp.cumsum(p_s, axis=-1)
        keep = (cum_s - p_s) < state.top_p[:, None]
        keep = keep & (jnp.arange(V)[None, :] < jnp.where(
            state.top_k[:, None] > 0, state.top_k[:, None], V
        ))
        m = jnp.where(keep, sorted_logits, -jnp.inf)
        d = jax.vmap(jax.random.categorical)(keys, m)
        full = jnp.take_along_axis(sorted_idx, d[:, None], axis=-1)[:, 0]
        return jnp.where(sort_needed, full.astype(s.dtype), s)

    sampled = jax.lax.cond(jnp.any(cat_needed), _tier2, lambda s: s, sampled)
    sampled = jax.lax.cond(jnp.any(sort_needed), _tier3, lambda s: s, sampled)
    return jnp.where(
        state.temperature == 0.0, exact_greedy, sampled
    ).astype(jnp.int32)


def split_keys(keys: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[B, 2] u32 -> (carry [B, 2], step [B, 2]), all on-device."""
    both = jax.vmap(lambda k: jax.random.split(k, 2))(keys)  # [B, 2, 2]
    return both[:, 0], both[:, 1]


def apply_penalties(
    logits: jax.Array,          # [B, V]
    token_counts: jax.Array,    # [B, V] int32 — output-token histogram
    presence: jax.Array,        # [B]
    frequency: jax.Array,       # [B]
) -> jax.Array:
    """OpenAI presence/frequency penalties from an output-token histogram
    (vLLM semantics: generated tokens only)."""
    present = (token_counts > 0).astype(logits.dtype)
    return (
        logits
        - presence[:, None] * present
        - frequency[:, None] * token_counts.astype(logits.dtype)
    )
