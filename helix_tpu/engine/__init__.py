from helix_tpu.engine.kv_cache import CacheConfig, PagedKVCache, PageAllocator
from helix_tpu.engine.sampling import SamplingParams, sample
from helix_tpu.engine.spec import SpecConfig, SpecDecoder
from helix_tpu.engine.engine import Engine, EngineConfig, Request

__all__ = [
    "CacheConfig",
    "PagedKVCache",
    "PageAllocator",
    "SamplingParams",
    "sample",
    "SpecConfig",
    "SpecDecoder",
    "Engine",
    "EngineConfig",
    "Request",
]
