"""Host-side speculative drafting: prompt-lookup n-grams, no draft model.

The decode-throughput lever the fused window (``decode_steps_per_sync``)
cannot reach: a fused window still runs ONE forward pass per emitted
token — it only amortises the host round trip.  Speculative decoding
amortises the *forward passes themselves*: draft ``k`` continuation
tokens cheaply on the host (the same async CPU-side work APEX overlaps
with device execution), then score all ``k+1`` positions in ONE device
call — since the ragged unification that call is simply the engine's
unified step (``engine._build_ragged_step_fn``) with each drafting slot
a ``1 + draft_len``-token row over its paged history, exactly the shape
the Ragged Paged Attention analysis shows TPUs handle well — and accept
the longest draft prefix the model agrees with.  The verify width is
EXACT (``spec_tokens + 1``) on every backend: the ragged kernel tiles
8-token query blocks internally, so pallas no longer buckets the width
up to a page_size multiple the way the dedicated pre-unification verify
trace did.  Decode-phase forwards are memory-bandwidth-bound, so
scoring k+1 positions costs roughly one position's HBM sweep — every
accepted draft token is a forward pass the request never pays for.

Drafting is prompt-lookup (vLLM's ``[ngram]`` speculative mode): match
the sequence's trailing n-gram
against *its own earlier tokens* (prompt + generated output) and propose
the continuation that followed last time.  No second model, no extra
HBM, and the draft cost is a numpy scan per slot per step.  It shines
exactly where serving traffic repeats itself — code edits, RAG answers
quoting retrieved context, extraction workloads echoing the document —
and degrades to nothing on novel text.

That degradation is managed per slot: a per-request acceptance EMA
disables speculation for slots whose drafts keep missing (the drafts
would otherwise waste verify-call width and host time), with a periodic
re-probe so a request that *becomes* repetitive (e.g. a long quoted
block later in the answer) gets speculation back.  A disabled slot runs
the plain fused-window decode path — the worst case is the engine we
already have.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass
class SpecConfig:
    """Drafter tuning (host-side only; never crosses into traced code)."""

    spec_tokens: int = 4      # max drafted tokens per slot per step
    max_ngram: int = 4        # longest trailing n-gram to match
    min_ngram: int = 1        # shortest n-gram worth matching
    ema_alpha: float = 0.35   # acceptance EMA update weight
    disable_below: float = 0.12   # EMA floor: speculation off under this
    reprobe_after: int = 64   # draft opportunities skipped before re-probe


@dataclasses.dataclass
class _SlotSpec:
    """Per-request drafting state (keyed by request id, not slot index:
    slots are recycled across requests but acceptance history is a
    property of the *request's* text)."""

    ema: float = 1.0          # optimistic start: every slot gets a shot
    enabled: bool = True
    cooldown: int = 0         # disabled-state countdown to the re-probe
    drafted: int = 0
    accepted: int = 0


def propose(
    tokens: Sequence[int],
    k: int,
    max_ngram: int = 4,
    min_ngram: int = 1,
) -> list:
    """Prompt-lookup draft: the continuation that followed the most
    recent earlier occurrence of the sequence's trailing n-gram.

    Longest n-gram first (a 4-gram match is far more predictive than a
    1-gram), most recent occurrence wins (locality: the repetition we
    are inside of beats one from the distant prompt).  Returns at most
    ``k`` tokens; empty when nothing matches.
    """
    n_tok = len(tokens)
    if k <= 0 or n_tok < min_ngram + 1:
        return []
    arr = np.asarray(tokens, dtype=np.int64)
    for n in range(min(max_ngram, n_tok - 1), min_ngram - 1, -1):
        pattern = arr[-n:]
        # windows over arr[:-1]: starts 0..n_tok-1-n, so the trailing
        # n-gram itself is never its own (trivial) match, while earlier
        # overlapping occurrences — the heart of "abcabcabc" — are kept
        windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], n)
        hits = np.nonzero((windows == pattern).all(axis=1))[0]
        if hits.size == 0:
            continue
        start = int(hits[-1]) + n
        cont = arr[start: start + k]
        if cont.size:
            return [int(t) for t in cont]
    return []


class SpecDecoder:
    """Per-engine drafting controller: proposes drafts per slot and
    folds verify outcomes back into each request's acceptance EMA.

    Mutating methods run on the engine thread (plain dict state, no
    locks); ``disabled_count`` additionally serves the /metrics thread
    off a GIL-atomic snapshot, and the engine exposes aggregate
    counters off its own GIL-atomic ints.
    """

    def __init__(self, cfg: Optional[SpecConfig] = None):
        self.cfg = cfg or SpecConfig()
        self._slots: dict = {}   # request id -> _SlotSpec

    def _state(self, req_id: str) -> _SlotSpec:
        st = self._slots.get(req_id)
        if st is None:
            st = self._slots[req_id] = _SlotSpec()
        return st

    def draft(self, req_id: str, tokens: Sequence[int], k: int) -> list:
        """Draft up to ``k`` tokens for one slot, honouring the slot's
        enable/cooldown state.  ``k`` may be below ``spec_tokens`` when
        the caller clamps to page-room/token-budget headroom."""
        st = self._state(req_id)
        if not st.enabled:
            st.cooldown -= 1
            if st.cooldown > 0:
                return []
            # re-probe: one tentative round right at the disable floor —
            # a hit climbs back to full speculation, a miss re-disables
            # on the next observe()
            st.enabled = True
            st.ema = self.cfg.disable_below
        k = min(k, self.cfg.spec_tokens)
        if k <= 0:
            return []
        return propose(
            tokens, k,
            max_ngram=self.cfg.max_ngram,
            min_ngram=self.cfg.min_ngram,
        )

    def observe(self, req_id: str, drafted: int, accepted: int) -> None:
        """Fold one verify outcome into the slot's acceptance EMA."""
        if drafted <= 0:
            return
        st = self._state(req_id)
        st.drafted += drafted
        st.accepted += accepted
        ratio = accepted / drafted
        st.ema = (1.0 - self.cfg.ema_alpha) * st.ema \
            + self.cfg.ema_alpha * ratio
        if st.enabled and st.ema < self.cfg.disable_below:
            st.enabled = False
            st.cooldown = self.cfg.reprobe_after

    def forget(self, req_id: str) -> None:
        self._slots.pop(req_id, None)

    def enabled(self, req_id: str) -> bool:
        """Would a draft() call currently propose for this request?
        (Read-only: does not tick the cooldown.)"""
        st = self._slots.get(req_id)
        return st is None or st.enabled or st.cooldown <= 0

    def disabled_count(self) -> int:
        """Live slots currently sitting out speculation (EMA floor).

        Unlike the other methods this one IS called off the engine
        thread (the /metrics collector) — ``list()`` snapshots the dict
        values in one GIL-atomic op so concurrent draft/forget churn on
        the engine thread cannot raise mid-iteration."""
        return sum(1 for st in list(self._slots.values()) if not st.enabled)
