"""In-memory inference router fed by heartbeats.

Line-for-line behavioural mirror of the reference's
``api/pkg/inferencerouter/router.go``: runner states keyed by id, updated
from heartbeats (``router.go:85-99``); ``pick_runner`` filters to runners
whose ACTIVE profile serves the model AND whose profile status is
``running``, then round-robins per model (``router.go:168-198``);
``available_models`` powers ``/v1/models`` (``:148``); stale runners are
evicted after a TTL (``router.go:113``).  Profile status strings are the
composemgr lifecycle set (``composemgr/manager.go:48``) with TPU semantics:
``assigning | loading | starting | running | failed`` (loading = weights ->
HBM instead of image pull).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

ROUTABLE_STATUS = "running"
PROFILE_STATUSES = ("assigning", "loading", "starting", "running", "failed")


@dataclasses.dataclass
class RunnerState:
    id: str
    models: list = dataclasses.field(default_factory=list)
    profile_name: str = ""
    profile_status: str = "assigning"
    accelerators: list = dataclasses.field(default_factory=list)
    last_heartbeat: float = dataclasses.field(default_factory=time.monotonic)
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def routable(self) -> bool:
        return self.profile_status == ROUTABLE_STATUS and bool(self.models)


class InferenceRouter:
    def __init__(self, ttl_seconds: float = 90.0):
        self.ttl = ttl_seconds
        self._runners: dict[str, RunnerState] = {}
        self._rr: dict[str, int] = {}  # per-model round-robin cursor
        self._lock = threading.Lock()

    def upsert_from_heartbeat(
        self,
        runner_id: str,
        *,
        models: Optional[list] = None,
        profile_name: str = "",
        profile_status: str = "assigning",
        accelerators: Optional[list] = None,
        meta: Optional[dict] = None,
    ) -> RunnerState:
        with self._lock:
            st = self._runners.get(runner_id)
            if st is None:
                st = RunnerState(id=runner_id)
                self._runners[runner_id] = st
            st.models = list(models or [])
            st.profile_name = profile_name
            st.profile_status = profile_status
            st.accelerators = list(accelerators or [])
            st.last_heartbeat = time.monotonic()
            if meta:
                st.meta.update(meta)
            return st

    def evict_stale(self) -> list:
        now = time.monotonic()
        with self._lock:
            dead = [
                rid
                for rid, st in self._runners.items()
                if now - st.last_heartbeat > self.ttl
            ]
            for rid in dead:
                del self._runners[rid]
            return dead

    def remove(self, runner_id: str) -> None:
        with self._lock:
            self._runners.pop(runner_id, None)

    def get(self, runner_id: str) -> Optional[RunnerState]:
        with self._lock:
            return self._runners.get(runner_id)

    def runners(self) -> list:
        with self._lock:
            return list(self._runners.values())

    def available_models(self) -> list:
        """Union of models on routable, fresh runners (for /v1/models)."""
        now = time.monotonic()
        with self._lock:
            out = set()
            for st in self._runners.values():
                if st.routable and now - st.last_heartbeat <= self.ttl:
                    out.update(st.models)
            return sorted(out)

    def model_map(self) -> dict:
        """{model: [runner ids serving it]} over routable, fresh runners
        (the /api/v1/model-info shape)."""
        now = time.monotonic()
        with self._lock:
            out: dict = {}
            for st in sorted(self._runners.values(), key=lambda s: s.id):
                if st.routable and now - st.last_heartbeat <= self.ttl:
                    for m in st.models:
                        out.setdefault(m, []).append(st.id)
            return out

    def pick_runner(self, model: str) -> Optional[RunnerState]:
        """Per-model round-robin over routable runners serving ``model``."""
        now = time.monotonic()
        with self._lock:
            candidates = [
                st
                for st in sorted(self._runners.values(), key=lambda s: s.id)
                if st.routable
                and model in st.models
                and now - st.last_heartbeat <= self.ttl
            ]
            if not candidates:
                return None
            cursor = self._rr.get(model, 0)
            chosen = candidates[cursor % len(candidates)]
            self._rr[model] = (cursor + 1) % max(len(candidates), 1)
            return chosen
