"""In-memory inference router fed by heartbeats.

Line-for-line behavioural mirror of the reference's
``api/pkg/inferencerouter/router.go``: runner states keyed by id, updated
from heartbeats (``router.go:85-99``); ``pick_runner`` filters to runners
whose ACTIVE profile serves the model AND whose profile status is
``running``, then round-robins per model (``router.go:168-198``);
``available_models`` powers ``/v1/models`` (``:148``); stale runners are
evicted after a TTL (``router.go:113``).  Profile status strings are the
composemgr lifecycle set (``composemgr/manager.go:48``) with TPU semantics:
``assigning | loading | starting | running | failed`` (loading = weights ->
HBM instead of image pull).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Iterable, Optional

ROUTABLE_STATUS = "running"
PROFILE_STATUSES = ("assigning", "loading", "starting", "running", "failed")


@dataclasses.dataclass(frozen=True)
class BreakerConfig:
    """Per-runner circuit breaker tuning (see README "Robustness knobs")."""

    window: int = 20              # sliding window of dispatch outcomes
    min_samples: int = 4          # outcomes required before the rate applies
    failure_threshold: float = 0.5  # failure rate that opens the breaker
    cooldown: float = 15.0        # seconds open before probing (half-open)
    half_open_probes: int = 2     # concurrent probe dispatches in half-open
    half_open_successes: int = 2  # probe successes required to close


class CircuitBreaker:
    """closed -> open (failure rate over a sliding window) -> half-open
    (after ``cooldown``) -> closed (probe successes) | open (probe failure).

    Callers must hold whatever lock guards the owning router; this class
    itself is not thread-safe.  The clock is injectable so state
    transitions are testable without sleeping."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        cfg: BreakerConfig,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg
        self.clock = clock
        self.state = self.CLOSED
        self.window: list[bool] = []   # True = failure
        self.opened_at = 0.0
        self.probe_inflight = 0
        self.probe_successes = 0
        self.opens = 0                 # lifetime open transitions (metrics)
        # epoch fences outcomes to the state generation their dispatch
        # started in: a long-lived stream that began before the breaker
        # tripped must not count as a half-open probe success later
        self.epoch = 0

    def _maybe_half_open(self) -> None:
        if (
            self.state == self.OPEN
            and self.clock() - self.opened_at >= self.cfg.cooldown
        ):
            self.state = self.HALF_OPEN
            self.probe_inflight = 0
            self.probe_successes = 0
            self.epoch += 1

    def allow(self) -> bool:
        """May a new dispatch go to this runner right now?"""
        self._maybe_half_open()
        if self.state == self.CLOSED:
            return True
        if self.state == self.HALF_OPEN:
            return self.probe_inflight < self.cfg.half_open_probes
        return False

    def on_dispatch(self) -> int:
        """Returns the epoch token the dispatch starts in; hand it back
        to record()/release() so stale outcomes can be fenced off."""
        self._maybe_half_open()
        if self.state == self.HALF_OPEN:
            self.probe_inflight += 1
        return self.epoch

    def _trip(self) -> None:
        self.state = self.OPEN
        self.opened_at = self.clock()
        self.opens += 1
        self.window.clear()
        self.probe_inflight = 0
        self.probe_successes = 0
        self.epoch += 1

    def release(self, epoch: Optional[int] = None) -> None:
        """Outcome unknowable (dispatch cancelled mid-flight): free the
        probe slot without counting a success or failure — a cancelled
        probe must never close a half-open breaker."""
        self._maybe_half_open()
        if epoch is not None and epoch != self.epoch:
            return
        if self.state == self.HALF_OPEN:
            self.probe_inflight = max(0, self.probe_inflight - 1)

    def record(self, failure: bool, epoch: Optional[int] = None) -> None:
        self._maybe_half_open()
        if epoch is not None and epoch != self.epoch:
            # outcome of a dispatch from a previous state generation
            # (e.g. a stream that started before the breaker tripped):
            # it says nothing about the runner NOW — a pre-open success
            # must not close a half-open breaker with zero real probes
            return
        if self.state == self.HALF_OPEN:
            self.probe_inflight = max(0, self.probe_inflight - 1)
            if failure:
                self._trip()
                return
            self.probe_successes += 1
            if self.probe_successes >= self.cfg.half_open_successes:
                self.state = self.CLOSED
                self.window.clear()
            return
        if self.state == self.OPEN:
            # stale outcome from a dispatch that started pre-open; the
            # breaker already acted on this runner, ignore it
            return
        self.window.append(failure)
        if len(self.window) > self.cfg.window:
            self.window.pop(0)
        if len(self.window) >= self.cfg.min_samples:
            rate = sum(self.window) / len(self.window)
            if rate >= self.cfg.failure_threshold:
                self._trip()

    def snapshot(self) -> dict:
        self._maybe_half_open()
        return {
            "state": self.state,
            "window_failures": sum(self.window),
            "window_size": len(self.window),
            "opens": self.opens,
            "probe_successes": self.probe_successes,
        }


@dataclasses.dataclass
class RunnerState:
    id: str
    models: list = dataclasses.field(default_factory=list)
    profile_name: str = ""
    profile_status: str = "assigning"
    accelerators: list = dataclasses.field(default_factory=list)
    last_heartbeat: float = dataclasses.field(default_factory=time.monotonic)
    meta: dict = dataclasses.field(default_factory=dict)
    # compact saturation summary from the last heartbeat (the
    # obs.flight.SATURATION_KEYS schema).  Living on RunnerState means it
    # is pruned with the runner on evict_stale()/remove() — no /metrics
    # label-cardinality leak under runner churn (same rule as breakers).
    saturation: dict = dataclasses.field(default_factory=dict)
    # per-tenant rollup from the last heartbeat (obs.slo.TENANT_KEYS
    # entries, top-K + __other__) — pruned with the runner like
    # saturation, so tenant gauges can never outlive their reporter
    tenants: dict = dataclasses.field(default_factory=dict)
    # graceful-shutdown state (ISSUE 11): a draining runner finishes /
    # migrates its in-flight work but takes NO new requests —
    # ``pick_runner`` skips it (including half-open breaker probes,
    # which would be burned on a runner about to exit).  It stays in
    # ``model_map`` so a cluster-wide drain answers 503 code=draining
    # instead of 404.  ``drain_deadline`` (unix seconds, 0 = unknown)
    # feeds the honest Retry-After on that 503.
    draining: bool = False
    drain_deadline: float = 0.0

    @property
    def routable(self) -> bool:
        return self.profile_status == ROUTABLE_STATUS and bool(self.models)


class InferenceRouter:
    def __init__(
        self,
        ttl_seconds: float = 90.0,
        breaker: Optional[BreakerConfig] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.ttl = ttl_seconds
        self.breaker_cfg = breaker or BreakerConfig()
        self.clock = clock
        self._runners: dict[str, RunnerState] = {}
        self._rr: dict[str, int] = {}  # per-model round-robin cursor
        self._breakers: dict[str, CircuitBreaker] = {}
        self._inflight: dict[str, int] = {}
        self._lock = threading.Lock()

    def _breaker(self, runner_id: str) -> CircuitBreaker:
        """Lock must be held."""
        br = self._breakers.get(runner_id)
        if br is None:
            br = CircuitBreaker(self.breaker_cfg, clock=self.clock)
            self._breakers[runner_id] = br
        return br

    def upsert_from_heartbeat(
        self,
        runner_id: str,
        *,
        models: Optional[list] = None,
        profile_name: str = "",
        profile_status: str = "assigning",
        accelerators: Optional[list] = None,
        meta: Optional[dict] = None,
        saturation: Optional[dict] = None,
        tenants: Optional[dict] = None,
        draining: bool = False,
        drain_deadline: float = 0.0,
    ) -> RunnerState:
        with self._lock:
            st = self._runners.get(runner_id)
            if st is None:
                st = RunnerState(id=runner_id)
                self._runners[runner_id] = st
            st.models = list(models or [])
            st.profile_name = profile_name
            st.profile_status = profile_status
            st.accelerators = list(accelerators or [])
            st.last_heartbeat = self.clock()
            if meta:
                st.meta.update(meta)
            if saturation is not None:
                st.saturation = dict(saturation)
            if tenants is not None:
                st.tenants = dict(tenants)
            st.draining = bool(draining)
            st.drain_deadline = float(drain_deadline or 0.0)
            return st

    def evict_stale(self) -> list:
        now = self.clock()
        with self._lock:
            dead = [
                rid
                for rid, st in self._runners.items()
                if now - st.last_heartbeat > self.ttl
            ]
            for rid in dead:
                del self._runners[rid]
                self._prune_dispatch_state(rid)
            return dead

    def _prune_dispatch_state(self, runner_id: str) -> None:
        """Drop breaker/in-flight state for a departed runner (lock must
        be held).  Without this, churning ephemeral runner ids grow the
        breaker map — and /metrics label cardinality — forever.  An
        in-flight dispatch keeps the entries alive until it completes;
        _record prunes when the last outcome for a departed id lands."""
        if self._inflight.get(runner_id, 0) == 0:
            self._breakers.pop(runner_id, None)
            self._inflight.pop(runner_id, None)

    def remove(self, runner_id: str) -> None:
        with self._lock:
            self._runners.pop(runner_id, None)
            self._prune_dispatch_state(runner_id)

    def get(self, runner_id: str) -> Optional[RunnerState]:
        with self._lock:
            return self._runners.get(runner_id)

    def runners(self) -> list:
        with self._lock:
            return list(self._runners.values())

    def available_models(self) -> list:
        """Union of models on routable, fresh runners (for /v1/models)."""
        now = self.clock()
        with self._lock:
            out = set()
            for st in self._runners.values():
                if st.routable and now - st.last_heartbeat <= self.ttl:
                    out.update(st.models)
            return sorted(out)

    def model_map(self) -> dict:
        """{model: [runner ids serving it]} over routable, fresh runners
        (the /api/v1/model-info shape)."""
        now = self.clock()
        with self._lock:
            out: dict = {}
            for st in sorted(self._runners.values(), key=lambda s: s.id):
                if st.routable and now - st.last_heartbeat <= self.ttl:
                    for m in st.models:
                        out.setdefault(m, []).append(st.id)
            return out

    def pick_runner(
        self, model: str, exclude: Iterable[str] = ()
    ) -> Optional[RunnerState]:
        """Failure- and load-aware pick over routable runners serving
        ``model``: skips runners in ``exclude`` (already tried this
        request) and runners whose circuit breaker is open (or half-open
        with no probe budget left), prefers the least-loaded of what
        remains, and round-robins per model among ties — so with healthy
        idle runners the behaviour is the seed's pure round-robin."""
        now = self.clock()
        exclude = set(exclude)
        with self._lock:
            candidates = [
                st
                for st in sorted(self._runners.values(), key=lambda s: s.id)
                if st.routable
                and not st.draining   # unroutable-for-new-work; also
                # keeps half-open breaker PROBES off a runner that is
                # about to exit — a probe burned there proves nothing
                and model in st.models
                and now - st.last_heartbeat <= self.ttl
                and st.id not in exclude
            ]
            if not candidates:
                return None
            allowed = [
                st for st in candidates if self._breaker(st.id).allow()
            ]
            if not allowed:
                return None
            min_load = min(
                self._inflight.get(st.id, 0) for st in allowed
            )
            least = [
                st
                for st in allowed
                if self._inflight.get(st.id, 0) == min_load
            ]
            cursor = self._rr.get(model, 0)
            chosen = least[cursor % len(least)]
            self._rr[model] = (cursor + 1) % max(len(least), 1)
            return chosen

    def drain_retry_after(self, model: str) -> Optional[int]:
        """When EVERY fresh, routable runner serving ``model`` is
        draining, the honest Retry-After in seconds (the latest reported
        drain deadline, floored at 1s; a conservative default when no
        runner reported one).  None = at least one non-draining runner
        exists (or none serve the model at all) — the caller keeps its
        ordinary error shape."""
        now = self.clock()
        with self._lock:
            serving = [
                st
                for st in self._runners.values()
                if st.routable
                and model in st.models
                and now - st.last_heartbeat <= self.ttl
            ]
            if not serving or any(not st.draining for st in serving):
                return None
            deadlines = [
                st.drain_deadline for st in serving if st.drain_deadline
            ]
            if not deadlines:
                return 5
            import time as _time

            return max(1, int(max(deadlines) - _time.time()) + 1)

    def draining_map(self) -> dict:
        """{runner_id: draining} over live runners — the drain-state
        gauge's source; pruned with the runner like saturation_map."""
        with self._lock:
            return {
                rid: st.draining
                for rid, st in sorted(self._runners.items())
            }

    def migration_targets(self, for_runner: str) -> list:
        """Peers a draining runner may ship snapshots to: fresh,
        routable, NOT draining, with an address, excluding the asker.
        Each entry carries the peer's model list so the shipper can
        match a snapshot's model to a runner that serves it."""
        now = self.clock()
        with self._lock:
            return [
                {
                    "id": st.id,
                    "address": st.meta.get("address", ""),
                    "models": list(st.models),
                }
                for st in sorted(
                    self._runners.values(), key=lambda s: s.id
                )
                if st.routable
                and not st.draining
                and st.id != for_runner
                and now - st.last_heartbeat <= self.ttl
                and st.meta.get("address")
            ]

    # -- dispatch feedback (breakers + load) -------------------------------

    def record_dispatch_start(self, runner_id: str) -> int:
        """The dispatcher is about to send a request to this runner.
        Returns the breaker epoch token to pass back to record_*, so an
        outcome that straddles a breaker state change is discarded
        instead of, e.g., closing a half-open breaker on the strength of
        a stream that started before the runner broke."""
        with self._lock:
            self._inflight[runner_id] = self._inflight.get(runner_id, 0) + 1
            return self._breaker(runner_id).on_dispatch()

    def _record(
        self, runner_id: str, failure: bool, epoch: Optional[int] = None
    ) -> None:
        with self._lock:
            self._inflight[runner_id] = max(
                0, self._inflight.get(runner_id, 0) - 1
            )
            self._breaker(runner_id).record(failure=failure, epoch=epoch)
            if runner_id not in self._runners:
                # runner departed while this dispatch was in flight: once
                # the last one lands, drop its state entirely
                self._prune_dispatch_state(runner_id)

    def record_success(
        self, runner_id: str, epoch: Optional[int] = None
    ) -> None:
        self._record(runner_id, failure=False, epoch=epoch)

    def record_failure(
        self, runner_id: str, epoch: Optional[int] = None
    ) -> None:
        self._record(runner_id, failure=True, epoch=epoch)

    def record_release(
        self, runner_id: str, epoch: Optional[int] = None
    ) -> None:
        """Dispatch ended with no attributable outcome (client cancelled
        mid-flight): free the in-flight slot and probe budget without
        feeding the breaker's failure window or probe successes."""
        with self._lock:
            self._inflight[runner_id] = max(
                0, self._inflight.get(runner_id, 0) - 1
            )
            self._breaker(runner_id).release(epoch=epoch)
            if runner_id not in self._runners:
                self._prune_dispatch_state(runner_id)

    def inflight(self, runner_id: str) -> int:
        with self._lock:
            return self._inflight.get(runner_id, 0)

    def saturation_map(self) -> dict:
        """{runner_id: last-heartbeat saturation summary} over runners
        that reported one.  Departed runners vanish here the moment they
        are evicted (the summary lives on RunnerState), so the
        ``helix_cp_runner_saturation_*`` gauges can never leak labels."""
        with self._lock:
            return {
                rid: dict(st.saturation)
                for rid, st in sorted(self._runners.items())
                if st.saturation
            }

    def tenants_map(self) -> dict:
        """{runner_id: last-heartbeat tenants rollup} over runners that
        reported one.  Pruned with the runner, like saturation_map — the
        cp's per-tenant burn gauges can never leak labels."""
        with self._lock:
            return {
                rid: dict(st.tenants)
                for rid, st in sorted(self._runners.items())
                if st.tenants
            }

    def breaker_states(self) -> dict:
        """{runner_id: breaker snapshot + inflight} for /metrics and
        operator introspection.  A runner evicted with dispatches still
        in flight lingers until its last outcome lands, then is pruned."""
        with self._lock:
            return {
                rid: {
                    **br.snapshot(),
                    "inflight": self._inflight.get(rid, 0),
                }
                for rid, br in sorted(self._breakers.items())
            }
