"""In-memory inference router fed by heartbeats.

Line-for-line behavioural mirror of the reference's
``api/pkg/inferencerouter/router.go``: runner states keyed by id, updated
from heartbeats (``router.go:85-99``); ``pick_runner`` filters to runners
whose ACTIVE profile serves the model AND whose profile status is
``running``, then round-robins per model (``router.go:168-198``);
``available_models`` powers ``/v1/models`` (``:148``); stale runners are
evicted after a TTL (``router.go:113``).  Profile status strings are the
composemgr lifecycle set (``composemgr/manager.go:48``) with TPU semantics:
``assigning | loading | starting | running | failed`` (loading = weights ->
HBM instead of image pull).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import logging
import os
import threading
import time
from typing import Callable, Iterable, Optional

from helix_tpu.obs.canary import canary_failing

log = logging.getLogger("helix.router")

ROUTABLE_STATUS = "running"
PROFILE_STATUSES = ("assigning", "loading", "starting", "running", "failed")

# ---------------------------------------------------------------------------
# routing policy (ISSUE 12): the control-plane feedback loop from federated
# heartbeat saturation into placement.  The ``helix_cp_route_*`` metric
# vocabulary is minted ONLY here (tools/lint_metrics.py contract 8); the
# control plane calls ``collect_cp_routing``.
# ---------------------------------------------------------------------------

ROUTE_POLICY_RR = "rr"          # the seed least-loaded/round-robin baseline
ROUTE_POLICY_SCORED = "scored"  # saturation/SLO-aware composite scoring

CP_ROUTE_POLICY = "helix_cp_route_policy_scored"
CP_ROUTE_DECISIONS = "helix_cp_route_decisions_total"
CP_ROUTE_HARD_AVOIDED = "helix_cp_route_hard_avoided_total"
CP_ROUTE_SATURATION_SHEDS = "helix_cp_route_saturation_sheds_total"
CP_ROUTE_AFFINITY_HITS = "helix_cp_route_affinity_hits_total"
CP_ROUTE_AFFINITY_YIELDS = "helix_cp_route_affinity_yields_total"
CP_ROUTE_CLASS_STEERED = "helix_cp_route_class_steered_total"
CP_ROUTE_STALE_NEUTRAL = "helix_cp_route_stale_neutral_total"
CP_ROUTE_AFFINITY_ENTRIES = "helix_cp_route_affinity_entries"
CP_ROUTE_ADAPTER_AFFINITY_HITS = (
    "helix_cp_route_adapter_affinity_hits_total"
)

# ---------------------------------------------------------------------------
# pool roles (ISSUE 14): disaggregated prefill/decode.  A runner's
# serving profile declares its pool (heartbeat-federated); the router
# schedules the pools independently — ordinary (decode) traffic never
# lands on a prefill-pool runner while any decode/mixed runner serves
# the model, and the prefill handoff picks strictly from the prefill
# pool.  The ``helix_cp_pool_*`` vocabulary is minted ONLY here
# (tools/lint_metrics.py contract 10); the control plane calls
# ``collect_cp_pools``.
# ---------------------------------------------------------------------------

POOL_PREFILL = "prefill"
POOL_DECODE = "decode"
POOL_MIXED = "mixed"
POOL_ROLES = (POOL_PREFILL, POOL_DECODE, POOL_MIXED)

CP_POOL_RUNNERS = "helix_cp_pool_runners"
CP_POOL_HANDOFFS = "helix_cp_pool_handoffs_total"
CP_POOL_HANDOFF_FALLBACKS = "helix_cp_pool_handoff_fallbacks_total"
CP_POOL_DISAGG_ENABLED = "helix_cp_pool_disagg_enabled"


def sanitize_pool_role(value) -> str:
    """Clamp a runner-supplied pool role to the known set — a malformed
    role degrades to ``mixed`` (fully routable), never rejects the
    heartbeat (the PR 4/7/11 heartbeat-hardening pattern)."""
    if isinstance(value, str) and value.strip().lower() in POOL_ROLES:
        return value.strip().lower()
    return POOL_MIXED


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except (TypeError, ValueError):
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass(frozen=True)
class RouterPolicy:
    """Placement policy knobs (see README "Routing & autoscaling").

    The default (``policy="rr"``, ``affinity=False``) preserves the seed
    least-loaded/round-robin behaviour bit-for-bit — the scored path is
    opt-in via ``HELIX_ROUTER_POLICY=scored``, the same default-off
    contract ``sched.py`` shipped with."""

    policy: str = ROUTE_POLICY_RR
    # hard-avoid: a runner at/past these is routed to only when no
    # alternative exists (its next admissions are one step from a typed
    # kv_exhausted shed)
    kv_avoid_threshold: float = 0.85
    host_avoid_threshold: float = 0.92
    # a scheduler prefill budget squeezed to (0, this] means SLO burn is
    # actively throttling admission there — hard-avoid (0 = unbudgeted,
    # never an avoid signal)
    prefill_avoid_tokens: float = 256.0
    # full: past this the runner is a GUARANTEED kv_exhausted for a new
    # admission — when every candidate is full the cp sheds with a typed
    # 503 instead of dispatching into certain failure
    kv_full_threshold: float = 0.98
    # batch-class traffic is steered away from runners whose tenants are
    # burning SLO budget faster than it accrues
    burn_steer_threshold: float = 1.0
    # saturation older than this (but inside the heartbeat TTL) is
    # treated as unknown — scored NEUTRAL, never best
    stale_after: float = 90.0
    # prefix-affinity routing (cp-side prompt-head digest -> runner)
    affinity: bool = False
    affinity_entries: int = 2048
    # corruption-aware routing (ISSUE 19): hard-avoid runners whose
    # federated correctness-canary health is failing/reprobing.  Opt-in
    # (HELIX_ROUTER_CANARY_AVOID=1) and orthogonal to the policy choice
    # — rr picks honour it too.  The LAST runner for a model is never
    # stranded: it serves-with-warning instead (counted + logged).
    canary_avoid: bool = False

    @classmethod
    def from_env(cls) -> "RouterPolicy":
        raw = os.environ.get("HELIX_ROUTER_POLICY", "").strip().lower()
        policy = (
            ROUTE_POLICY_SCORED if raw == ROUTE_POLICY_SCORED
            else ROUTE_POLICY_RR
        )
        return cls(
            policy=policy,
            kv_avoid_threshold=_env_float(
                "HELIX_ROUTER_KV_AVOID_THRESHOLD", 0.85
            ),
            host_avoid_threshold=_env_float(
                "HELIX_ROUTER_HOST_AVOID_THRESHOLD", 0.92
            ),
            prefill_avoid_tokens=_env_float(
                "HELIX_ROUTER_PREFILL_AVOID_TOKENS", 256.0
            ),
            kv_full_threshold=_env_float(
                "HELIX_ROUTER_KV_FULL_THRESHOLD", 0.98
            ),
            burn_steer_threshold=_env_float(
                "HELIX_ROUTER_BURN_STEER_THRESHOLD", 1.0
            ),
            affinity=os.environ.get("HELIX_PREFIX_AFFINITY", "")
            not in ("", "0"),
            affinity_entries=_env_int(
                "HELIX_PREFIX_AFFINITY_ENTRIES", 2048
            ),
            canary_avoid=os.environ.get("HELIX_ROUTER_CANARY_AVOID", "")
            not in ("", "0"),
        )


def prompt_head(body: dict) -> str:
    """The routing-relevant head of an OpenAI-shaped request body: the
    first message (where the shared system prompt lives) for chat, the
    prompt head for completions.  Bounded so hashing cost is O(1) in
    prompt length — multimodal content lists are summarised from their
    first text part (never serialised whole: a base64 image part would
    cost megabytes of json.dumps per dispatch); '' disables affinity
    for this request."""
    msgs = body.get("messages")
    if isinstance(msgs, list) and msgs:
        first = msgs[0] if isinstance(msgs[0], dict) else {}
        content = first.get("content", "")
        if isinstance(content, list):
            # OpenAI multimodal parts: key on the first TEXT part (the
            # shared system/instruction text) plus the part-type shape,
            # without touching image payload bytes
            text = next(
                (
                    str(p.get("text", ""))[:512]
                    for p in content[:8]
                    if isinstance(p, dict) and p.get("type") == "text"
                ),
                "",
            )
            shape = ",".join(
                str(p.get("type", "?")) if isinstance(p, dict) else "?"
                for p in content[:8]
            )
            content = f"[{shape}]{text}"
        elif not isinstance(content, str):
            content = str(content)[:512]
        return f"{first.get('role', '')}:{content[:512]}"
    prompt = body.get("prompt", "")
    if isinstance(prompt, list):
        # pre-tokenised / batched prompts: a bounded slice is plenty of
        # head identity and keeps the dump O(1) in prompt length
        try:
            prompt = json.dumps(prompt[:128])
        except (TypeError, ValueError):
            prompt = str(prompt[:16])
    elif not isinstance(prompt, str):
        prompt = str(prompt)[:512]
    return prompt[:512]


def prefix_digest(model: str, head: str) -> Optional[str]:
    """Stable digest of (model, prompt head) — the prefix-affinity map
    key.  None when there is no head to hash (affinity disabled for the
    request, never a shared empty-string bucket)."""
    if not head:
        return None
    h = hashlib.blake2b(digest_size=8)
    h.update(model.encode("utf-8", "replace"))
    h.update(b"\x00")
    h.update(head.encode("utf-8", "replace"))
    return h.hexdigest()


class PrefixAffinity:
    """Bounded LRU of prefix digest -> the runner whose PrefixCache /
    host tier most recently served that prompt head.  A hint, not a pin:
    ``pick_runner`` honours it only while the runner is routable and not
    saturated (affinity yields to saturation)."""

    def __init__(self, max_entries: int = 2048):
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._map: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict()
        )

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            rid = self._map.get(key)
            if rid is not None:
                self._map.move_to_end(key)
            return rid

    def put(self, key: str, runner_id: str) -> None:
        with self._lock:
            self._map.pop(key, None)
            self._map[key] = runner_id
            while len(self._map) > self.max_entries:
                self._map.popitem(last=False)

    def forget_runner(self, runner_id: str) -> None:
        """Drop every hint pointing at a departed runner (evict/remove)."""
        with self._lock:
            for k in [
                k for k, v in self._map.items() if v == runner_id
            ]:
                del self._map[k]

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)


@dataclasses.dataclass(frozen=True)
class BreakerConfig:
    """Per-runner circuit breaker tuning (see README "Robustness knobs")."""

    window: int = 20              # sliding window of dispatch outcomes
    min_samples: int = 4          # outcomes required before the rate applies
    failure_threshold: float = 0.5  # failure rate that opens the breaker
    cooldown: float = 15.0        # seconds open before probing (half-open)
    half_open_probes: int = 2     # concurrent probe dispatches in half-open
    half_open_successes: int = 2  # probe successes required to close


class CircuitBreaker:
    """closed -> open (failure rate over a sliding window) -> half-open
    (after ``cooldown``) -> closed (probe successes) | open (probe failure).

    Callers must hold whatever lock guards the owning router; this class
    itself is not thread-safe.  The clock is injectable so state
    transitions are testable without sleeping."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(
        self,
        cfg: BreakerConfig,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg
        self.clock = clock
        self.state = self.CLOSED
        self.window: list[bool] = []   # True = failure
        self.opened_at = 0.0
        self.probe_inflight = 0
        self.probe_successes = 0
        self.opens = 0                 # lifetime open transitions (metrics)
        # epoch fences outcomes to the state generation their dispatch
        # started in: a long-lived stream that began before the breaker
        # tripped must not count as a half-open probe success later
        self.epoch = 0

    def _maybe_half_open(self) -> None:
        if (
            self.state == self.OPEN
            and self.clock() - self.opened_at >= self.cfg.cooldown
        ):
            self.state = self.HALF_OPEN
            self.probe_inflight = 0
            self.probe_successes = 0
            self.epoch += 1

    def allow(self) -> bool:
        """May a new dispatch go to this runner right now?"""
        self._maybe_half_open()
        if self.state == self.CLOSED:
            return True
        if self.state == self.HALF_OPEN:
            return self.probe_inflight < self.cfg.half_open_probes
        return False

    def on_dispatch(self) -> int:
        """Returns the epoch token the dispatch starts in; hand it back
        to record()/release() so stale outcomes can be fenced off."""
        self._maybe_half_open()
        if self.state == self.HALF_OPEN:
            self.probe_inflight += 1
        return self.epoch

    def _trip(self) -> None:
        self.state = self.OPEN
        self.opened_at = self.clock()
        self.opens += 1
        self.window.clear()
        self.probe_inflight = 0
        self.probe_successes = 0
        self.epoch += 1

    def release(self, epoch: Optional[int] = None) -> None:
        """Outcome unknowable (dispatch cancelled mid-flight): free the
        probe slot without counting a success or failure — a cancelled
        probe must never close a half-open breaker."""
        self._maybe_half_open()
        if epoch is not None and epoch != self.epoch:
            return
        if self.state == self.HALF_OPEN:
            self.probe_inflight = max(0, self.probe_inflight - 1)

    def record(self, failure: bool, epoch: Optional[int] = None) -> None:
        self._maybe_half_open()
        if epoch is not None and epoch != self.epoch:
            # outcome of a dispatch from a previous state generation
            # (e.g. a stream that started before the breaker tripped):
            # it says nothing about the runner NOW — a pre-open success
            # must not close a half-open breaker with zero real probes
            return
        if self.state == self.HALF_OPEN:
            self.probe_inflight = max(0, self.probe_inflight - 1)
            if failure:
                self._trip()
                return
            self.probe_successes += 1
            if self.probe_successes >= self.cfg.half_open_successes:
                self.state = self.CLOSED
                self.window.clear()
            return
        if self.state == self.OPEN:
            # stale outcome from a dispatch that started pre-open; the
            # breaker already acted on this runner, ignore it
            return
        self.window.append(failure)
        if len(self.window) > self.cfg.window:
            self.window.pop(0)
        if len(self.window) >= self.cfg.min_samples:
            rate = sum(self.window) / len(self.window)
            if rate >= self.cfg.failure_threshold:
                self._trip()

    def snapshot(self) -> dict:
        self._maybe_half_open()
        return {
            "state": self.state,
            "window_failures": sum(self.window),
            "window_size": len(self.window),
            "opens": self.opens,
            "probe_successes": self.probe_successes,
        }


@dataclasses.dataclass
class RunnerState:
    id: str
    models: list = dataclasses.field(default_factory=list)
    profile_name: str = ""
    profile_status: str = "assigning"
    accelerators: list = dataclasses.field(default_factory=list)
    last_heartbeat: float = dataclasses.field(default_factory=time.monotonic)
    meta: dict = dataclasses.field(default_factory=dict)
    # compact saturation summary from the last heartbeat (the
    # obs.flight.SATURATION_KEYS schema).  Living on RunnerState means it
    # is pruned with the runner on evict_stale()/remove() — no /metrics
    # label-cardinality leak under runner churn (same rule as breakers).
    saturation: dict = dataclasses.field(default_factory=dict)
    # clock() stamp of the last NON-EMPTY saturation block: the scored
    # policy treats saturation older than ``RouterPolicy.stale_after``
    # (or never reported) as unknown — scored neutral, never best
    saturation_at: float = 0.0
    # per-tenant rollup from the last heartbeat (obs.slo.TENANT_KEYS
    # entries, top-K + __other__) — pruned with the runner like
    # saturation, so tenant gauges can never outlive their reporter
    tenants: dict = dataclasses.field(default_factory=dict)
    # multi-LoRA residency block (ISSUE 15): bounded, sanitised
    # `model@adapter` ids HBM-resident on this runner (validated by
    # engine.adapters.validate_adapter_block at heartbeat ingestion) —
    # the adapter-affinity hint's signal, pruned with the runner
    adapters: list = dataclasses.field(default_factory=list)
    # graceful-shutdown state (ISSUE 11): a draining runner finishes /
    # migrates its in-flight work but takes NO new requests —
    # ``pick_runner`` skips it (including half-open breaker probes,
    # which would be burned on a runner about to exit).  It stays in
    # ``model_map`` so a cluster-wide drain answers 503 code=draining
    # instead of 404.  ``drain_deadline`` (unix seconds, 0 = unknown)
    # feeds the honest Retry-After on that 503.
    draining: bool = False
    drain_deadline: float = 0.0
    # pool role (ISSUE 14): prefill | decode | mixed.  Profile-declared,
    # heartbeat-federated; ordinary picks avoid prefill-pool runners
    # (they serve handoff prefills), the disagg handoff picks from them
    # strictly.  Mixed (the default) behaves exactly as before roles
    # existed.
    role: str = POOL_MIXED
    # mesh-health block (ISSUE 17): per-model multi-host role plus
    # follower lag-ladder states / takeover counters, sanitised by
    # multihost_serving.validate_mh_block at heartbeat ingestion —
    # /v1/cluster/status renders it, pruned with the runner
    multihost: dict = dataclasses.field(default_factory=dict)
    # correctness-canary health block (ISSUE 19): rung + counters +
    # failing axes, sanitised by obs.canary.validate_canary_block at
    # heartbeat ingestion — the corruption-aware avoid's signal,
    # pruned with the runner like saturation
    canary: dict = dataclasses.field(default_factory=dict)

    @property
    def routable(self) -> bool:
        return self.profile_status == ROUTABLE_STATUS and bool(self.models)


class InferenceRouter:
    def __init__(
        self,
        ttl_seconds: float = 90.0,
        breaker: Optional[BreakerConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        policy: Optional[RouterPolicy] = None,
    ):
        self.ttl = ttl_seconds
        self.breaker_cfg = breaker or BreakerConfig()
        self.clock = clock
        self.policy = policy if policy is not None else (
            RouterPolicy.from_env()
        )
        self._runners: dict[str, RunnerState] = {}
        self._rr: dict[str, int] = {}  # per-model round-robin cursor
        self._breakers: dict[str, CircuitBreaker] = {}
        self._inflight: dict[str, int] = {}
        self._lock = threading.Lock()
        # prefix-affinity map (a hint store: always constructed, only
        # consulted when policy.affinity) + routing decision counters
        # for collect_cp_routing (plain ints mutated under the lock)
        self._affinity = PrefixAffinity(self.policy.affinity_entries)
        self.route_decisions_rr = 0
        self.route_decisions_scored = 0
        self.route_hard_avoided = 0
        self.route_saturation_sheds = 0
        self.route_affinity_hits = 0
        self.route_affinity_yields = 0
        self.route_class_steered = 0
        self.route_stale_neutral = 0
        # multi-LoRA adapter-affinity (ISSUE 15): picks placed on a
        # runner whose heartbeat residency block held the adapter
        self.route_adapter_affinity_hits = 0
        # corruption-aware routing (ISSUE 19): picks steered around a
        # canary-failing runner, and picks served BY one because it was
        # the last candidate for the model (serve-with-warning)
        self.route_canary_avoided = 0
        self.route_canary_served_failing = 0
        # disaggregated prefill/decode (ISSUE 14): handoff outcomes,
        # incremented by the dispatch orchestration (plain ints, GIL-
        # atomic) and rendered by collect_cp_pools
        self.pool_handoffs = 0
        self.pool_handoff_fallbacks = 0
        # trace federation (ISSUE 18): the control plane hooks this so
        # a dead runner's federated spans are pruned the same moment
        # its saturation/breaker/affinity state is (called outside the
        # lock, once per departed runner id)
        self.on_evict: Optional[Callable[[str], None]] = None

    def _breaker(self, runner_id: str) -> CircuitBreaker:
        """Lock must be held."""
        br = self._breakers.get(runner_id)
        if br is None:
            br = CircuitBreaker(self.breaker_cfg, clock=self.clock)
            self._breakers[runner_id] = br
        return br

    def upsert_from_heartbeat(
        self,
        runner_id: str,
        *,
        models: Optional[list] = None,
        profile_name: str = "",
        profile_status: str = "assigning",
        accelerators: Optional[list] = None,
        meta: Optional[dict] = None,
        saturation: Optional[dict] = None,
        tenants: Optional[dict] = None,
        adapters: Optional[list] = None,
        draining: bool = False,
        drain_deadline: float = 0.0,
        role: str = POOL_MIXED,
        multihost: Optional[dict] = None,
        canary: Optional[dict] = None,
    ) -> RunnerState:
        with self._lock:
            st = self._runners.get(runner_id)
            if st is None:
                st = RunnerState(id=runner_id)
                self._runners[runner_id] = st
            st.role = sanitize_pool_role(role)
            st.models = list(models or [])
            st.profile_name = profile_name
            st.profile_status = profile_status
            st.accelerators = list(accelerators or [])
            st.last_heartbeat = self.clock()
            if meta:
                st.meta.update(meta)
            if saturation is not None:
                st.saturation = dict(saturation)
                if saturation:
                    st.saturation_at = self.clock()
            if tenants is not None:
                st.tenants = dict(tenants)
            if adapters is not None:
                st.adapters = list(adapters)
            if multihost is not None:
                st.multihost = dict(multihost)
            if canary is not None:
                st.canary = dict(canary)
            st.draining = bool(draining)
            st.drain_deadline = float(drain_deadline or 0.0)
            return st

    def evict_stale(self) -> list:
        now = self.clock()
        with self._lock:
            dead = [
                rid
                for rid, st in self._runners.items()
                if now - st.last_heartbeat > self.ttl
            ]
            for rid in dead:
                del self._runners[rid]
                self._prune_dispatch_state(rid)
        for rid in dead:
            self._affinity.forget_runner(rid)
            if self.on_evict is not None:
                try:
                    self.on_evict(rid)
                except Exception:  # noqa: BLE001 — eviction must finish
                    pass
        return dead

    def _prune_dispatch_state(self, runner_id: str) -> None:
        """Drop breaker/in-flight state for a departed runner (lock must
        be held).  Without this, churning ephemeral runner ids grow the
        breaker map — and /metrics label cardinality — forever.  An
        in-flight dispatch keeps the entries alive until it completes;
        _record prunes when the last outcome for a departed id lands."""
        if self._inflight.get(runner_id, 0) == 0:
            self._breakers.pop(runner_id, None)
            self._inflight.pop(runner_id, None)

    def remove(self, runner_id: str) -> None:
        with self._lock:
            self._runners.pop(runner_id, None)
            self._prune_dispatch_state(runner_id)
        self._affinity.forget_runner(runner_id)
        if self.on_evict is not None:
            try:
                self.on_evict(runner_id)
            except Exception:  # noqa: BLE001 — removal must finish
                pass

    def get(self, runner_id: str) -> Optional[RunnerState]:
        with self._lock:
            return self._runners.get(runner_id)

    def runners(self) -> list:
        with self._lock:
            return list(self._runners.values())

    def available_models(self) -> list:
        """Union of models on routable, fresh runners (for /v1/models)."""
        now = self.clock()
        with self._lock:
            out = set()
            for st in self._runners.values():
                if st.routable and now - st.last_heartbeat <= self.ttl:
                    out.update(st.models)
            return sorted(out)

    def available_adapters(self) -> list:
        """Union of heartbeat-federated ``model@adapter`` residency
        entries on routable, fresh runners, bounded — the cp
        /v1/models adapter listing (ISSUE 15)."""
        now = self.clock()
        with self._lock:
            out = set()
            for st in self._runners.values():
                if st.routable and now - st.last_heartbeat <= self.ttl:
                    out.update(st.adapters)
        return sorted(out)[:128]

    def model_map(self) -> dict:
        """{model: [runner ids serving it]} over routable, fresh runners
        (the /api/v1/model-info shape)."""
        now = self.clock()
        with self._lock:
            out: dict = {}
            for st in sorted(self._runners.values(), key=lambda s: s.id):
                if st.routable and now - st.last_heartbeat <= self.ttl:
                    for m in st.models:
                        out.setdefault(m, []).append(st.id)
            return out

    def pick_runner(
        self, model: str, exclude: Iterable[str] = (),
        sched_class: str = "", affinity_key: Optional[str] = None,
        role: Optional[str] = None, adapter: str = "",
        trace_id: str = "",
    ) -> Optional[RunnerState]:
        """Failure- and load-aware pick over routable runners serving
        ``model``: skips runners in ``exclude`` (already tried this
        request) and runners whose circuit breaker is open (or half-open
        with no probe budget left).

        Under the default ``rr`` policy the remainder is the seed
        behaviour bit-for-bit: prefer the least-loaded, round-robin per
        model among ties.  Under ``scored`` (HELIX_ROUTER_POLICY) the
        pick closes the loop from federated heartbeat saturation:
        runners near KV/host-pool exhaustion (or with a squeezed prefill
        budget) are hard-avoided unless no alternative exists, runners
        past the FULL threshold are never picked (``None`` — the caller
        sheds via ``saturation_retry_after`` instead of dispatching into
        a guaranteed kv_exhausted), queue depth / slot and KV occupancy
        / in-flight dispatches / spec acceptance soft-rank the rest,
        batch-class traffic (``sched_class``) steers away from runners
        whose tenants are burning SLO budget, and stale or missing
        saturation scores NEUTRAL — never best.  ``affinity_key`` (a
        ``prefix_digest``) is honoured as a hint when the remembered
        runner is a non-avoided candidate; it yields to saturation.

        Pool roles (ISSUE 14): ``role="prefill"`` restricts the pick to
        prefill-pool runners (None when the pool is empty — the caller
        degrades to colocated serving).  Ordinary picks
        (``role=None``) avoid prefill-pool runners while ANY
        decode/mixed runner serves the model; when the prefill pool is
        all there is, it serves ordinary traffic too (degrade-to-local
        by design — a role is scheduling intent, not capability).

        Corruption-aware avoid (ISSUE 19, ``policy.canary_avoid``):
        runners whose federated correctness-canary health is failing or
        reprobing are hard-avoided under BOTH policies — wrong tokens
        are worse than slow ones.  Exception: when every remaining
        candidate is canary-failing, the pick proceeds anyway
        (serve-with-warning, counted + logged with ``trace_id``) — a
        possibly-false-positive probe must not shed a whole model,
        mirroring the all-candidates-full rule."""
        now = self.clock()
        exclude = set(exclude)
        with self._lock:
            candidates = [
                st
                for st in sorted(self._runners.values(), key=lambda s: s.id)
                if st.routable
                and not st.draining   # unroutable-for-new-work; also
                # keeps half-open breaker PROBES off a runner that is
                # about to exit — a probe burned there proves nothing
                and model in st.models
                and now - st.last_heartbeat <= self.ttl
                and st.id not in exclude
            ]
            if role == POOL_PREFILL:
                candidates = [
                    st for st in candidates if st.role == POOL_PREFILL
                ]
            else:
                non_prefill = [
                    st for st in candidates if st.role != POOL_PREFILL
                ]
                if non_prefill:
                    candidates = non_prefill
            if not candidates:
                return None
            allowed = [
                st for st in candidates if self._breaker(st.id).allow()
            ]
            if not allowed:
                return None
            if self.policy.canary_avoid:
                healthy = [
                    st for st in allowed if not canary_failing(st.canary)
                ]
                if healthy:
                    if len(healthy) < len(allowed):
                        self.route_canary_avoided += 1
                    allowed = healthy
                else:
                    # every candidate is canary-failing: serving wrong-
                    # token-SUSPECTED beats shedding the whole model on
                    # a possibly-false-positive probe
                    self.route_canary_served_failing += 1
                    log.warning(
                        "model %s: every candidate runner is canary-"
                        "failing (%s) — serving with warning "
                        "(trace_id=%s)",
                        model, sorted(st.id for st in allowed),
                        trace_id or "-",
                    )
            if self.policy.policy == ROUTE_POLICY_SCORED:
                return self._pick_scored(
                    model, allowed, now, sched_class, affinity_key,
                    adapter=adapter,
                )
            # -- seed baseline (bit-for-bit): least-loaded + RR ---------
            min_load = min(
                self._inflight.get(st.id, 0) for st in allowed
            )
            if adapter:
                # adapter-affinity (ISSUE 15): prefer a runner whose
                # heartbeat residency block already holds this adapter
                # in HBM — a HINT like prefix affinity, honoured only
                # among the least-loaded so a popular adapter
                # rebalances instead of pinning onto one runner.  No
                # resident runner = plain pick (the chosen runner's
                # residency ladder loads it on admission).
                key = f"{model}{'@'}{adapter}"
                warm = [
                    st for st in allowed
                    if key in st.adapters
                    and self._inflight.get(st.id, 0) <= min_load
                ]
                if warm:
                    self.route_adapter_affinity_hits += 1
                    self.route_decisions_rr += 1
                    return warm[0]
            if affinity_key is not None and self.policy.affinity:
                # a hint, not a pin, under rr too: honoured only while
                # the hinted runner is among the least-loaded — a busy
                # runner's popular prompt head rebalances instead of
                # pinning all same-head traffic onto it
                hint = self._affinity.get(affinity_key)
                chosen = next(
                    (
                        st for st in allowed
                        if st.id == hint
                        and self._inflight.get(st.id, 0) <= min_load
                    ),
                    None,
                )
                if chosen is not None:
                    self.route_affinity_hits += 1
                    self.route_decisions_rr += 1
                    self._affinity.put(affinity_key, chosen.id)
                    return chosen
                if hint is not None:
                    self.route_affinity_yields += 1
            least = [
                st
                for st in allowed
                if self._inflight.get(st.id, 0) == min_load
            ]
            cursor = self._rr.get(model, 0)
            chosen = least[cursor % len(least)]
            self._rr[model] = (cursor + 1) % max(len(least), 1)
            self.route_decisions_rr += 1
            if affinity_key is not None and self.policy.affinity:
                self._affinity.put(affinity_key, chosen.id)
            return chosen

    # -- scored policy internals (lock must be held) -----------------------

    def _score(
        self, st: RunnerState, now: float, sched_class: str
    ) -> tuple:
        """One candidate's routing verdict: ``(full, avoid, score,
        steered)``.  Score components live in [0, 1], lower = better;
        unknown (missing/stale) saturation pins every saturation-derived
        component at the 0.5 midpoint so an unreporting runner is
        NEUTRAL — it can win against a loaded runner but never against
        one that reports being idle (the 'fresh heartbeat with no
        saturation yet looks idle' bugfix)."""
        p = self.policy
        sat = st.saturation
        fresh = bool(sat) and (now - st.saturation_at) <= p.stale_after
        full = avoid = False
        if not fresh:
            self.route_stale_neutral += 1
            kv = host = slots = queue = spec = 0.5
        else:
            kv = min(max(float(sat.get("kv_occupancy", 0.0)), 0.0), 1.0)
            host = min(
                max(float(sat.get("kv_host_occupancy", 0.0)), 0.0), 1.0
            )
            total = float(sat.get("slots_total", 0) or 0)
            slots = (
                min(float(sat.get("slots_busy", 0)) / total, 1.0)
                if total > 0 else 0.5
            )
            qd = max(float(sat.get("queue_depth", 0)), 0.0)
            queue = qd / (qd + 4.0)
            # warm speculative acceptance is a soft preference; ratio 0
            # usually means spec is off/cold — neutral, not worst
            ratio = min(
                max(float(sat.get("spec_acceptance_ratio", 0.0)), 0.0),
                1.0,
            )
            spec = (1.0 - ratio) if ratio > 0 else 0.5
            budget = float(sat.get("prefill_budget_tokens", 0) or 0)
            avoid = (
                kv >= p.kv_avoid_threshold
                or host >= p.host_avoid_threshold
                # 0 = unbudgeted; a budget squeezed to the floor means
                # the scheduler's SLO-burn feedback is throttling there
                or 0 < budget <= p.prefill_avoid_tokens
            )
            full = kv >= p.kv_full_threshold
        infl = float(self._inflight.get(st.id, 0))
        load = infl / (infl + 4.0)
        score = (
            0.30 * kv + 0.10 * host + 0.15 * slots
            + 0.20 * queue + 0.15 * load + 0.10 * spec
        )
        steered = False
        if sched_class == "batch":
            top = (st.tenants or {}).get("top") or []
            worst = max(
                (
                    float(e.get("burn_rate_fast", 0.0) or 0.0)
                    for e in top
                    if isinstance(e, dict)
                ),
                default=0.0,
            )
            if worst > p.burn_steer_threshold:
                # keep batch floods off a runner whose interactive
                # tenants are already burning SLO budget — a soft
                # penalty, not an avoid (batch still lands somewhere)
                score += 0.5
                steered = True
        return full, avoid, score, steered

    def _pick_scored(
        self, model: str, allowed: list, now: float,
        sched_class: str, affinity_key: Optional[str],
        adapter: str = "",
    ) -> Optional[RunnerState]:
        scored = [
            (st, *self._score(st, now, sched_class)) for st in allowed
        ]
        # FULL runners are excluded from BOTH pools (a dispatch there is
        # a guaranteed kv_exhausted) — including from `ok`, so a config
        # with kv_avoid_threshold above kv_full_threshold cannot sneak a
        # full-but-not-avoided runner back in
        ok = [e for e in scored if not e[1] and not e[2]]
        last_resort = [e for e in scored if e[2] and not e[1]]
        if ok and len(ok) < len(scored):
            self.route_hard_avoided += 1
        if any(e[4] for e in scored):
            self.route_class_steered += 1
        pool = ok or last_resort
        if not pool:
            # every candidate is FULL: dispatching is a guaranteed typed
            # kv_exhausted at the runner — the caller sheds at the cp
            # with an honest Retry-After (saturation_retry_after)
            return None
        if affinity_key is not None and self.policy.affinity:
            hint = self._affinity.get(affinity_key)
            if hint is not None:
                entry = next(
                    (e for e in ok if e[0].id == hint), None
                )
                if entry is not None:
                    self.route_affinity_hits += 1
                    self.route_decisions_scored += 1
                    self._affinity.put(affinity_key, entry[0].id)
                    return entry[0]
                # the remembered runner is gone, excluded, or saturated:
                # affinity is a hint, not a pin — yield to the scorer
                self.route_affinity_yields += 1
        if adapter:
            # adapter-affinity (ISSUE 15): restrict to NON-AVOIDED
            # candidates already holding this adapter in HBM (the
            # heartbeat residency block) — still score-ordered within,
            # and yielding entirely to saturation like prefix affinity
            key = f"{model}{'@'}{adapter}"
            warm = [e for e in ok if key in e[0].adapters]
            if warm:
                self.route_adapter_affinity_hits += 1
                pool = warm
        best = min(e[3] for e in pool)
        least = [e[0] for e in pool if e[3] <= best + 1e-9]
        cursor = self._rr.get(model, 0)
        chosen = least[cursor % len(least)]
        self._rr[model] = (cursor + 1) % max(len(least), 1)
        self.route_decisions_scored += 1
        if affinity_key is not None and self.policy.affinity:
            self._affinity.put(affinity_key, chosen.id)
        return chosen

    def saturation_retry_after(self, model: str) -> Optional[int]:
        """When the scored policy refused to place a request because
        EVERY fresh, routable, non-draining runner serving ``model`` is
        past the FULL KV threshold: the honest Retry-After in seconds
        (cluster queue backlog over cluster goodput, clamped to [1, 30]).
        None = not a saturation shed — the caller keeps its ordinary
        error shape (breakers-open / no-candidates)."""
        if self.policy.policy != ROUTE_POLICY_SCORED:
            return None
        now = self.clock()
        with self._lock:
            serving = [
                st
                for st in self._runners.values()
                if st.routable
                and not st.draining
                and model in st.models
                and now - st.last_heartbeat <= self.ttl
            ]
            if not serving:
                return None
            qd = tps = 0.0
            for st in serving:
                sat = st.saturation
                fresh = bool(sat) and (
                    now - st.saturation_at <= self.policy.stale_after
                )
                if not fresh or (
                    float(sat.get("kv_occupancy", 0.0))
                    < self.policy.kv_full_threshold
                ):
                    return None
                qd += max(float(sat.get("queue_depth", 0)), 0.0)
                tps += max(float(sat.get("tokens_per_sec", 0.0)), 0.0)
            self.route_saturation_sheds += 1
            return max(1, min(30, int(qd / max(tps, 1.0)) + 1))

    def routing_status(self) -> dict:
        """The /v1/cluster/status 'routing' block: live policy +
        decision counters (the JSON twin of collect_cp_routing)."""
        p = self.policy
        return {
            "policy": p.policy,
            "prefix_affinity": p.affinity,
            "kv_avoid_threshold": p.kv_avoid_threshold,
            "kv_full_threshold": p.kv_full_threshold,
            "host_avoid_threshold": p.host_avoid_threshold,
            "prefill_avoid_tokens": p.prefill_avoid_tokens,
            "burn_steer_threshold": p.burn_steer_threshold,
            "decisions_rr": self.route_decisions_rr,
            "decisions_scored": self.route_decisions_scored,
            "hard_avoided": self.route_hard_avoided,
            "saturation_sheds": self.route_saturation_sheds,
            "affinity_hits": self.route_affinity_hits,
            "affinity_yields": self.route_affinity_yields,
            "class_steered": self.route_class_steered,
            "stale_neutral": self.route_stale_neutral,
            "affinity_entries": len(self._affinity),
            "canary_avoid": p.canary_avoid,
            "canary_avoided": self.route_canary_avoided,
            "canary_served_failing": self.route_canary_served_failing,
        }

    def drain_retry_after(self, model: str) -> Optional[int]:
        """When EVERY fresh, routable runner serving ``model`` is
        draining, the honest Retry-After in seconds (the latest reported
        drain deadline, floored at 1s; a conservative default when no
        runner reported one).  None = at least one non-draining runner
        exists (or none serve the model at all) — the caller keeps its
        ordinary error shape."""
        now = self.clock()
        with self._lock:
            serving = [
                st
                for st in self._runners.values()
                if st.routable
                and model in st.models
                and now - st.last_heartbeat <= self.ttl
            ]
            if not serving or any(not st.draining for st in serving):
                return None
            deadlines = [
                st.drain_deadline for st in serving if st.drain_deadline
            ]
            if not deadlines:
                return 5
            import time as _time

            return max(1, int(max(deadlines) - _time.time()) + 1)

    def draining_map(self) -> dict:
        """{runner_id: draining} over live runners — the drain-state
        gauge's source; pruned with the runner like saturation_map."""
        with self._lock:
            return {
                rid: st.draining
                for rid, st in sorted(self._runners.items())
            }

    def note_pool_handoff(self) -> None:
        """A disaggregated prefill handoff reached its decode peer."""
        self.pool_handoffs += 1

    def note_pool_fallback(self) -> None:
        """A disaggregated handoff attempt fell back to colocated
        serving (prefill runner failed / ship failed / resume failed)."""
        self.pool_handoff_fallbacks += 1

    def role_counts(self) -> dict:
        """{role: routable fresh runners} — the pool-shape gauge source
        and the /v1/cluster/status pools block."""
        now = self.clock()
        out = {r: 0 for r in POOL_ROLES}
        with self._lock:
            for st in self._runners.values():
                if st.routable and now - st.last_heartbeat <= self.ttl:
                    out[sanitize_pool_role(st.role)] += 1
        return out

    def pools_status(self) -> dict:
        """The /v1/cluster/status 'pools' block (the JSON twin of
        collect_cp_pools)."""
        return {
            "roles": self.role_counts(),
            "handoffs": self.pool_handoffs,
            "handoff_fallbacks": self.pool_handoff_fallbacks,
        }

    def migration_targets(self, for_runner: str) -> list:
        """Peers a draining runner may ship snapshots to: fresh,
        routable, NOT draining, with an address, excluding the asker.
        Each entry carries the peer's model list so the shipper can
        match a snapshot's model to a runner that serves it."""
        now = self.clock()
        with self._lock:
            return [
                {
                    "id": st.id,
                    "address": st.meta.get("address", ""),
                    "models": list(st.models),
                    "role": st.role,
                }
                for st in sorted(
                    self._runners.values(), key=lambda s: s.id
                )
                if st.routable
                and not st.draining
                and st.id != for_runner
                and now - st.last_heartbeat <= self.ttl
                and st.meta.get("address")
            ]

    # -- dispatch feedback (breakers + load) -------------------------------

    def record_dispatch_start(self, runner_id: str) -> int:
        """The dispatcher is about to send a request to this runner.
        Returns the breaker epoch token to pass back to record_*, so an
        outcome that straddles a breaker state change is discarded
        instead of, e.g., closing a half-open breaker on the strength of
        a stream that started before the runner broke."""
        with self._lock:
            self._inflight[runner_id] = self._inflight.get(runner_id, 0) + 1
            return self._breaker(runner_id).on_dispatch()

    def _record(
        self, runner_id: str, failure: bool, epoch: Optional[int] = None
    ) -> None:
        with self._lock:
            self._inflight[runner_id] = max(
                0, self._inflight.get(runner_id, 0) - 1
            )
            self._breaker(runner_id).record(failure=failure, epoch=epoch)
            if runner_id not in self._runners:
                # runner departed while this dispatch was in flight: once
                # the last one lands, drop its state entirely
                self._prune_dispatch_state(runner_id)

    def record_success(
        self, runner_id: str, epoch: Optional[int] = None
    ) -> None:
        self._record(runner_id, failure=False, epoch=epoch)

    def record_failure(
        self, runner_id: str, epoch: Optional[int] = None
    ) -> None:
        self._record(runner_id, failure=True, epoch=epoch)

    def record_release(
        self, runner_id: str, epoch: Optional[int] = None
    ) -> None:
        """Dispatch ended with no attributable outcome (client cancelled
        mid-flight): free the in-flight slot and probe budget without
        feeding the breaker's failure window or probe successes."""
        with self._lock:
            self._inflight[runner_id] = max(
                0, self._inflight.get(runner_id, 0) - 1
            )
            self._breaker(runner_id).release(epoch=epoch)
            if runner_id not in self._runners:
                self._prune_dispatch_state(runner_id)

    def inflight(self, runner_id: str) -> int:
        with self._lock:
            return self._inflight.get(runner_id, 0)

    def saturation_map(self) -> dict:
        """{runner_id: last-heartbeat saturation summary} over runners
        that reported one.  Departed runners vanish here the moment they
        are evicted (the summary lives on RunnerState), so the
        ``helix_cp_runner_saturation_*`` gauges can never leak labels."""
        with self._lock:
            return {
                rid: dict(st.saturation)
                for rid, st in sorted(self._runners.items())
                if st.saturation
            }

    def tenants_map(self) -> dict:
        """{runner_id: last-heartbeat tenants rollup} over runners that
        reported one.  Pruned with the runner, like saturation_map — the
        cp's per-tenant burn gauges can never leak labels."""
        with self._lock:
            return {
                rid: dict(st.tenants)
                for rid, st in sorted(self._runners.items())
                if st.tenants
            }

    def canary_map(self) -> dict:
        """{runner_id: last-heartbeat canary health block} over runners
        that reported one.  Pruned with the runner, like saturation_map
        — the cp's ``helix_cp_canary_*`` series can never leak labels."""
        with self._lock:
            return {
                rid: dict(st.canary)
                for rid, st in sorted(self._runners.items())
                if st.canary
            }

    def breaker_states(self) -> dict:
        """{runner_id: breaker snapshot + inflight} for /metrics and
        operator introspection.  A runner evicted with dispatches still
        in flight lingers until its last outcome lands, then is pruned."""
        with self._lock:
            return {
                rid: {
                    **br.snapshot(),
                    "inflight": self._inflight.get(rid, 0),
                }
                for rid, br in sorted(self._breakers.items())
            }


def collect_cp_routing(c, router: "InferenceRouter") -> None:
    """Control-plane routing series (called from the cp's scrape-time
    collector; plain GIL-atomic reads).  The ``helix_cp_route_*``
    vocabulary is minted here and only here (lint contract 8)."""
    c.gauge(
        CP_ROUTE_POLICY,
        1 if router.policy.policy == ROUTE_POLICY_SCORED else 0,
        help="1 while the saturation-aware scored routing policy is on",
    )
    c.counter(
        CP_ROUTE_DECISIONS, router.route_decisions_rr,
        {"policy": ROUTE_POLICY_RR},
        help="Placement decisions by policy",
    )
    c.counter(
        CP_ROUTE_DECISIONS, router.route_decisions_scored,
        {"policy": ROUTE_POLICY_SCORED},
    )
    c.counter(
        CP_ROUTE_HARD_AVOIDED, router.route_hard_avoided,
        help="Picks that steered around a runner near KV/host-pool "
             "exhaustion or with a squeezed prefill budget",
    )
    c.counter(
        CP_ROUTE_SATURATION_SHEDS, router.route_saturation_sheds,
        help="Requests shed at the control plane (typed 503) because "
             "every candidate runner was past the FULL KV threshold",
    )
    c.counter(
        CP_ROUTE_AFFINITY_HITS, router.route_affinity_hits,
        help="Dispatches placed on the prefix-affinity hinted runner",
    )
    c.counter(
        CP_ROUTE_AFFINITY_YIELDS, router.route_affinity_yields,
        help="Affinity hints not honoured (runner gone, excluded, or "
             "saturated) — affinity yields to saturation",
    )
    c.counter(
        CP_ROUTE_CLASS_STEERED, router.route_class_steered,
        help="Batch-class picks where at least one candidate was "
             "penalised for tenant SLO-budget burn",
    )
    c.counter(
        CP_ROUTE_STALE_NEUTRAL, router.route_stale_neutral,
        help="Candidate scorings that fell back to the neutral midpoint "
             "because the runner's saturation was missing or stale",
    )
    c.gauge(
        CP_ROUTE_AFFINITY_ENTRIES, len(router._affinity),
        help="Live prefix-digest -> runner entries in the affinity LRU",
    )
    c.counter(
        CP_ROUTE_ADAPTER_AFFINITY_HITS,
        router.route_adapter_affinity_hits,
        help="Dispatches placed on a runner whose heartbeat residency "
             "block already held the request's adapter in HBM",
    )


def collect_cp_pools(
    c, router: "InferenceRouter", disagg_enabled: bool = False
) -> None:
    """Control-plane pool-role series (ISSUE 14, called from the cp's
    scrape-time collector).  The ``helix_cp_pool_*`` vocabulary is
    minted here and only here (lint contract 10)."""
    for role, n in sorted(router.role_counts().items()):
        c.gauge(
            CP_POOL_RUNNERS, n, {"role": role},
            help="Routable runners by declared pool role",
        )
    c.counter(
        CP_POOL_HANDOFFS, router.pool_handoffs,
        help="Disaggregated prefill handoffs that resumed on the "
             "decode peer",
    )
    c.counter(
        CP_POOL_HANDOFF_FALLBACKS, router.pool_handoff_fallbacks,
        help="Handoff attempts that fell back to colocated serving "
             "(prefill/ship/resume failure — the degrade ladder)",
    )
    c.gauge(
        CP_POOL_DISAGG_ENABLED, 1 if disagg_enabled else 0,
        help="1 while disaggregated prefill/decode routing is enabled",
    )
