"""Control-plane store: SQLite-backed persistence.

The reference persists ~80 GORM entities in Postgres
(``api/pkg/store/postgres.go:170-258``).  This build uses stdlib SQLite so
the control plane stays a single self-hostable process with zero external
dependencies; the entity surface starts with the serving plane's tables
(profiles, assignments, runner snapshots, sessions/interactions, api keys)
and grows with the layers above it.  JSON documents in columns play the
role of GORM's struct serialisation; every access goes through one lock
(SQLite is the bottleneck only far beyond this control plane's write rates).
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
import uuid
from typing import Any, Optional

_SCHEMA = """
CREATE TABLE IF NOT EXISTS profiles (
    name TEXT PRIMARY KEY,
    doc  TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS assignments (
    runner_id TEXT PRIMARY KEY,
    profile_name TEXT,
    assigned_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS runners (
    runner_id TEXT PRIMARY KEY,
    last_heartbeat TEXT,      -- JSON snapshot of last heartbeat
    updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS sessions (
    id TEXT PRIMARY KEY,
    owner TEXT,
    name TEXT,
    doc TEXT NOT NULL,        -- JSON: model, system prompt, app binding...
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS interactions (
    id TEXT PRIMARY KEY,
    session_id TEXT NOT NULL,
    seq INTEGER NOT NULL,
    doc TEXT NOT NULL,        -- JSON: role, content, usage, state
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_interactions_session
    ON interactions(session_id, seq);
CREATE TABLE IF NOT EXISTS api_keys (
    key TEXT PRIMARY KEY,
    owner TEXT NOT NULL,
    name TEXT,
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS llm_calls (
    id TEXT PRIMARY KEY,
    session_id TEXT,
    model TEXT,
    provider TEXT,
    doc TEXT NOT NULL,        -- JSON: request/response summary, usage, ms
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS usage_metrics (
    id TEXT PRIMARY KEY,
    owner TEXT,
    model TEXT,
    prompt_tokens INTEGER,
    completion_tokens INTEGER,
    created_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS apps (
    id TEXT PRIMARY KEY,
    owner TEXT,
    name TEXT NOT NULL,
    doc TEXT NOT NULL,        -- JSON: assistants, triggers, secrets refs
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS kv (
    k TEXT PRIMARY KEY,
    v TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS eval_suites (
    id TEXT PRIMARY KEY,
    app_id TEXT,
    owner TEXT,
    doc TEXT NOT NULL,        -- JSON: name, description, questions[]
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_eval_suites_app ON eval_suites(app_id);
CREATE TABLE IF NOT EXISTS eval_runs (
    id TEXT PRIMARY KEY,
    suite_id TEXT NOT NULL,
    app_id TEXT,
    owner TEXT,
    status TEXT NOT NULL,     -- pending|running|completed|failed|cancelled
    doc TEXT NOT NULL,        -- JSON: summary, results[], error
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_eval_runs_suite ON eval_runs(suite_id);
"""


class Store:
    def __init__(self, path=":memory:"):
        from helix_tpu.control.db import Database

        self._db = Database.resolve(path)
        self._conn = self._db.conn
        self._lock = self._db.lock
        self._db.migrate("core", [(1, "initial", _SCHEMA)])

    # -- profiles ----------------------------------------------------------
    def upsert_profile(self, name: str, doc: dict) -> None:
        now = time.time()
        with self._lock:
            self._conn.execute(
                "INSERT INTO profiles(name, doc, created_at, updated_at) "
                "VALUES(?,?,?,?) ON CONFLICT(name) DO UPDATE SET "
                "doc=excluded.doc, updated_at=excluded.updated_at",
                (name, json.dumps(doc), now, now),
            )
            self._db.commit()

    def get_profile(self, name: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT doc FROM profiles WHERE name=?", (name,)
            ).fetchone()
        return json.loads(row[0]) if row else None

    def list_profiles(self) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT doc FROM profiles ORDER BY name"
            ).fetchall()
        return [json.loads(r[0]) for r in rows]

    def delete_profile(self, name: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM profiles WHERE name=?", (name,)
            )
            self._db.commit()
            return cur.rowcount > 0

    # -- assignments -------------------------------------------------------
    def set_assignment(self, runner_id: str, profile_name: Optional[str]) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO assignments(runner_id, profile_name, assigned_at) "
                "VALUES(?,?,?) ON CONFLICT(runner_id) DO UPDATE SET "
                "profile_name=excluded.profile_name, "
                "assigned_at=excluded.assigned_at",
                (runner_id, profile_name, time.time()),
            )
            self._db.commit()

    def list_assignments(self) -> list:
        """[(runner_id, profile_name)] for runners with a live assignment
        (the autoscaler's shed-protection set)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT runner_id, profile_name FROM assignments "
                "WHERE profile_name IS NOT NULL"
            ).fetchall()
        return [(r[0], r[1]) for r in rows]

    def get_assignment(self, runner_id: str) -> Optional[str]:
        with self._lock:
            row = self._conn.execute(
                "SELECT profile_name FROM assignments WHERE runner_id=?",
                (runner_id,),
            ).fetchone()
        return row[0] if row else None

    # -- runners -----------------------------------------------------------
    def record_heartbeat(self, runner_id: str, payload: dict) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO runners(runner_id, last_heartbeat, updated_at) "
                "VALUES(?,?,?) ON CONFLICT(runner_id) DO UPDATE SET "
                "last_heartbeat=excluded.last_heartbeat, "
                "updated_at=excluded.updated_at",
                (runner_id, json.dumps(payload), time.time()),
            )
            self._db.commit()

    def get_runner(self, runner_id: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT last_heartbeat FROM runners WHERE runner_id=?",
                (runner_id,),
            ).fetchone()
        return json.loads(row[0]) if row and row[0] else None

    def list_runners(self) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT runner_id, last_heartbeat, updated_at FROM runners"
            ).fetchall()
        return [
            {
                "runner_id": r[0],
                "last_heartbeat": json.loads(r[1]) if r[1] else None,
                "updated_at": r[2],
            }
            for r in rows
        ]

    # -- sessions / interactions ------------------------------------------
    def create_session(self, owner: str, name: str, doc: dict) -> str:
        sid = f"ses_{uuid.uuid4().hex[:16]}"
        now = time.time()
        with self._lock:
            self._conn.execute(
                "INSERT INTO sessions(id, owner, name, doc, created_at, "
                "updated_at) VALUES(?,?,?,?,?,?)",
                (sid, owner, name, json.dumps(doc), now, now),
            )
            self._db.commit()
        return sid

    def get_session(self, sid: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, owner, name, doc, created_at, updated_at "
                "FROM sessions WHERE id=?",
                (sid,),
            ).fetchone()
        if not row:
            return None
        return {
            "id": row[0], "owner": row[1], "name": row[2],
            "doc": json.loads(row[3]),
            "created_at": row[4], "updated_at": row[5],
        }

    def update_session(self, sid: str, doc: dict) -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE sessions SET doc=?, updated_at=? WHERE id=?",
                (json.dumps(doc), time.time(), sid),
            )
            self._db.commit()

    def rename_session(self, sid: str, name: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "UPDATE sessions SET name=?, updated_at=? WHERE id=?",
                (name, time.time(), sid),
            )
            self._db.commit()
        return cur.rowcount > 0

    def search_sessions(self, q: str, owner: Optional[str] = None,
                        limit: int = 50) -> list:
        """Name-substring search (reference /sessions?search= surface).
        LIKE metacharacters in the query are literals: 'q=50%' must match
        names containing '50%', not anything containing '50'."""
        from helix_tpu.utils import like_escape

        like = f"%{like_escape(q)}%"
        sql = ("SELECT id, owner, name, created_at, updated_at FROM"
               " sessions WHERE name LIKE ? ESCAPE '\\'")
        args: list = [like]
        if owner:
            sql += " AND owner=?"
            args.append(owner)
        sql += " ORDER BY updated_at DESC LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._conn.execute(sql, args).fetchall()
        return [
            {
                "id": r[0], "owner": r[1], "name": r[2],
                "created_at": r[3], "updated_at": r[4],
            }
            for r in rows
        ]

    def list_sessions(self, owner: Optional[str] = None) -> list:
        q = "SELECT id, owner, name, created_at, updated_at FROM sessions"
        args: tuple = ()
        if owner:
            q += " WHERE owner=?"
            args = (owner,)
        q += " ORDER BY updated_at DESC"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [
            {
                "id": r[0], "owner": r[1], "name": r[2],
                "created_at": r[3], "updated_at": r[4],
            }
            for r in rows
        ]

    def delete_session(self, sid: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM sessions WHERE id=?", (sid,))
            self._conn.execute(
                "DELETE FROM interactions WHERE session_id=?", (sid,)
            )
            self._db.commit()

    def add_interaction(self, session_id: str, doc: dict) -> str:
        iid = f"int_{uuid.uuid4().hex[:16]}"
        with self._lock:
            row = self._conn.execute(
                "SELECT COALESCE(MAX(seq), -1) FROM interactions "
                "WHERE session_id=?",
                (session_id,),
            ).fetchone()
            seq = (row[0] if row else -1) + 1
            self._conn.execute(
                "INSERT INTO interactions(id, session_id, seq, doc, "
                "created_at) VALUES(?,?,?,?,?)",
                (iid, session_id, seq, json.dumps(doc), time.time()),
            )
            self._db.commit()
        return iid

    def list_interactions(self, session_id: str) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, seq, doc, created_at FROM interactions "
                "WHERE session_id=? ORDER BY seq",
                (session_id,),
            ).fetchall()
        return [
            {"id": r[0], "seq": r[1], **json.loads(r[2]), "created_at": r[3]}
            for r in rows
        ]

    # -- telemetry ---------------------------------------------------------
    def log_llm_call(self, doc: dict, session_id="", model="", provider="") -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO llm_calls(id, session_id, model, provider, doc, "
                "created_at) VALUES(?,?,?,?,?,?)",
                (
                    f"llm_{uuid.uuid4().hex[:16]}", session_id, model,
                    provider, json.dumps(doc), time.time(),
                ),
            )
            self._db.commit()

    def list_llm_calls(self, session_id: str = "", limit: int = 100) -> list:
        """Admin observability surface (reference /api/v1/llm_calls):
        newest first, optionally filtered to one session."""
        q = ("SELECT id, session_id, model, provider, doc, created_at"
             " FROM llm_calls")
        args: tuple = ()
        if session_id:
            q += " WHERE session_id=?"
            args = (session_id,)
        q += " ORDER BY created_at DESC LIMIT ?"
        with self._lock:
            rows = self._conn.execute(q, (*args, limit)).fetchall()
        return [
            {
                "id": r[0], "session_id": r[1], "model": r[2],
                "provider": r[3], "doc": json.loads(r[4]),
                "created_at": r[5],
            }
            for r in rows
        ]

    def add_usage(self, owner: str, model: str, prompt: int, completion: int):
        with self._lock:
            self._conn.execute(
                "INSERT INTO usage_metrics(id, owner, model, prompt_tokens, "
                "completion_tokens, created_at) VALUES(?,?,?,?,?,?)",
                (
                    f"use_{uuid.uuid4().hex[:16]}", owner, model,
                    prompt, completion, time.time(),
                ),
            )
            self._db.commit()

    def usage_summary(self, owner: Optional[str] = None) -> dict:
        q = (
            "SELECT model, SUM(prompt_tokens), SUM(completion_tokens), "
            "COUNT(*) FROM usage_metrics"
        )
        args: tuple = ()
        if owner:
            q += " WHERE owner=?"
            args = (owner,)
        q += " GROUP BY model"
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return {
            r[0]: {
                "prompt_tokens": r[1] or 0,
                "completion_tokens": r[2] or 0,
                "requests": r[3],
            }
            for r in rows
        }

    # -- apps --------------------------------------------------------------
    def upsert_app(self, name: str, owner: str, doc: dict,
                   app_id: Optional[str] = None) -> str:
        now = time.time()
        with self._lock:
            if app_id is None:
                row = self._conn.execute(
                    "SELECT id FROM apps WHERE name=? AND owner=?",
                    (name, owner),
                ).fetchone()
                app_id = row[0] if row else f"app_{uuid.uuid4().hex[:16]}"
            self._conn.execute(
                "INSERT INTO apps(id, owner, name, doc, created_at, "
                "updated_at) VALUES(?,?,?,?,?,?) ON CONFLICT(id) DO UPDATE "
                "SET doc=excluded.doc, name=excluded.name, "
                "updated_at=excluded.updated_at",
                (app_id, owner, name, json.dumps(doc), now, now),
            )
            self._db.commit()
        return app_id

    def get_app(self, app_id: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, owner, name, doc FROM apps WHERE id=? OR name=?",
                (app_id, app_id),
            ).fetchone()
        if not row:
            return None
        return {
            "id": row[0], "owner": row[1], "name": row[2],
            "doc": json.loads(row[3]),
        }

    def list_apps(self, owner: Optional[str] = None) -> list:
        q = "SELECT id, owner, name, doc FROM apps"
        args: tuple = ()
        if owner:
            q += " WHERE owner=?"
            args = (owner,)
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [
            {"id": r[0], "owner": r[1], "name": r[2], "doc": json.loads(r[3])}
            for r in rows
        ]

    def delete_app(self, app_id: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM apps WHERE id=?", (app_id,)
            )
            self._db.commit()
            return cur.rowcount > 0

    # -- kv ----------------------------------------------------------------
    def kv_set(self, k: str, v: Any) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv(k, v) VALUES(?,?) ON CONFLICT(k) "
                "DO UPDATE SET v=excluded.v",
                (k, json.dumps(v)),
            )
            self._db.commit()

    def kv_get(self, k: str, default=None) -> Any:
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k=?", (k,)
            ).fetchone()
        return json.loads(row[0]) if row else default

    # -- evaluation suites / runs ------------------------------------------
    # (reference: EvaluationSuite/EvaluationRun entities,
    #  api/pkg/types/evaluation.go + store/postgres.go:245-246)
    def create_eval_suite(self, app_id: str, owner: str, doc: dict) -> str:
        sid = "evs-" + uuid.uuid4().hex[:12]
        now = time.time()
        with self._lock:
            self._conn.execute(
                "INSERT INTO eval_suites(id, app_id, owner, doc, "
                "created_at, updated_at) VALUES(?,?,?,?,?,?)",
                (sid, app_id, owner, json.dumps(doc), now, now),
            )
            self._db.commit()
        return sid

    def update_eval_suite(self, sid: str, doc: dict) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "UPDATE eval_suites SET doc=?, updated_at=? WHERE id=?",
                (json.dumps(doc), time.time(), sid),
            )
            self._db.commit()
            return cur.rowcount > 0

    def get_eval_suite(self, sid: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, app_id, owner, doc, created_at, updated_at "
                "FROM eval_suites WHERE id=?",
                (sid,),
            ).fetchone()
        return self._suite_row(row) if row else None

    def list_eval_suites(self, app_id: Optional[str] = None) -> list:
        """None = every suite; "" = standalone question sets only; any
        other value = that app's suites."""
        q = ("SELECT id, app_id, owner, doc, created_at, updated_at "
             "FROM eval_suites")
        args: tuple = ()
        if app_id is not None:
            q += " WHERE app_id=?"
            args = (app_id,)
        with self._lock:
            rows = self._conn.execute(
                q + " ORDER BY created_at", args
            ).fetchall()
        return [self._suite_row(r) for r in rows]

    def delete_eval_suite(self, sid: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM eval_suites WHERE id=?", (sid,)
            )
            self._conn.execute(
                "DELETE FROM eval_runs WHERE suite_id=?", (sid,)
            )
            self._db.commit()
            return cur.rowcount > 0

    @staticmethod
    def _suite_row(row) -> dict:
        doc = json.loads(row[3])
        doc.update(
            id=row[0], app_id=row[1], owner=row[2],
            created_at=row[4], updated_at=row[5],
        )
        return doc

    def create_eval_run(
        self, suite_id: str, app_id: str, owner: str, doc: dict,
        status: str = "pending",
    ) -> str:
        rid = "evr-" + uuid.uuid4().hex[:12]
        now = time.time()
        with self._lock:
            self._conn.execute(
                "INSERT INTO eval_runs(id, suite_id, app_id, owner, status, "
                "doc, created_at, updated_at) VALUES(?,?,?,?,?,?,?,?)",
                (rid, suite_id, app_id, owner, status, json.dumps(doc),
                 now, now),
            )
            self._db.commit()
        return rid

    def update_eval_run(self, rid: str, status: str, doc: dict) -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE eval_runs SET status=?, doc=?, updated_at=? "
                "WHERE id=?",
                (status, json.dumps(doc), time.time(), rid),
            )
            self._db.commit()

    def get_eval_run(self, rid: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, suite_id, app_id, owner, status, doc, "
                "created_at, updated_at FROM eval_runs WHERE id=?",
                (rid,),
            ).fetchone()
        return self._run_row(row) if row else None

    def list_eval_runs(self, suite_id: Optional[str] = None) -> list:
        q = ("SELECT id, suite_id, app_id, owner, status, doc, created_at, "
             "updated_at FROM eval_runs")
        args: tuple = ()
        if suite_id:
            q += " WHERE suite_id=?"
            args = (suite_id,)
        with self._lock:
            rows = self._conn.execute(
                q + " ORDER BY created_at", args
            ).fetchall()
        return [self._run_row(r) for r in rows]

    def delete_eval_run(self, rid: str) -> bool:
        with self._lock:
            cur = self._conn.execute(
                "DELETE FROM eval_runs WHERE id=?", (rid,)
            )
            self._db.commit()
            return cur.rowcount > 0

    @staticmethod
    def _run_row(row) -> dict:
        doc = json.loads(row[5])
        doc.update(
            id=row[0], suite_id=row[1], app_id=row[2], owner=row[3],
            status=row[4], created_at=row[6], updated_at=row[7],
        )
        return doc
