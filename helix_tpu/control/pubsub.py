"""In-process pub/sub: the embedded-NATS equivalent.

The reference embeds a NATS JetStream server for request/response queues,
session events and the runner WS bridge (``api/pkg/pubsub/nats.go:39-60``
and the in-memory variant used in tests, ``serve.go:113``).  A single
self-hosted process doesn't need a broker protocol between its own
subsystems — this bus supplies the same interface surface (publish /
subscribe with wildcards / queue groups / request-reply) in-process, and
the WebSocket gateway on the control plane plays the role of the
user-facing event stream (``/ws/user``).
"""

from __future__ import annotations

import fnmatch
import itertools
import queue
import threading
import uuid
from typing import Callable, Optional


class Subscription:
    def __init__(self, bus, topic: str, cb, group: Optional[str]):
        self.bus = bus
        self.topic = topic
        self.cb = cb
        self.group = group
        self.id = uuid.uuid4().hex

    def unsubscribe(self):
        self.bus._remove(self)


class EventBus:
    def __init__(self):
        self._subs: list = []
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._jetstream = None

    def attach_jetstream(self, js) -> None:
        """Make publishes durable: every publish also lands in whatever
        JetStream streams match the topic (reference: the embedded NATS
        server IS JetStream-enabled, ``pubsub/nats.go:39-60``).

        Persistence runs on a dedicated writer thread — publish() is
        called from async handlers, and a SQLite COMMIT (disk fsync) on
        the event loop would stall every connection.  Queue + thread are
        set up BEFORE the attach becomes visible to publishers, and a
        re-attach just swaps the target (the writer reads
        ``self._jetstream`` per message) instead of leaking a thread."""
        if getattr(self, "_js_queue", None) is None:
            self._js_queue: "queue.Queue" = queue.Queue()

            def writer():
                while True:
                    topic, message = self._js_queue.get()
                    target = self._jetstream
                    if target is None:
                        continue
                    try:
                        target.publish(topic, message)
                    except Exception:  # noqa: BLE001 — durability is
                        import traceback  # best effort; fanout already ran

                        traceback.print_exc()

            threading.Thread(
                target=writer, daemon=True, name="jetstream-writer"
            ).start()
        self._jetstream = js

    # -- core ----------------------------------------------------------------
    def subscribe(
        self, topic: str, cb: Callable[[str, dict], None],
        group: Optional[str] = None,
    ) -> Subscription:
        """``topic`` supports fnmatch wildcards (``sessions.*``).  Within a
        queue ``group``, each message goes to exactly one member."""
        sub = Subscription(self, topic, cb, group)
        with self._lock:
            self._subs.append(sub)
        return sub

    def _remove(self, sub: Subscription):
        with self._lock:
            self._subs = [s for s in self._subs if s.id != sub.id]

    def publish(self, topic: str, message: dict) -> int:
        if self._jetstream is not None:
            self._js_queue.put((topic, message))
        with self._lock:
            matching = [s for s in self._subs if fnmatch.fnmatch(topic, s.topic)]
        # queue groups: one delivery per group, round-robin
        by_group: dict = {}
        solo = []
        for s in matching:
            if s.group:
                by_group.setdefault(s.group, []).append(s)
            else:
                solo.append(s)
        targets = list(solo)
        for members in by_group.values():
            targets.append(members[next(self._rr) % len(members)])
        for s in targets:
            try:
                s.cb(topic, message)
            except Exception:  # noqa: BLE001 — one subscriber must not break fanout
                import traceback

                traceback.print_exc()
        return len(targets)

    # -- request / reply -------------------------------------------------------
    def request(self, topic: str, message: dict, timeout: float = 5.0) -> dict:
        """NATS-style request: publish with a reply inbox, await one reply."""
        inbox = f"_inbox.{uuid.uuid4().hex}"
        q: "queue.Queue" = queue.Queue()
        sub = self.subscribe(inbox, lambda t, m: q.put(m))
        try:
            n = self.publish(topic, {**message, "_reply_to": inbox})
            if n == 0:
                raise TimeoutError(f"no responders on {topic}")
            return q.get(timeout=timeout)
        finally:
            sub.unsubscribe()

    def respond(self, request_message: dict, reply: dict) -> None:
        inbox = request_message.get("_reply_to")
        if inbox:
            self.publish(inbox, reply)
