"""Notifications: email / Slack / Discord notifiers.

Mirrors ``api/pkg/notification`` (email/Slack/Discord notifiers wired at
``serve.go:286-289``): lifecycle events (task done/failed, CI red) fan out
to every configured sink; a sink failure never breaks the caller.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Optional


@dataclasses.dataclass
class Notification:
    kind: str            # task_done | task_failed | ci_failed | custom...
    title: str
    body: str = ""
    meta: dict = dataclasses.field(default_factory=dict)
    created_at: float = dataclasses.field(default_factory=time.time)

    def to_dict(self):
        return dataclasses.asdict(self)


class Notifier:
    def send(self, n: Notification) -> None:  # pragma: no cover - interface
        raise NotImplementedError


class SlackWebhookNotifier(Notifier):
    """Incoming-webhook sink (https://hooks.slack.com/services/...)."""

    def __init__(self, url: str, http_post=None):
        self.url = url
        self.http_post = http_post or _default_post

    def send(self, n: Notification) -> None:
        self.http_post(
            self.url,
            {"text": f"*{n.title}*\n{n.body}".strip()},
        )


class DiscordWebhookNotifier(Notifier):
    def __init__(self, url: str, http_post=None):
        self.url = url
        self.http_post = http_post or _default_post

    def send(self, n: Notification) -> None:
        self.http_post(
            self.url,
            {"content": f"**{n.title}**\n{n.body}".strip()[:2000]},
        )


class EmailNotifier(Notifier):
    def __init__(self, host: str, port: int, sender: str, to: str,
                 username: str = "", password: str = "", use_tls=True):
        self.host, self.port = host, port
        self.sender, self.to = sender, to
        self.username, self.password = username, password
        self.use_tls = use_tls

    def send(self, n: Notification) -> None:
        import smtplib
        from email.message import EmailMessage

        msg = EmailMessage()
        msg["Subject"] = n.title
        msg["From"] = self.sender
        msg["To"] = self.to
        msg.set_content(n.body or n.title)
        with smtplib.SMTP(self.host, self.port, timeout=30) as s:
            if self.use_tls:
                s.starttls()
            if self.username:
                s.login(self.username, self.password)
            s.send_message(msg)


def _default_post(url: str, doc: dict) -> None:
    import requests

    requests.post(url, json=doc, timeout=15).raise_for_status()


class NotificationService:
    """Fan-out with per-sink error isolation + a ring buffer the admin UI
    reads (recent notifications survive even with zero sinks)."""

    def __init__(self, notifiers: Optional[list] = None, history: int = 200):
        self.notifiers: list[Notifier] = list(notifiers or [])
        self.recent: collections.deque = collections.deque(maxlen=history)
        self._lock = threading.Lock()
        # sinks run on one worker thread so a slow SMTP/webhook endpoint
        # never stalls the caller (the orchestrator's poll loop)
        import queue as _queue

        self._queue: _queue.Queue = _queue.Queue()
        self._worker: Optional[threading.Thread] = None

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "NotificationService":
        import os

        env = env if env is not None else os.environ
        sinks: list[Notifier] = []
        if env.get("HELIX_SLACK_WEBHOOK_URL"):
            sinks.append(SlackWebhookNotifier(env["HELIX_SLACK_WEBHOOK_URL"]))
        if env.get("HELIX_DISCORD_WEBHOOK_URL"):
            sinks.append(
                DiscordWebhookNotifier(env["HELIX_DISCORD_WEBHOOK_URL"])
            )
        if env.get("HELIX_SMTP_HOST"):
            sinks.append(
                EmailNotifier(
                    host=env["HELIX_SMTP_HOST"],
                    port=int(env.get("HELIX_SMTP_PORT", "587")),
                    sender=env.get("HELIX_SMTP_FROM", "helix@localhost"),
                    to=env.get("HELIX_SMTP_TO", ""),
                    username=env.get("HELIX_SMTP_USER", ""),
                    password=env.get("HELIX_SMTP_PASSWORD", ""),
                )
            )
        return cls(sinks)

    def notify(self, kind: str, title: str, body: str = "",
               **meta) -> Notification:
        n = Notification(kind=kind, title=title, body=body, meta=meta)
        with self._lock:
            self.recent.appendleft(n)
            if self.notifiers and self._worker is None:
                self._worker = threading.Thread(
                    target=self._drain, name="helix-notify", daemon=True
                )
                self._worker.start()
        if self.notifiers:
            self._queue.put(n)
        return n

    def _drain(self):
        while True:
            n = self._queue.get()
            for sink in self.notifiers:
                try:
                    sink.send(n)
                except Exception:  # noqa: BLE001 — a sink never breaks us
                    import logging

                    logging.getLogger(__name__).warning(
                        "notifier %s failed", type(sink).__name__,
                        exc_info=True,
                    )
            self._queue.task_done()

    def flush(self, timeout: float = 10.0) -> None:
        """Block until queued notifications have been delivered (tests)."""
        import time as _time

        deadline = _time.time() + timeout
        while not self._queue.empty() and _time.time() < deadline:
            _time.sleep(0.02)
        # one extra beat for the in-flight item past the queue
        _time.sleep(0.05)

    def history(self, limit: int = 50) -> list:
        with self._lock:
            return [n.to_dict() for n in list(self.recent)[:limit]]
