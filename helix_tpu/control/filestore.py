"""Filestore: user/app file storage behind the control plane.

Mirrors ``api/pkg/filestore`` (local-FS or GCS blob store with presigned
viewer URLs, ``serve.go:129-201``): a rooted local backend with
path-traversal protection, per-owner prefixes, and HMAC-signed time-limited
download URLs standing in for presigning (a cloud backend implements the
same interface).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import shutil
import time
from typing import Optional


class Filestore:
    def __init__(self, root: str, secret: Optional[bytes] = None):
        self.root = os.path.realpath(root)
        os.makedirs(self.root, exist_ok=True)
        if secret is None:
            # Random per-store URL-signing secret persisted under the
            # root: a hard-coded default would make every unconfigured
            # deployment's signed download URLs forgeable.
            secret = self._load_or_create_secret()
        self._secret = secret

    def _load_or_create_secret(self) -> bytes:
        from helix_tpu.utils import load_or_create_keyfile

        return load_or_create_keyfile(
            os.path.join(self.root, ".signing-secret")
        )

    def _resolve(self, owner: str, path: str) -> str:
        if (
            not owner
            or owner.startswith(".")  # reserves dotfiles (.signing-secret)
            or "/" in owner
            or os.sep in owner
            or ".." in owner
        ):
            raise PermissionError("invalid owner id")
        base = os.path.realpath(os.path.join(self.root, owner))
        # os.sep-terminated prefix compare: without it, '../ownerX' would
        # pass a bare startswith check against sibling dirs whose names
        # extend the owner id as a string prefix.
        if base != self.root and not base.startswith(self.root + os.sep):
            raise PermissionError("owner escapes the filestore")
        p = os.path.realpath(os.path.join(base, path.lstrip("/")))
        if p != base and not p.startswith(base + os.sep):
            raise PermissionError("path escapes the filestore")
        return p

    # -- blob operations -------------------------------------------------------
    def write(self, owner: str, path: str, data: bytes) -> dict:
        p = self._resolve(owner, path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)
        return self.stat(owner, path)

    def read(self, owner: str, path: str) -> bytes:
        with open(self._resolve(owner, path), "rb") as f:
            return f.read()

    def stat(self, owner: str, path: str) -> dict:
        p = self._resolve(owner, path)
        st = os.stat(p)
        return {
            "path": path.lstrip("/"),
            "size": st.st_size,
            "modified": st.st_mtime,
            "is_dir": os.path.isdir(p),
        }

    def list(self, owner: str, path: str = "") -> list:
        p = self._resolve(owner, path or ".")
        if not os.path.isdir(p):
            return []
        out = []
        for name in sorted(os.listdir(p)):
            out.append(self.stat(owner, os.path.join(path, name)))
        return out

    def delete(self, owner: str, path: str) -> bool:
        p = self._resolve(owner, path)
        if os.path.isdir(p):
            shutil.rmtree(p)
            return True
        if os.path.exists(p):
            os.remove(p)
            return True
        return False

    # -- signed URLs -----------------------------------------------------------
    def sign(self, owner: str, path: str, ttl: float = 3600.0) -> dict:
        """Presigned-style viewer token (reference: presigned viewer URLs)."""
        self._resolve(owner, path)  # validate before signing
        expires = int(time.time() + ttl)
        msg = f"{owner}:{path}:{expires}".encode()
        sig = hmac.new(self._secret, msg, hashlib.sha256).hexdigest()
        return {
            "path": path,
            "owner": owner,
            "expires": expires,
            "signature": sig,
            "url": f"/files/view?owner={owner}&path={path}"
                   f"&expires={expires}&sig={sig}",
        }

    def verify(self, owner: str, path: str, expires: int, sig: str) -> bool:
        if time.time() > expires:
            return False
        msg = f"{owner}:{path}:{expires}".encode()
        want = hmac.new(self._secret, msg, hashlib.sha256).hexdigest()
        return hmac.compare_digest(want, sig)
