"""Filestore: user/app file storage behind the control plane.

Mirrors ``api/pkg/filestore`` (local-FS or GCS blob store with presigned
viewer URLs, ``serve.go:129-201``): a rooted local backend with
path-traversal protection, per-owner prefixes, and HMAC-signed time-limited
download URLs standing in for presigning (a cloud backend implements the
same interface).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import shutil
import time
from typing import Optional


class Filestore:
    def __init__(self, root: str, secret: bytes = b"helix-filestore"):
        self.root = os.path.realpath(root)
        os.makedirs(self.root, exist_ok=True)
        self._secret = secret

    def _resolve(self, owner: str, path: str) -> str:
        p = os.path.realpath(
            os.path.join(self.root, owner, path.lstrip("/"))
        )
        if not p.startswith(os.path.join(self.root, owner)):
            raise PermissionError("path escapes the filestore")
        return p

    # -- blob operations -------------------------------------------------------
    def write(self, owner: str, path: str, data: bytes) -> dict:
        p = self._resolve(owner, path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)
        return self.stat(owner, path)

    def read(self, owner: str, path: str) -> bytes:
        with open(self._resolve(owner, path), "rb") as f:
            return f.read()

    def stat(self, owner: str, path: str) -> dict:
        p = self._resolve(owner, path)
        st = os.stat(p)
        return {
            "path": path.lstrip("/"),
            "size": st.st_size,
            "modified": st.st_mtime,
            "is_dir": os.path.isdir(p),
        }

    def list(self, owner: str, path: str = "") -> list:
        p = self._resolve(owner, path or ".")
        if not os.path.isdir(p):
            return []
        out = []
        for name in sorted(os.listdir(p)):
            out.append(self.stat(owner, os.path.join(path, name)))
        return out

    def delete(self, owner: str, path: str) -> bool:
        p = self._resolve(owner, path)
        if os.path.isdir(p):
            shutil.rmtree(p)
            return True
        if os.path.exists(p):
            os.remove(p)
            return True
        return False

    # -- signed URLs -----------------------------------------------------------
    def sign(self, owner: str, path: str, ttl: float = 3600.0) -> dict:
        """Presigned-style viewer token (reference: presigned viewer URLs)."""
        expires = int(time.time() + ttl)
        msg = f"{owner}:{path}:{expires}".encode()
        sig = hmac.new(self._secret, msg, hashlib.sha256).hexdigest()
        return {
            "path": path,
            "owner": owner,
            "expires": expires,
            "signature": sig,
            "url": f"/files/view?owner={owner}&path={path}"
                   f"&expires={expires}&sig={sig}",
        }

    def verify(self, owner: str, path: str, expires: int, sig: str) -> bool:
        if time.time() > expires:
            return False
        msg = f"{owner}:{path}:{expires}".encode()
        want = hmac.new(self._secret, msg, hashlib.sha256).hexdigest()
        return hmac.compare_digest(want, sig)
