"""GCE provider for the pool autoscaler: real cloud instances for runners.

The reference ships a real cloud implementation of its compute Provider
(``api/pkg/sandbox/compute/yellowdog/provider.go:115-123`` — YellowDog
provision/health/deprovision against a REST API); this is the TPU-native
counterpart against the Google Compute Engine REST API, the natural home
for v5e/v5p runner hosts:

- ``provision`` POSTs ``instances.insert`` with the configured machine
  type, boot image, optional TPU accelerator, and a startup script that
  launches ``helix_tpu serve-node`` pointed at the control plane (the
  cloud-init analogue of the reference's sandbox bootstrap);
- ``health_check`` maps GCE instance status to the manager's states
  (PROVISIONING/STAGING -> provisioning, RUNNING -> ready,
  STOPPING/TERMINATED -> failed, 404 -> gone);
- ``deprovision`` DELETEs the instance (404 treated as already gone).

Auth is a bearer token from (in order) an explicit ``token_provider``
callable, ``GCE_TOKEN`` in the environment, or the GCE metadata server —
no SDK dependency. ``api_base`` is injectable so the unit tests (and any
GCE-compatible shim) run against a fake server; nothing here requires
real cloud credentials until ``provision`` is actually called.
"""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request
import uuid
from typing import Callable, Optional

from helix_tpu.control.compute import Provider, Spec

log = logging.getLogger(__name__)

_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "service-accounts/default/token"
)


class GCEProvider(Provider):
    def __init__(
        self,
        project: str,
        zone: str,
        machine_type: str = "n2-standard-8",
        source_image: str = (
            "projects/debian-cloud/global/images/family/debian-12"
        ),
        network: str = "global/networks/default",
        control_plane_url: str = "",
        runner_token: str = "",
        startup_script: Optional[str] = None,
        api_base: str = "https://compute.googleapis.com/compute/v1",
        token_provider: Optional[Callable[[], str]] = None,
        timeout: float = 30.0,
        name_prefix: str = "helix-node",
    ):
        self.project = project
        self.zone = zone
        self.machine_type = machine_type
        self.source_image = source_image
        self.network = network
        self.control_plane_url = control_plane_url
        self.runner_token = runner_token
        self.startup_script = startup_script
        self.api_base = api_base.rstrip("/")
        self.token_provider = token_provider
        self.timeout = timeout
        self.name_prefix = name_prefix

    # -- auth ---------------------------------------------------------------
    def _token(self) -> str:
        if self.token_provider is not None:
            return self.token_provider()
        import os

        tok = os.environ.get("GCE_TOKEN", "")
        if tok:
            return tok
        try:
            req = urllib.request.Request(
                _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
            )
            with urllib.request.urlopen(req, timeout=2) as resp:
                return json.loads(resp.read()).get("access_token", "")
        except OSError:
            return ""

    def _call(self, method: str, path: str, body: Optional[dict] = None):
        tok = self._token()
        req = urllib.request.Request(
            f"{self.api_base}{path}",
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={
                "Content-Type": "application/json",
                **({"Authorization": f"Bearer {tok}"} if tok else {}),
            },
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read() or b"{}")

    # -- Provider surface ----------------------------------------------------
    def name(self) -> str:
        return "gce"

    def _default_startup(self) -> str:
        # NOTE: instance metadata (including this script) is readable by
        # any principal with compute.instances.get, so the runner token
        # here is only as private as project viewer access. For stricter
        # deployments pass ``startup_script`` that pulls the token from
        # Secret Manager instead of embedding it.
        import shlex

        return (
            "#!/bin/sh\n"
            f"export HELIX_RUNNER_TOKEN={shlex.quote(self.runner_token)}\n"
            # bind heartbeats to this host's autoscaler row: the GCE
            # instance name IS the provider id the ComputeManager knows
            # (InstanceStore.find_by_provider), and on GCE the hostname
            # is the instance name — without this the manager never sees
            # a heartbeat for the row, flips it offline after the stale
            # window and reaps a perfectly healthy host
            "export HELIX_INSTANCE_ID=\"$(hostname)\"\n"
            "python -m helix_tpu serve-node "
            f"--control-plane {shlex.quote(self.control_plane_url)} "
            "--runner-id \"$(hostname)\" --tunnel\n"
        )

    def provision(self, spec: Spec) -> str:
        iname = f"{self.name_prefix}-{uuid.uuid4().hex[:8]}"
        zprefix = f"projects/{self.project}/zones/{self.zone}"
        body = {
            "name": iname,
            "machineType": f"{zprefix}/machineTypes/{self.machine_type}",
            "disks": [{
                "boot": True,
                "autoDelete": True,
                "initializeParams": {"sourceImage": self.source_image},
            }],
            "networkInterfaces": [{
                "network": self.network,
                "accessConfigs": [
                    {"type": "ONE_TO_ONE_NAT", "name": "External NAT"}
                ],
            }],
            "labels": {
                "helix-pool": "runner",
                **{k: str(v) for k, v in (spec.labels or {}).items()},
            },
            "metadata": {"items": [{
                "key": "startup-script",
                "value": self.startup_script or self._default_startup(),
            }]},
        }
        if spec.accelerator and spec.accelerator.startswith("v"):
            # v5e/v5p runner hosts: GCE exposes single-host TPU slices as
            # accelerator resources on the VM (multi-host slices go
            # through the TPU API instead — out of scope for the pool
            # autoscaler, which manages single-host runners)
            body["guestAccelerators"] = [{
                "acceleratorType":
                    f"{zprefix}/acceleratorTypes/{spec.accelerator}",
                "acceleratorCount": 1,
            }]
            body["scheduling"] = {"onHostMaintenance": "TERMINATE"}
        self._call("POST", f"/{zprefix}/instances", body)
        return iname

    def health_check(self, provider_id: str) -> str:
        zprefix = f"projects/{self.project}/zones/{self.zone}"
        try:
            doc = self._call(
                "GET", f"/{zprefix}/instances/{provider_id}"
            )
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return "gone"
            log.warning("gce health_check %s: HTTP %s", provider_id, e.code)
            return "provisioning"   # transient API error: don't roll back
        except OSError as e:
            log.warning("gce health_check %s: %s", provider_id, e)
            return "provisioning"
        status = doc.get("status", "")
        if status in ("PROVISIONING", "STAGING"):
            return "provisioning"
        if status == "RUNNING":
            return "ready"
        if status in ("STOPPING", "STOPPED", "SUSPENDED", "TERMINATED"):
            return "failed"
        return "provisioning"

    def deprovision(self, provider_id: str) -> None:
        zprefix = f"projects/{self.project}/zones/{self.zone}"
        try:
            self._call(
                "DELETE", f"/{zprefix}/instances/{provider_id}"
            )
        except urllib.error.HTTPError as e:
            if e.code != 404:        # already gone is success
                raise


def from_env() -> Optional[GCEProvider]:
    """Config-gated construction: returns a provider iff HELIX_GCE_PROJECT
    and HELIX_GCE_ZONE are set (the reference gates its cloud provider on
    provider credentials the same way)."""
    import os

    project = os.environ.get("HELIX_GCE_PROJECT", "")
    zone = os.environ.get("HELIX_GCE_ZONE", "")
    if not (project and zone):
        return None
    return GCEProvider(
        project=project,
        zone=zone,
        machine_type=os.environ.get(
            "HELIX_GCE_MACHINE_TYPE", "n2-standard-8"
        ),
        source_image=os.environ.get(
            "HELIX_GCE_IMAGE",
            "projects/debian-cloud/global/images/family/debian-12",
        ),
        control_plane_url=os.environ.get("HELIX_GCE_CONTROL_PLANE", ""),
        runner_token=os.environ.get("HELIX_RUNNER_TOKEN", ""),
    )
