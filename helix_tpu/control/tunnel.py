"""Reverse tunnels: NAT'd runners dial OUT; the control plane dials back
through the same websocket.

The TPU-native counterpart of the reference's RevDial + Connman transport
(``api/pkg/revdial/revdial.go:5-18``: "a dialer that for the machine that
accepted the original connection becomes the dialing side";
``api/pkg/connman/connman.go:20-40``: keyed dialers, 30s reconnect grace,
queued Dial waiters) and of the raw-conn SSE trick in
``api/pkg/openai/helix_openai_server.go:279-307`` — responses stream
chunk-for-chunk, never buffered.

Design (idiomatic asyncio rather than a Go net.Conn translation): one
websocket per runner carries multiplexed logical HTTP streams.  Binary
frames: ``[sid: u32 BE][op: u8][payload]``.

    OP_OPEN  (control->runner)  JSON {method, path, headers}
    OP_BODY  (both directions)  raw body bytes
    OP_END   (both directions)  body finished
    OP_RESP  (runner->control)  JSON {status, headers}
    OP_ERR   (runner->control)  JSON {error}
    OP_CLOSE (both directions)  abort the stream

The runner serves its OpenAI surface on a unix socket (no listening TCP
port at all — exactly how the reference's hydra daemon runs, SURVEY.md
§2.3) and the ``TunnelAgent`` bridges frames to it.
"""

from __future__ import annotations

import asyncio
import json
import struct
from typing import AsyncIterator, Optional

import aiohttp
from aiohttp import web

from helix_tpu.obs.trace import TRACE_HEADER

OP_OPEN = 0
OP_BODY = 1
OP_END = 2
OP_RESP = 3
OP_ERR = 4
OP_CLOSE = 5

_HDR = struct.Struct(">IB")


def pack_frame(sid: int, op: int, payload: bytes = b"") -> bytes:
    return _HDR.pack(sid, op) + payload


def unpack_frame(data: bytes) -> tuple[int, int, bytes]:
    sid, op = _HDR.unpack_from(data)
    return sid, op, data[_HDR.size:]


class TunnelClosed(Exception):
    """The runner's tunnel dropped (mid-stream or before dispatch)."""


class _Stream:
    """Control-plane view of one logical request through the tunnel."""

    def __init__(self):
        self.resp_fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self.chunks: asyncio.Queue = asyncio.Queue()

    def push_error(self, msg: str):
        if not self.resp_fut.done():
            self.resp_fut.set_exception(TunnelClosed(msg))
        else:
            self.chunks.put_nowait(TunnelClosed(msg))


class TunnelConn:
    """One live runner websocket; multiplexes logical streams over it."""

    def __init__(self, runner_id: str, ws: web.WebSocketResponse):
        self.runner_id = runner_id
        self.ws = ws
        self._streams: dict[int, _Stream] = {}
        self._next_sid = 1
        self._closed = False

    async def pump(self):
        """Read frames until the socket dies; fan out to streams."""
        try:
            async for msg in self.ws:
                if msg.type != web.WSMsgType.BINARY:
                    continue
                sid, op, payload = unpack_frame(msg.data)
                st = self._streams.get(sid)
                if st is None:
                    continue
                if op == OP_RESP:
                    doc = json.loads(payload)
                    if not st.resp_fut.done():
                        st.resp_fut.set_result(doc)
                elif op == OP_BODY:
                    st.chunks.put_nowait(payload)
                elif op == OP_END:
                    st.chunks.put_nowait(None)
                    self._streams.pop(sid, None)
                elif op in (OP_ERR, OP_CLOSE):
                    detail = ""
                    if payload:
                        try:
                            detail = json.loads(payload).get("error", "")
                        except Exception:  # noqa: BLE001
                            detail = payload[:200].decode("utf-8", "replace")
                    st.push_error(detail or "stream closed by runner")
                    self._streams.pop(sid, None)
        finally:
            self.close("tunnel disconnected")

    def close(self, reason: str):
        if self._closed:
            return
        self._closed = True
        for st in list(self._streams.values()):
            st.push_error(reason)
        self._streams.clear()

    @property
    def closed(self) -> bool:
        return self._closed

    async def request(
        self,
        method: str,
        path: str,
        headers: Optional[dict] = None,
        body: bytes = b"",
    ) -> tuple[int, dict, AsyncIterator[bytes]]:
        """Dispatch one HTTP request through the tunnel.  The returned
        iterator yields response body chunks as they arrive (SSE-safe:
        chunk-for-chunk, no buffering)."""
        if self._closed:
            raise TunnelClosed("tunnel is closed")
        sid = self._next_sid
        self._next_sid += 1
        st = _Stream()
        self._streams[sid] = st
        try:
            await self.ws.send_bytes(
                pack_frame(
                    sid, OP_OPEN,
                    json.dumps(
                        {
                            "method": method,
                            "path": path,
                            "headers": headers or {},
                        }
                    ).encode(),
                )
            )
            if body:
                await self.ws.send_bytes(pack_frame(sid, OP_BODY, body))
            await self.ws.send_bytes(pack_frame(sid, OP_END))
        except (ConnectionError, OSError, RuntimeError) as e:
            self._streams.pop(sid, None)
            raise TunnelClosed(f"tunnel send failed: {e}") from e
        doc = await st.resp_fut

        async def body_iter():
            try:
                while True:
                    chunk = await st.chunks.get()
                    if chunk is None:
                        return
                    if isinstance(chunk, Exception):
                        raise chunk
                    yield chunk
            finally:
                # consumer stopped early (client disconnect): abort the
                # runner-side generation instead of letting it burn chips
                # for a dead client
                if self._streams.get(sid) is st:
                    await self.cancel(sid)

        return int(doc["status"]), dict(doc.get("headers", {})), body_iter()

    async def cancel(self, sid: int):
        """Abort one logical stream: tell the runner to stop generating
        (client went away) and drop the local bookkeeping."""
        self._streams.pop(sid, None)
        try:
            await self.ws.send_bytes(pack_frame(sid, OP_CLOSE))
        except Exception:  # noqa: BLE001 — socket already gone
            pass


class TunnelHub:
    """Keyed runner tunnels with reconnect grace and queued dials
    (connman semantics: ``connman.go:20-40``)."""

    def __init__(self, grace: float = 30.0):
        self.grace = grace
        self._conns: dict[str, TunnelConn] = {}
        self._waiters: dict[str, list[asyncio.Future]] = {}

    def connected(self, runner_id: str) -> bool:
        c = self._conns.get(runner_id)
        return c is not None and not c.closed

    async def handle_ws(self, runner_id: str, request) -> web.WebSocketResponse:
        """Accept a runner's outbound dial (the server becomes the dialing
        side from here on)."""
        ws = web.WebSocketResponse(heartbeat=20, max_msg_size=0)
        await ws.prepare(request)
        old = self._conns.get(runner_id)
        if old is not None and not old.closed:
            old.close("replaced by a newer tunnel")
        conn = TunnelConn(runner_id, ws)
        self._conns[runner_id] = conn
        for fut in self._waiters.pop(runner_id, []):
            if not fut.done():
                fut.set_result(conn)
        try:
            await conn.pump()
        finally:
            if self._conns.get(runner_id) is conn:
                del self._conns[runner_id]
        return ws

    async def _get_conn(self, runner_id: str) -> TunnelConn:
        c = self._conns.get(runner_id)
        if c is not None and not c.closed:
            return c
        # queued dial: wait for the runner to re-dial within the grace
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiters.setdefault(runner_id, []).append(fut)
        try:
            return await asyncio.wait_for(fut, timeout=self.grace)
        except asyncio.TimeoutError:
            raise TunnelClosed(
                f"runner {runner_id} has no tunnel (waited {self.grace}s)"
            ) from None
        finally:
            waiters = self._waiters.get(runner_id)
            if waiters and fut in waiters:
                waiters.remove(fut)
                if not waiters:
                    del self._waiters[runner_id]

    async def request(
        self,
        runner_id: str,
        method: str,
        path: str,
        headers: Optional[dict] = None,
        body: bytes = b"",
    ) -> tuple[int, dict, AsyncIterator[bytes]]:
        conn = await self._get_conn(runner_id)
        return await conn.request(method, path, headers, body)


class TunnelAgent:
    """Runner-side: dial the control plane, serve tunneled requests against
    the local (unix-socket) HTTP surface, stream responses back."""

    def __init__(
        self,
        runner_id: str,
        control_url: str,
        *,
        unix_socket: Optional[str] = None,
        local_base: str = "http://localhost",
        runner_token: str = "",
        reconnect_delay: float = 1.0,
    ):
        self.runner_id = runner_id
        self.control_url = control_url.rstrip("/")
        self.unix_socket = unix_socket
        self.local_base = local_base.rstrip("/")
        self.runner_token = runner_token
        self.reconnect_delay = reconnect_delay
        self._stop = asyncio.Event()
        self.connects = 0   # observability: how many times we dialed

    def _connector(self):
        if self.unix_socket:
            return aiohttp.UnixConnector(path=self.unix_socket)
        return None

    async def run(self):
        """Dial-out loop with reconnect backoff (runner keeps re-dialing
        for the life of the process; the hub's grace window makes brief
        drops invisible to callers)."""
        url = f"{self.control_url}/api/v1/runners/{self.runner_id}/tunnel"
        headers = (
            {"X-Runner-Token": self.runner_token}
            if self.runner_token
            else {}
        )
        while not self._stop.is_set():
            try:
                async with aiohttp.ClientSession() as session:
                    async with session.ws_connect(
                        url, headers=headers, heartbeat=20, max_msg_size=0
                    ) as ws:
                        self.connects += 1
                        await self._serve(ws)
            except (aiohttp.ClientError, OSError, asyncio.TimeoutError):
                pass
            if not self._stop.is_set():
                await asyncio.sleep(self.reconnect_delay)

    def stop(self):
        self._stop.set()

    async def _serve(self, ws):
        bodies: dict[int, bytearray] = {}
        opens: dict[int, dict] = {}
        tasks: dict[int, asyncio.Task] = {}
        try:
            async for msg in ws:
                if msg.type != aiohttp.WSMsgType.BINARY:
                    continue
                sid, op, payload = unpack_frame(msg.data)
                if op == OP_OPEN:
                    opens[sid] = json.loads(payload)
                    bodies[sid] = bytearray()
                elif op == OP_BODY and sid in bodies:
                    bodies[sid] += payload
                elif op == OP_END and sid in opens:
                    spec = opens.pop(sid)
                    body = bytes(bodies.pop(sid))
                    t = asyncio.create_task(
                        self._dispatch(ws, sid, spec, body)
                    )
                    tasks[sid] = t
                    t.add_done_callback(lambda _t, s=sid: tasks.pop(s, None))
                elif op == OP_CLOSE:
                    # control plane aborted the stream (client went away):
                    # cancel the local request so the engine aborts too
                    opens.pop(sid, None)
                    bodies.pop(sid, None)
                    t = tasks.pop(sid, None)
                    if t is not None:
                        t.cancel()
        finally:
            for t in tasks.values():
                t.cancel()

    async def _dispatch(self, ws, sid: int, spec: dict, body: bytes):
        """One tunneled request -> local HTTP -> frames back.  Chunks are
        forwarded as they arrive so SSE streams token-by-token."""
        try:
            async with aiohttp.ClientSession(
                connector=self._connector(),
                timeout=aiohttp.ClientTimeout(total=600),
            ) as session:
                async with session.request(
                    spec.get("method", "POST"),
                    f"{self.local_base}{spec.get('path', '/')}",
                    data=body if body else None,
                    headers=spec.get("headers") or {},
                ) as resp:
                    headers = {
                        "Content-Type": resp.headers.get(
                            "Content-Type", "application/json"
                        ),
                    }
                    # trace correlation survives the tunnel hop: the
                    # runner echoes X-Helix-Trace-Id; forward it so the
                    # control plane (and client) see the same id the
                    # runner logged
                    tid = resp.headers.get(TRACE_HEADER)
                    if tid:
                        headers[TRACE_HEADER] = tid
                    await ws.send_bytes(
                        pack_frame(
                            sid, OP_RESP,
                            json.dumps(
                                {"status": resp.status, "headers": headers}
                            ).encode(),
                        )
                    )
                    async for chunk in resp.content.iter_any():
                        await ws.send_bytes(pack_frame(sid, OP_BODY, chunk))
                    await ws.send_bytes(pack_frame(sid, OP_END))
        except Exception as e:  # noqa: BLE001 — reported through the tunnel
            try:
                await ws.send_bytes(
                    pack_frame(
                        sid, OP_ERR,
                        json.dumps({"error": f"{type(e).__name__}: {e}"})
                        .encode(),
                    )
                )
            except Exception:  # noqa: BLE001 — socket already gone
                pass
